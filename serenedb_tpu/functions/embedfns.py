"""ai_embed(): text → embedding vectors via a provider registry.

Reference analog: server/connector/functions/embedding/{embedding,provider,
provider_openai}.cpp — ai_embed(text, model, secret_name) resolving a
provider by model protocol and batch-embedding through it.

Providers here:
- local[:dim] — deterministic signed char-trigram feature hashing,
  L2-normalized (no network; the offline default, and the only provider
  exercised by tests — this image has zero egress).
- openai:<model> / http:<url> — real HTTP providers; constructing the
  request requires a secret created with create_secret(), and the call
  surfaces a clear SqlError when the network is unreachable.

Vectors render as JSON array text — the engine's vector representation
(search/ivf.parse_vector), so ai_embed output feeds vec_* operators and
IVF indexes directly.
"""

from __future__ import annotations

import hashlib
import json
import math

import numpy as np

from .. import errors
from ..columnar import dtypes as dt
from ..sql.expr import make_string_column, propagate_nulls, string_values
from .scalar import FunctionResolution, _REGISTRY, register


def _db():
    from ..engine import CURRENT_CONNECTION
    conn = CURRENT_CONNECTION.get()
    return None if conn is None else conn.db


def _secrets(db) -> dict:
    s = getattr(db, "secrets", None)
    if s is None:
        s = db.secrets = {}
    return s


def local_embed(text: str, dim: int = 64) -> np.ndarray:
    """Signed char-trigram feature hashing, L2-normalized. Deterministic
    across processes (blake2b, not PYTHONHASHSEED-dependent)."""
    v = np.zeros(dim, dtype=np.float64)
    t = f"  {text.lower()} "
    for i in range(len(t) - 2):
        h = hashlib.blake2b(t[i:i + 3].encode(), digest_size=8).digest()
        x = int.from_bytes(h, "big")
        v[x % dim] += 1.0 if (x >> 63) & 1 else -1.0
    n = math.sqrt(float((v * v).sum()))
    return v / n if n > 0 else v


def _parse_model(model: str) -> tuple[str, str]:
    """'local:128' / 'openai:text-embedding-3-small' / 'http:<url>' →
    (provider, param)."""
    s = (model or "local").strip()
    proto, _, rest = s.partition(":")
    proto = proto.lower()
    if proto in ("local", "openai", "http", "https"):
        return proto, rest
    raise errors.SqlError("22023",
                          f"ai_embed: unknown provider {proto!r} "
                          "(expected local / openai / http)")


def _http_embed(provider: str, param: str, texts: list[str],
                secret: str) -> list[list[float]]:
    import urllib.error
    import urllib.request
    if provider == "openai":
        url = "https://api.openai.com/v1/embeddings"
        payload = {"model": param or "text-embedding-3-small",
                   "input": texts}
        headers = {"Authorization": f"Bearer {secret}",
                   "Content-Type": "application/json"}
    else:
        url = (("https:" if provider == "https" else "http:") + param)
        payload = {"input": texts}
        headers = {"Authorization": f"Bearer {secret}",
                   "Content-Type": "application/json"}
    req = urllib.request.Request(url, data=json.dumps(payload).encode(),
                                 headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            body = json.loads(resp.read().decode())
    except (urllib.error.URLError, OSError) as e:
        raise errors.SqlError(
            "58030", f"ai_embed: provider request failed: {e}")
    try:
        if "data" in body:   # OpenAI shape
            return [d["embedding"] for d in body["data"]]
        return body["embeddings"]
    except (KeyError, TypeError):
        raise errors.SqlError("58030",
                              "ai_embed: malformed provider response")


@register("ai_embed")
def _ai_embed(ts):
    if not ts or len(ts) > 3:
        return None

    def _local_dim(param: str) -> int:
        try:
            dim = int(param) if param else 64
        except ValueError:
            raise errors.SqlError(
                "22023", f"ai_embed: invalid local dim {param!r}")
        if not (1 <= dim <= 4096):
            raise errors.SqlError("22023",
                                  "ai_embed: dim must be in [1, 4096]")
        return dim

    def impl(cols, n):
        texts = string_values(cols[0])
        valid = propagate_nulls(cols)
        models = (string_values(cols[1]) if len(cols) > 1
                  else ["local"] * n)
        snames = (string_values(cols[2]) if len(cols) > 2
                  else [None] * n)
        out = [""] * n
        live = [i for i in range(n) if valid is None or valid[i]]
        # group rows by (model, secret): local rows embed inline, each
        # remote group goes out as ONE batched provider request
        groups: dict[tuple, list[int]] = {}
        for i in live:
            groups.setdefault((str(models[i]), snames[i]), []).append(i)
        for (model, sname), idxs in groups.items():
            provider, param = _parse_model(model)
            if provider == "local":
                dim = _local_dim(param)
                for i in idxs:
                    vec = local_embed(str(texts[i]), dim)
                    out[i] = json.dumps([round(float(x), 6) for x in vec])
                continue
            if len(cols) < 3:
                raise errors.SqlError(
                    "22023", "ai_embed: remote providers need a secret "
                             "name: ai_embed(text, model, secret_name)")
            db = _db()
            secret = _secrets(db).get(sname) if db is not None else None
            if secret is None:
                raise errors.SqlError(
                    "22023", f"ai_embed: secret '{sname}' not found — "
                             "create_secret(name, value) first")
            vecs = _http_embed(provider, param,
                               [str(texts[i]) for i in idxs], secret)
            if len(vecs) != len(idxs):
                raise errors.SqlError("58030",
                                      "ai_embed: provider returned "
                                      f"{len(vecs)} vectors for "
                                      f"{len(idxs)} inputs")
            for i, vec in zip(idxs, vecs):
                out[i] = json.dumps(vec)
        return make_string_column(
            np.asarray(out, dtype=object).astype(str), valid)
    return FunctionResolution(dt.VARCHAR, impl)


@register("create_secret")
def _create_secret(ts):
    if len(ts) != 2:
        return None

    def impl(cols, n):
        db = _db()
        if db is None:
            raise errors.SqlError("55000", "no database in scope")
        names = string_values(cols[0])
        values = string_values(cols[1])
        for i in range(n):
            _secrets(db)[str(names[i])] = str(values[i])
        return make_string_column(
            np.asarray(["ok"] * n, dtype=object).astype(str), None)
    return FunctionResolution(dt.VARCHAR, impl)


@register("drop_secret")
def _drop_secret(ts):
    if len(ts) != 1:
        return None

    def impl(cols, n):
        db = _db()
        if db is None:
            raise errors.SqlError("55000", "no database in scope")
        names = string_values(cols[0])
        from ..columnar.column import Column
        out = np.zeros(n, dtype=bool)
        for i in range(n):
            out[i] = _secrets(db).pop(str(names[i]), None) is not None
        return Column(dt.BOOL, out)
    return FunctionResolution(dt.BOOL, impl)
