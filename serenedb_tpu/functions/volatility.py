"""Scalar-function volatility classification (PG's volatility classes).

Reference analog: PostgreSQL's provolatile — every function is
IMMUTABLE (pure: same arguments, same result, forever), STABLE (fixed
within one statement but free to change between statements: now(),
current_setting(), subquery expressions over table state the plan walk
cannot see) or VOLATILE (every evaluation may differ: random(),
nextval(), clock_timestamp()).

Three consumers, three different bars:

- bind-time literal folding (binder._fold_if_const): STABLE is foldable
  — binding happens once per statement, so folding now() IS its
  statement-stability. Only VOLATILE must re-evaluate per call.
- analysis-time folding (binder.fold_constant, the zone-map interval
  extractor): only IMMUTABLE folds. A stable value folded during
  analysis could disagree with the per-row evaluation (a scan crossing
  midnight must not prune blocks with the stale day).
- the result cache (cache/result.py): only IMMUTABLE may appear in a
  cached plan. STABLE results vary between statements even when no
  table changed (the publication tuples in the cache key capture data
  state, not wall-clock state), and VOLATILE must never be replayed.

Anything not classified here defaults to IMMUTABLE — the scalar library
(functions/scalar.py) is pure by construction; stateful functions are
the enumerated exceptions. Name-prefix rules catch whole families:
`sdb_*` table/introspection helpers are VOLATILE (they read live engine
state), `pg_*` catalog readers are STABLE (they read the catalog, which
the cache key does not observe).
"""

from __future__ import annotations

IMMUTABLE = "immutable"
STABLE = "stable"
VOLATILE = "volatile"

#: every evaluation may return a different value — never folded, never
#: cached, evaluated once per row when used as a column DEFAULT
VOLATILE_FUNCS = frozenset({
    "random", "setseed",
    "nextval", "setval",
    "gen_random_uuid", "uuid_generate_v4",
    "clock_timestamp", "timeofday",
    "ai_embed",          # remote model call
    "set_config",
    # secret-store mutators (functions/embedfns.py): SELECT-invoked
    # side effects must run on every execution, never replay
    "create_secret", "drop_secret",
})

#: pinned within one statement, free to drift between statements —
#: foldable at bind time (once per statement), never cacheable across
#: statements, never folded during predicate analysis
STABLE_FUNCS = frozenset({
    "now", "current_timestamp", "transaction_timestamp",
    "statement_timestamp", "current_date", "current_time",
    "localtime", "localtimestamp", "age",
    "currval", "lastval",
    "current_setting", "current_user", "session_user", "user",
    "current_schema", "current_schemas", "current_database",
    "current_catalog", "current_role", "inet_client_addr",
    "inet_server_addr", "txid_current", "version",
    "to_regclass", "to_regtype", "to_regproc", "to_regnamespace",
    # subquery expression forms (binder-synthesized BoundFunc names):
    # they embed nested plans over tables the outer plan walk cannot
    # see, so a cached statement must never contain one
    "scalar_subquery", "array_subquery", "in_subquery", "exists",
})


def volatility(name: str) -> str:
    """Volatility class of a function by its bound name. Synthesized
    binder names (cast/not/and/or/like/is_null/op*) are pure and fall
    through to the IMMUTABLE default."""
    n = name.lower()
    if n in VOLATILE_FUNCS:
        return VOLATILE
    if n in STABLE_FUNCS:
        return STABLE
    if n.startswith("sdb_"):
        return VOLATILE
    if n.startswith("pg_"):
        return STABLE
    return IMMUTABLE


def is_immutable(name: str) -> bool:
    return volatility(name) is IMMUTABLE
