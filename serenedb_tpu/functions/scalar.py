"""Scalar function & operator library (CPU reference implementations).

Reference analog: server/connector/functions/{math,string,array,json,...}.cpp
(~8 kLoC of PG-compatible functions; SURVEY.md §2.5). Semantics follow
PostgreSQL: strict NULL propagation unless noted, integer division truncates,
division by zero raises 22012, 1-based string indexing.

Each registry entry resolves (arg_types) -> (result_type, impl) where impl is
(cols: list[Column], n_rows) -> Column.
"""

from __future__ import annotations

import json
import math
import re
from typing import Callable, Optional

import numpy as np

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Column
from ..sql.expr import make_string_column, propagate_nulls, string_values


class FunctionResolution:
    def __init__(self, result_type: dt.SqlType, impl: Callable):
        self.result_type = result_type
        self.impl = impl


_REGISTRY: dict[str, Callable] = {}


def register(name):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def resolve(name: str, arg_types: list[dt.SqlType]) -> FunctionResolution:
    fn = _REGISTRY.get(name)
    if fn is None:
        raise errors.SqlError(errors.UNDEFINED_FUNCTION,
                              f"function {name}({', '.join(map(str, arg_types))}) "
                              "does not exist")
    res = fn(arg_types)
    if res is None:
        raise errors.SqlError(errors.UNDEFINED_FUNCTION,
                              f"function {name}({', '.join(map(str, arg_types))}) "
                              "does not exist")
    return res


def exists(name: str) -> bool:
    return name in _REGISTRY


# -- helpers ---------------------------------------------------------------

def _num(col: Column) -> np.ndarray:
    return col.data


def _result(typ: dt.SqlType, data: np.ndarray, cols: list[Column],
            extra_invalid: Optional[np.ndarray] = None) -> Column:
    validity = propagate_nulls(cols)
    if extra_invalid is not None and extra_invalid.any():
        validity = (validity if validity is not None
                    else np.ones(len(data), dtype=bool)) & ~extra_invalid
    return Column(typ, np.ascontiguousarray(data, dtype=typ.np_dtype), validity)


def _all_numeric(ts: list[dt.SqlType]) -> bool:
    return all(t.is_numeric or t.id in (dt.TypeId.TIMESTAMP, dt.TypeId.DATE)
               or t.id is dt.TypeId.NULL for t in ts)


# -- comparisons -----------------------------------------------------------

_CMP_NP = {
    "=": np.equal, "<>": np.not_equal, "!=": np.not_equal,
    "<": np.less, "<=": np.less_equal, ">": np.greater, ">=": np.greater_equal,
}


def _make_compare(op: str):
    def resolver(ts: list[dt.SqlType]):
        if len(ts) == 2 and all(t.id is dt.TypeId.RECORD for t in ts):
            return _record_compare(op)

        def impl(cols, n):
            a, b = cols
            if a.type.is_string or b.type.is_string:
                av, bv = string_values(a), string_values(b)
                data = _CMP_NP[op](av, bv)
            else:
                data = _CMP_NP[op](a.data, b.data)
                # PG float total order: NaN = NaN, NaN > everything
                # (reference: server/pg/serialize.cpp float semantics)
                anan = (np.isnan(a.data) if a.data.dtype.kind == "f"
                        else np.zeros(len(a.data), dtype=bool))
                bnan = (np.isnan(b.data) if b.data.dtype.kind == "f"
                        else np.zeros(len(b.data), dtype=bool))
                nan_rows = anan | bnan
                if nan_rows.any():
                    both = anan & bnan
                    if op == "=":
                        fix = both
                    elif op in ("<>", "!="):
                        fix = nan_rows & ~both
                    elif op == "<":
                        fix = bnan & ~anan
                    elif op == "<=":
                        fix = bnan
                    elif op == ">":
                        fix = anan & ~bnan
                    else:  # >=
                        fix = anan
                    data = np.where(nan_rows, fix, data)
            return _result(dt.BOOL, data, cols)
        return FunctionResolution(dt.BOOL, impl)
    return resolver


def _record_compare(op: str) -> FunctionResolution:
    """Field-wise record comparison (PG record_eq/record_cmp family):
    physical-text compare would order ROW(10) before ROW(2) and miss
    cross-width equality, so records parse and compare by value."""
    def impl(cols, n):
        from ..columnar.pgcopy import record_cmp_sql
        av, bv = string_values(cols[0]), string_values(cols[1])
        data = np.zeros(n, dtype=bool)
        sqlnull = np.zeros(n, dtype=bool)
        for i in range(n):
            c = record_cmp_sql(str(av[i]), str(bv[i]))
            if c is None:
                sqlnull[i] = True
            elif op == "=":
                data[i] = c == 0
            elif op in ("<>", "!="):
                data[i] = c != 0
            elif op == "<":
                data[i] = c < 0
            elif op == "<=":
                data[i] = c <= 0
            elif op == ">":
                data[i] = c > 0
            else:
                data[i] = c >= 0
        return _result(dt.BOOL, data, cols, extra_invalid=sqlnull)
    return FunctionResolution(dt.BOOL, impl)


for _op in _CMP_NP:
    _REGISTRY[f"op{_op}"] = _make_compare(_op)


@register("is_distinct_from")
def _is_distinct(ts):
    def impl(cols, n):
        a, b = cols
        av, bv = a.valid_mask(), b.valid_mask()
        if a.type.is_string or b.type.is_string:
            eq = string_values(a) == string_values(b)
        else:
            eq = a.data == b.data
        same = (av & bv & eq) | (~av & ~bv)
        return Column(dt.BOOL, ~same)
    return FunctionResolution(dt.BOOL, impl)


@register("is_not_distinct_from")
def _is_not_distinct(ts):
    inner = _is_distinct(ts)

    def impl(cols, n):
        c = inner.impl(cols, n)
        return Column(dt.BOOL, ~c.data)
    return FunctionResolution(dt.BOOL, impl)


# -- arithmetic ------------------------------------------------------------

def _arith_type(op: str, a: dt.SqlType, b: dt.SqlType) -> dt.SqlType:
    t = dt.common_numeric(a, b)
    if t.id is dt.TypeId.BOOL:
        raise errors.SqlError(errors.DATATYPE_MISMATCH,
                              f"operator {op} does not accept boolean")
    return t


_US_DAY = 86_400_000_000


def _datetime_arith(op: str, ts: list):
    """Result SqlType for timestamp/date/interval arithmetic (PG rules);
    None when the operand types are not a datetime combination."""
    TS, D, IV = dt.TypeId.TIMESTAMP, dt.TypeId.DATE, dt.TypeId.INTERVAL
    a, b = ts[0].id, ts[1].id
    NULL = dt.TypeId.NULL
    if NULL in (a, b) and {a, b} & {TS, D, IV}:
        # NULL operand: the result is NULL of the natural result type
        other = ts[1] if a is NULL else ts[0]
        if op in ("+", "-"):
            return other if other.id is not D else dt.DATE
        if op in ("*", "/") and other.id is IV:
            return dt.INTERVAL
    if op == "+":
        pairs = {
            (TS, IV): dt.TIMESTAMP, (IV, TS): dt.TIMESTAMP,
            (D, IV): dt.TIMESTAMP, (IV, D): dt.TIMESTAMP,
            (IV, IV): dt.INTERVAL,
        }
        r = pairs.get((a, b))
        if r is not None:
            return r
        if a is D and ts[1].is_integer:
            return dt.DATE
        if ts[0].is_integer and b is D:
            return dt.DATE
    elif op == "-":
        pairs = {
            (TS, IV): dt.TIMESTAMP, (D, IV): dt.TIMESTAMP,
            (TS, TS): dt.INTERVAL, (IV, IV): dt.INTERVAL,
            (TS, D): dt.INTERVAL, (D, TS): dt.INTERVAL,
        }
        r = pairs.get((a, b))
        if r is not None:
            return r
        if a is D and b is D:
            return dt.INT            # days
        if a is D and ts[1].is_integer:
            return dt.DATE
    elif op in ("*", "/"):
        if a is IV and ts[1].is_numeric and b is not dt.TypeId.BOOL:
            return dt.INTERVAL
        if op == "*" and ts[0].is_numeric and b is IV and \
                a is not dt.TypeId.BOOL:
            return dt.INTERVAL
    return None


def _to_us(col, n):
    """Column value in microseconds (dates scale by the day)."""
    x = col.data.astype(np.int64)
    if col.type.id is dt.TypeId.DATE:
        x = x * _US_DAY
    return x


def _make_datetime_arith(op: str, ts: list, out_t):
    def impl(cols, n):
        D, IV = dt.TypeId.DATE, dt.TypeId.INTERVAL
        a, b = cols[0], cols[1]
        if op in ("*", "/"):
            iv = a if a.type.id is IV else b
            num = b if a.type.id is IV else a
            x = num.data.astype(np.float64)
            with np.errstate(all="ignore"):
                data = (iv.data.astype(np.float64) * x if op == "*"
                        else iv.data.astype(np.float64) / x)
            if op == "/":
                zero = x == 0
                pn = propagate_nulls(cols)
                live_zero = zero if pn is None else (zero & pn)
                if live_zero.any():
                    raise errors.SqlError(errors.DIVISION_BY_ZERO,
                                          "division by zero")
                with np.errstate(all="ignore"):
                    data = np.where(zero, 0.0, data)
            return _result(out_t, np.round(data).astype(np.int64), cols)
        if out_t.id is dt.TypeId.DATE:
            # date ± integer days
            d = a if a.type.id is D else b
            k = b if a.type.id is D else a
            kk = k.data.astype(np.int64)
            data = (d.data.astype(np.int64) + kk if op == "+"
                    else d.data.astype(np.int64) - kk)
            pn = propagate_nulls(cols)
            over = np.abs(data) > 2**31 - 1
            if pn is not None:
                over &= pn
            if over.any():
                raise errors.SqlError("22008", "date out of range")
            return _result(dt.DATE, data.astype(np.int32), cols)
        if out_t.id is dt.TypeId.INT:
            # date - date = days
            return _result(dt.INT, (a.data.astype(np.int64) -
                                    b.data.astype(np.int64)).astype(
                                        np.int32), cols)
        av, bv = _to_us(a, n), _to_us(b, n)
        data = av + bv if op == "+" else av - bv
        return _result(out_t, data, cols)
    return FunctionResolution(out_t, impl)


def _make_arith(op: str):
    def resolver(ts: list[dt.SqlType]):
        if len(ts) == 2:
            out_t = _datetime_arith(op, ts)
            if out_t is not None:
                return _make_datetime_arith(op, ts, out_t)
        if len(ts) != 2 or not _all_numeric(ts):
            return None
        t = _arith_type(op, ts[0], ts[1])
        if op == "/" and t.is_integer:
            pass  # PG: int/int truncates toward zero
        def impl(cols, n):
            a, b = cols[0].data, cols[1].data
            extra_invalid = None
            if op in ("+", "-", "*") and t.is_integer:
                # compute in int64 and range-check: PG raises 22003 on
                # int32/int64 overflow instead of silently wrapping
                aa = a.astype(np.int64)
                bb = b.astype(np.int64)
                with np.errstate(over="ignore"):
                    if op == "+":
                        data64 = aa + bb
                        bad = ((aa > 0) & (bb > 0) & (data64 < 0)) | \
                              ((aa < 0) & (bb < 0) & (data64 > 0))
                    elif op == "-":
                        data64 = aa - bb
                        bad = ((aa >= 0) & (bb < 0) & (data64 < 0)) | \
                              ((aa < 0) & (bb > 0) & (data64 > 0))
                    else:
                        data64 = aa * bb
                        # verify from BOTH sides: -1 * INT64_MIN wraps and
                        # the aa-side division wraps back to bb, hiding it
                        bad = (aa != 0) & (data64 // np.where(aa == 0, 1,
                                                              aa) != bb)
                        bad |= (bb != 0) & (
                            data64 // np.where(bb == 0, 1, bb) != aa)
                pn = propagate_nulls(cols)
                if pn is not None:
                    bad &= pn
                info = np.iinfo(t.np_dtype)
                small = (data64 < info.min) | (data64 > info.max)
                if pn is not None:
                    small &= pn
                if bad.any() or small.any():
                    kind = {np.dtype(np.int16): "smallint",
                            np.dtype(np.int32): "integer"}.get(
                        np.dtype(t.np_dtype), "bigint")
                    raise errors.SqlError(
                        "22003", f"{kind} out of range")
                data = data64.astype(t.np_dtype)
            elif op == "+":
                data = a.astype(t.np_dtype) + b.astype(t.np_dtype)
            elif op == "-":
                data = a.astype(t.np_dtype) - b.astype(t.np_dtype)
            elif op == "*":
                data = a.astype(t.np_dtype) * b.astype(t.np_dtype)
            elif op in ("/", "%"):
                bb = b.astype(t.np_dtype)
                zero = bb == 0
                # only error on division by zero in non-NULL rows
                pn = propagate_nulls(cols)
                live_zero = zero if pn is None else (zero & pn)
                if t.is_integer:
                    if live_zero.any():
                        raise errors.SqlError(errors.DIVISION_BY_ZERO,
                                              "division by zero")
                    aa = a.astype(np.int64)
                    bb64 = b.astype(np.int64)
                    # zeros can remain in NULL rows; divide by 1 there
                    bsafe = np.where(bb64 == 0, 1, bb64)
                    q = (np.abs(aa) // np.abs(bsafe)) * np.sign(aa) * np.sign(bsafe)
                    data = q if op == "/" else aa - q * bb64
                    data = data.astype(t.np_dtype)
                else:
                    if live_zero.any():
                        raise errors.SqlError(errors.DIVISION_BY_ZERO,
                                              "division by zero")
                    with np.errstate(divide="ignore", invalid="ignore"):
                        data = (a.astype(t.np_dtype) / bb) if op == "/" \
                            else np.fmod(a.astype(t.np_dtype), bb)
            else:
                raise AssertionError(op)
            return _result(t, data, cols, extra_invalid)
        return FunctionResolution(t, impl)
    return resolver


for _op in ("+", "-", "*", "/", "%"):
    _REGISTRY[f"op{_op}"] = _make_arith(_op)


# '+' and comparison registrations collide on name; re-dispatch by type:
def _dispatch(name, arith, compare=None):
    def resolver(ts):
        r = arith(ts)
        if r is not None:
            return r
        return compare(ts) if compare else None
    return resolver


_REGISTRY["op||"] = None  # set below


@register("opneg")
def _neg(ts):
    t = ts[0] if (ts[0].is_numeric or
                  ts[0].id is dt.TypeId.INTERVAL) else None
    if t is None:
        return None

    def impl(cols, n):
        return _result(t, -cols[0].data, cols)
    return FunctionResolution(t, impl)


# -- concat ----------------------------------------------------------------

def _concat_resolver(ts):
    def impl(cols, n):
        parts = [_col_text_values(c) for c in cols]
        data = parts[0]
        for p in parts[1:]:
            data = np.char.add(data, p)
        return make_string_column(data, propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


_REGISTRY["op||"] = _concat_resolver


def _concat_skip_nulls(ts):
    """concat(...) ignores NULL arguments (PG); only || propagates them."""
    if not ts:
        return None   # concat() with no args: 42883, like PG
    def impl(cols, n):
        parts = []
        for c in cols:
            valid = c.valid_mask() if c.validity is not None else None
            vals = _col_text_values(c)
            if valid is not None:
                vals = np.where(valid, vals, "")
            parts.append(vals)
        data = parts[0]
        for p in parts[1:]:
            data = np.char.add(data, p)
        return make_string_column(data, None)
    return FunctionResolution(dt.VARCHAR, impl)


_REGISTRY["concat"] = _concat_skip_nulls


def _concat_ws(ts):
    """concat_ws(sep, ...) joins non-NULL arguments with the separator
    (PG); a NULL separator yields NULL."""
    if len(ts) < 1:
        return None

    def impl(cols, n):
        sep_col = cols[0]
        sep_valid = sep_col.valid_mask()
        seps = string_values(sep_col) if sep_col.type.is_string else \
            np.asarray([_pg_text(v) for v in sep_col.to_pylist()],
                       dtype=object).astype(str)
        pieces = []
        for c in cols[1:]:
            valid = c.valid_mask()
            if c.type.is_string:
                vals = string_values(c)
            else:
                vals = np.asarray([_pg_text(v) for v in c.to_pylist()],
                                  dtype=object).astype(str)
            pieces.append((vals, valid))
        out = np.empty(n, dtype=object)
        for i in range(n):
            parts = [str(v[i]) for v, valid in pieces if valid[i]]
            out[i] = str(seps[i]).join(parts)
        return make_string_column(out, None if sep_valid.all()
                                  else sep_valid)
    return FunctionResolution(dt.VARCHAR, impl)


_REGISTRY["concat_ws"] = _concat_ws


def _pg_text(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "t" if v else "f"
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v)) if v == int(v) else str(v)
    return str(v)


def _col_text_values(c) -> np.ndarray:
    """Column → PG cast-to-text renderings (DATE/TIMESTAMP/INTERVAL as
    their text, bool as true/false — expression-context semantics, not
    the wire's t/f)."""
    if c.type.is_string:
        return string_values(c)
    if c.type.id in (dt.TypeId.DATE, dt.TypeId.TIMESTAMP,
                     dt.TypeId.INTERVAL):
        from ..columnar.pgcopy import _scalar_field_text
        return np.asarray(
            ["" if v is None else _scalar_field_text(c.type, v)
             for v in c.to_pylist()], dtype=object).astype(str)
    if c.type.id is dt.TypeId.BOOL:
        return np.asarray(
            ["" if v is None else ("true" if v else "false")
             for v in c.to_pylist()], dtype=object).astype(str)
    return np.asarray([_pg_text(v) for v in c.to_pylist()],
                      dtype=object).astype(str)


# -- math functions --------------------------------------------------------

def _unary_math(np_fn, out_type=None, domain=None, domain_msg=""):
    """domain: predicate over the input array; rows where a VALID input
    falls outside it raise (PG: sqrt(-1)/ln(0) are errors, not NaN)."""
    def resolver(ts):
        if len(ts) != 1 or not _all_numeric(ts):
            return None
        t = out_type or (ts[0] if ts[0].is_integer and np_fn in (np.abs,)
                         else dt.DOUBLE)
        def impl(cols, n):
            x = cols[0].data.astype(np.float64 if t == dt.DOUBLE else t.np_dtype)
            if domain is not None:
                valid = cols[0].valid_mask() \
                    if cols[0].validity is not None else None
                bad = ~domain(x)
                if valid is not None:
                    bad &= valid
                if bad.any():
                    raise errors.SqlError("2201F", domain_msg or
                                          "input is out of range")
            with np.errstate(all="ignore"):
                data = np_fn(x)
            return _result(t, data, cols)
        return FunctionResolution(t, impl)
    return resolver


_REGISTRY["abs"] = _unary_math(np.abs)


@register("round")
def _round(ts):
    t = dt.DOUBLE if ts[0].is_float else ts[0]
    def impl(cols, n):
        x = cols[0].data.astype(np.float64)
        d = cols[1].data.astype(np.int64) if len(cols) > 1 else 0
        # PG rounds half away from zero
        scale = np.power(10.0, d)
        data = np.sign(x) * np.floor(np.abs(x) * scale + 0.5) / scale
        return _result(dt.DOUBLE if not ts[0].is_integer else ts[0], data, cols)
    return FunctionResolution(dt.DOUBLE if not ts[0].is_integer else ts[0], impl)


for name, fn in [("floor", np.floor), ("ceil", np.ceil), ("ceiling", np.ceil),
                 ("exp", np.exp), ("sin", np.sin), ("cos", np.cos),
                 ("tan", np.tan), ("atan", np.arctan),
                 ("degrees", np.degrees), ("radians", np.radians),
                 ("cbrt", np.cbrt)]:
    _REGISTRY[name] = _unary_math(fn)


@register("trunc")
def _trunc(ts):
    """trunc(x[, digits]): toward zero, optional decimal places (PG
    trunc(numeric, int))."""
    if len(ts) not in (1, 2):
        return None
    if len(ts) == 1:
        return _unary_math(np.trunc)(ts)

    def impl(cols, n):
        x = cols[0].data.astype(np.float64)
        d = cols[1].data.astype(np.int64)
        scale = np.power(10.0, d)
        data = np.trunc(x * scale) / scale
        return _result(dt.DOUBLE, data, cols)
    return FunctionResolution(dt.DOUBLE, impl)

_REGISTRY["sqrt"] = _unary_math(
    np.sqrt, domain=lambda x: x >= 0,
    domain_msg="cannot take square root of a negative number")
_REGISTRY["ln"] = _unary_math(
    np.log, domain=lambda x: x > 0,
    domain_msg="cannot take logarithm of zero or a negative number")
_REGISTRY["log10"] = _unary_math(
    np.log10, domain=lambda x: x > 0,
    domain_msg="cannot take logarithm of zero or a negative number")
_REGISTRY["asin"] = _unary_math(np.arcsin, domain=lambda x: np.abs(x) <= 1)
_REGISTRY["acos"] = _unary_math(np.arccos, domain=lambda x: np.abs(x) <= 1)


@register("factorial")
def _factorial(ts):
    def impl(cols, n):
        import math as _math
        vals = cols[0].data.astype(np.int64)
        if (vals < 0).any():
            raise errors.SqlError("2201F",
                                  "factorial of a negative number")
        data = np.asarray([_math.factorial(int(v)) if int(v) < 21 else 0
                           for v in vals], dtype=np.int64)
        if (vals > 20).any():
            raise errors.SqlError("22003", "factorial out of BIGINT range")
        return _result(dt.BIGINT, data, cols)
    return FunctionResolution(dt.BIGINT, impl)


@register("log")
def _log(ts):
    if len(ts) == 1:
        return _REGISTRY["log10"](ts)
    def impl(cols, n):
        base = cols[0].data.astype(np.float64)
        x = cols[1].data.astype(np.float64)
        with np.errstate(all="ignore"):
            data = np.log(x) / np.log(base)
        return _result(dt.DOUBLE, data, cols)
    return FunctionResolution(dt.DOUBLE, impl)


@register("power")
@register("pow")
def _power(ts):
    def impl(cols, n):
        with np.errstate(all="ignore"):
            data = np.power(cols[0].data.astype(np.float64),
                            cols[1].data.astype(np.float64))
        return _result(dt.DOUBLE, data, cols)
    return FunctionResolution(dt.DOUBLE, impl)


@register("mod")
def _mod(ts):
    return _make_arith("%")(ts)


@register("div")
def _div(ts):
    """PG div(a, b): integer-truncating division (toward zero); 22012 on
    zero divisor.  Reference: server/connector/functions/math.cpp."""
    if len(ts) != 2 or not _all_numeric(ts):
        return None
    if ts[0].is_integer and ts[1].is_integer:
        return _make_arith("/")(ts)
    def impl(cols, n):
        b = cols[1].data.astype(np.float64)
        pn = propagate_nulls(cols)
        zero = b == 0
        live_zero = zero if pn is None else (zero & pn)
        if live_zero.any():
            raise errors.SqlError(errors.DIVISION_BY_ZERO,
                                  "division by zero")
        with np.errstate(all="ignore"):
            data = np.trunc(cols[0].data.astype(np.float64) /
                            np.where(zero, 1.0, b))
        return _result(dt.DOUBLE, data, cols)
    return FunctionResolution(dt.DOUBLE, impl)


# -- bitwise operators (parser-desugared: & | # << >> ~) -------------------

def _bitwise(np_fn):
    def resolver(ts):
        if len(ts) != 2 or not all(t.is_integer or t.id is dt.TypeId.NULL
                                   for t in ts):
            return None
        t = max(ts, key=lambda x: x.np_dtype.itemsize if x.is_integer
                else 0)
        if not t.is_integer:
            t = dt.INT
        def impl(cols, n):
            a = cols[0].data.astype(np.int64)
            b = cols[1].data.astype(np.int64)
            with np.errstate(all="ignore"):
                data = np_fn(a, b)
            return _result(t, data.astype(t.np_dtype), cols)
        return FunctionResolution(t, impl)
    return resolver


_REGISTRY["bitand"] = _bitwise(np.bitwise_and)
_REGISTRY["bitor"] = _bitwise(np.bitwise_or)
_REGISTRY["bitxor"] = _bitwise(np.bitwise_xor)
_REGISTRY["bitshiftleft"] = _bitwise(
    lambda a, b: np.left_shift(a, np.clip(b, 0, 63)))
_REGISTRY["bitshiftright"] = _bitwise(
    lambda a, b: np.right_shift(a, np.clip(b, 0, 63)))


@register("bitnot")
def _bitnot(ts):
    if len(ts) != 1 or not (ts[0].is_integer or ts[0].id is dt.TypeId.NULL):
        return None
    t = ts[0] if ts[0].is_integer else dt.INT
    def impl(cols, n):
        return _result(t, np.bitwise_not(
            cols[0].data.astype(np.int64)).astype(t.np_dtype), cols)
    return FunctionResolution(t, impl)


@register("gcd")
def _gcd(ts):
    if len(ts) != 2 or not _all_numeric(ts):
        return None
    def impl(cols, n):
        a = cols[0].data.astype(np.int64)
        b = cols[1].data.astype(np.int64)
        return _result(dt.BIGINT, np.gcd(a, b), cols)
    return FunctionResolution(dt.BIGINT, impl)


@register("lcm")
def _lcm(ts):
    if len(ts) != 2 or not _all_numeric(ts):
        return None
    def impl(cols, n):
        a = cols[0].data.astype(np.int64)
        b = cols[1].data.astype(np.int64)
        with np.errstate(all="ignore"):
            data = np.lcm(a, b)
        return _result(dt.BIGINT, data, cols)
    return FunctionResolution(dt.BIGINT, impl)


@register("width_bucket")
def _width_bucket(ts):
    if len(ts) != 4 or not _all_numeric(ts):
        return None
    def impl(cols, n):
        x = cols[0].data.astype(np.float64)
        lo = cols[1].data.astype(np.float64)
        hi = cols[2].data.astype(np.float64)
        cnt = cols[3].data.astype(np.int64)
        pn = propagate_nulls(cols)
        live = np.ones(n, dtype=bool) if pn is None else pn
        if ((cnt <= 0) & live).any():
            raise errors.SqlError("2201G",
                                  "count must be greater than zero")
        if ((lo == hi) & live).any():
            raise errors.SqlError("2201G",
                                  "lower bound cannot equal upper bound")
        with np.errstate(all="ignore"):
            frac = (x - lo) / np.where(hi == lo, 1.0, hi - lo)
            buck = np.floor(frac * cnt).astype(np.int64) + 1
        buck = np.clip(buck, 0, cnt + 1)
        # descending ranges mirror (PG: operand < bound counts from top)
        desc = hi < lo
        with np.errstate(all="ignore"):
            fd = (lo - x) / np.where(lo == hi, 1.0, lo - hi)
            bd = np.floor(fd * cnt).astype(np.int64) + 1
        buck = np.where(desc, np.clip(bd, 0, cnt + 1), buck)
        return _result(dt.INT, buck, cols)
    return FunctionResolution(dt.INT, impl)


@register("num_nulls")
def _num_nulls(ts):
    def impl(cols, n):
        counts = np.zeros(n, dtype=np.int32)
        for c in cols:
            if c.type.id is dt.TypeId.NULL:
                counts += 1
            elif c.validity is not None:
                counts += (~c.valid_mask()).astype(np.int32)
        return Column(dt.INT, counts)
    return FunctionResolution(dt.INT, impl)


@register("num_nonnulls")
def _num_nonnulls(ts):
    def impl(cols, n):
        counts = np.zeros(n, dtype=np.int32)
        for c in cols:
            if c.type.id is dt.TypeId.NULL:
                continue
            if c.validity is not None:
                counts += c.valid_mask().astype(np.int32)
            else:
                counts += 1
        return Column(dt.INT, counts)
    return FunctionResolution(dt.INT, impl)


@register("sign")
def _sign(ts):
    def impl(cols, n):
        return _result(dt.DOUBLE, np.sign(cols[0].data.astype(np.float64)), cols)
    return FunctionResolution(dt.DOUBLE, impl)


@register("pi")
def _pi(ts):
    def impl(cols, n):
        return Column(dt.DOUBLE, np.full(n, math.pi))
    return FunctionResolution(dt.DOUBLE, impl)


# -- string functions ------------------------------------------------------

def _str_fn(result_type):
    def deco(fn):
        def resolver(ts):
            def impl(cols, n):
                return fn(cols, n)
            return FunctionResolution(result_type, impl)
        return resolver
    return deco


@register("upper")
def _upper(ts):
    def impl(cols, n):
        return make_string_column(np.char.upper(string_values(cols[0])),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("lower")
def _lower(ts):
    def impl(cols, n):
        return make_string_column(np.char.lower(string_values(cols[0])),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("length")
@register("char_length")
def _length(ts):
    def impl(cols, n):
        data = np.char.str_len(string_values(cols[0])).astype(np.int64)
        return _result(dt.BIGINT, data, cols)
    return FunctionResolution(dt.BIGINT, impl)


@register("substr")
@register("substring")
def _substr(ts):
    if len(ts) == 2 and ts[1].is_string:
        # substring(str FROM 'regex'): first regex match, NULL if none;
        # with a capture group, the group (PG semantics)
        def impl_rx(cols, n):
            s = string_values(cols[0])
            pats = string_values(cols[1])
            out = np.empty(n, dtype=object)
            miss = np.zeros(n, dtype=bool)
            for i in range(n):
                try:
                    m = re.search(pats[i], s[i])
                except re.error as e:
                    raise errors.SqlError(
                        "2201B", f"invalid regular expression: {e}")
                if m is None:
                    out[i] = ""
                    miss[i] = True
                else:
                    out[i] = m.group(1) if m.groups() else m.group(0)
                    if out[i] is None:
                        out[i] = ""
                        miss[i] = True
            validity = propagate_nulls(cols)
            if miss.any():
                validity = (validity if validity is not None
                            else np.ones(n, dtype=bool)) & ~miss
            return make_string_column(out.astype(str), validity)
        return FunctionResolution(dt.VARCHAR, impl_rx)
    def impl(cols, n):
        s = string_values(cols[0])
        start = cols[1].data.astype(np.int64)
        ln = cols[2].data.astype(np.int64) if len(cols) > 2 else None
        out = np.empty(len(s), dtype=object)
        for i in range(len(s)):
            st = start[i] - 1  # PG 1-based
            end = None if ln is None else max(st + ln[i], 0) if st >= 0 else max(start[i] - 1 + ln[i], 0)
            if st < 0:
                st2 = 0
                end = None if ln is None else max(start[i] - 1 + ln[i], 0)
            else:
                st2 = st
            out[i] = s[i][st2:end]
        return make_string_column(out.astype(str), propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("replace")
def _replace(ts):
    def impl(cols, n):
        s, old, new = (string_values(c) for c in cols)
        out = np.asarray([a.replace(b, c) for a, b, c in zip(s, old, new)],
                         dtype=object)
        return make_string_column(out.astype(str), propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


def _make_trim(which):
    def resolver(ts):
        def impl(cols, n):
            s = string_values(cols[0])
            chars = None
            if len(cols) > 1:
                chars = string_values(cols[1])
            out = []
            for i, v in enumerate(s):
                ch = None if chars is None else chars[i]
                if which == "both":
                    out.append(v.strip(ch))
                elif which == "left":
                    out.append(v.lstrip(ch))
                else:
                    out.append(v.rstrip(ch))
            return make_string_column(np.asarray(out, dtype=object).astype(str),
                                      propagate_nulls(cols))
        return FunctionResolution(dt.VARCHAR, impl)
    return resolver


_REGISTRY["trim"] = _make_trim("both")
_REGISTRY["btrim"] = _make_trim("both")
_REGISTRY["ltrim"] = _make_trim("left")
_REGISTRY["rtrim"] = _make_trim("right")


@register("starts_with")
def _starts_with(ts):
    def impl(cols, n):
        a, b = string_values(cols[0]), string_values(cols[1])
        data = np.asarray([x.startswith(y) for x, y in zip(a, b)])
        return _result(dt.BOOL, data, cols)
    return FunctionResolution(dt.BOOL, impl)


@register("contains")
def _contains(ts):
    def impl(cols, n):
        a, b = string_values(cols[0]), string_values(cols[1])
        data = np.asarray([y in x for x, y in zip(a, b)])
        return _result(dt.BOOL, data, cols)
    return FunctionResolution(dt.BOOL, impl)


@register("strpos")
@register("position")
def _strpos(ts):
    def impl(cols, n):
        a, b = string_values(cols[0]), string_values(cols[1])
        data = np.asarray([x.find(y) + 1 for x, y in zip(a, b)], dtype=np.int64)
        return _result(dt.BIGINT, data, cols)
    return FunctionResolution(dt.BIGINT, impl)


def _pad_impl(ts, left_side: bool):
    if len(ts) not in (2, 3):
        return None

    def impl(cols, n):
        s = string_values(cols[0])
        k = cols[1].data.astype(np.int64)
        fill = string_values(cols[2]) if len(cols) > 2 else [" "] * n
        out = []
        for v, kk, f in zip(s, k, fill):
            kk = int(kk)
            if kk <= len(v):
                out.append(v[:max(kk, 0)])
            elif not f:
                out.append(v)
            else:
                pad = (f * ((kk - len(v)) // len(f) + 1))[:kk - len(v)]
                out.append(pad + v if left_side else v + pad)
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


_REGISTRY["lpad"] = lambda ts: _pad_impl(ts, left_side=True)
_REGISTRY["rpad"] = lambda ts: _pad_impl(ts, left_side=False)


@register("initcap")
def _initcap(ts):
    def impl(cols, n):
        s = string_values(cols[0])
        out = [v.title() for v in s]
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("ascii")
def _ascii(ts):
    def impl(cols, n):
        s = string_values(cols[0])
        data = np.asarray([ord(v[0]) if v else 0 for v in s],
                          dtype=np.int32)
        return _result(dt.INT, data, cols)
    return FunctionResolution(dt.INT, impl)


@register("chr")
def _chr(ts):
    def impl(cols, n):
        k = cols[0].data.astype(np.int64)
        valid = cols[0].valid_mask() \
            if cols[0].validity is not None else None
        bad = (k <= 0) | (k > 0x10FFFF)
        if valid is not None:
            bad &= valid
        if bad.any():
            raise errors.SqlError(
                "54000", "character number must be between 1 and 1114111")
        out = [chr(int(v)) if 0 < v <= 0x10FFFF else "" for v in k]
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("md5")
def _md5(ts):
    def impl(cols, n):
        import hashlib
        s = string_values(cols[0])
        out = [hashlib.md5(v.encode()).hexdigest() for v in s]
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("octet_length")
def _octet_length(ts):
    def impl(cols, n):
        s = string_values(cols[0])
        out = np.asarray([len(v.encode()) for v in s], dtype=np.int32)
        return _result(dt.INT, out, cols)
    return FunctionResolution(dt.INT, impl)


@register("bit_length")
def _bit_length(ts):
    def impl(cols, n):
        s = string_values(cols[0])
        out = np.asarray([8 * len(v.encode()) for v in s], dtype=np.int32)
        return _result(dt.INT, out, cols)
    return FunctionResolution(dt.INT, impl)


@register("overlay")
def _overlay(ts):
    """overlay(str, repl, start[, count]) — 1-based; count defaults to
    the replacement length (PG)."""
    if len(ts) not in (3, 4):
        return None

    def impl(cols, n):
        sv = string_values(cols[0])
        rv = string_values(cols[1])
        starts = cols[2].data.astype(np.int64)
        counts = cols[3].data.astype(np.int64) if len(cols) > 3 else None
        out = []
        for i in range(n):
            s0, r0 = str(sv[i]), str(rv[i])
            st = max(int(starts[i]), 1)
            cnt = int(counts[i]) if counts is not None else len(r0)
            out.append(s0[: st - 1] + r0 + s0[st - 1 + max(cnt, 0):])
        return make_string_column(np.asarray(out, dtype=object),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("encode")
def _encode(ts):
    """encode(text, format): base64 / hex / escape over the UTF-8 bytes
    (PG encode over bytea; text input is its byte form here)."""
    if len(ts) != 2:
        return None

    def impl(cols, n):
        import base64 as _b64
        data = string_values(cols[0])
        fmts = string_values(cols[1])
        out = []
        for i in range(n):
            raw = str(data[i]).encode("utf-8")
            f = str(fmts[i]).lower()
            if f == "base64":
                out.append(_b64.b64encode(raw).decode())
            elif f == "hex":
                out.append(raw.hex())
            elif f == "escape":
                out.append("".join(
                    chr(b) if 32 <= b < 127 and b != 92
                    else f"\\{b:03o}" for b in raw))
            else:
                raise errors.SqlError(
                    "22023", f"unrecognized encoding: {f!r}")
        return make_string_column(np.asarray(out, dtype=object),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("decode")
def _decode(ts):
    if len(ts) != 2:
        return None

    def impl(cols, n):
        import base64 as _b64
        data = string_values(cols[0])
        fmts = string_values(cols[1])
        out = []
        for i in range(n):
            f = str(fmts[i]).lower()
            s0 = str(data[i])
            try:
                if f == "base64":
                    raw = _b64.b64decode(s0, validate=True)
                elif f == "hex":
                    raw = bytes.fromhex(s0)
                else:
                    raise errors.SqlError(
                        "22023", f"unrecognized encoding: {f!r}")
            except (ValueError, Exception) as e:
                if isinstance(e, errors.SqlError):
                    raise
                raise errors.SqlError("22023",
                                      f"invalid {f} input: {s0!r}")
            out.append(raw.decode("utf-8", errors="replace"))
        return make_string_column(np.asarray(out, dtype=object),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("to_hex")
def _to_hex(ts):
    if len(ts) != 1 or not (ts[0].is_integer or ts[0].id is dt.TypeId.NULL):
        return None
    def impl(cols, n):
        k = cols[0].data.astype(np.int64)
        # PG prints the two's-complement hex of the 32/64-bit value
        width = 32 if ts[0].np_dtype.itemsize <= 4 else 64
        out = [format(int(v) & ((1 << width) - 1), "x") for v in k]
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("format")
def _format(ts):
    if not ts:
        return None
    def impl(cols, n):
        fmt = string_values(cols[0])
        args = cols[1:]
        arg_valid = [c.valid_mask() if c.validity is not None else None
                     for c in args]
        arg_text = [[_pg_text(v) for v in c.to_pylist()] for c in args]
        out = []
        for row in range(n):
            s, pos, res = fmt[row], 0, []
            k = 0
            while k < len(s):
                ch = s[k]
                if ch != "%":
                    res.append(ch)
                    k += 1
                    continue
                if k + 1 >= len(s):
                    raise errors.SqlError(
                        "22023", "unterminated format() type specifier")
                spec = s[k + 1]
                k += 2
                if spec == "%":
                    res.append("%")
                    continue
                if spec not in ("s", "I", "L"):
                    raise errors.SqlError(
                        "22023",
                        f'unrecognized format() type specifier "{spec}"')
                if pos >= len(args):
                    raise errors.SqlError(
                        "22023", "too few arguments for format()")
                is_null = (arg_valid[pos] is not None
                           and not arg_valid[pos][row]) or \
                    args[pos].type.id is dt.TypeId.NULL
                v = None if is_null else arg_text[pos][row]
                pos += 1
                if spec == "s":
                    res.append("" if v is None else v)
                elif spec == "I":
                    if v is None:
                        raise errors.SqlError(
                            "22004",
                            "null values cannot be formatted as an "
                            "SQL identifier")
                    res.append(v if v.isidentifier() and v == v.lower()
                               else '"' + v.replace('"', '""') + '"')
                else:   # %L
                    res.append("NULL" if v is None
                               else "'" + v.replace("'", "''") + "'")
            out.append("".join(res))
        validity = (cols[0].valid_mask()
                    if cols[0].validity is not None else None)
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  validity)
    return FunctionResolution(dt.VARCHAR, impl)


@register("__similar_to")
def _similar_to(ts):
    """SQL SIMILAR TO: SQL wildcards (% _) + regex branches, anchored
    full-match (reference analog: similar_to_escape in PG's regexp.c)."""
    def impl(cols, n):
        pats = string_values(cols[1])
        s = string_values(cols[0])
        out = np.zeros(n, dtype=bool)
        cache = {}
        for i in range(n):
            p = pats[i]
            rx = cache.get(p)
            if rx is None:
                buf = []
                k = 0
                while k < len(p):
                    c = p[k]
                    if c == "%":
                        buf.append(".*")
                    elif c == "_":
                        buf.append(".")
                    elif c == "\\" and k + 1 < len(p):
                        buf.append(re.escape(p[k + 1]))
                        k += 1
                    elif c in ".^$":
                        buf.append(re.escape(c))
                    else:
                        buf.append(c)   # | * + ? { } ( ) [ ] stay regex
                    k += 1
                try:
                    rx = cache[p] = re.compile("(?s)\\A(?:%s)\\Z"
                                               % "".join(buf))
                except re.error as e:
                    raise errors.SqlError(
                        "2201B", f"invalid SIMILAR TO pattern: {e}")
            out[i] = rx.match(s[i]) is not None
        return _result(dt.BOOL, out, cols)
    return FunctionResolution(dt.BOOL, impl)


#: TypeId → pg_typeof() rendering (PG spellings)
_PG_TYPE_NAMES = {
    dt.TypeId.BOOL: "boolean", dt.TypeId.TINYINT: "smallint",
    dt.TypeId.SMALLINT: "smallint", dt.TypeId.INT: "integer",
    dt.TypeId.BIGINT: "bigint", dt.TypeId.FLOAT: "real",
    dt.TypeId.DOUBLE: "double precision", dt.TypeId.VARCHAR: "text",
    dt.TypeId.TIMESTAMP: "timestamp without time zone",
    dt.TypeId.DATE: "date", dt.TypeId.INTERVAL: "interval",
    dt.TypeId.NULL: "unknown", dt.TypeId.OID: "oid",
    dt.TypeId.REGCLASS: "regclass", dt.TypeId.REGTYPE: "regtype",
    dt.TypeId.REGPROC: "regproc", dt.TypeId.REGNAMESPACE: "regnamespace",
}


@register("to_date")
def _to_date(ts):
    if len(ts) != 2:
        return None
    def impl(cols, n):
        from datetime import date as _date
        s = string_values(cols[0])
        fmts = string_values(cols[1])
        epoch = _date(1970, 1, 1)
        out = np.zeros(n, dtype=np.int32)
        import datetime as _dt_mod
        # longest patterns first: "Month" must map before "Mon", "YYYY"
        # before "YY"
        py_map = [("Month", "%B"), ("HH24", "%H"), ("YYYY", "%Y"),
                  ("Mon", "%b"), ("MM", "%m"), ("DD", "%d"),
                  ("MI", "%M"), ("SS", "%S"), ("YY", "%y")]
        for i in range(n):
            f = fmts[i]
            for pat, py in py_map:
                f = f.replace(pat, py)
            try:
                d = _dt_mod.datetime.strptime(s[i], f).date()
            except ValueError as e:
                raise errors.SqlError("22008",
                                      f"invalid value for to_date: {e}")
            out[i] = (d - epoch).days
        return _result(dt.DATE, out, cols)
    return FunctionResolution(dt.DATE, impl)


@register("make_interval")
def _make_interval(ts):
    """make_interval(years, months, weeks, days, hours, mins, secs) —
    positional prefix; calendar units must be zero (this engine's
    intervals are fixed-duration micros, binder.parse_interval)."""
    if len(ts) > 7 or not _all_numeric(ts):
        return None
    def impl(cols, n):
        vals = [c.data.astype(np.float64) for c in cols]
        while len(vals) < 7:
            vals.append(np.zeros(n))
        years, months, weeks, days, hours, mins, secs = vals
        pn = propagate_nulls(cols)
        live = np.ones(n, dtype=bool) if pn is None else pn
        if (((years != 0) | (months != 0)) & live).any():
            raise errors.unsupported(
                "calendar interval units (month/year) — use fixed units "
                "(days/hours/...)")
        us = ((weeks * 7 + days) * 86_400_000_000 +
              hours * 3_600_000_000 + mins * 60_000_000 +
              secs * 1_000_000)
        return _result(dt.INTERVAL, np.round(us).astype(np.int64), cols)
    return FunctionResolution(dt.INTERVAL, impl)


@register("isfinite")
def _isfinite(ts):
    if len(ts) != 1 or ts[0].id not in (dt.TypeId.DATE, dt.TypeId.TIMESTAMP,
                                        dt.TypeId.INTERVAL):
        return None
    def impl(cols, n):
        # epoch-int storage has no infinity encoding: always finite
        return _result(dt.BOOL, np.ones(n, dtype=bool), cols)
    return FunctionResolution(dt.BOOL, impl)


@register("pg_typeof")
def _pg_typeof(ts):
    if len(ts) != 1:
        return None
    name = _PG_TYPE_NAMES.get(ts[0].id, str(ts[0]).lower())
    def impl(cols, n):
        return make_string_column(
            np.asarray([name] * n, dtype=object).astype(str), None)
    # rendered as text (PG's regtype output is its textual type name)
    return FunctionResolution(dt.VARCHAR, impl)


@register("translate")
def _translate(ts):
    def impl(cols, n):
        s = string_values(cols[0])
        frm = string_values(cols[1])
        to = string_values(cols[2])
        out = []
        for v, f, t in zip(s, frm, to):
            # chars beyond len(to) are deleted (PG semantics)
            table = {ord(c): (t[i] if i < len(t) else None)
                     for i, c in enumerate(f)}
            out.append(v.translate(table))
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("left")
def _left(ts):
    def impl(cols, n):
        s = string_values(cols[0])
        k = cols[1].data.astype(np.int64)
        out = [v[:kk] if kk >= 0 else v[:len(v) + kk] for v, kk in zip(s, k)]
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("right")
def _right(ts):
    def impl(cols, n):
        s = string_values(cols[0])
        k = cols[1].data.astype(np.int64)
        out = [(v[-kk:] if kk > 0 else v[-(len(v) + kk):] if len(v) + kk > 0 else "")
               if kk != 0 else "" for v, kk in zip(s, k)]
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("reverse")
def _reverse(ts):
    def impl(cols, n):
        s = string_values(cols[0])
        out = [v[::-1] for v in s]
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("repeat")
def _repeat(ts):
    def impl(cols, n):
        s = string_values(cols[0])
        k = cols[1].data.astype(np.int64)
        out = [v * max(int(kk), 0) for v, kk in zip(s, k)]
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("split_part")
def _split_part(ts):
    def impl(cols, n):
        s = string_values(cols[0])
        sep = string_values(cols[1])
        k = cols[2].data.astype(np.int64)
        out = []
        for v, sp, kk in zip(s, sep, k):
            parts = v.split(sp) if sp else [v]
            idx = int(kk) - 1
            out.append(parts[idx] if 0 <= idx < len(parts) else "")
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


def _like_to_regex(pattern: str) -> str:
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "\\" and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return "^" + "".join(out) + "$"


def like_impl(cols, n, negated=False, ci=False):
    a = string_values(cols[0])
    pats = string_values(cols[1])
    flags = re.IGNORECASE | re.DOTALL if ci else re.DOTALL
    if len(set(pats.tolist())) == 1 and len(pats) > 0:
        rx = re.compile(_like_to_regex(pats[0]), flags)
        data = np.asarray([bool(rx.match(x)) for x in a])
    else:
        data = np.asarray([bool(re.compile(_like_to_regex(p), flags).match(x))
                           for x, p in zip(a, pats)])
    if negated:
        data = ~data
    return _result(dt.BOOL, data, cols)


# (the former backtracking-`re` regexp_match_op path was removed: all
# regex operators now route through the linear-time NFA above)


def _pg_regex_replacement(r: str) -> str:
    """PG replacement syntax → Python re: \\1..\\9 group refs, \\& whole
    match, literal backslash pairs."""
    out = []
    k = 0
    while k < len(r):
        c = r[k]
        if c == "\\" and k + 1 < len(r):
            nxt = r[k + 1]
            if nxt.isdigit():
                out.append("\\" + nxt)
            elif nxt == "&":
                out.append("\\g<0>")
            elif nxt == "\\":
                out.append("\\\\")
            else:
                out.append(re.escape(nxt))
            k += 2
            continue
        out.append(c.replace("\\", "\\\\"))
        k += 1
    return "".join(out)


@register("regexp_replace")
def _regexp_replace(ts):
    if len(ts) not in (3, 4):
        return None
    def impl(cols, n):
        s = string_values(cols[0])
        pat = string_values(cols[1])
        rep = string_values(cols[2])
        flags = string_values(cols[3]) if len(cols) > 3 else None
        out = []
        for i in range(n):
            fl = 0
            count = 1
            if flags is not None:
                for f in flags[i]:
                    if f == "g":
                        count = 0
                    elif f == "i":
                        fl |= re.IGNORECASE
                    elif f == "n" or f == "m":
                        fl |= re.MULTILINE
                    elif f == "s":
                        fl |= re.DOTALL
                    else:
                        raise errors.SqlError(
                            "22023",
                            f'invalid regular expression option: "{f}"')
            try:
                out.append(re.sub(pat[i], _pg_regex_replacement(rep[i]),
                                  s[i], count=count, flags=fl))
            except re.error as e:
                raise errors.SqlError("2201B",
                                      f"invalid regular expression: {e}")
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


@register("regexp_matches")
@register("regexp_match")
def _regexp_match(ts):
    """First-match capture groups (regexp_match); without groups, the
    whole match. Returns NULL on no match (array rendered PG-style)."""
    if len(ts) not in (2, 3):
        return None
    def impl(cols, n):
        s = string_values(cols[0])
        pat = string_values(cols[1])
        flags = string_values(cols[2]) if len(cols) > 2 else None
        out = []
        miss = np.zeros(n, dtype=bool)
        for i in range(n):
            fl = re.IGNORECASE if flags is not None and "i" in flags[i] \
                else 0
            m = re.search(pat[i], s[i], flags=fl)
            if m is None:
                out.append("")
                miss[i] = True
            elif m.groups():
                out.append("{" + ",".join(
                    "NULL" if g is None else g for g in m.groups()) + "}")
            else:
                out.append("{" + m.group(0) + "}")
        validity = propagate_nulls(cols)
        if miss.any():
            validity = (validity if validity is not None
                        else np.ones(n, dtype=bool)) & ~miss
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  validity)
    return FunctionResolution(dt.VARCHAR, impl)


@register("regexp_split_to_array")
def _regexp_split_to_array(ts):
    """regexp_split_to_array(text, pattern[, flags]) → text array
    (physical JSON, rendered PG-style)."""
    if len(ts) not in (2, 3):
        return None

    def impl(cols, n):
        s = string_values(cols[0])
        pat = string_values(cols[1])
        flags = string_values(cols[2]) if len(cols) > 2 else None
        out = []
        for i in range(n):
            fl = re.IGNORECASE if flags is not None and "i" in flags[i] \
                else 0
            try:
                out.append(json.dumps(re.split(pat[i], s[i], flags=fl)))
            except re.error as e:
                raise errors.SqlError(
                    "2201B", f"invalid regular expression: {e}")
        col = make_string_column(
            np.asarray(out, dtype=object).astype(str),
            propagate_nulls(cols))
        return Column(dt.array_of(dt.VARCHAR), col.data, col.validity,
                      col.dictionary)
    return FunctionResolution(dt.array_of(dt.VARCHAR), impl)


# -- conditionals ----------------------------------------------------------

@register("coalesce")
def _coalesce(ts):
    t = next((x for x in ts if x.id is not dt.TypeId.NULL), dt.NULLTYPE)
    def impl(cols, n):
        vals = [c.to_pylist() for c in cols]
        out = []
        for i in range(n):
            v = None
            for col_vals in vals:
                if col_vals[i] is not None:
                    v = col_vals[i]
                    break
            out.append(v)
        return Column.from_pylist(out, t)
    return FunctionResolution(t, impl)


@register("nullif")
def _nullif(ts):
    t = ts[0]
    def impl(cols, n):
        a, b = cols
        if a.type.is_string or b.type.is_string:
            eq = string_values(a) == string_values(b)
        else:
            eq = a.data == b.data
        both_valid = a.valid_mask() & b.valid_mask()
        make_null = both_valid & eq
        validity = a.valid_mask() & ~make_null
        return Column(t, a.data, None if validity.all() else validity,
                      a.dictionary)
    return FunctionResolution(t, impl)


def _make_extreme(is_greatest):
    def resolver(ts):
        t = ts[0]
        for x in ts[1:]:
            if x.is_numeric and t.is_numeric:
                t = dt.common_numeric(t, x)
        def impl(cols, n):
            # NULLs are ignored (PG GREATEST/LEAST semantics)
            vals = [c.to_pylist() for c in cols]
            out = []
            for i in range(n):
                cand = [v[i] for v in vals if v[i] is not None]
                out.append((max(cand) if is_greatest else min(cand)) if cand else None)
            return Column.from_pylist(out, t)
        return FunctionResolution(t, impl)
    return resolver


_REGISTRY["greatest"] = _make_extreme(True)
_REGISTRY["least"] = _make_extreme(False)


# -- date/time -------------------------------------------------------------

_EXTRACT_FIELDS = {"year", "month", "day", "hour", "minute", "second", "dow",
                   "isodow", "doy", "epoch", "quarter", "week", "century",
                   "millennium", "millisecond", "milliseconds",
                   "microsecond", "microseconds"}


@register("extract")
@register("date_part")
def _extract(ts):
    def impl(cols, n):
        field = string_values(cols[0])[0] if n else "year"
        if cols[1].type.id is dt.TypeId.INTERVAL:
            # duration fields over µs (normalized: hour < 24 etc.; our
            # intervals are fixed-duration, unlike PG's month/day split)
            us = cols[1].data.astype(np.int64)
            sign = np.sign(us)
            a = np.abs(us)
            if field == "epoch":
                data = us / 1e6
            elif field == "day":
                data = sign * (a // 86_400_000_000).astype(np.float64)
            elif field == "hour":
                data = sign * ((a // 3_600_000_000) % 24).astype(np.float64)
            elif field == "minute":
                data = sign * ((a // 60_000_000) % 60).astype(np.float64)
            elif field == "second":
                data = sign * ((a % 60_000_000) / 1e6)
            elif field in ("millisecond", "milliseconds"):
                data = sign * ((a % 60_000_000) / 1e3)
            elif field in ("microsecond", "microseconds"):
                data = sign * (a % 60_000_000).astype(np.float64)
            else:
                raise errors.unsupported(
                    f"extract field {field!r} from interval")
            return _result(dt.DOUBLE, data, cols[1:])
        micros = cols[1].data.astype("datetime64[us]") \
            if cols[1].type.id is dt.TypeId.TIMESTAMP \
            else cols[1].data.astype("datetime64[D]").astype("datetime64[us]")
        dts = micros
        Y = dts.astype("datetime64[Y]").astype(np.int64) + 1970
        if field == "year":
            data = Y.astype(np.float64)
        elif field == "month":
            data = (dts.astype("datetime64[M]").astype(np.int64) % 12 + 1).astype(np.float64)
        elif field == "day":
            data = ((dts.astype("datetime64[D]") -
                     dts.astype("datetime64[M]").astype("datetime64[D]"))
                    .astype(np.int64) + 1).astype(np.float64)
        elif field == "hour":
            data = ((dts.astype(np.int64) // 3_600_000_000) % 24).astype(np.float64)
        elif field == "minute":
            data = ((dts.astype(np.int64) // 60_000_000) % 60).astype(np.float64)
        elif field == "second":
            data = ((dts.astype(np.int64) % 60_000_000) / 1e6)
        elif field == "epoch":
            data = dts.astype(np.int64) / 1e6
        elif field == "dow":
            data = ((dts.astype("datetime64[D]").astype(np.int64) + 4) % 7).astype(np.float64)
        elif field == "isodow":
            # PG: Monday=1 … Sunday=7
            data = ((dts.astype("datetime64[D]").astype(np.int64) + 3) % 7
                    + 1).astype(np.float64)
        elif field == "doy":
            data = ((dts.astype("datetime64[D]") -
                     dts.astype("datetime64[Y]").astype("datetime64[D]"))
                    .astype(np.int64) + 1).astype(np.float64)
        elif field == "week":
            # ISO 8601 week number: the week containing the year's first
            # Thursday is week 1
            days = dts.astype("datetime64[D]").astype(np.int64)
            # Thursday of each date's ISO week (Mon-based week start)
            thu = days - (days + 3) % 7 + 3
            thu_d = thu.astype("datetime64[D]")
            year_start = thu_d.astype("datetime64[Y]").astype("datetime64[D]")
            data = ((thu - year_start.astype(np.int64)) // 7
                    + 1).astype(np.float64)
        elif field == "quarter":
            m = dts.astype("datetime64[M]").astype(np.int64) % 12
            data = (m // 3 + 1).astype(np.float64)
        elif field == "century":
            data = np.ceil(Y / 100.0)
        elif field == "millennium":
            data = np.ceil(Y / 1000.0)
        elif field in ("millisecond", "milliseconds"):
            data = (dts.astype(np.int64) % 60_000_000) / 1e3
        elif field in ("microsecond", "microseconds"):
            data = (dts.astype(np.int64) % 60_000_000).astype(np.float64)
        else:
            raise errors.unsupported(f"extract field {field!r}")
        return _result(dt.DOUBLE, data, cols[1:])
    return FunctionResolution(dt.DOUBLE, impl)


_MONTHS = ["January", "February", "March", "April", "May", "June", "July",
           "August", "September", "October", "November", "December"]
_DAYS = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
         "Saturday", "Sunday"]

#: to_char template patterns, longest-first (reference: PG formatting.c)
_TO_CHAR_PATS = [
    ("HH24", lambda d: f"{d.hour:02d}"),
    ("HH12", lambda d: f"{(d.hour % 12) or 12:02d}"),
    ("YYYY", lambda d: f"{d.year:04d}"),
    ("MONTH", lambda d: _MONTHS[d.month - 1].upper().ljust(9)),
    ("Month", lambda d: _MONTHS[d.month - 1].ljust(9)),
    ("month", lambda d: _MONTHS[d.month - 1].lower().ljust(9)),
    ("DDD", lambda d: f"{d.timetuple().tm_yday:03d}"),
    ("DAY", lambda d: _DAYS[d.weekday()].upper().ljust(9)),
    ("Day", lambda d: _DAYS[d.weekday()].ljust(9)),
    ("day", lambda d: _DAYS[d.weekday()].lower().ljust(9)),
    ("MON", lambda d: _MONTHS[d.month - 1][:3].upper()),
    ("Mon", lambda d: _MONTHS[d.month - 1][:3]),
    ("mon", lambda d: _MONTHS[d.month - 1][:3].lower()),
    ("DY", lambda d: _DAYS[d.weekday()][:3].upper()),
    ("Dy", lambda d: _DAYS[d.weekday()][:3]),
    ("dy", lambda d: _DAYS[d.weekday()][:3].lower()),
    ("MS", lambda d: f"{d.microsecond // 1000:03d}"),
    ("US", lambda d: f"{d.microsecond:06d}"),
    ("HH", lambda d: f"{(d.hour % 12) or 12:02d}"),
    ("MM", lambda d: f"{d.month:02d}"),
    ("DD", lambda d: f"{d.day:02d}"),
    ("MI", lambda d: f"{d.minute:02d}"),
    ("SS", lambda d: f"{d.second:02d}"),
    ("YY", lambda d: f"{d.year % 100:02d}"),
    ("AM", lambda d: "AM" if d.hour < 12 else "PM"),
    ("PM", lambda d: "AM" if d.hour < 12 else "PM"),
    ("am", lambda d: "am" if d.hour < 12 else "pm"),
    ("pm", lambda d: "am" if d.hour < 12 else "pm"),
    ("Q", lambda d: str((d.month - 1) // 3 + 1)),
]


def _to_char_one(dtv, fmt: str) -> str:
    out = []
    i = 0
    while i < len(fmt):
        if fmt[i] == '"':                 # quoted literal section
            j = fmt.find('"', i + 1)
            if j < 0:
                out.append(fmt[i + 1:])
                break
            out.append(fmt[i + 1:j])
            i = j + 1
            continue
        for pat, fn in _TO_CHAR_PATS:
            if fmt.startswith(pat, i):
                out.append(fn(dtv))
                i += len(pat)
                break
        else:
            out.append(fmt[i])
            i += 1
    return "".join(out)


@register("to_char")
def _to_char(ts):
    if len(ts) != 2:
        return None
    src = ts[0]

    def impl(cols, n):
        import datetime as _dtmod
        fmts = string_values(cols[1])
        valid = propagate_nulls(cols)
        out = []
        for i in range(n):
            if valid is not None and not valid[i]:
                out.append("")
                continue
            v = cols[0].data[i]
            if src.id is dt.TypeId.DATE:
                d = _dtmod.datetime(1970, 1, 1) + \
                    _dtmod.timedelta(days=int(v))
            elif src.id is dt.TypeId.TIMESTAMP:
                d = _dtmod.datetime(1970, 1, 1) + \
                    _dtmod.timedelta(microseconds=int(v))
            else:
                # numeric to_char: render the value through the literal
                # text of the format's 9/0 slots is overkill — print it
                out.append(str(cols[0].decode(i)))
                continue
            out.append(_to_char_one(d, fmts[i]))
        return make_string_column(np.asarray(out, dtype=object).astype(str),
                                  valid)
    return FunctionResolution(dt.VARCHAR, impl)


@register("to_timestamp")
def _to_timestamp(ts):
    def impl(cols, n):
        secs = cols[0].data.astype(np.float64)
        return _result(dt.TIMESTAMP, (secs * 1e6).astype(np.int64), cols)
    return FunctionResolution(dt.TIMESTAMP, impl)


# -- system ----------------------------------------------------------------

@register("version")
def _version(ts):
    def impl(cols, n):
        from .. import __version__
        v = f"PostgreSQL 16.0 (serenedb_tpu {__version__})"
        return Column.from_pylist([v] * max(n, 1), dt.VARCHAR)
    return FunctionResolution(dt.VARCHAR, impl)


@register("current_schema")
def _current_schema(ts):
    def impl(cols, n):
        return Column.from_pylist(["main"] * max(n, 1), dt.VARCHAR)
    return FunctionResolution(dt.VARCHAR, impl)


# -- vector functions (CPU oracle; reference: functions/vector.cpp) --------

def _make_vec_fn(metric):
    def resolver(ts):
        def impl(cols, n):
            # strict NULL propagation: never parse rows where either side is
            # NULL ('' placeholders would raise)
            from ..search.ivf import parse_vector
            a = string_values(cols[0])
            b = string_values(cols[1])
            valid = propagate_nulls(cols)
            out = np.zeros(n, dtype=np.float64)
            for i in range(n):
                if valid is not None and not valid[i]:
                    continue
                x = parse_vector(a[i])
                y = parse_vector(b[i])
                if len(x) != len(y):
                    raise errors.SqlError(
                        errors.DATATYPE_MISMATCH,
                        f"vector dims differ: {len(x)} vs {len(y)}")
                if metric == "l2":
                    d = x.astype(np.float64) - y.astype(np.float64)
                    out[i] = float(np.dot(d, d))
                elif metric == "ip":
                    out[i] = -float(np.dot(x.astype(np.float64),
                                           y.astype(np.float64)))
                else:
                    nx = np.linalg.norm(x)
                    ny = np.linalg.norm(y)
                    out[i] = 1.0 - float(np.dot(x, y)) / max(nx * ny, 1e-9)
            return _result(dt.DOUBLE, out, cols)
        return FunctionResolution(dt.DOUBLE, impl)
    return resolver


_REGISTRY["vec_l2"] = _make_vec_fn("l2")
_REGISTRY["vec_ip"] = _make_vec_fn("ip")
_REGISTRY["vec_cos"] = _make_vec_fn("cos")


@register("vec_maxsim")
def _vec_maxsim(ts):
    """ColBERT-style late interaction between two token matrices
    ('[[...], ...]'): Σ_s max_t <q_s, d_t>, float64 (the exact host
    oracle the device MaxSim program is checked against). A doc or
    query without tokens scores NULL."""
    def impl(cols, n):
        from ..search.ivf import parse_multi_vector
        a = string_values(cols[0])
        b = string_values(cols[1])
        valid = propagate_nulls(cols)
        out = np.zeros(n, dtype=np.float64)
        nulls = np.zeros(n, dtype=bool)
        for i in range(n):
            if valid is not None and not valid[i]:
                continue
            x = parse_multi_vector(a[i])
            y = parse_multi_vector(b[i])
            if x is None or y is None:
                nulls[i] = True
                continue
            if x.shape[1] != y.shape[1]:
                raise errors.SqlError(
                    errors.DATATYPE_MISMATCH,
                    f"vector dims differ: {x.shape[1]} vs {y.shape[1]}")
            sim = y.astype(np.float64) @ x.astype(np.float64).T
            out[i] = float(sim.max(axis=1).sum())
        return _result(dt.DOUBLE, out, cols, extra_invalid=nulls)
    return FunctionResolution(dt.DOUBLE, impl)


@register("vec_dims")
def _vec_dims(ts):
    def impl(cols, n):
        from ..search.ivf import parse_vector
        vals = string_values(cols[0])
        valid = propagate_nulls(cols)
        out = np.zeros(n, dtype=np.int64)
        for i in range(n):
            if valid is None or valid[i]:
                out[i] = len(parse_vector(vals[i]))
        return _result(dt.BIGINT, out, cols)
    return FunctionResolution(dt.BIGINT, impl)


# -- sequence functions (context-dependent; reference: functions/sequence.cpp)

def _current_conn():
    from ..engine import CURRENT_CONNECTION
    conn = CURRENT_CONNECTION.get()
    if conn is None:
        raise errors.SqlError("55000",
                              "sequence functions need a connection context")
    return conn


@register("nextval")
def _nextval(ts):
    def impl(cols, n):
        conn = _current_conn()
        names = string_values(cols[0])
        valid = propagate_nulls(cols)
        cur = dict(getattr(conn, "seq_currval", {}))
        out = np.zeros(n, dtype=np.int64)
        for i, nm in enumerate(names):
            if valid is not None and not valid[i]:
                continue  # NULL name → NULL result, no side effect
            out[i] = conn.db.sequence_nextval(nm)
            cur[nm] = int(out[i])
        conn.seq_currval = cur
        return _result(dt.BIGINT, out, cols)
    return FunctionResolution(dt.BIGINT, impl)


@register("currval")
def _currval(ts):
    def impl(cols, n):
        conn = _current_conn()
        names = string_values(cols[0])
        cur = getattr(conn, "seq_currval", {})
        out = np.zeros(n, dtype=np.int64)
        for i, nm in enumerate(names):
            if nm not in cur:
                raise errors.SqlError(
                    "55000", f'currval of sequence "{nm}" is not yet '
                             "defined in this session")
            out[i] = cur[nm]
        return _result(dt.BIGINT, out, cols)
    return FunctionResolution(dt.BIGINT, impl)


@register("setval")
def _setval(ts):
    def impl(cols, n):
        conn = _current_conn()
        names = string_values(cols[0])
        vals = cols[1].data.astype(np.int64)
        valid = propagate_nulls(cols)
        out = np.zeros(n, dtype=np.int64)
        for i, (nm, v) in enumerate(zip(names, vals)):
            if valid is not None and not valid[i]:
                continue
            out[i] = conn.db.sequence_setval(nm, int(v))
        return _result(dt.BIGINT, out, cols)
    return FunctionResolution(dt.BIGINT, impl)


# -- more datetime ---------------------------------------------------------

_TRUNC_UNITS = ("year", "quarter", "month", "week", "day", "hour", "minute",
                "second")


@register("date_trunc")
def _date_trunc(ts):
    if len(ts) == 2 and ts[1].id not in (dt.TypeId.TIMESTAMP, dt.TypeId.DATE,
                                         dt.TypeId.NULL):
        raise errors.SqlError(errors.DATATYPE_MISMATCH,
                              f"date_trunc does not accept {ts[1]}")

    def impl(cols, n):
        valid = propagate_nulls(cols)
        units = np.char.lower(string_values(cols[0])) if n else \
            np.empty(0, dtype=str)
        distinct_units = {units[i] for i in range(n)
                          if valid is None or valid[i]}
        bad = distinct_units - set(_TRUNC_UNITS)
        if bad:
            raise errors.unsupported(f"date_trunc unit {bad.pop()!r}")
        if len(distinct_units) > 1:
            # per-row units: compute per distinct unit and stitch
            out = np.zeros(n, dtype=np.int64)
            for u in distinct_units:
                mask = (units == u) & (valid if valid is not None
                                       else np.ones(n, dtype=bool))
                sub = impl([Column.const(u, int(mask.sum()), dt.VARCHAR),
                            cols[1].filter(mask)], int(mask.sum()))
                out[np.flatnonzero(mask)] = sub.data
            return Column(dt.TIMESTAMP, out,
                          valid if valid is not None and not valid.all()
                          else valid)
        unit = distinct_units.pop() if distinct_units else "day"
        src = cols[1]
        if src.type.id is dt.TypeId.DATE:
            us = src.data.astype("datetime64[D]").astype("datetime64[us]")
        else:
            us = src.data.astype("datetime64[us]")
        if unit == "year":
            out = us.astype("datetime64[Y]").astype("datetime64[us]")
        elif unit == "quarter":
            months = us.astype("datetime64[M]").astype(np.int64)
            out = ((months // 3) * 3).astype("datetime64[M]") \
                .astype("datetime64[us]")
        elif unit == "month":
            out = us.astype("datetime64[M]").astype("datetime64[us]")
        elif unit == "week":
            days = us.astype("datetime64[D]").astype(np.int64)
            # 1970-01-01 was a Thursday; ISO weeks start Monday (+3 offset)
            out = (((days + 3) // 7) * 7 - 3).astype("datetime64[D]") \
                .astype("datetime64[us]")
        elif unit == "day":
            out = us.astype("datetime64[D]").astype("datetime64[us]")
        elif unit == "hour":
            out = us.astype("datetime64[h]").astype("datetime64[us]")
        elif unit == "minute":
            out = us.astype("datetime64[m]").astype("datetime64[us]")
        else:
            out = us.astype("datetime64[s]").astype("datetime64[us]")
        return _result(dt.TIMESTAMP, out.astype(np.int64), cols)
    return FunctionResolution(dt.TIMESTAMP, impl)


def _now_resolver(ts):
    def impl(cols, n):
        import time as _time
        conn = _current_conn()
        v = getattr(conn, "stmt_now_us", None) if conn is not None \
            else None
        if v is None:    # outside a statement (tests, internal evals)
            v = int(_time.time() * 1e6)
        return Column(dt.TIMESTAMP, np.full(max(n, 1), v, dtype=np.int64))
    return FunctionResolution(dt.TIMESTAMP, impl)


def _clock_timestamp_resolver(ts):
    def impl(cols, n):
        import time as _time
        v = int(_time.time() * 1e6)
        return Column(dt.TIMESTAMP, np.full(max(n, 1), v, dtype=np.int64))
    return FunctionResolution(dt.TIMESTAMP, impl)


_REGISTRY["clock_timestamp"] = _clock_timestamp_resolver


_REGISTRY["now"] = _now_resolver
_REGISTRY["current_timestamp"] = _now_resolver
_REGISTRY["transaction_timestamp"] = _now_resolver


@register("current_date")
def _current_date(ts):
    def impl(cols, n):
        import time as _time
        v = int(_time.time() // 86400)
        return Column(dt.DATE, np.full(max(n, 1), v, dtype=np.int32))
    return FunctionResolution(dt.DATE, impl)


@register("age")
def _age(ts):
    """age(ts, ts) → INTERVAL (micros; PG renders day/time parts).
    age(ts) → midnight of current_date minus ts (PG 1-arg form)."""
    if len(ts) == 1:
        if ts[0].id not in (dt.TypeId.TIMESTAMP, dt.TypeId.DATE):
            return None
        arg_is_date = ts[0].id is dt.TypeId.DATE

        def impl1(cols, n, _date=arg_is_date):
            # statement-stable reference (like now()): every batch/morsel
            # of one statement sees the same "today's midnight"
            conn = _current_conn()
            now_us = getattr(conn, "stmt_now_us", None) \
                if conn is not None else None
            if now_us is None:
                import time as _time
                now_us = int(_time.time() * 1e6)
            midnight = (now_us // 86_400_000_000) * 86_400_000_000
            a = cols[0].data.astype(np.int64)
            if _date:          # DATE stores days-since-epoch, not micros
                a = a * 86_400_000_000
            return _result(dt.INTERVAL, midnight - a, cols)
        return FunctionResolution(dt.INTERVAL, impl1)
    if len(ts) != 2:
        return None   # clean 42883 undefined-function, not an IndexError

    def impl(cols, n):
        a = cols[0].data.astype(np.int64)
        b = cols[1].data.astype(np.int64)
        return _result(dt.INTERVAL, a - b, cols)
    return FunctionResolution(dt.INTERVAL, impl)


@register("atan2")
def _atan2(ts):
    if len(ts) != 2:
        return None

    def impl(cols, n):
        y = cols[0].data.astype(np.float64)
        x = cols[1].data.astype(np.float64)
        return _result(dt.DOUBLE, np.arctan2(y, x), cols)
    return FunctionResolution(dt.DOUBLE, impl)


@register("random")
def _random(ts):
    if ts:
        return None

    def impl(cols, n):
        rng = np.random.default_rng()
        return Column(dt.DOUBLE, rng.random(max(n, 1)))
    return FunctionResolution(dt.DOUBLE, impl)


@register("gen_random_uuid")
def _gen_random_uuid(ts):
    if ts:
        return None

    def impl(cols, n):
        import uuid as _uuid
        out = [str(_uuid.uuid4()) for _ in range(max(n, 1))]
        return make_string_column(np.asarray(out, dtype=object), None)
    return FunctionResolution(dt.VARCHAR, impl)


@register("array_remove")
def _array_remove(ts):
    if len(ts) != 2:
        return None

    def impl(cols, n):
        vals = cols[0].to_pylist()
        rem = cols[1].to_pylist()
        out = []
        for i in range(n):
            v = vals[i]
            if v is None:
                out.append(None)
                continue
            try:
                arr = json.loads(str(v))
            except json.JSONDecodeError:
                arr = None
            if not isinstance(arr, list):
                out.append(v)
                continue
            out.append(json.dumps([x for x in arr if x != rem[i]]))
        col = make_string_column(
            np.asarray(["" if v is None else v for v in out],
                       dtype=object),
            np.asarray([v is not None for v in out]))
        t = ts[0] if ts[0].id is dt.TypeId.ARRAY else dt.array_of(None)
        return Column(t, col.data, col.validity, col.dictionary)
    return FunctionResolution(
        ts[0] if ts[0].id is dt.TypeId.ARRAY else dt.array_of(None), impl)


@register("array_upper")
def _array_upper(ts):
    if len(ts) != 2:
        return None

    def impl(cols, n):
        vals = cols[0].to_pylist()
        dims = cols[1].to_pylist()
        out = np.zeros(n, dtype=np.int64)
        invalid = np.zeros(n, dtype=bool)
        for i in range(n):
            try:
                arr = json.loads(str(vals[i])) if vals[i] is not None \
                    else None
            except json.JSONDecodeError:
                arr = None
            # arrays here are 1-D: any dim other than 1 is NULL (PG)
            if dims[i] == 1 and isinstance(arr, list) and arr:
                out[i] = len(arr)
            else:
                invalid[i] = True
        return _result(dt.INT, out, cols, extra_invalid=invalid)
    return FunctionResolution(dt.INT, impl)


@register("make_date")
def _make_date(ts):
    def impl(cols, n):
        y = cols[0].data.astype(np.int64)
        m = cols[1].data.astype(np.int64)
        d = cols[2].data.astype(np.int64)
        valid = propagate_nulls(cols)
        out = np.zeros(n, dtype=np.int32)
        for i in range(n):
            if valid is not None and not valid[i]:
                continue  # NULL row: sentinel components never parsed
            try:
                out[i] = np.datetime64(
                    f"{y[i]:04d}-{m[i]:02d}-{d[i]:02d}", "D").astype(np.int32)
            except ValueError:
                raise errors.SqlError(
                    "22008", f"date field value out of range: "
                             f"{y[i]}-{m[i]}-{d[i]}")
        return _result(dt.DATE, out, cols)
    return FunctionResolution(dt.DATE, impl)


@register("make_timestamp")
def _make_timestamp(ts):
    if len(ts) != 6:
        return None

    def impl(cols, n):
        y, mo, d, h, mi = (cols[k].data.astype(np.int64) for k in range(5))
        sec = cols[5].data.astype(np.float64)
        valid = propagate_nulls(cols)
        out = np.zeros(n, dtype=np.int64)
        for i in range(n):
            if valid is not None and not valid[i]:
                continue
            try:
                day_us = np.datetime64(
                    f"{y[i]:04d}-{mo[i]:02d}-{d[i]:02d}", "D") \
                    .astype("datetime64[us]").astype(np.int64)
            except ValueError:
                raise errors.SqlError(
                    "22008", f"date field value out of range: "
                             f"{y[i]}-{mo[i]}-{d[i]}")
            if not (0 <= h[i] < 24 and 0 <= mi[i] < 60
                    and 0 <= sec[i] < 60):
                raise errors.SqlError(
                    "22008", "time field value out of range")
            out[i] = day_us + (h[i] * 3600 + mi[i] * 60) * 1_000_000 \
                + int(round(sec[i] * 1e6))
        return _result(dt.TIMESTAMP, out, cols)
    return FunctionResolution(dt.TIMESTAMP, impl)


# -- json (documents stored as TEXT; reference: functions/json.cpp) --------

def _json_extract_impl(ts, as_text: bool):
    def impl(cols, n):
        import json as _json
        docs = string_values(cols[0])
        paths = string_values(cols[1])
        valid = propagate_nulls(cols)
        out = []
        bad = np.zeros(n, dtype=bool)
        for i in range(n):
            if valid is not None and not valid[i]:
                out.append("")
                continue
            try:
                obj = _json.loads(docs[i])
            except _json.JSONDecodeError:
                out.append("")
                bad[i] = True
                continue
            path = paths[i].lstrip("$").lstrip(".")
            cur = obj
            ok = True
            for part in [p for p in re.split(r"[.\[\]]+", path) if p]:
                if isinstance(cur, dict) and part in cur:
                    cur = cur[part]
                elif isinstance(cur, list) and part.isdigit() and \
                        int(part) < len(cur):
                    cur = cur[int(part)]
                else:
                    ok = False
                    break
            if not ok or cur is None:
                out.append("")
                bad[i] = True
            elif isinstance(cur, str) and as_text:
                out.append(cur)         # ..._string: bare text (PG ->>)
            else:
                out.append(_json.dumps(cur))  # json_extract: valid JSON
        col = make_string_column(np.asarray(out, dtype=object).astype(str),
                                 valid)
        if bad.any():
            v = col.valid_mask() & ~bad
            col = Column(dt.VARCHAR, col.data,
                         None if v.all() else v, col.dictionary)
        return col
    return FunctionResolution(dt.VARCHAR, impl)


_REGISTRY["json_extract"] = lambda ts: _json_extract_impl(ts, as_text=False)
_REGISTRY["json_extract_string"] = \
    lambda ts: _json_extract_impl(ts, as_text=True)


# -- PG json operators (-> ->> #> #>> @> <@ ? ?| ?&) -----------------------
# Desugared by the parser (sql/parser.py _JSON_OPS) to these functions
# (reference: the DuckDB fork's json operator → json_extract lowering).

def _json_docs(col, n):
    """Per-row parsed JSON values (None for SQL NULL rows)."""
    texts = string_values(col)
    valid = col.valid_mask() if col.validity is not None else None
    out = []
    for i in range(n):
        if valid is not None and not valid[i]:
            out.append(None)
            continue
        try:
            out.append(json.loads(texts[i]))
        except json.JSONDecodeError:
            raise errors.SqlError(
                errors.INVALID_TEXT_REPRESENTATION,
                f"invalid input syntax for type json: {texts[i][:40]!r}")
    return out


def _json_render(v, as_text: bool):
    if v is None:
        return None
    if as_text and isinstance(v, str):
        return v
    if as_text and isinstance(v, bool):
        return "true" if v else "false"
    return json.dumps(v)


def _json_getelem_impl(ts, as_text: bool):
    if len(ts) != 2:
        return None
    key_is_int = ts[1].is_integer

    def impl(cols, n):
        docs = _json_docs(cols[0], n)
        keys = cols[1].to_pylist()
        out, missing = [], np.zeros(n, dtype=bool)
        for i in range(n):
            doc, cur = docs[i], None
            k = _json_scalar(keys, i)
            if doc is not None and k is not None:
                if key_is_int and isinstance(doc, list):
                    k = int(k)
                    if -len(doc) <= k < len(doc):
                        cur = doc[k]
                elif not key_is_int and isinstance(doc, dict):
                    cur = doc.get(str(k))
            r = _json_render(cur, as_text)
            missing[i] = r is None
            out.append(r or "")
        return _result_text(out, missing, cols)
    return FunctionResolution(dt.VARCHAR, impl)


def _result_text(out, missing, cols):
    col = make_string_column(np.asarray(out, dtype=object).astype(str),
                             propagate_nulls(cols))
    if missing.any():
        v = col.valid_mask() & ~missing
        col = Column(dt.VARCHAR, col.data,
                     None if v.all() else v, col.dictionary)
    return col


def _pg_path_elems(p):
    """'{a,1,b}' (PG text[] literal) or '["a","b"]' (this engine's array
    encoding) → ['a','1','b']."""
    p = p.strip()
    if p.startswith("["):
        try:
            return [str(e) for e in json.loads(p)]
        except json.JSONDecodeError:
            pass
    if p.startswith("{") and p.endswith("}"):
        p = p[1:-1]
    return [e.strip().strip('"') for e in p.split(",") if e.strip() != ""]


def _json_getpath_impl(ts, as_text: bool):
    if len(ts) != 2:
        return None

    def impl(cols, n):
        docs = _json_docs(cols[0], n)
        paths = string_values(cols[1])
        out, missing = [], np.zeros(n, dtype=bool)
        for i in range(n):
            cur = docs[i]
            for part in _pg_path_elems(paths[i]) if cur is not None else []:
                if isinstance(cur, dict) and part in cur:
                    cur = cur[part]
                elif isinstance(cur, list) and \
                        part.lstrip("-").isdigit() and \
                        -len(cur) <= int(part) < len(cur):
                    cur = cur[int(part)]
                else:
                    cur = None
                    break
            r = _json_render(cur, as_text)
            missing[i] = r is None
            out.append(r or "")
        return _result_text(out, missing, cols)
    return FunctionResolution(dt.VARCHAR, impl)


_REGISTRY["json_getelem"] = lambda ts: _json_getelem_impl(ts, as_text=False)
_REGISTRY["json_getelem_text"] = \
    lambda ts: _json_getelem_impl(ts, as_text=True)
_REGISTRY["json_getpath"] = lambda ts: _json_getpath_impl(ts, as_text=False)
_REGISTRY["json_getpath_text"] = \
    lambda ts: _json_getpath_impl(ts, as_text=True)


def _jsonb_contains(a, b, top: bool = True) -> bool:
    """PG jsonb containment: objects pairwise-recursive; arrays ⊇ every
    RHS element; a TOP-LEVEL array contains an RHS scalar (the one special
    case — nested values must match in kind); scalars by equality."""
    if isinstance(a, dict) and isinstance(b, dict):
        return all(k in a and _jsonb_contains(a[k], v, top=False)
                   for k, v in b.items())
    if isinstance(a, list) and isinstance(b, list):
        return all(any(_jsonb_contains(x, y, top=False) for x in a)
                   for y in b)
    if isinstance(a, list) and top:
        return any(_jsonb_contains(x, b, top=False) for x in a)
    if isinstance(a, (dict, list)) or isinstance(b, (dict, list)):
        return False
    return type(a) is type(b) and a == b or \
        (isinstance(a, (int, float)) and not isinstance(a, bool)
         and isinstance(b, (int, float)) and not isinstance(b, bool)
         and a == b)


def _containment_impl(ts, flipped: bool):
    if len(ts) != 2 or not (_stringish(ts[0]) and _stringish(ts[1])):
        return None

    def impl(cols, n):
        a = _json_docs(cols[0], n)
        b = _json_docs(cols[1], n)
        if flipped:
            a, b = b, a
        data = np.asarray([x is not None and y is not None
                           and _jsonb_contains(x, y)
                           for x, y in zip(a, b)])
        return _result(dt.BOOL, data, cols)
    return FunctionResolution(dt.BOOL, impl)


_REGISTRY["contains_op"] = lambda ts: _containment_impl(ts, flipped=False)
_REGISTRY["contained_op"] = lambda ts: _containment_impl(ts, flipped=True)


@register("json_exists_op")
def _json_exists_op(ts):
    if len(ts) != 2:
        return None

    def impl(cols, n):
        docs = _json_docs(cols[0], n)
        keys = string_values(cols[1])
        data = np.asarray([
            (isinstance(d, dict) and keys[i] in d)
            or (isinstance(d, list) and keys[i] in d)
            for i, d in enumerate(docs)])
        return _result(dt.BOOL, data, cols)
    return FunctionResolution(dt.BOOL, impl)


def _json_exists_multi(ts, want_all: bool):
    if len(ts) != 2:
        return None

    def impl(cols, n):
        docs = _json_docs(cols[0], n)
        key_lists = string_values(cols[1])
        out = np.zeros(n, dtype=bool)
        for i, d in enumerate(docs):
            ks = _pg_path_elems(key_lists[i])
            def has(k):
                return (isinstance(d, dict) and k in d) or \
                    (isinstance(d, list) and k in d)
            out[i] = all(map(has, ks)) if want_all else any(map(has, ks))
        return _result(dt.BOOL, out, cols)
    return FunctionResolution(dt.BOOL, impl)


_REGISTRY["json_exists_any"] = \
    lambda ts: _json_exists_multi(ts, want_all=False)
_REGISTRY["json_exists_all"] = \
    lambda ts: _json_exists_multi(ts, want_all=True)


@register("json_valid")
def _json_valid(ts):
    def impl(cols, n):
        import json as _json
        docs = string_values(cols[0])
        out = np.zeros(n, dtype=bool)
        for i in range(n):
            try:
                _json.loads(docs[i])
                out[i] = True
            except _json.JSONDecodeError:
                pass
        return _result(dt.BOOL, out, cols)
    return FunctionResolution(dt.BOOL, impl)


def _make_regex_match(ci: bool, negated: bool):
    """PG ~ / ~* / !~ / !~* — unanchored regex search over strings,
    compiled on the linear-time NFA (search/regexp.py): user patterns
    never hit a backtracking engine."""
    def resolver(ts):
        if len(ts) != 2 or not all(
                t.is_string or t.id is dt.TypeId.NULL for t in ts):
            return None

        def impl(cols, n):
            from ..exec.plan import check_cancel
            from ..search.regexp import RegexpError, compile_regexp
            texts = string_values(cols[0])
            pats = string_values(cols[1])
            valid = propagate_nulls(cols)
            comp_cache: dict = {}
            out = np.zeros(n, dtype=bool)
            for i in range(n):
                if (i & 0x3FF) == 0:
                    # regex over a wide batch is the slowest row loop in
                    # the engine — finer cancel granularity than the
                    # batch boundary (~1k rows ≈ ms)
                    check_cancel()
                if valid is not None and not valid[i]:
                    continue
                pat = pats[i]
                r = comp_cache.get(pat)
                if r is None:
                    try:
                        # unanchored search; ^/$ are real zero-width
                        # assertions in the NFA, composing with the
                        # wrapper per PG semantics (per-branch anchors)
                        r = compile_regexp(f"(.|\n)*({pat})(.|\n)*",
                                           case_fold=ci)
                    except RegexpError as e:
                        raise errors.SqlError(
                            errors.INVALID_REGULAR_EXPRESSION,
                            f"invalid regular expression: {e}")
                    comp_cache[pat] = r
                hay = texts[i].lower() if ci else texts[i]
                out[i] = r.fullmatch(hay)
            if negated:
                out = ~out
            return _result(dt.BOOL, out, cols)
        return FunctionResolution(dt.BOOL, impl)
    return resolver


_REGISTRY["op~"] = _make_regex_match(False, False)
_REGISTRY["op~*"] = _make_regex_match(True, False)
_REGISTRY["op!~"] = _make_regex_match(False, True)
_REGISTRY["op!~*"] = _make_regex_match(True, True)


# -- geo functions ---------------------------------------------------------
# Reference analog: libs/geo (S2-backed WKB/GeoJSON parsing + spherical
# geometry; SURVEY.md §2 "Geo"). TPU re-design: points are WKT/GeoJSON
# text; distance math is vectorized spherical trig over whole columns
# (VPU-friendly batch math, no per-row geometry objects).

_EARTH_RADIUS_M = 6371008.8          # mean radius, as in _sphere functions


def _stringish(t) -> bool:
    return t.is_string or t.id is dt.TypeId.NULL


def _parse_point(s):
    """Accepts 'POINT(lon lat)', '[lon, lat]', or GeoJSON Point."""
    t = s.strip()
    if t[:1] in "[{":
        v = json.loads(t)
        if isinstance(v, dict):
            if str(v.get("type", "")).lower() != "point":
                raise ValueError("not a Point")
            v = v.get("coordinates")
        if not isinstance(v, list) or len(v) != 2:
            raise ValueError("expected two coordinates")
        return float(v[0]), float(v[1])
    if t[:5].upper() == "POINT":
        inner = t[t.index("(") + 1:t.rindex(")")]
        parts = inner.replace(",", " ").split()
        if len(parts) != 2:
            raise ValueError("expected two coordinates")
        return float(parts[0]), float(parts[1])
    raise ValueError("unrecognized point syntax")


def _point_cols(cols, n):
    """(lon, lat) arrays per point-text column. Parse failures raise, so
    validity is exactly propagate_nulls(cols) — which _result applies."""
    lons, lats = [], []
    valid = propagate_nulls(cols)
    for c in cols:
        texts = string_values(c)
        lon = np.zeros(n, dtype=np.float64)
        lat = np.zeros(n, dtype=np.float64)
        for i in range(n):
            if valid is not None and not valid[i]:
                continue
            try:
                lon[i], lat[i] = _parse_point(texts[i])
            except (ValueError, IndexError, TypeError) as e:
                raise errors.SqlError(
                    errors.INVALID_TEXT_REPRESENTATION,
                    f"invalid geometry {texts[i][:40]!r}: {e}")
        lons.append(lon)
        lats.append(lat)
    return lons, lats


def _haversine_m(lon1, lat1, lon2, lat2):
    p1, p2 = np.radians(lat1), np.radians(lat2)
    dp = p2 - p1
    dl = np.radians(lon2 - lon1)
    a = np.sin(dp / 2.0) ** 2 + \
        np.cos(p1) * np.cos(p2) * np.sin(dl / 2.0) ** 2
    return 2.0 * _EARTH_RADIUS_M * np.arcsin(np.minimum(np.sqrt(a), 1.0))


@register("st_point")
def _st_point(ts):
    if len(ts) != 2 or not _all_numeric(ts):
        return None

    def impl(cols, n):
        lon = cols[0].data.astype(np.float64)
        lat = cols[1].data.astype(np.float64)
        # shortest-repr floats: st_x(st_point(x, y)) must round-trip x
        out = np.asarray([f"POINT({float(lon[i])!r} {float(lat[i])!r})"
                          for i in range(n)], dtype=object)
        return make_string_column(out.astype(str), propagate_nulls(cols))
    return FunctionResolution(dt.VARCHAR, impl)


def _st_coord(idx):
    def resolver(ts):
        if len(ts) != 1 or not _stringish(ts[0]):
            return None

        def impl(cols, n):
            (lon,), (lat,) = _point_cols(cols[:1], n)
            return _result(dt.DOUBLE, (lon, lat)[idx], cols)
        return FunctionResolution(dt.DOUBLE, impl)
    return resolver


_REGISTRY["st_x"] = _st_coord(0)
_REGISTRY["st_y"] = _st_coord(1)


@register("st_distance")
def _st_distance(ts):
    if len(ts) != 2 or not all(_stringish(t) for t in ts):
        return None

    def impl(cols, n):
        (lon1, lon2), (lat1, lat2) = _point_cols(cols[:2], n)
        data = _haversine_m(lon1, lat1, lon2, lat2)
        return _result(dt.DOUBLE, data, cols)
    return FunctionResolution(dt.DOUBLE, impl)


_REGISTRY["st_distance_sphere"] = _REGISTRY["st_distance"]


@register("st_dwithin")
def _st_dwithin(ts):
    if len(ts) != 3 or not all(_stringish(t) for t in ts[:2]) or \
            not ts[2].is_numeric:
        return None

    def impl(cols, n):
        (lon1, lon2), (lat1, lat2) = _point_cols(cols[:2], n)
        radius = cols[2].data.astype(np.float64)
        data = _haversine_m(lon1, lat1, lon2, lat2) <= radius
        return _result(dt.BOOL, data, cols)
    return FunctionResolution(dt.BOOL, impl)


# -- array functions -------------------------------------------------------
# Reference analog: server/connector/functions/array.cpp. Arrays are JSON
# text (same encoding array_agg produces), columnar-friendly: a VARCHAR
# column of '[...]' values.


def _array_rows(col, n):
    """Per-row parsed arrays (list or None); non-array JSON raises 22P02."""
    texts = string_values(col)
    valid = col.valid_mask() if col.validity is not None else None
    out = []
    for i in range(n):
        if valid is not None and not valid[i]:
            out.append(None)
            continue
        try:
            v = json.loads(texts[i])
        except json.JSONDecodeError:
            raise errors.SqlError(
                errors.INVALID_TEXT_REPRESENTATION,
                f"invalid array literal: {texts[i][:40]!r}")
        if not isinstance(v, list):
            raise errors.SqlError(
                errors.INVALID_TEXT_REPRESENTATION,
                f"expected a JSON array, got: {texts[i][:40]!r}")
        out.append(v)
    return out


def _json_scalar(vals, i):
    """vals: the column's to_pylist(), materialized ONCE by the caller."""
    v = vals[i]
    if isinstance(v, np.generic):
        v = v.item()
    return v


@register("make_array")
def _make_array(ts):
    # the user-callable spelling: every element taken verbatim
    def impl(cols, n):
        pylists = [c.to_pylist() for c in cols]
        out = []
        for i in range(n):
            out.append(json.dumps(
                [_json_scalar(vals, i) for vals in pylists]))
        return make_string_column(
            np.asarray(out, dtype=object).astype(str), None)
    return FunctionResolution(dt.VARCHAR, impl)


@register("__make_array")
def _make_array_spliced(ts):
    """Parser-internal spelling for ARRAY[...] literals: the first arg is
    a literal splice map (comma-separated indices of elements that are
    array-valued expressions) — never reachable by user SQL."""
    def impl(cols, n):
        spec = cols[0].decode(0) if n else ""
        splice = {int(x) for x in str(spec or "").split(",") if x != ""}
        pylists = [c.to_pylist() for c in cols[1:]]
        out = []
        for i in range(n):
            row = []
            for ci, vals in enumerate(pylists):
                v = _json_scalar(vals, i)
                if ci in splice and isinstance(v, str):
                    try:
                        v = json.loads(v)
                    except json.JSONDecodeError:
                        raise errors.SqlError(
                            errors.INVALID_TEXT_REPRESENTATION,
                            f"invalid array element: {v[:40]!r}")
                row.append(v)
            out.append(json.dumps(row))
        col = make_string_column(
            np.asarray(out, dtype=object).astype(str), None)
        col.type = t
        return col
    # element type: first non-NULL argument after the splice map
    elem = next((x for x in ts[1:]
                 if x.id is not dt.TypeId.NULL), dt.VARCHAR)
    t = dt.array_of(elem)
    return FunctionResolution(t, impl)


@register("__quant_cmp")
def _quant_cmp(ts):
    """op ANY/ALL(array) — parser-internal spelling. SQL three-valued
    semantics: ANY is an OR fold, ALL an AND fold, NULL elements give
    UNKNOWN (reference: PG quantified comparison; used by psql's
    `nspname = ANY(current_schemas(true))`)."""
    if len(ts) != 4:
        return None

    def cmp_one(op, a, b):
        if a is None or b is None:
            return None
        if op in ("~", "~*", "!~", "!~*"):
            flags = re.IGNORECASE if op.endswith("*") else 0
            m = re.search(str(b), str(a), flags) is not None
            return (not m) if op.startswith("!") else m
        if isinstance(a, str) != isinstance(b, str):
            # PG resolves the unknown-typed side toward the typed side:
            # numeric-vs-text coerces the text numerically, never
            # lexicographically (9 < ALL(ARRAY['10']) is true)
            s = a if isinstance(a, str) else b
            try:
                conv = float(s)
                if isinstance(a, str):
                    a = conv
                else:
                    b = conv
            except ValueError:
                if op == "=":
                    return str(a) == str(b)
                if op in ("<>", "!="):
                    return str(a) != str(b)
                raise errors.SqlError(
                    errors.INVALID_TEXT_REPRESENTATION,
                    f'invalid input syntax for type numeric: "{s}"')
        try:
            if op == "=":
                return a == b
            if op in ("<>", "!="):
                return a != b
            if op == "<":
                return a < b
            if op == "<=":
                return a <= b
            if op == ">":
                return a > b
            if op == ">=":
                return a >= b
        except TypeError:
            return str(a) == str(b) if op == "=" else None
        return None

    def impl(cols, n):
        op = cols[0].decode(0) if n else "="
        quant = cols[1].decode(0) if n else "ANY"
        left = cols[2].to_pylist()
        arrs = _array_rows(cols[3], n)
        out = np.zeros(n, dtype=bool)
        validity = np.ones(n, dtype=bool)
        for i in range(n):
            arr = arrs[i]
            if arr is None:
                validity[i] = False
                continue
            votes = [cmp_one(op, left[i], el) for el in arr]
            if quant == "ANY":
                if any(v is True for v in votes):
                    out[i] = True
                elif any(v is None for v in votes):
                    validity[i] = False
            else:  # ALL
                if any(v is False for v in votes):
                    out[i] = False
                elif any(v is None for v in votes):
                    validity[i] = False
                else:
                    out[i] = True
        return Column(dt.BOOL, out, validity if not validity.all() else None)
    return FunctionResolution(dt.BOOL, impl)


@register("array_length")
def _array_length(ts):
    if not ts or not _stringish(ts[0]):
        return None

    def impl(cols, n):
        arrs = _array_rows(cols[0], n)
        data = np.asarray([len(a) if a is not None else 0 for a in arrs],
                          dtype=np.int32)
        return _result(dt.INT, data, cols[:1])
    return FunctionResolution(dt.INT, impl)


_REGISTRY["cardinality"] = _REGISTRY["array_length"]


@register("array_get")
def _array_get(ts):
    if len(ts) != 2 or not _stringish(ts[0]) or not (
            ts[1].is_numeric or ts[1].id is dt.TypeId.NULL):
        return None

    def impl(cols, n):
        arrs = _array_rows(cols[0], n)
        idx = cols[1].data.astype(np.int64)
        out = []
        ok = np.ones(n, dtype=bool)
        for i in range(n):
            a = arrs[i]
            j = int(idx[i]) - 1           # PG arrays are 1-based
            if a is None or j < 0 or j >= len(a) or a[j] is None:
                out.append("")
                ok[i] = False
            else:
                v = a[j]
                if isinstance(v, str):
                    out.append(v)
                elif isinstance(v, (list, dict)):
                    out.append(json.dumps(v))   # nested arrays stay JSON
                else:
                    out.append(_pg_text(v))
        base = propagate_nulls(cols)
        if base is not None:
            ok &= base
        return make_string_column(
            np.asarray(out, dtype=object).astype(str),
            None if ok.all() else ok)
    return FunctionResolution(dt.VARCHAR, impl)


@register("array_append")
def _array_append(ts):
    if len(ts) != 2 or not _stringish(ts[0]):
        return None

    def impl(cols, n):
        arrs = _array_rows(cols[0], n)
        vals = cols[1].to_pylist()
        out = []
        for i in range(n):
            # PG semantics: a NULL array behaves as empty — the result is
            # never NULL (array_append(NULL, 5) = {5})
            a = list(arrs[i]) if arrs[i] is not None else []
            a.append(_json_scalar(vals, i))
            out.append(json.dumps(a))
        col = make_string_column(
            np.asarray(out, dtype=object).astype(str), None)
        col.type = t
        return col
    if ts[0].id is dt.TypeId.ARRAY and ts[0].elem is not None:
        elem = dt.SqlType(ts[0].elem)
        v = ts[1]
        # appended value must fit the element type (PG: 42883 otherwise)
        if v.id is not dt.TypeId.NULL and \
                elem.is_numeric != v.is_numeric:
            return None
        t = ts[0]
    else:
        t = ts[0] if ts[0].id is dt.TypeId.ARRAY else dt.array_of(ts[1])
    return FunctionResolution(t, impl)


@register("array_cat")
def _array_cat(ts):
    if len(ts) != 2 or not all(_stringish(t) for t in ts):
        return None

    def impl(cols, n):
        a1 = _array_rows(cols[0], n)
        a2 = _array_rows(cols[1], n)
        # PG: NULL || x = x; NULL only when BOTH sides are NULL
        out = [json.dumps((x or []) + (y or [])) for x, y in zip(a1, a2)]
        both_null = np.asarray([x is None and y is None
                                for x, y in zip(a1, a2)])
        col = make_string_column(
            np.asarray(out, dtype=object).astype(str),
            None if not both_null.any() else ~both_null)
        col.type = t
        return col
    t = next((x for x in ts if x.id is dt.TypeId.ARRAY),
             dt.array_of(None))
    return FunctionResolution(t, impl)


@register("array_position")
def _array_position(ts):
    if len(ts) != 2 or not _stringish(ts[0]):
        return None

    def impl(cols, n):
        arrs = _array_rows(cols[0], n)
        vals = cols[1].to_pylist()
        out = np.zeros(n, dtype=np.int32)
        absent = np.zeros(n, dtype=bool)
        for i in range(n):
            a = arrs[i]
            needle = _json_scalar(vals, i)
            if a is not None and needle in a:
                out[i] = a.index(needle) + 1
            else:
                absent[i] = True
        return _result(dt.INT, out, cols, extra_invalid=absent)
    return FunctionResolution(dt.INT, impl)


@register("array_contains")
def _array_contains(ts):
    if len(ts) != 2 or not _stringish(ts[0]):
        return None

    def impl(cols, n):
        arrs = _array_rows(cols[0], n)
        vals = cols[1].to_pylist()
        data = np.asarray(
            [a is not None and _json_scalar(vals, i) in a
             for i, a in enumerate(arrs)])
        return _result(dt.BOOL, data, cols)
    return FunctionResolution(dt.BOOL, impl)


@register("string_to_array")
def _string_to_array(ts):
    if len(ts) != 2 or not all(_stringish(t) for t in ts):
        return None

    def impl(cols, n):
        s = string_values(cols[0])
        d = string_values(cols[1])
        d_null = (~cols[1].valid_mask() if cols[1].validity is not None
                  else np.zeros(n, dtype=bool))
        out = []
        for i in range(n):
            if d_null[i]:
                parts = list(s[i])        # PG: NULL delimiter → per char
            elif d[i] == "":
                parts = [s[i]]            # PG: '' delimiter → one element
            else:
                parts = s[i].split(d[i])
            out.append(json.dumps(parts))
        # NULL only when the input string is NULL (non-strict in delim)
        col = make_string_column(
            np.asarray(out, dtype=object).astype(str),
            propagate_nulls(cols[:1]))
        col.type = t
        return col
    t = dt.array_of(dt.VARCHAR)
    return FunctionResolution(t, impl)


@register("array_to_string")
def _array_to_string(ts):
    """array_to_string(arr, delim[, null_string]) — PG skips NULL
    elements unless a null replacement is given."""
    if len(ts) not in (2, 3) or not _stringish(ts[0]) or \
            not _stringish(ts[1]) or \
            (len(ts) == 3 and not (_stringish(ts[2]) or
                                   ts[2].id is dt.TypeId.NULL)):
        return None

    def impl(cols, n):
        arrs = _array_rows(cols[0], n)
        d = string_values(cols[1])
        nulls = _col_text_values(cols[2]) if len(cols) > 2 else None
        # PG: a NULL null_string means NULL elements are simply omitted
        # — it must NOT null the whole result
        nulls_ok = cols[2].valid_mask() if len(cols) > 2 else None
        out = []
        for i in range(n):
            a = arrs[i] or []
            parts = []
            for v in a:
                if v is None:
                    if nulls is not None and nulls_ok[i]:
                        parts.append(str(nulls[i]))
                    continue
                parts.append(v if isinstance(v, str)
                             else json.dumps(v)
                             if isinstance(v, (list, dict))
                             else _pg_text(v))
            out.append(d[i].join(parts))
        return make_string_column(
            np.asarray(out, dtype=object).astype(str),
            propagate_nulls(cols[:2]))
    return FunctionResolution(dt.VARCHAR, impl)


def _json_values(col) -> list:
    """Column → JSON-ready python values: temporal internals render as
    their PG text (PG to_json semantics), everything else passes
    through."""
    vals = col.to_pylist()
    if col.type.id in (dt.TypeId.DATE, dt.TypeId.TIMESTAMP,
                       dt.TypeId.INTERVAL):
        from ..columnar.pgcopy import _scalar_field_text
        return [None if v is None else _scalar_field_text(col.type, v)
                for v in vals]
    return vals


@register("json_build_object")
def _json_build_object(ts):
    """json_build_object(k1, v1, ...) — PG variadic builder."""
    if len(ts) % 2 != 0:
        return None

    def impl(cols, n):
        lists = [_json_values(c) for c in cols]
        out = []
        for i in range(n):
            obj = {}
            for k in range(0, len(lists), 2):
                key = lists[k][i]
                if key is None:
                    raise errors.SqlError(
                        "22004",
                        "null value not allowed for object key")
                obj[str(key)] = lists[k + 1][i]
            out.append(json.dumps(obj))
        return make_string_column(np.asarray(out, dtype=object), None)
    return FunctionResolution(dt.VARCHAR, impl)


@register("json_build_array")
def _json_build_array(ts):
    def impl(cols, n):
        lists = [_json_values(c) for c in cols]
        out = [json.dumps([lst[i] for lst in lists]) for i in range(n)]
        return make_string_column(np.asarray(out, dtype=object), None)
    return FunctionResolution(dt.VARCHAR, impl)


@register("json_typeof")
def _json_typeof(ts):
    if not ts or not _stringish(ts[0]):
        return None

    def impl(cols, n):
        docs = string_values(cols[0])
        valid = propagate_nulls(cols)
        out = []
        bad = np.zeros(n, dtype=bool)
        for i in range(n):
            if valid is not None and not valid[i]:
                out.append("")
                continue
            try:
                v = json.loads(docs[i])
            except json.JSONDecodeError:
                out.append("")
                bad[i] = True
                continue
            out.append("null" if v is None else
                       "boolean" if isinstance(v, bool) else
                       "number" if isinstance(v, (int, float)) else
                       "string" if isinstance(v, str) else
                       "array" if isinstance(v, list) else "object")
        col = make_string_column(np.asarray(out, dtype=object).astype(str),
                                 valid)
        if bad.any():
            v = col.valid_mask() & ~bad
            col = Column(dt.VARCHAR, col.data,
                         None if v.all() else v, col.dictionary)
        return col
    return FunctionResolution(dt.VARCHAR, impl)


@register("json_array_length")
def _json_array_length(ts):
    if not ts or not _stringish(ts[0]):
        return None

    def impl(cols, n):
        arrs = _array_rows(cols[0], n)
        data = np.asarray([len(a) if a is not None else 0 for a in arrs],
                          dtype=np.int32)
        return _result(dt.INT, data, cols)
    return FunctionResolution(dt.INT, impl)


@register("json_object_keys")
def _json_object_keys(ts):
    """Keys of a JSON object as a JSON array (PG's set-returning variant
    maps onto unnest(json_object_keys(x)))."""
    if not ts or not _stringish(ts[0]):
        return None

    def impl(cols, n):
        docs = string_values(cols[0])
        valid = propagate_nulls(cols)
        out = []
        for i in range(n):
            if valid is not None and not valid[i]:
                out.append("")
                continue
            try:
                v = json.loads(docs[i])
            except json.JSONDecodeError:
                raise errors.SqlError(
                    errors.INVALID_TEXT_REPRESENTATION,
                    f"invalid JSON: {docs[i][:40]!r}")
            if not isinstance(v, dict):
                raise errors.SqlError(
                    errors.INVALID_TEXT_REPRESENTATION,
                    "json_object_keys expects a JSON object")
            out.append(json.dumps(list(v.keys())))
        return make_string_column(
            np.asarray(out, dtype=object).astype(str), valid)
    return FunctionResolution(dt.VARCHAR, impl)


# PG system/introspection functions register themselves on import (kept in
# a separate module so the catalog surface doesn't bloat this file)
from . import pgsys  # noqa: E402,F401  (registration side effects)
# Geo shape functions (WKT/WKB/GeoJSON, predicates, measures) — same
# registration-on-import pattern
from . import geofns  # noqa: E402,F401  (registration side effects)
# Embedding provider layer (ai_embed + secrets)
from . import embedfns  # noqa: E402,F401  (registration side effects)


# -- ROW(...) anonymous composites (reference: server/pg/serialize.cpp
# record path; record values render as (f1,f2) text and the binary
# record format with per-field OIDs) --------------------------------------

@register("row")
def _row(ts):
    from ..columnar.pgcopy import field_oid
    oids = [field_oid(t) for t in ts]

    def impl(cols, n):
        # to_pylist() yields pure Python scalars (it .item()s numpy
        # values), so rows JSON-encode directly
        pylists = [c.to_pylist() for c in cols]
        out = []
        for i in range(n):
            out.append(json.dumps({"o": oids,
                                   "v": [pl[i] for pl in pylists]},
                                  separators=(",", ":")))
        col = make_string_column(np.asarray(out, dtype=object), None)
        return Column(dt.RECORD, col.data, col.validity, col.dictionary)

    return FunctionResolution(dt.RECORD, impl)
