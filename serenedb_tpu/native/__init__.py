"""Native (C++) runtime components, loaded via ctypes.

The compute path is JAX/XLA/Pallas; the CPU-bound runtime pieces mirror the
reference's native implementation — currently the inverted-index builder
(tokenize + postings in one pass). Compiled on first use with g++ into
_build/; everything degrades gracefully to the Python implementations when
no toolchain is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from ..utils import log

_lock = threading.Lock()
_lib = None
_tried = False


def _build_dir() -> str:
    d = os.path.join(os.path.dirname(__file__), "_build")
    os.makedirs(d, exist_ok=True)
    return d


def load() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        src = os.path.join(os.path.dirname(__file__), "indexer.cpp")
        so = os.path.join(_build_dir(), "libsdbnative.so")
        try:
            if not os.path.exists(so) or \
                    os.path.getmtime(so) < os.path.getmtime(src):
                cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                       "-pthread", "-o", so + ".tmp", src]
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=120)
                os.replace(so + ".tmp", so)
            lib = ctypes.CDLL(so)
        except (OSError, subprocess.SubprocessError) as e:
            log.warn("native", f"native indexer unavailable: {e}")
            return None
        lib.sdb_build_index.restype = ctypes.c_void_p
        lib.sdb_build_index.argtypes = [ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_int64),
                                        ctypes.c_int64]
        lib.sdb_build_index_mt.restype = ctypes.c_void_p
        lib.sdb_build_index_mt.argtypes = [ctypes.c_char_p,
                                           ctypes.POINTER(ctypes.c_int64),
                                           ctypes.c_int64, ctypes.c_int32]
        for name in ("sdb_num_terms", "sdb_postings_len",
                     "sdb_positions_len", "sdb_terms_bytes",
                     "sdb_total_tokens"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_void_p]
        lib.sdb_fill.restype = None
        lib.sdb_fill.argtypes = [ctypes.c_void_p] + \
            [ctypes.c_char_p] + [ctypes.POINTER(ctypes.c_int64)] + \
            [ctypes.POINTER(ctypes.c_int32)] + \
            [ctypes.POINTER(ctypes.c_int64)] + \
            [ctypes.POINTER(ctypes.c_int32)] * 2 + \
            [ctypes.POINTER(ctypes.c_int64)] + \
            [ctypes.POINTER(ctypes.c_int32)] * 2
        lib.sdb_free.restype = None
        lib.sdb_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def ingest_threads() -> int:
    """Parallel-ingest width: SDB_INGEST_THREADS overrides, else all
    cores (the reference's ParallelSink uses the scheduler's thread
    count the same way)."""
    env = os.environ.get("SDB_INGEST_THREADS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, os.cpu_count() or 1)


def build_field_index_native(texts,
                             n_threads: Optional[int] = None
                             ) -> Optional["FieldIndex"]:
    """Build a FieldIndex with the C++ one-pass indexer (multithreaded —
    the ctypes call drops the GIL and the shards tokenize on std::threads).
    Returns None when the native library is unavailable (caller falls back
    to Python)."""
    lib = load()
    if lib is None:
        return None
    from ..search.segment import FieldIndex

    parts = []
    doc_offsets = np.zeros(len(texts) + 1, dtype=np.int64)
    total = 0
    for i, t in enumerate(texts):
        if t:
            b = t.encode("utf-8")
            parts.append(b)
            total += len(b)
        doc_offsets[i + 1] = total
    buf = b"".join(parts)

    handle = lib.sdb_build_index_mt(
        buf, doc_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(texts),
        ingest_threads() if n_threads is None else max(1, int(n_threads)))
    try:
        t_count = lib.sdb_num_terms(handle)
        p_len = lib.sdb_postings_len(handle)
        pp_len = lib.sdb_positions_len(handle)
        t_bytes = lib.sdb_terms_bytes(handle)
        total_tokens = lib.sdb_total_tokens(handle)

        terms_buf = ctypes.create_string_buffer(max(int(t_bytes), 1))
        term_offsets = np.zeros(t_count + 1, dtype=np.int64)
        doc_freq = np.zeros(max(t_count, 1), dtype=np.int32)
        offsets = np.zeros(t_count + 1, dtype=np.int64)
        post_docs = np.zeros(max(p_len, 1), dtype=np.int32)
        post_tfs = np.zeros(max(p_len, 1), dtype=np.int32)
        pos_offsets = np.zeros(p_len + 1, dtype=np.int64)
        positions = np.zeros(max(pp_len, 1), dtype=np.int32)
        norms = np.zeros(max(len(texts), 1), dtype=np.int32)

        def p64(a):
            return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))

        def p32(a):
            return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

        lib.sdb_fill(handle, terms_buf, p64(term_offsets), p32(doc_freq),
                     p64(offsets), p32(post_docs), p32(post_tfs),
                     p64(pos_offsets), p32(positions), p32(norms))
    finally:
        lib.sdb_free(handle)

    raw = terms_buf.raw
    terms = np.asarray(
        [raw[term_offsets[i]:term_offsets[i + 1]].decode("utf-8")
         for i in range(t_count)], dtype=object)
    return FieldIndex(
        terms=terms,
        doc_freq=doc_freq[:t_count],
        offsets=offsets,
        post_docs=post_docs[:p_len],
        post_tfs=post_tfs[:p_len],
        pos_offsets=pos_offsets,
        positions=positions[:pp_len],
        norms=norms[:len(texts)],
        block_max_tf=np.empty(0, dtype=np.int32),
        block_offsets=np.zeros(t_count + 1, dtype=np.int64),
        total_tokens=int(total_tokens),
    )
