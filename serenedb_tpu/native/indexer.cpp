// Native inverted-index builder: tokenize + postings, sort-based.
//
// Reference analog: IResearch's segment_writer/field_data pipeline
// (libs/iresearch/index/segment_writer.cpp) — the analysis/indexing hot
// path is native C++ in the reference, and stays native here: Python hands
// a concatenated UTF-8 buffer of documents, C++ returns the full
// FieldIndex arrays (sorted terms, postings, positions, norms) ready to
// wrap as numpy arrays.
//
// Design: per-token work is ONE hash lookup into a term dictionary and
// ONE int32 append to a flat term-id stream — no per-posting containers.
// Postings are then produced by a counting-sort scatter of the stream by
// term rank (stable, so per-term entries stay in (doc, position) order),
// and a final linear grouping pass. Multithreading (the ParallelSink
// analog, reference: server/connector/duckdb_physical_search_insert.h)
// shards documents into contiguous byte-balanced ranges — shard s+1's
// doc ids all exceed shard s's, so a k-way merge of shard dictionaries
// concatenates per-term runs in shard order with no posting re-sort.
//
// Tokenization matches the engine's "simple" analyzer for ASCII: word
// characters are [A-Za-z0-9_] (lowercased) plus any non-ASCII byte
// (UTF-8 continuation-safe). Stemming/stopwords stay in Python analyzers.
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

inline bool is_word_byte(unsigned char c) {
    return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
           (c >= '0' && c <= '9') || c == '_' || c >= 0x80;
}

inline char lower_ascii(char c) {
    return (c >= 'A' && c <= 'Z') ? char(c - 'A' + 'a') : c;
}

// Open-addressing term dictionary over a byte arena: one FNV-1a hash per
// token (computed while lowercasing), linear probing, no per-term string
// allocation. ~3x faster than std::unordered_map on short zipf terms.
struct TermDict {
    struct Entry {
        uint64_t hash;
        int64_t arena_off;
        int32_t len;
    };
    std::vector<int64_t> slots;   // entry index, -1 = empty; pow2 size
    std::vector<Entry> entries;   // term id = index
    std::string arena;

    TermDict() : slots(1 << 12, -1) {}

    size_t size() const { return entries.size(); }

    std::string_view term(size_t id) const {
        const Entry& e = entries[id];
        return {arena.data() + e.arena_off, static_cast<size_t>(e.len)};
    }

    void grow() {
        std::vector<int64_t> ns(slots.size() * 2, -1);
        const uint64_t mask = ns.size() - 1;
        for (size_t i = 0; i < entries.size(); ++i) {
            uint64_t s = entries[i].hash & mask;
            while (ns[s] != -1) s = (s + 1) & mask;
            ns[s] = static_cast<int64_t>(i);
        }
        slots.swap(ns);
    }

    int32_t lookup_or_insert(const char* p, int32_t len, uint64_t h) {
        const uint64_t mask = slots.size() - 1;
        uint64_t s = h & mask;
        while (true) {
            const int64_t id = slots[s];
            if (id == -1) break;
            const Entry& e = entries[static_cast<size_t>(id)];
            if (e.hash == h && e.len == len &&
                std::memcmp(arena.data() + e.arena_off, p,
                            static_cast<size_t>(len)) == 0)
                return static_cast<int32_t>(id);
            s = (s + 1) & mask;
        }
        const int32_t id = static_cast<int32_t>(entries.size());
        entries.push_back({h, static_cast<int64_t>(arena.size()), len});
        arena.append(p, static_cast<size_t>(len));
        slots[s] = id;
        if (entries.size() * 10 > slots.size() * 7) grow();
        return id;
    }
};

// Output of one shard's tokenize + scatter passes: local term dictionary
// in sorted order, and (doc, pos) occurrence runs grouped by term rank.
struct ShardOut {
    std::vector<std::string> sorted_terms;
    std::vector<int64_t> run_offsets;   // (T_local+1) into out_docs/out_pos
    std::vector<int32_t> out_docs;      // global doc ids, stream-stable
    std::vector<int32_t> out_pos;       // token position within doc
};

void build_shard(const char* buf, const int64_t* doc_offsets,
                 int64_t doc_lo, int64_t doc_hi, int32_t* norms_out,
                 int64_t* total_tokens_out, ShardOut& out) {
    // pass 1: tokenize to a flat term-id stream
    TermDict dict;
    std::vector<int32_t> stream;
    const int64_t shard_bytes = doc_offsets[doc_hi] - doc_offsets[doc_lo];
    stream.reserve(static_cast<size_t>(shard_bytes / 6) + 16);
    std::vector<int32_t> doc_len(static_cast<size_t>(doc_hi - doc_lo), 0);
    std::string token;
    for (int64_t d = doc_lo; d < doc_hi; ++d) {
        const char* p = buf + doc_offsets[d];
        const char* end = buf + doc_offsets[d + 1];
        int32_t pos = 0;
        // doc_offsets[d] == doc_offsets[d+1] encodes NULL/empty: norm 0
        while (p < end) {
            while (p < end && !is_word_byte(static_cast<unsigned char>(*p)))
                ++p;
            if (p >= end) break;
            token.clear();
            uint64_t h = 1469598103934665603ull;   // FNV-1a 64
            while (p < end && is_word_byte(static_cast<unsigned char>(*p))) {
                const char c = lower_ascii(*p);
                token.push_back(c);
                h = (h ^ static_cast<unsigned char>(c)) * 1099511628211ull;
                ++p;
            }
            stream.push_back(dict.lookup_or_insert(
                token.data(), static_cast<int32_t>(token.size()), h));
            ++pos;
        }
        doc_len[static_cast<size_t>(d - doc_lo)] = pos;
        norms_out[d] = pos;
        *total_tokens_out += pos;
    }

    // rank terms by string order
    const size_t T = dict.size();
    {
        std::vector<int32_t> ids(T);
        for (size_t i = 0; i < T; ++i) ids[i] = static_cast<int32_t>(i);
        std::sort(ids.begin(), ids.end(),
                  [&dict](int32_t a, int32_t b) {
                      return dict.term(static_cast<size_t>(a)) <
                             dict.term(static_cast<size_t>(b));
                  });
        std::vector<int32_t> rank_of_id(T);
        for (size_t r = 0; r < T; ++r)
            rank_of_id[static_cast<size_t>(ids[r])] =
                static_cast<int32_t>(r);
        // rewrite the stream in-place to term ranks
        for (auto& tid : stream) tid = rank_of_id[static_cast<size_t>(tid)];
        out.sorted_terms.resize(T);
        for (size_t r = 0; r < T; ++r)
            out.sorted_terms[r] = std::string(
                dict.term(static_cast<size_t>(ids[r])));
    }

    // pass 2: counting-sort scatter by rank (stable in stream order)
    const size_t N = stream.size();
    out.run_offsets.assign(T + 1, 0);
    for (int32_t r : stream)
        ++out.run_offsets[static_cast<size_t>(r) + 1];
    for (size_t t = 0; t < T; ++t)
        out.run_offsets[t + 1] += out.run_offsets[t];
    out.out_docs.resize(N);
    out.out_pos.resize(N);
    std::vector<int64_t> cursor(out.run_offsets.begin(),
                                out.run_offsets.end() - 1);
    size_t i = 0;
    for (int64_t d = doc_lo; d < doc_hi; ++d) {
        const int32_t len = doc_len[static_cast<size_t>(d - doc_lo)];
        for (int32_t pos = 0; pos < len; ++pos, ++i) {
            const int64_t slot = cursor[static_cast<size_t>(stream[i])]++;
            out.out_docs[static_cast<size_t>(slot)] =
                static_cast<int32_t>(d);
            out.out_pos[static_cast<size_t>(slot)] = pos;
        }
    }
}

}  // namespace

struct BuildResult {
    std::vector<std::string> sorted_terms;
    std::vector<int32_t> doc_freq;
    std::vector<int64_t> offsets;       // (T+1)
    std::vector<int32_t> post_docs;
    std::vector<int32_t> post_tfs;
    std::vector<int64_t> pos_offsets;   // (P+1)
    std::vector<int32_t> positions;
    std::vector<int32_t> norms;
    int64_t total_tokens = 0;
};

namespace {

// K-way merge of shard outputs into the final postings arrays. Shard doc
// ranges ascend with shard index, so per-term runs concatenate in shard
// order; consecutive equal docs within a run group into one posting.
BuildResult* assemble(std::vector<ShardOut>& shards,
                      std::vector<int32_t>&& norms, int64_t total_tokens) {
    auto* r = new BuildResult();
    r->norms = std::move(norms);
    r->total_tokens = total_tokens;

    const size_t S = shards.size();
    std::vector<size_t> cur(S, 0);          // per-shard term cursor
    int64_t total_occ = 0;
    for (auto& sh : shards) total_occ += static_cast<int64_t>(
        sh.out_docs.size());
    r->positions.reserve(static_cast<size_t>(total_occ));
    r->pos_offsets.reserve(static_cast<size_t>(total_occ / 2) + 16);
    r->offsets.push_back(0);
    r->pos_offsets.push_back(0);

    std::vector<size_t> contrib;            // shards holding current term
    contrib.reserve(S);
    while (true) {
        // smallest term among shard cursors
        const std::string* best = nullptr;
        for (size_t s = 0; s < S; ++s) {
            if (cur[s] >= shards[s].sorted_terms.size()) continue;
            const std::string& t = shards[s].sorted_terms[cur[s]];
            if (best == nullptr || t < *best) best = &t;
        }
        if (best == nullptr) break;
        contrib.clear();
        for (size_t s = 0; s < S; ++s) {
            if (cur[s] < shards[s].sorted_terms.size() &&
                shards[s].sorted_terms[cur[s]] == *best)
                contrib.push_back(s);
        }
        int32_t df = 0;
        for (size_t s : contrib) {
            ShardOut& sh = shards[s];
            const int64_t lo = sh.run_offsets[cur[s]];
            const int64_t hi = sh.run_offsets[cur[s] + 1];
            int64_t i = lo;
            while (i < hi) {
                const int32_t doc = sh.out_docs[static_cast<size_t>(i)];
                int64_t j = i;
                while (j < hi &&
                       sh.out_docs[static_cast<size_t>(j)] == doc) {
                    r->positions.push_back(
                        sh.out_pos[static_cast<size_t>(j)]);
                    ++j;
                }
                r->post_docs.push_back(doc);
                r->post_tfs.push_back(static_cast<int32_t>(j - i));
                r->pos_offsets.push_back(
                    static_cast<int64_t>(r->positions.size()));
                ++df;
                i = j;
            }
            ++cur[s];
        }
        r->sorted_terms.push_back(std::move(
            shards[contrib.front()].sorted_terms
                [cur[contrib.front()] - 1]));
        r->doc_freq.push_back(df);
        r->offsets.push_back(static_cast<int64_t>(r->post_docs.size()));
    }
    return r;
}

}  // namespace

extern "C" {

BuildResult* sdb_build_index_mt(const char* buf, const int64_t* doc_offsets,
                                int64_t n_docs, int32_t n_threads) {
    int32_t hw = static_cast<int32_t>(std::thread::hardware_concurrency());
    if (n_threads <= 0) n_threads = hw > 0 ? hw : 1;
    if (n_threads > n_docs) n_threads = n_docs > 0 ?
        static_cast<int32_t>(n_docs) : 1;

    std::vector<int32_t> norms(static_cast<size_t>(n_docs), 0);
    std::vector<int64_t> totals(static_cast<size_t>(n_threads), 0);
    std::vector<ShardOut> shards(static_cast<size_t>(n_threads));

    if (n_threads <= 1) {
        build_shard(buf, doc_offsets, 0, n_docs, norms.data(),
                    &totals[0], shards[0]);
        return assemble(shards, std::move(norms), totals[0]);
    }

    // byte-balanced contiguous shard bounds
    const int64_t total_bytes = doc_offsets[n_docs];
    std::vector<int64_t> bounds(static_cast<size_t>(n_threads) + 1, 0);
    bounds[static_cast<size_t>(n_threads)] = n_docs;
    for (int32_t t = 1; t < n_threads; ++t) {
        const int64_t target = total_bytes * t / n_threads;
        const int64_t* lo = std::lower_bound(
            doc_offsets, doc_offsets + n_docs + 1, target);
        int64_t d = lo - doc_offsets;
        if (d > n_docs) d = n_docs;
        if (d < bounds[static_cast<size_t>(t) - 1])
            d = bounds[static_cast<size_t>(t) - 1];
        bounds[static_cast<size_t>(t)] = d;
    }

    std::vector<std::thread> pool;
    pool.reserve(static_cast<size_t>(n_threads));
    for (int32_t t = 0; t < n_threads; ++t) {
        pool.emplace_back(build_shard, buf, doc_offsets,
                          bounds[static_cast<size_t>(t)],
                          bounds[static_cast<size_t>(t) + 1],
                          norms.data(), &totals[static_cast<size_t>(t)],
                          std::ref(shards[static_cast<size_t>(t)]));
    }
    for (auto& th : pool) th.join();

    int64_t total_tokens = 0;
    for (int64_t v : totals) total_tokens += v;
    return assemble(shards, std::move(norms), total_tokens);
}

BuildResult* sdb_build_index(const char* buf, const int64_t* doc_offsets,
                             int64_t n_docs) {
    return sdb_build_index_mt(buf, doc_offsets, n_docs, 1);
}

int64_t sdb_num_terms(BuildResult* r) {
    return static_cast<int64_t>(r->sorted_terms.size());
}
int64_t sdb_postings_len(BuildResult* r) {
    return static_cast<int64_t>(r->post_docs.size());
}
int64_t sdb_positions_len(BuildResult* r) {
    return static_cast<int64_t>(r->positions.size());
}
int64_t sdb_terms_bytes(BuildResult* r) {
    int64_t total = 0;
    for (const auto& t : r->sorted_terms) total += static_cast<int64_t>(t.size());
    return total;
}
int64_t sdb_total_tokens(BuildResult* r) { return r->total_tokens; }

// Fill pre-allocated numpy buffers (sizes from the getters above).
void sdb_fill(BuildResult* r, char* terms_buf, int64_t* term_offsets,
              int32_t* doc_freq, int64_t* offsets, int32_t* post_docs,
              int32_t* post_tfs, int64_t* pos_offsets, int32_t* positions,
              int32_t* norms) {
    int64_t off = 0;
    int64_t ti = 0;
    term_offsets[0] = 0;
    for (const auto& t : r->sorted_terms) {
        std::memcpy(terms_buf + off, t.data(), t.size());
        off += static_cast<int64_t>(t.size());
        term_offsets[++ti] = off;
    }
    std::memcpy(doc_freq, r->doc_freq.data(),
                r->doc_freq.size() * sizeof(int32_t));
    std::memcpy(offsets, r->offsets.data(),
                r->offsets.size() * sizeof(int64_t));
    std::memcpy(post_docs, r->post_docs.data(),
                r->post_docs.size() * sizeof(int32_t));
    std::memcpy(post_tfs, r->post_tfs.data(),
                r->post_tfs.size() * sizeof(int32_t));
    std::memcpy(pos_offsets, r->pos_offsets.data(),
                r->pos_offsets.size() * sizeof(int64_t));
    std::memcpy(positions, r->positions.data(),
                r->positions.size() * sizeof(int32_t));
    std::memcpy(norms, r->norms.data(), r->norms.size() * sizeof(int32_t));
}

void sdb_free(BuildResult* r) { delete r; }

}  // extern "C"
