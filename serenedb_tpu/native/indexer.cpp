// Native inverted-index builder: tokenize + postings in one pass.
//
// Reference analog: IResearch's segment_writer/field_data pipeline
// (libs/iresearch/index/segment_writer.cpp) — the analysis/indexing hot
// path is native C++ in the reference, and stays native here: Python hands
// a concatenated UTF-8 buffer of documents, C++ returns the full
// FieldIndex arrays (sorted terms, postings, positions, norms) ready to
// wrap as numpy arrays.
//
// Tokenization matches the engine's "simple" analyzer for ASCII: word
// characters are [A-Za-z0-9_] (lowercased) plus any non-ASCII byte
// (UTF-8 continuation-safe). Stemming/stopwords stay in Python analyzers.
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

struct Posting {
    int32_t doc;
    std::vector<int32_t> positions;
};

struct TermEntry {
    std::vector<Posting> postings;
};

struct Builder {
    // term -> postings; string keys own their bytes
    std::unordered_map<std::string, TermEntry> terms;
    std::vector<int32_t> norms;
    int64_t total_tokens = 0;
};

inline bool is_word_byte(unsigned char c) {
    return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
           (c >= '0' && c <= '9') || c == '_' || c >= 0x80;
}

inline char lower_ascii(char c) {
    return (c >= 'A' && c <= 'Z') ? char(c - 'A' + 'a') : c;
}

}  // namespace

struct BuildResult {
    std::vector<std::string> sorted_terms;
    std::vector<int32_t> doc_freq;
    std::vector<int64_t> offsets;       // (T+1)
    std::vector<int32_t> post_docs;
    std::vector<int32_t> post_tfs;
    std::vector<int64_t> pos_offsets;   // (P+1)
    std::vector<int32_t> positions;
    std::vector<int32_t> norms;
    int64_t total_tokens = 0;
};

extern "C" {

BuildResult* sdb_build_index(const char* buf, const int64_t* doc_offsets,
                             int64_t n_docs) {
    Builder b;
    b.norms.resize(static_cast<size_t>(n_docs), 0);
    std::string token;
    for (int64_t d = 0; d < n_docs; ++d) {
        const char* start = buf + doc_offsets[d];
        const char* end = buf + doc_offsets[d + 1];
        int32_t pos = 0;
        const char* p = start;
        // doc_offsets[d] == doc_offsets[d+1] encodes NULL/empty: norm 0
        while (p < end) {
            while (p < end && !is_word_byte(static_cast<unsigned char>(*p)))
                ++p;
            if (p >= end) break;
            token.clear();
            while (p < end && is_word_byte(static_cast<unsigned char>(*p))) {
                token.push_back(lower_ascii(*p));
                ++p;
            }
            auto& entry = b.terms[token];
            if (entry.postings.empty() ||
                entry.postings.back().doc != static_cast<int32_t>(d)) {
                entry.postings.push_back({static_cast<int32_t>(d), {}});
            }
            entry.postings.back().positions.push_back(pos);
            ++pos;
        }
        b.norms[static_cast<size_t>(d)] = pos;
        b.total_tokens += pos;
    }

    auto* r = new BuildResult();
    r->norms = std::move(b.norms);
    r->total_tokens = b.total_tokens;
    r->sorted_terms.reserve(b.terms.size());
    for (auto& kv : b.terms) r->sorted_terms.push_back(kv.first);
    std::sort(r->sorted_terms.begin(), r->sorted_terms.end());

    r->offsets.push_back(0);
    r->pos_offsets.push_back(0);
    for (const auto& term : r->sorted_terms) {
        auto& entry = b.terms[term];
        r->doc_freq.push_back(static_cast<int32_t>(entry.postings.size()));
        for (auto& p : entry.postings) {
            r->post_docs.push_back(p.doc);
            r->post_tfs.push_back(static_cast<int32_t>(p.positions.size()));
            r->positions.insert(r->positions.end(), p.positions.begin(),
                                p.positions.end());
            r->pos_offsets.push_back(
                static_cast<int64_t>(r->positions.size()));
        }
        r->offsets.push_back(static_cast<int64_t>(r->post_docs.size()));
    }
    return r;
}

int64_t sdb_num_terms(BuildResult* r) {
    return static_cast<int64_t>(r->sorted_terms.size());
}
int64_t sdb_postings_len(BuildResult* r) {
    return static_cast<int64_t>(r->post_docs.size());
}
int64_t sdb_positions_len(BuildResult* r) {
    return static_cast<int64_t>(r->positions.size());
}
int64_t sdb_terms_bytes(BuildResult* r) {
    int64_t total = 0;
    for (const auto& t : r->sorted_terms) total += static_cast<int64_t>(t.size());
    return total;
}
int64_t sdb_total_tokens(BuildResult* r) { return r->total_tokens; }

// Fill pre-allocated numpy buffers (sizes from the getters above).
void sdb_fill(BuildResult* r, char* terms_buf, int64_t* term_offsets,
              int32_t* doc_freq, int64_t* offsets, int32_t* post_docs,
              int32_t* post_tfs, int64_t* pos_offsets, int32_t* positions,
              int32_t* norms) {
    int64_t off = 0;
    int64_t ti = 0;
    term_offsets[0] = 0;
    for (const auto& t : r->sorted_terms) {
        std::memcpy(terms_buf + off, t.data(), t.size());
        off += static_cast<int64_t>(t.size());
        term_offsets[++ti] = off;
    }
    std::memcpy(doc_freq, r->doc_freq.data(),
                r->doc_freq.size() * sizeof(int32_t));
    std::memcpy(offsets, r->offsets.data(),
                r->offsets.size() * sizeof(int64_t));
    std::memcpy(post_docs, r->post_docs.data(),
                r->post_docs.size() * sizeof(int32_t));
    std::memcpy(post_tfs, r->post_tfs.data(),
                r->post_tfs.size() * sizeof(int32_t));
    std::memcpy(pos_offsets, r->pos_offsets.data(),
                r->pos_offsets.size() * sizeof(int64_t));
    std::memcpy(positions, r->positions.data(),
                r->positions.size() * sizeof(int32_t));
    std::memcpy(norms, r->norms.data(), r->norms.size() * sizeof(int32_t));
}

void sdb_free(BuildResult* r) { delete r; }

}  // extern "C"
