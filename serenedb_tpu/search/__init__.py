"""Search engine (IResearch analog): analyzers, inverted-index segments,
posting-block scoring kernels, scorers, and the SQL full-text surface."""
