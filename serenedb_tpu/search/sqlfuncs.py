"""SQL surface of the search engine: ts_* functions and the ##/@@ operators.

Reference analog: server/connector/functions/ts_*.cpp + search.cpp:149-330
(phrase `##`, tsquery `@@`, scorer functions bm25()/tfidf(), ts_offsets,
highlights) and the vector distance operators `<->`/`<#>`/`<=>`
(functions/vector.cpp). Bound here; execution is CPU text-match for
un-indexed columns and is *claimed by the index pushdown optimizer* when the
scan has a search index (exec/pushdown.py), mirroring the reference's
IResearchPushdownComplexFilter (optimizer/iresearch_plan.cpp:1068-1097).

Phase-2 will replace the brute-force CPU fallbacks with segment scoring; the
semantics defined here are the contract.
"""

from __future__ import annotations

import numpy as np

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Column

_SEARCH_FUNCS = {"ts_match", "bm25", "tfidf", "lm_dirichlet",
                 "jelinek_mercer", "dfi", "to_tsquery", "ts_offsets",
                 "ts_headline"}


def is_search_function(name: str) -> bool:
    return name in _SEARCH_FUNCS


def bind_operator(binder, e):
    """Bind `col ## 'phrase'` (phrase match) and `col @@ 'query'`."""
    from ..sql.expr import BoundFunc
    from .analysis import default_analyzer
    from .query import match_phrase_brute, match_query_brute

    if e.op in ("<->", "<#>", "<=>"):
        # vector distance operators → vec_* functions (CPU oracle; the
        # rewrite pass claims ORDER BY ... LIMIT k into the IVF index scan)
        fname = {"<->": "vec_l2", "<#>": "vec_ip", "<=>": "vec_cos"}[e.op]
        left = binder.bind(e.left)
        right = binder.bind(e.right)
        return binder._call(fname, [left, right])
    from ..sql import ast as _ast
    op = e.op
    right_ast = e.right
    # ts_phrase('...') inside @@ means phrase semantics
    # (reference demo0: text @@ ts_phrase('breathtaking cinematography'))
    if op == "@@" and isinstance(right_ast, _ast.FuncCall) and \
            right_ast.name == "ts_phrase" and len(right_ast.args) == 1:
        op = "##"
        right_ast = right_ast.args[0]
    left = binder.bind(e.left)
    right = binder.bind(right_ast)
    if not left.type.is_string:
        raise errors.SqlError(errors.DATATYPE_MISMATCH,
                              f"operator {op} requires a text column")
    fn = match_phrase_brute if op == "##" else match_query_brute

    def impl(cols, batch, _fn=fn):
        hay, needle = cols
        from ..sql.expr import propagate_nulls, string_values
        texts = string_values(hay)
        pats = string_values(needle)
        data = _fn(texts, pats)
        validity = propagate_nulls(cols)
        return Column(dt.BOOL, data, validity)

    name = "ts_phrase" if op == "##" else "ts_query"
    return BoundFunc(name, [left, right], dt.BOOL, impl)


def bind_function(binder, e):
    from ..sql.expr import BoundFunc
    name = e.name
    if name == "ts_match":
        rewritten = type(e)  # FuncCall
        if len(e.args) != 2:
            raise errors.syntax("ts_match(column, query) takes 2 arguments")
        import dataclasses
        from ..sql import ast as _ast
        return bind_operator(binder, _ast.BinaryOp("@@", e.args[0], e.args[1]))
    if name in ("bm25", "tfidf", "lm_dirichlet", "jelinek_mercer",
                "dfi"):
        # scorer over an indexed scan; meaningful only with pushdown — the
        # optimizer replaces it with the scan's score column. Unpushed use
        # yields 0.0 (reference: unscored context returns default score).
        args = [binder.bind(a) for a in e.args]

        def impl(cols, batch):
            return Column(dt.FLOAT, np.zeros(batch.num_rows, dtype=np.float32))
        return BoundFunc(name, args, dt.FLOAT, impl)
    if name == "to_tsquery":
        args = [binder.bind(a) for a in e.args]

        def impl(cols, batch):
            return cols[-1]
        return BoundFunc(name, args, dt.VARCHAR, impl)
    if name in ("ts_offsets", "ts_headline"):
        # reference: byte-range highlight via per-row re-analysis
        # (server/connector/highlight/memory_index.*)
        headline = name == "ts_headline"
        n_args = 3 if headline else 2
        if not 2 <= len(e.args) <= n_args:
            raise errors.syntax(
                f"{name}(column, query[, options]) takes "
                f"{n_args} arguments at most")
        args = [binder.bind(a) for a in e.args]

        def _hl_opts(spec: str) -> dict:
            # PG ts_headline options string: 'StartSel=[, StopSel=]'
            out = {}
            for part in spec.split(","):
                k, _, v = part.partition("=")
                out[k.strip().lower()] = v.strip()
            return out

        def impl(cols, batch, _headline=headline):
            import json
            from ..sql.expr import (make_string_column, propagate_nulls,
                                    string_values)
            from .analysis import default_analyzer
            from .highlight import headline as _hl
            from .highlight import match_offsets
            texts = string_values(cols[0])
            queries = string_values(cols[1])
            an = default_analyzer()
            valid = propagate_nulls(cols)
            # the query argument is almost always a constant column: parse
            # each distinct query string once, not once per row
            from .highlight import _positive_terms, token_matches
            from .query import parse_query as _pq
            qcache: dict[str, tuple] = {}

            def parsed(q: str):
                hit = qcache.get(q)
                if hit is None:
                    hit = qcache[q] = _positive_terms(_pq(q, an))
                return hit

            out = []
            for i in range(batch.num_rows):
                if valid is not None and not valid[i]:
                    out.append("")
                    continue
                terms, prefixes, fuzzies, regexes = parsed(queries[i])
                spans = [[t.start, t.end] for t in an.tokenize(texts[i])
                         if token_matches(t.term, terms, prefixes,
                                          fuzzies, regexes)]
                if _headline:
                    start_sel, stop_sel = "<b>", "</b>"
                    if len(cols) > 2:
                        opts = _hl_opts(string_values(cols[2])[i])
                        start_sel = opts.get("startsel", start_sel)
                        stop_sel = opts.get("stopsel", stop_sel)
                    out.append(_hl(an, texts[i], queries[i], spans=spans,
                                   start_sel=start_sel, stop_sel=stop_sel))
                else:
                    out.append(json.dumps(spans))
            col = make_string_column(
                np.asarray(out, dtype=object).astype(str), valid)
            return col
        return BoundFunc(name, args, dt.VARCHAR, impl)
    raise errors.unsupported(f"search function {name}")
