"""Immutable inverted-index segments, TPU-shaped.

Reference analog: IResearch segments — postings in 128-doc blocks with
block-max (WAND) metadata, columnstore for stored fields, norms for scoring
(reference: libs/iresearch/formats/posting/format_block_128.cpp,
wand_writer.hpp; SURVEY.md §2.7). The 128-doc block granularity is kept —
it is exactly one TPU lane row — but postings live as flat HBM arrays with
per-term offsets; queries gather (n_blocks, 128) tiles by index matrix and
score them on the MXU/VPU (ops/bm25.py).

A segment is immutable once built; deletes are a live-docs bitmap owned by
the enclosing shard (storage layer); merges rebuild segments.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional

import numpy as np

from .analysis import Analyzer, get_analyzer

BLOCK = 128


@dataclass
class FieldIndex:
    """Inverted index of one text field within a segment."""

    terms: np.ndarray          # (T,) object, sorted unique terms
    doc_freq: np.ndarray       # (T,) int32
    offsets: np.ndarray        # (T+1,) int64 into postings arrays
    post_docs: np.ndarray      # (P,) int32 doc ids, ascending per term
    post_tfs: np.ndarray       # (P,) int32 term frequencies
    pos_offsets: np.ndarray    # (P+1,) int64 into positions
    positions: np.ndarray      # (PP,) int32 token positions (phrase queries)
    norms: np.ndarray          # (ndocs,) int32 tokens per document
    block_max_tf: np.ndarray   # (NB_total,) int32 — per 128-block max tf
    block_offsets: np.ndarray  # (T+1,) int64 into block_max_tf
    total_tokens: int

    @property
    def num_terms(self) -> int:
        return len(self.terms)

    @property
    def avgdl(self) -> float:
        n = len(self.norms)
        return (self.total_tokens / n) if n else 0.0

    @property
    def ctf(self) -> np.ndarray:
        """Collection term frequency per term (LM-family scorers); lazily
        reduced over the postings and memoized — segments are immutable."""
        c = getattr(self, "_ctf", None)
        if c is None:
            if len(self.offsets) > 1 and len(self.post_tfs):
                c = np.add.reduceat(
                    self.post_tfs.astype(np.int64), self.offsets[:-1])
                # reduceat repeats values for empty ranges; terms always
                # have ≥1 posting here, but guard stays cheap
            else:
                c = np.zeros(max(len(self.offsets) - 1, 0), dtype=np.int64)
            self._ctf = c
        return c

    @property
    def terms_str(self) -> np.ndarray:
        """str-dtype view of the term dictionary, materialized once (term
        lookups are the hot path — no per-query O(T) copies)."""
        ts = getattr(self, "_terms_str", None)
        if ts is None:
            ts = self._terms_str = self.terms.astype(str)
        return ts

    def term_id(self, term: str) -> int:
        """-1 if absent."""
        ts = self.terms_str
        i = int(np.searchsorted(ts, term))
        if i < len(ts) and ts[i] == term:
            return i
        return -1

    def term_range(self, lo: str, hi: str) -> np.ndarray:
        """Term ids with lo <= term < hi (prefix/range expansion)."""
        ts = self.terms_str
        a = int(np.searchsorted(ts, lo, side="left"))
        b = int(np.searchsorted(ts, hi, side="left"))
        return np.arange(a, b, dtype=np.int64)

    def prefix_term_ids(self, prefix: str) -> np.ndarray:
        return self.term_range(prefix, prefix + "￿")

    def postings(self, tid: int) -> tuple[np.ndarray, np.ndarray]:
        s, e = int(self.offsets[tid]), int(self.offsets[tid + 1])
        return self.post_docs[s:e], self.post_tfs[s:e]

    def positions_of(self, tid: int, within_docs: np.ndarray) -> dict[int, np.ndarray]:
        """doc id → positions array, for the given docs (phrase check)."""
        s, e = int(self.offsets[tid]), int(self.offsets[tid + 1])
        docs = self.post_docs[s:e]
        idx = np.searchsorted(docs, within_docs)
        out = {}
        for k, d in zip(idx, within_docs):
            if k < len(docs) and docs[k] == d:
                p = s + k
                out[int(d)] = self.positions[
                    int(self.pos_offsets[p]):int(self.pos_offsets[p + 1])]
        return out


@dataclass
class Segment:
    """One immutable segment: per-field inverted indexes + doc count.
    Stored fields live in the enclosing table's columnstore (the provider's
    Batch), addressed by this segment's base row offset."""

    fields: dict[str, FieldIndex]
    num_docs: int
    base_row: int = 0           # offset of doc 0 in the table's row space

    def field(self, name: str) -> Optional[FieldIndex]:
        return self.fields.get(name)


def build_field_index(texts: Iterable[Optional[str]],
                      analyzer: Analyzer) -> FieldIndex:
    """Tokenize a column of documents into a FieldIndex (host-side; analysis
    is CPU work by design — SURVEY.md §7 hard part 5).

    The "simple" analyzer over pure-ASCII corpora takes the native C++
    one-pass indexer (serenedb_tpu/native); everything else (stemming,
    stopwords, unicode casing) uses the Python analyzers."""
    texts = list(texts)
    if getattr(analyzer, "name", "") == "simple" and \
            all(t is None or t.isascii() for t in texts):
        from ..native import build_field_index_native
        fi = build_field_index_native(texts)
        if fi is not None:
            _add_block_max(fi)
            return fi
    term_postings: dict[str, list] = {}
    norms = []
    total_tokens = 0
    for doc_id, text in enumerate(texts):
        if text is None:
            norms.append(0)
            continue
        toks = analyzer.tokenize(text)
        norms.append(len(toks))
        total_tokens += len(toks)
        per_term: dict[str, list[int]] = {}
        for t in toks:
            per_term.setdefault(t.term, []).append(t.position)
        for term, poss in per_term.items():
            term_postings.setdefault(term, []).append((doc_id, poss))
    terms_sorted = sorted(term_postings)
    T = len(terms_sorted)
    doc_freq = np.zeros(T, dtype=np.int32)
    offsets = np.zeros(T + 1, dtype=np.int64)
    post_docs_l: list[int] = []
    post_tfs_l: list[int] = []
    pos_offsets_l: list[int] = [0]
    positions_l: list[int] = []
    block_max_l: list[int] = []
    block_offsets = np.zeros(T + 1, dtype=np.int64)
    for ti, term in enumerate(terms_sorted):
        plist = term_postings[term]
        doc_freq[ti] = len(plist)
        for doc_id, poss in plist:
            post_docs_l.append(doc_id)
            post_tfs_l.append(len(poss))
            positions_l.extend(poss)
            pos_offsets_l.append(len(positions_l))
        offsets[ti + 1] = len(post_docs_l)
        # per-128-block max tf (WAND metadata)
        tfs = np.asarray(post_tfs_l[offsets[ti]:offsets[ti + 1]],
                         dtype=np.int32)
        nb = -(-len(tfs) // BLOCK) if len(tfs) else 0
        for bi in range(nb):
            block_max_l.append(int(tfs[bi * BLOCK:(bi + 1) * BLOCK].max()))
        block_offsets[ti + 1] = len(block_max_l)
    return FieldIndex(
        terms=np.asarray(terms_sorted, dtype=object),
        doc_freq=doc_freq,
        offsets=offsets,
        post_docs=np.asarray(post_docs_l, dtype=np.int32),
        post_tfs=np.asarray(post_tfs_l, dtype=np.int32),
        pos_offsets=np.asarray(pos_offsets_l, dtype=np.int64),
        positions=np.asarray(positions_l, dtype=np.int32),
        norms=np.asarray(norms, dtype=np.int32),
        block_max_tf=np.asarray(block_max_l, dtype=np.int32),
        block_offsets=block_offsets,
        total_tokens=total_tokens,
    )


def _add_block_max(fi: FieldIndex) -> None:
    """Compute per-128-block max-tf metadata for an index built without it
    (the native builder returns raw postings; the parallel merge recomputes
    it because posting blocks span chunk boundaries). Vectorized: every
    term holds >= 1 posting, so the per-block start indices are strictly
    increasing and one maximum.reduceat covers all terms — same values as
    the per-term loop, bit for bit."""
    T = fi.num_terms
    block_offsets = np.zeros(T + 1, dtype=np.int64)
    if T == 0 or len(fi.post_tfs) == 0:
        fi.block_max_tf = np.zeros(0, dtype=np.int32)
        fi.block_offsets = block_offsets
        return
    df = (fi.offsets[1:] - fi.offsets[:-1]).astype(np.int64)
    nb = -(-df // BLOCK)
    block_offsets[1:] = np.cumsum(nb)
    total_blocks = int(block_offsets[-1])
    within = np.arange(total_blocks, dtype=np.int64) - \
        np.repeat(block_offsets[:-1], nb)
    starts = np.repeat(fi.offsets[:-1], nb) + within * BLOCK
    fi.block_max_tf = np.maximum.reduceat(
        fi.post_tfs, starts).astype(np.int32)
    fi.block_offsets = block_offsets


def merge_field_indexes(parts: list[FieldIndex],
                        doc_offsets: list[int]) -> FieldIndex:
    """Merge per-chunk FieldIndexes built over a partition of one document
    batch into the index the serial builder would have produced, bit for
    bit. `doc_offsets[i]` is chunk i's first doc id in the merged space;
    chunks arrive in ascending doc order, so concatenating each term's
    per-chunk postings in part order (doc ids shifted by the chunk offset)
    preserves the ascending-doc-id postings invariant without any sort.
    WAND block metadata is recomputed — 128-doc posting blocks span chunk
    boundaries, so per-chunk block maxima cannot be reused."""
    if len(parts) == 1 and not doc_offsets[0]:
        return parts[0]
    term_arrays = [p.terms_str for p in parts if p.num_terms]
    if not term_arrays:
        norms = np.concatenate([p.norms for p in parts]).astype(np.int32)
        return FieldIndex(
            terms=np.asarray([], dtype=object),
            doc_freq=np.zeros(0, dtype=np.int32),
            offsets=np.zeros(1, dtype=np.int64),
            post_docs=np.zeros(0, dtype=np.int32),
            post_tfs=np.zeros(0, dtype=np.int32),
            pos_offsets=np.zeros(1, dtype=np.int64),
            positions=np.zeros(0, dtype=np.int32),
            norms=norms,
            block_max_tf=np.zeros(0, dtype=np.int32),
            block_offsets=np.zeros(1, dtype=np.int64),
            total_tokens=0)
    merged_terms = np.unique(np.concatenate(term_arrays))
    T = len(merged_terms)
    maps = [np.searchsorted(merged_terms, p.terms_str) if p.num_terms
            else np.zeros(0, dtype=np.int64) for p in parts]
    # per-term doc freq, then postings laid out by a running per-term
    # write cursor — parts visit the cursor in chunk order, so each
    # term's merged postings are its chunks' postings concatenated
    df = np.zeros(T, dtype=np.int64)
    for p, m in zip(parts, maps):
        if p.num_terms:
            df[m] += p.doc_freq          # terms are unique per part
    offsets = np.zeros(T + 1, dtype=np.int64)
    np.cumsum(df, out=offsets[1:])
    n_post = int(offsets[-1])
    post_docs = np.empty(n_post, dtype=np.int32)
    post_tfs = np.empty(n_post, dtype=np.int32)
    pos_lens = np.empty(n_post, dtype=np.int64)
    cursor = offsets[:-1].copy()
    dsts = []
    for p, m, doc_off in zip(parts, maps, doc_offsets):
        if not p.num_terms:
            dsts.append(None)
            continue
        dfp = p.doc_freq.astype(np.int64)
        within = np.arange(len(p.post_docs), dtype=np.int64) - \
            np.repeat(p.offsets[:-1], dfp)
        dst = np.repeat(cursor[m], dfp) + within
        post_docs[dst] = p.post_docs + np.int32(doc_off)
        post_tfs[dst] = p.post_tfs
        pos_lens[dst] = np.diff(p.pos_offsets)
        cursor[m] += dfp
        dsts.append(dst)
    pos_offsets = np.zeros(n_post + 1, dtype=np.int64)
    np.cumsum(pos_lens, out=pos_offsets[1:])
    positions = np.empty(int(pos_offsets[-1]), dtype=np.int32)
    for p, dst in zip(parts, dsts):
        if dst is None or not len(p.positions):
            continue
        plens = np.diff(p.pos_offsets)
        pwithin = np.arange(len(p.positions), dtype=np.int64) - \
            np.repeat(p.pos_offsets[:-1], plens)
        positions[np.repeat(pos_offsets[dst], plens) + pwithin] = \
            p.positions
    fi = FieldIndex(
        terms=np.asarray([str(t) for t in merged_terms], dtype=object),
        doc_freq=df.astype(np.int32),
        offsets=offsets,
        post_docs=post_docs,
        post_tfs=post_tfs,
        pos_offsets=pos_offsets,
        positions=positions,
        norms=np.concatenate([p.norms for p in parts]).astype(np.int32),
        block_max_tf=np.zeros(0, dtype=np.int32),
        block_offsets=np.zeros(T + 1, dtype=np.int64),
        total_tokens=sum(p.total_tokens for p in parts),
    )
    _add_block_max(fi)
    return fi


def _ingest_setting(settings, name: str):
    """Resolve a write-path setting: explicit session settings, the
    executing connection's session, or the global default."""
    if settings is None:
        from ..engine import CURRENT_CONNECTION
        conn = CURRENT_CONNECTION.get()
        if conn is not None:
            settings = conn.settings
    from ..utils.config import REGISTRY
    try:
        if settings is not None:
            return settings.get(name)
        return REGISTRY.get_global(name)
    except KeyError:
        return None


def build_field_index_auto(texts, analyzer: Analyzer,
                           settings=None) -> FieldIndex:
    """build_field_index, chunk-split across the shared worker pool when
    `serene_parallel_ingest` is on and the corpus spans at least two
    chunks. The fixed-size chunk split is independent of worker count and
    the merge is deterministic, so the result is BIT-IDENTICAL to the
    serial build at any parallelism (off/small corpora run the serial
    path — the parity oracle)."""
    texts = list(texts)
    n = len(texts)
    chunk = _ingest_setting(settings, "serene_ingest_chunk_docs") or 4096
    chunk = max(64, int(chunk))
    if not _ingest_setting(settings, "serene_parallel_ingest") or \
            n < 2 * chunk:
        return build_field_index(texts, analyzer)
    from ..parallel.pool import parallel_map, session_workers
    if session_workers(settings) <= 1:
        return build_field_index(texts, analyzer)
    bounds = list(range(0, n, chunk))
    parts = parallel_map(
        settings, lambda b: build_field_index(texts[b:b + chunk], analyzer),
        bounds)
    return merge_field_indexes(parts, bounds)


def build_segment(columns: dict[str, Iterable[Optional[str]]],
                  analyzers: dict[str, str],
                  num_docs: int, base_row: int = 0) -> Segment:
    fields = {}
    for name, texts in columns.items():
        an = get_analyzer(analyzers.get(name, "text"))
        fields[name] = build_field_index(texts, an)
    return Segment(fields, num_docs, base_row)


def merge_segments(segments: list[Segment], live_masks: list[np.ndarray],
                   columns_of, analyzers: dict[str, str]) -> Segment:
    """Compaction: rebuild one segment from the live docs of many.
    `columns_of(seg) -> dict[field, list[str]]` re-reads stored text (the
    reference's merge_writer reads the columnstore the same way)."""
    all_cols: dict[str, list] = {}
    total = 0
    for seg, live in zip(segments, live_masks):
        cols = columns_of(seg)
        keep = np.flatnonzero(live[:seg.num_docs])
        for name, texts in cols.items():
            all_cols.setdefault(name, []).extend(
                [texts[i] for i in keep])
        total += len(keep)
    return build_segment(all_cols, analyzers, total,
                         segments[0].base_row if segments else 0)
