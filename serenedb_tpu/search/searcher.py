"""Index-backed query evaluation: filters on CPU set algebra, scoring on TPU.

Reference analog: prepared queries over a DirectoryReader snapshot —
ScanMode::Stream (filter → doc iterator) and ScanMode::TopK (parallel scored
collectors) (reference: server/connector/duckdb_search_full_scan.hpp:54-76).

Split of labor (SURVEY.md §7 phase 2): term dictionary lookups and boolean
doc-set algebra stay on CPU (pointer-chasing), BM25 scoring + top-k runs as
the dense block kernel in ops/bm25.py. Results must match the brute-force
semantics contract in search/query.py — asserted by parity tests.

Scoring semantics: a document's score is the sum of BM25 contributions of
every positive leaf term of the query (phrase members and prefix expansions
included); NOT-subtrees and phrase adjacency affect *matching* only.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.device import pad_len
from ..ops import bm25 as bm25_ops
from . import posting_pool
from .analysis import Analyzer
from .automaton import intersect_sorted, levenshtein_nfa
from .query import (QAnd, QFuzzy, QNode, QNot, QNothing, QOr, QPhrase,
                    QPrefix, QRegex, QTerm, parse_query)
from .segment import BLOCK, FieldIndex

K1 = 1.2
B = 0.75  # reference defaults: libs/iresearch/search/bm25.hpp

_HOST_BACKEND: Optional[bool] = None


def _host_backend() -> bool:
    """True when jax runs on the host CPU backend: there the ragged
    numpy accumulate beats a per-query score plane, while on a real
    accelerator the plane + fused top-k stays on device and batching
    amortizes the dispatch RTT instead."""
    global _HOST_BACKEND
    if _HOST_BACKEND is None:
        _HOST_BACKEND = jax.default_backend() == "cpu"
    return _HOST_BACKEND


class _RaggedSlice(NamedTuple):
    """One (plane, term) slice of an admitted ragged query, in the
    plane kernel's flatten order: the KEPT postings (docs/tfs), the
    term weight, and enough provenance — term id, full posting range,
    within-term kept positions — for the posting pool to key pages and
    build page-table gather slots. `idx` is None when every posting of
    the term survives (light tails, unpruned heavy planes)."""

    docs: np.ndarray
    tfs: np.ndarray
    w: float
    tid: int
    s: int
    e: int
    idx: Optional[np.ndarray]


def _maxscore_split(plan) -> set:
    """Non-essential terms of a WandPlan: the ascending-maxscore prefix
    whose cumulative sum stays below θ — docs containing only those terms
    can never reach the top-k. Shared by the device candidate generation
    and the CPU WAND baseline so the split rule cannot diverge."""
    cum = 0.0
    non_ess = set()
    for tid, ms in sorted(plan.maxscore.items(), key=lambda t: t[1]):
        if cum + ms < plan.theta:
            cum += ms
            non_ess.add(tid)
        else:
            break
    return non_ess


class SegmentSearcher:
    def __init__(self, index: FieldIndex, analyzer: Analyzer, num_docs: int):
        self.index = index
        self.analyzer = analyzer
        self.num_docs = num_docs
        self._dev = None

    # -- device posting store (lazy, cached) ------------------------------

    def _device_store(self) -> bm25_ops.BlockStore:
        if self._dev is None:
            self._dev = bm25_ops.build_block_store(
                self.index.offsets, self.index.post_docs,
                self.index.post_tfs, self.index.doc_freq,
                self.index.norms, self.num_docs)
        return self._dev

    def _dense_store(self, scorer: str,
                     avgdl: float) -> bm25_ops.DenseStore:
        """Dense saturation matrix for the small-corpus matmul path,
        cached per (scorer shape, avgdl) — segments are immutable, and
        avgdl only drifts when collection stats change."""
        cache = getattr(self, "_dense_cache", None)
        if cache is None:
            cache = self._dense_cache = {}
        # tfidf's S (sqrt tf) is avgdl-independent — don't rebuild it when
        # collection stats drift
        key = ("tfidf",) if scorer == "tfidf" \
            else ("bm25", round(avgdl, 6))
        hit = cache.get(key)
        if hit is None:
            if len(cache) >= 2:   # S is the dominant HBM tenant — keep ≤2
                cache.clear()
            hit = cache[key] = bm25_ops.build_dense_store(
                self._device_store(), self.index.doc_freq, avgdl, K1, B,
                scorer)
        return hit

    # -- filter evaluation (CPU doc-set algebra) --------------------------

    def eval_filter(self, node: QNode) -> np.ndarray:
        """Sorted doc ids matching the query node. Memoized in the
        process-wide fragment cache (cache/fragments.py): segments are
        immutable, so a filter doc set is valid for this object's whole
        lifetime — the ES shard-request-cache analog. Recursive
        sub-nodes memoize individually, so `a AND b` reuses a cached
        `a`. Unknown node shapes and `serene_result_cache = off`
        sessions compute straight through."""
        from ..cache.fragments import FRAGMENTS, qnode_sig
        sig = qnode_sig(node)
        return FRAGMENTS.cached(
            self, None if sig is None else ("filter", sig),
            lambda: self._eval_filter_uncached(node))

    def _eval_filter_uncached(self, node: QNode) -> np.ndarray:
        if isinstance(node, QTerm):
            tid = self.index.term_id(node.term)
            if tid < 0:
                return np.empty(0, dtype=np.int32)
            return self.index.postings(tid)[0]
        if isinstance(node, QPrefix):
            return self._union_postings(self.index.prefix_term_ids(
                node.prefix))
        if isinstance(node, QFuzzy):
            return self._union_postings(self._fuzzy_term_ids(node))
        if isinstance(node, QRegex):
            return self._union_postings(self._regex_term_ids(node))
        if isinstance(node, QPhrase):
            return self._eval_phrase(node.groups, node.slop)
        if isinstance(node, QNothing):
            return np.empty(0, dtype=np.int32)
        if isinstance(node, QAnd):
            if not node.args:
                return np.empty(0, dtype=np.int32)
            pos = [a for a in node.args if not isinstance(a, QNot)]
            neg = [a for a in node.args if isinstance(a, QNot)]
            if pos:
                acc = self.eval_filter(pos[0])
                for a in pos[1:]:
                    acc = np.intersect1d(acc, self.eval_filter(a),
                                         assume_unique=True)
            else:
                acc = np.arange(self.num_docs, dtype=np.int32)
            for a in neg:
                acc = np.setdiff1d(acc, self.eval_filter(a.arg),
                                   assume_unique=True)
            return acc
        if isinstance(node, QOr):
            parts = [self.eval_filter(a) for a in node.args]
            return np.unique(np.concatenate(parts)) if parts \
                else np.empty(0, dtype=np.int32)
        if isinstance(node, QNot):
            inner = self.eval_filter(node.arg)
            return np.setdiff1d(np.arange(self.num_docs, dtype=np.int32),
                                inner, assume_unique=True)
        return np.empty(0, dtype=np.int32)

    def _union_postings(self, tids) -> np.ndarray:
        """Sorted unique doc ids across the postings of several terms
        (multi-term leaves: prefix / fuzzy / regex expansions)."""
        parts = [self.index.postings(t)[0] for t in tids]
        return np.unique(np.concatenate(parts)) if parts \
            else np.empty(0, dtype=np.int32)

    def _eval_phrase(self, groups: list[list[str]],
                     slop: int = 0) -> np.ndarray:
        """Phrase over per-position alternative groups: each slot is the
        union of its alternatives' postings (synonym expansions), slots
        must land on consecutive doc positions — or, with slop > 0, in
        order with total extra gap <= slop (Lucene `"..."~N`, minus its
        bounded-reorder allowance; same contract as query._sloppy_match)."""
        if not groups:
            return np.empty(0, dtype=np.int32)
        gtids = [[t for t in (self.index.term_id(a) for a in g) if t >= 0]
                 for g in groups]
        if any(not g for g in gtids):
            return np.empty(0, dtype=np.int32)
        cand = self._union_postings(gtids[0])
        for g in gtids[1:]:
            cand = np.intersect1d(cand, self._union_postings(g),
                                  assume_unique=True)
        if len(groups) == 1 or len(cand) == 0:
            return cand
        # doc → union of positions across the group's alternatives
        pos_maps = []
        for g in gtids:
            merged: dict[int, set] = {}
            for t in g:
                for d, ps in self.index.positions_of(t, cand).items():
                    merged.setdefault(int(d), set()).update(
                        int(p) for p in ps)
            pos_maps.append(merged)
        from .query import _sloppy_match
        out = []
        for d in cand:
            d = int(d)
            first = pos_maps[0].get(d)
            if first is None:
                continue
            rest = [pm.get(d) for pm in pos_maps[1:]]
            if any(r is None for r in rest):
                continue
            if slop > 0:
                hit = _sloppy_match(first, rest, slop)
            else:
                hit = any(all((p + k1) in rs
                              for k1, rs in enumerate(rest, 1))
                          for p in first)
            if hit:
                out.append(d)
        return np.asarray(out, dtype=np.int32)

    def _fuzzy_term_ids(self, node: QFuzzy) -> list[int]:
        """Edit-distance expansion over the term dictionary (reference:
        levenshtein parametric automata over the burst trie; here a
        length-banded numpy prefilter + banded edit distance). Uncapped —
        indexed results must equal brute-force evaluation. Memoized per
        (term, edits) while the segment is alive (segments are
        immutable)."""
        cache = getattr(self, "_fuzzy_cache", None)
        if cache is None:
            cache = self._fuzzy_cache = {}
        key = (node.term, node.max_edits)
        hit = cache.get(key)
        if hit is not None:
            return hit
        start, end = levenshtein_nfa(node.term, node.max_edits)
        out = intersect_sorted(start, end, self.index.terms_str)
        cache[key] = out
        return out

    def _regex_term_ids(self, node: QRegex) -> list[int]:
        """Full-term regex expansion over the term dictionary (reference:
        by_regexp runs an automaton over the burst trie; here a linear scan
        of the sorted dictionary — segments are immutable, so memoized)."""
        cache = getattr(self, "_regex_cache", None)
        if cache is None:
            cache = self._regex_cache = {}
        hit = cache.get(node.pattern)
        if hit is not None:
            return hit
        rx = node.compiled
        out = intersect_sorted(rx.start, rx.end, self.index.terms_str)
        cache[node.pattern] = out
        return out

    # -- scoring (device) --------------------------------------------------

    def scoring_terms(self, node: QNode) -> list[int]:
        """Positive leaf term ids contributing to the score."""
        out: list[int] = []

        def rec(nd):
            if isinstance(nd, QTerm):
                t = self.index.term_id(nd.term)
                if t >= 0:
                    out.append(t)
            elif isinstance(nd, QPhrase):
                for term in nd.terms:
                    t = self.index.term_id(term)
                    if t >= 0:
                        out.append(t)
            elif isinstance(nd, QPrefix):
                out.extend(int(t) for t in
                           self.index.prefix_term_ids(nd.prefix))
            elif isinstance(nd, QFuzzy):
                out.extend(self._fuzzy_term_ids(nd))
            elif isinstance(nd, QRegex):
                out.extend(self._regex_term_ids(nd))
            elif isinstance(nd, (QAnd, QOr)):
                for a in nd.args:
                    rec(a)
            # QNot: no score contribution
        rec(node)
        seen = set()
        uniq = []
        for t in out:
            if t not in seen:
                seen.add(t)
                uniq.append(t)
        return uniq

    def _query_shape(self, node: QNode) -> tuple[list[int], int, bool, bool]:
        """(scoring term ids, require_all, needs_exact_mask, always_empty).

        always_empty: a pure conjunction containing a term absent from the
        index can never match (scoring_terms silently drops absent terms, so
        require_all alone would degrade the AND)."""
        tids = self.scoring_terms(node)
        require_all = 0
        needs_mask = False
        empty = False
        if isinstance(node, (QTerm, QPrefix, QFuzzy, QRegex)):
            pass
        elif isinstance(node, QOr) and all(
                isinstance(a, QTerm) for a in node.args):
            pass
        elif isinstance(node, QAnd) and all(
                isinstance(a, QTerm) for a in node.args):
            require_all = len(tids)
            if any(self.index.term_id(a.term) < 0 for a in node.args):
                empty = True
        else:
            needs_mask = True
        return tids, require_all, needs_mask, empty

    def _wand_plan_cached(self, store, tids, k: int, avgdl: float,
                          scorer: str, idf_of):
        """wand_plan with a per-store memo — segments are immutable, and
        batched QPS workloads repeat query shapes."""
        tid_arr = np.asarray(tids, dtype=np.int64)
        if idf_of is not None:
            idf = np.asarray(idf_of(tid_arr), dtype=np.float32)
        else:
            idf = bm25_ops.idf_for(scorer, self.num_docs,
                                   self.index.doc_freq[tid_arr])
        cache = getattr(store, "_plan_cache", None)
        if cache is None:
            cache = store._plan_cache = {}
        if len(cache) > 8192:  # stale stats (avgdl/idf drift) accumulate keys
            cache.clear()
        key = (tuple(int(t) for t in tids), k, round(avgdl, 6), scorer,
               idf.tobytes())
        if key in cache:
            return cache[key]
        plan = bm25_ops.wand_plan(store, tids, idf, k, avgdl, K1, B, scorer)
        cache[key] = plan
        return plan

    # candidate cap for the sparse MaxScore path: above this, the dense
    # device kernel amortizes better than host gather-scoring
    MAXSCORE_CAND_CAP = 4096

    def _maxscore_candidates(self, plan, tids, k: int) -> Optional[np.ndarray]:
        """MaxScore essential-list split: if the non-essential terms' max
        scores sum below θ, docs containing ONLY non-essential terms can
        never reach the top-k, so the candidate set is the union of the
        essential terms' postings. Returns sorted candidate doc ids when
        the sparse path applies (small enough and ≥ k docs), else None.

        Reference analog: the max-score optimization of
        block_disjunction.hpp / max_score_iterator."""
        non_ess = _maxscore_split(plan)
        if not non_ess:
            return None
        ess = [t for t in tids if int(t) not in non_ess]
        if not ess:
            return None
        fi = self.index
        total = sum(int(fi.doc_freq[int(t)]) for t in ess)
        if total > self.MAXSCORE_CAND_CAP:
            return None
        parts = [fi.postings(int(t))[0] for t in ess]
        cand = np.unique(np.concatenate(parts)) if parts else None
        if cand is None or len(cand) < k:
            return None  # too few candidates to fill k exact slots
        return cand.astype(np.int32)

    def topk(self, node: QNode, k: int, scorer: str = "bm25",
             mesh_n: int = 0) -> tuple[np.ndarray, np.ndarray]:
        return self.topk_batch([node], k, scorer, mesh_n=mesh_n)[0]

    # cap on per-dispatch accumulator entries (B × ndocs_pad f32): bounds
    # HBM at large corpora — the batch splits into query chunks instead of
    # materializing (256, 8.8M) at MS-MARCO scale
    ACC_ENTRY_CAP = 128 * 1024 * 1024

    #: per-query cap on ragged host-path posting entries: past this the
    #: candidate sort/accumulate costs approach the dense plane's and the
    #: query stays on the device dispatch
    RAGGED_ENTRY_CAP = 1 << 18

    def topk_batch(self, nodes: list[QNode], k: int, scorer: str = "bm25",
                   idf_of=None, avgdl_override=None, mesh_n: int = 0,
                   ragged: bool = False,
                   ) -> list[tuple[np.ndarray, np.ndarray]]:
        """Top-k (scores, doc ids) for a batch of queries in ONE device
        dispatch (amortizes dispatch latency — the QPS regime). Pure term
        disjunctions/conjunctions run fully on device; other shapes get an
        exact-match CPU mask applied to the device scores.

        ragged=True (the batched-serving path, search/batcher.py) admits
        pure disjunctions on the host jax backend to `_ragged_resolve`:
        WAND-kept postings flatten into ragged (contribution, query-offset)
        arrays, score in one tiny elementwise dispatch, and top-k on the
        candidate sets — bit-identical to the score-plane kernel by the
        contrib_flat contract (ops/bm25.py), an order of magnitude cheaper
        at top-10-of-millions scale. Never taken when this store would use
        the dense matmul path, so ragged on/off can't change a single
        result bit there either."""
        if self.num_docs == 0:
            return [(np.empty(0, dtype=np.float32),
                     np.empty(0, dtype=np.int32))] * len(nodes)
        if scorer in bm25_ops.LM_SCORERS and idf_of is None:
            # LM-family weights are collection probabilities, not idf
            ctf, total = self.index.ctf, float(self.index.total_tokens)

            def idf_of(tids, _ctf=ctf, _tot=total):
                return bm25_ops.term_weight_for(
                    scorer, self.num_docs, None, _ctf[tids], _tot)
        store = self._device_store()
        max_b = max(1, self.ACC_ENTRY_CAP // store.ndocs_pad)
        if len(nodes) > max_b:
            out = []
            for i in range(0, len(nodes), max_b):
                out.extend(self.topk_batch(nodes[i:i + max_b], k, scorer,
                                           idf_of, avgdl_override, mesh_n,
                                           ragged))
            return out
        nd_pad = store.ndocs_pad
        shapes = [self._query_shape(n) for n in nodes]
        queries = [(np.asarray(tids, dtype=np.int64) if not empty
                    else np.empty(0, dtype=np.int64), req)
                   for tids, req, _, empty in shapes]
        # pad the query axis to a power of two with no-op empties: the
        # packed/mesh kernels are jitted per n_queries, and coalesced
        # batches arrive at every size — without bucketing each new size
        # would compile a fresh program. Empty pads scatter nothing and
        # their accumulator rows are never read back, so real queries'
        # bits are untouched.
        for _ in range(bm25_ops._pow2(len(queries), 1) - len(queries)):
            queries.append((np.empty(0, dtype=np.int64), 0))
        # block-max WAND applies to pure disjunctions whose device top-k is
        # final (no exact-match mask re-ranking a subset afterwards); the
        # LM scorers don't decompose as w·sat, so their bounds don't hold
        prunable = [req == 0 and not needs_mask and not empty and
                    scorer not in bm25_ops.LM_SCORERS
                    for _, req, needs_mask, empty in shapes]
        avgdl = (avgdl_override if avgdl_override is not None
                 else self.index.avgdl)
        k_true = min(max(k, 1), max(self.num_docs, 1))
        if mesh_n > 1 and len(jax.devices()) >= mesh_n and \
                not any(req for _, req in queries):
            # mesh-sharded scoring: posting-row sections shard across the
            # devices, score planes psum over ICI (SURVEY §5.7 — "scale
            # one query across all compute"). require-free shapes only;
            # _finish_batch applies exact-match masks as usual.
            qb = bm25_ops.assemble_query_batch(
                store, self.num_docs, queries, self.index.doc_freq,
                scorer, idf_of=idf_of)
            kk = min(bm25_ops.pad_k(k_true), nd_pad)
            if any(len(q[0]) > 0 for q in queries):
                vals, docs = jax.device_get(bm25_ops.score_topk_mesh(
                    store, qb, nd_pad, kk, mesh_n,
                    bm25_ops.scorer_param(scorer, K1), B, avgdl, scorer))
            else:
                vals = np.zeros((qb.n_queries, kk), dtype=np.float32)
                docs = np.zeros((qb.n_queries, kk), dtype=np.int32)
            return self._finish_batch(nodes, shapes, vals, docs, {}, k,
                                      scorer, idf_of, avgdl_override,
                                      nd_pad)
        plans: list = [None] * len(queries)
        host_results: dict[int, tuple[np.ndarray, np.ndarray]] = {}
        use_dense = (scorer not in bm25_ops.LM_SCORERS and
                     (scorer == "tfidf" or avgdl > 0.0) and
                     bm25_ops.dense_fits(store.ndocs_pad,
                                         len(self.index.doc_freq)))
        if use_dense:
            # small-corpus matmul path: one MXU dispatch, no host WAND
            # planning needed (the dense kernel is not scatter-bound)
            ds = self._dense_store(scorer, avgdl)
            W, require_arr, _ = bm25_ops.assemble_dense_weights(
                ds.v_pad, queries, self.num_docs, self.index.doc_freq,
                scorer, idf_of)
            kk = min(bm25_ops.pad_k(k_true), store.ndocs_pad)
            vals, docs = bm25_ops.dense_topk(
                ds.S, jnp.asarray(W), jnp.asarray(require_arr), kk,
                bool(require_arr.any()))
            vals, docs = jax.device_get((vals, docs))
            return self._finish_batch(nodes, shapes, vals, docs,
                                      host_results, k, scorer, idf_of,
                                      avgdl_override, store.ndocs_pad)
        if store.norms_host is not None and \
                (scorer == "tfidf" or avgdl > 0.0):
            for qi, (tids, req, needs_mask, empty) in enumerate(shapes):
                if not (prunable[qi] and tids):
                    continue
                plan = self._wand_plan_cached(store, tids, k_true, avgdl,
                                              scorer, idf_of)
                if plan is None:
                    continue
                plans[qi] = plan
                cand = self._maxscore_candidates(plan, tids, k_true)
                if cand is not None:
                    host_results[qi] = self._cpu_score(
                        cand, tids, k, scorer, idf_of, avgdl_override)
                    queries[qi] = (np.empty(0, dtype=np.int64), 0)
        if ragged and _host_backend() and \
                (scorer == "tfidf" or avgdl > 0.0) and \
                store.norms_host is not None:
            todo = [qi for qi in range(len(shapes))
                    if prunable[qi] and shapes[qi][0] and
                    qi not in host_results]
            if todo:
                for qi, res in self._ragged_resolve(
                        store, todo, shapes, plans, k, scorer, idf_of,
                        avgdl).items():
                    host_results[qi] = res
                    queries[qi] = (np.empty(0, dtype=np.int64), 0)
        qb = bm25_ops.assemble_query_batch(store, self.num_docs, queries,
                                           self.index.doc_freq, scorer,
                                           idf_of=idf_of, plans=plans)
        kk = bm25_ops.pad_k(k_true)
        kk = min(kk, nd_pad)
        nq = qb.n_queries
        if any(len(q[0]) > 0 for q in queries):
            ints, floats, nb, nr, tt, nq = bm25_ops.pack_query_batch(qb)
            vals, docs = bm25_ops.score_topk_packed(
                store.block_base, store.block_gaps, store.block_tfs8,
                store.raw_docs, store.raw_tfs, store.norms,
                jnp.asarray(ints), jnp.asarray(floats), nb, nr, tt,
                nd_pad, kk, nq, bool(qb.require.any()),
                bm25_ops.scorer_param(scorer, K1), B, avgdl, scorer)
            vals, docs = jax.device_get((vals, docs))
        else:  # every query resolved host-side — skip the dispatch entirely
            vals = np.zeros((nq, kk), dtype=np.float32)
            docs = np.zeros((nq, kk), dtype=np.int32)
        return self._finish_batch(nodes, shapes, vals, docs, host_results,
                                  k, scorer, idf_of, avgdl_override, nd_pad)

    #: byte budget for the ragged memo caches hung off plans and stores
    #: (_ragged_slices masked copies, _ragged_accum candidate tables,
    #: the posting pool's batch descriptor memo): past this EVERY memo
    #: clears — the bounded-cache discipline PR 15 applied to programs,
    #: here for the one-entry-per-novel-query-shape growth class
    RAGGED_MEMO_BYTES_CAP = 64 << 20

    @staticmethod
    def _ragged_memo_charge(store, nbytes: int) -> None:
        """Account freshly-memoized ragged bytes against the store's
        running total; crossing the cap clears every ragged memo (they
        are pure recomputable functions of plan + store, so clearing is
        always safe — the next query repays the arithmetic once)."""
        total = getattr(store, "_ragged_memo_bytes", 0) + int(nbytes)
        if total > SegmentSearcher.RAGGED_MEMO_BYTES_CAP:
            for plan in getattr(store, "_plan_cache", {}).values():
                if plan is None:
                    continue
                for attr in ("_ragged_slices", "_ragged_accum"):
                    if hasattr(plan, attr):
                        delattr(plan, attr)
            cache = getattr(store, "_ragged_plain", None)
            if cache:
                cache.clear()
            memo = getattr(store, "_pool_batch_memo", None)
            if memo:
                memo.clear()
            total = int(nbytes)
        store._ragged_memo_bytes = total

    def _ragged_candidates(self, store, plan, slices):
        """Sorted candidate-doc union + per-slice scatter indices for
        one admitted query — a pure function of the plan's kept
        postings, memoized on the plan so repeat queries pay only the
        f32 adds + top-k. Shared VERBATIM by the host accumulate and
        the posting pool's device descriptors, so their per-doc scatter
        targets cannot diverge."""
        pre = getattr(plan, "_ragged_accum", None) \
            if plan is not None else None
        if pre is not None:
            return pre
        cand = np.unique(np.concatenate([sl.docs for sl in slices]))
        ixs = [np.searchsorted(cand, sl.docs).astype(np.int32)
               for sl in slices]
        if plan is not None:
            plan._ragged_accum = (cand, ixs)
            self._ragged_memo_charge(
                store, cand.nbytes + sum(ix.nbytes for ix in ixs))
        return cand, ixs

    def _ragged_resolve(self, store, qis, shapes, plans, k: int,
                        scorer: str, idf_of, avgdl,
                        ) -> dict[int, tuple[np.ndarray, np.ndarray]]:
        """Batched ragged top-k for pure-disjunction queries.

        Every admitted query's postings — WAND-kept block rows of heavy
        terms plus light-term tails, exactly the entries the plane kernel
        would scatter — flatten into one (contribution, query-offset)
        ragged array set. ONE elementwise `contrib_flat` dispatch scores
        all postings of all queries; accumulation then runs per query as
        ordered slice adds over its sorted candidate set (each term
        touches a doc at most once, so `acc[ix] += c` per slice replays
        the scatter's per-doc f32 addition order bit-for-bit), and
        `topk_tie_exact` makes the same (score desc, doc asc) selection
        as lax.top_k. Queries past RAGGED_ENTRY_CAP stay on the device
        dispatch.

        Device tier (serene_posting_pool, search/posting_pool.py):
        queries whose terms are page-resident in the pool's HBM region
        never flatten on the host at all — one jitted gather-and-
        accumulate program over page tables scores them with the SAME
        contrib expression tree and candidate tables, so the host path
        here remains the bit-identical parity oracle. Partial residency
        scores the resident slice PREFIX on device and adds the suffix
        slices below in the same order — an identical f32 addition
        sequence."""
        fi = self.index
        per_q: list[tuple[int, object, list]] = []
        for qi in qis:
            tids = shapes[qi][0]
            plan = plans[qi]
            tid_arr = np.asarray(tids, dtype=np.int64)
            if idf_of is not None:
                idf = np.asarray(idf_of(tid_arr), dtype=np.float32)
            else:
                idf = bm25_ops.idf_for(scorer, self.num_docs,
                                       fi.doc_freq[tid_arr])
            slices: list[_RaggedSlice] = []
            entries = 0
            for plane in (0, 1, 2):
                for j, tid in enumerate(tids):
                    tid = int(tid)
                    s, e = int(store.offsets[tid]), int(store.offsets[tid + 1])
                    if e <= s:
                        continue
                    heavy = bool(store.heavy[tid])
                    if heavy == (plane == 2):
                        continue   # heavy → tile planes, light → tails
                    w = float(idf[j])
                    if not heavy:
                        d, t, idx = (store.flat_docs[s:e],
                                     store.flat_tfs[s:e], None)
                    else:
                        d, t, idx = self._ragged_tile_slice(store, plan,
                                                            tid, plane, s, e)
                        if d is None:
                            continue
                    slices.append(_RaggedSlice(d, t, w, tid, s, e, idx))
                    entries += len(d)
            if entries > self.RAGGED_ENTRY_CAP:
                continue   # device plane amortizes better past the cap
            per_q.append((qi, plan, slices))
        if not per_q:
            return {}
        out: dict[int, tuple[np.ndarray, np.ndarray]] = {}
        pool_hits: dict = {}
        if posting_pool.enabled():
            pool_hits = posting_pool.POOL.score_queries(
                self, store, per_q, k, scorer, avgdl, K1, B,
                self._ragged_candidates)
        flat_d, flat_t, flat_w = [], [], []
        work = []   # (qi, spans, slice scatter ixs, device acc0, cand)
        pos = 0
        for qi, plan, slices in per_q:
            hit = pool_hits.get(qi)
            if hit is not None and hit[0] == "full":
                out[qi] = (hit[1], hit[2])
                continue
            if not slices:
                out[qi] = (np.empty(0, dtype=np.float32),
                           np.empty(0, dtype=np.int32))
                continue
            cand, ixs = self._ragged_candidates(store, plan, slices)
            if hit is not None:
                # partial residency: the device already accumulated the
                # resident slice prefix — continue from its accumulator
                acc0, n0 = hit[1], hit[2]
                use, use_ix = slices[n0:], ixs[n0:]
            else:
                acc0, use, use_ix = None, slices, ixs
            spans = []
            for sl in use:
                flat_d.append(sl.docs)
                flat_t.append(sl.tfs)
                flat_w.append(np.full(len(sl.docs), sl.w,
                                      dtype=np.float32))
                spans.append((pos, pos + len(sl.docs)))
                pos += len(sl.docs)
            work.append((qi, spans, use_ix, acc0, cand))
        if not work:
            return out
        if flat_d:
            dcat = np.concatenate(flat_d)
            contribs = bm25_ops.ragged_contribs(
                np.concatenate(flat_t), store.norms_host[dcat],
                np.concatenate(flat_w), K1, B, avgdl, scorer)
        else:
            contribs = np.empty(0, dtype=np.float32)
        for qi, spans, use_ix, acc0, cand in work:
            acc = acc0 if acc0 is not None \
                else np.zeros(len(cand), dtype=np.float32)
            for ix, (a, b) in zip(use_ix, spans):
                acc[ix] += contribs[a:b]
            out[qi] = bm25_ops.topk_tie_exact(acc, cand, k)
        return out

    @staticmethod
    def _ragged_tile_slice(store, plan, tid: int, plane: int, s: int,
                           e: int):
        """(docs, tfs, kept_positions) of one heavy term's postings
        surviving the plan's kept-row pruning on one tile plane, or
        (None, None, None). kept_positions is None when every posting
        survives (the slice IS the full term range), else the
        within-term indices of the survivors — the posting pool expands
        them into page-table gather slots. Memoized on the plan (plans
        are memoized per query shape, so repeat queries skip the mask
        arithmetic) or, plan-free, on the store; masked copies charge
        RAGGED_MEMO_BYTES_CAP. Cached arrays are read-only by
        convention — accumulation never writes through them."""
        cache = None
        if plan is not None:
            cache = getattr(plan, "_ragged_slices", None)
            if cache is None:
                cache = plan._ragged_slices = {}
        else:
            cache = getattr(store, "_ragged_plain", None)
            if cache is None:
                cache = store._ragged_plain = {}
            if len(cache) > 4096:   # vocab-sized growth bound
                cache.clear()
        hit = cache.get((plane, tid))
        if hit is not None:
            return hit
        b0 = int(store.block_offsets[tid])
        rowof = b0 + np.arange(e - s, dtype=np.int64) // bm25_ops.BLOCK
        m = store.row_plane[rowof] == plane
        if plan is not None:
            kept = plan.kept[tid]
            if len(kept) == 0:
                m = np.zeros_like(m)
            else:
                ix = np.searchsorted(kept, rowof)
                np.clip(ix, 0, len(kept) - 1, out=ix)
                m &= kept[ix] == rowof
        if not m.any():
            out = (None, None, None)
        elif m.all():
            out = (store.flat_docs[s:e], store.flat_tfs[s:e], None)
        else:
            idx = np.flatnonzero(m)
            out = (store.flat_docs[s:e][m], store.flat_tfs[s:e][m], idx)
            SegmentSearcher._ragged_memo_charge(
                store, out[0].nbytes + out[1].nbytes + idx.nbytes)
        cache[(plane, tid)] = out
        return out

    def _finish_batch(self, nodes, shapes, vals, docs, host_results, k,
                      scorer, idf_of, avgdl_override, nd_pad,
                      ) -> list[tuple[np.ndarray, np.ndarray]]:
        """Shared device-result postprocessing: host-resolved queries,
        always-empty conjunctions, zero-score matches, exact-match mask
        application (with CPU rescore when a non-match cracked the
        device top-k)."""
        out = []
        for qi, (node, (tids, req, needs_mask, empty)) in enumerate(
                zip(nodes, shapes)):
            if qi in host_results:
                scores, dd = host_results[qi]
                keep = scores > 0.0
                out.append((scores[keep][:k], dd[keep][:k]))
                continue
            scores, dd = vals[qi], docs[qi]
            if empty:
                out.append((np.empty(0, dtype=np.float32),
                            np.empty(0, dtype=np.int32)))
                continue
            if not tids:
                # no scoring terms (e.g. pure negation): matches exist but
                # all score 0 — return the first k matches with zero scores
                match = self.eval_filter(node)[:k]
                out.append((np.zeros(len(match), dtype=np.float32),
                            match.astype(np.int32)))
                continue
            if needs_mask:
                match = self.eval_filter(node)
                mset = np.zeros(nd_pad, dtype=bool)
                mset[match] = True
                ok = mset[dd]
                if (~ok[scores > 0.0]).any() and len(match) > 0:
                    # a non-match made device top-k → the survivors may not
                    # be the true top-k of the match set; exact CPU rescore
                    scores, dd = self._cpu_score(match, tids, k, scorer,
                                                 idf_of, avgdl_override)
                else:
                    scores, dd = scores[ok], dd[ok]
            keep = scores > 0.0
            scores, dd = scores[keep], dd[keep]
            out.append((scores[:k], dd[:k]))
        return out

    def cpu_topk_wand(self, tids: list[int], k: int, scorer: str = "bm25",
                      idf_of=None, avgdl_override=None,
                      require_all: int = 0) -> tuple[np.ndarray, np.ndarray]:
        """Host top-k with block-max WAND + MaxScore pruning — the honest
        CPU competitor (reference: search/block_disjunction.hpp +
        max_score_iterator; Lucene/Tantivy-class baselines implement the
        same family). Numpy-vectorized block-at-a-time variant:

        1. champion pass → θ, a lower bound on the k-th score (exact
           scoring of the best upper-bound block rows + light tails);
        2. MaxScore split: terms whose max scores cumulatively stay below
           θ are non-essential — their postings alone can't lift a doc
           into the top-k, so candidates come from essential terms only;
        3. block-max pruning: essential heavy terms drop whole 128-doc
           blocks whose own upper bound plus the OTHER terms' maxscore sum
           cannot reach θ;
        4. exact scoring of the surviving candidates over all terms.

        Exact top-k: every dropped doc is provably below θ ≤ true k-th
        score. Falls back to exhaustive scoring when no safe θ exists.
        Conjunctions (require_all=N) intersect postings first — WAND is a
        disjunction optimization (reference: conjunction.hpp is a
        separate, already-selective iterator)."""
        store = self._device_store()
        fi = self.index
        avgdl = max(avgdl_override if avgdl_override is not None
                    else fi.avgdl, 1e-9)
        if require_all > 0:
            docs = None
            for tid in tids:
                pd = fi.postings(int(tid))[0]
                docs = pd if docs is None else \
                    np.intersect1d(docs, pd, assume_unique=True)
            if docs is None:
                docs = np.empty(0, dtype=np.int32)
            return self._cpu_score(docs, tids, k, scorer, idf_of,
                                   avgdl_override)
        plan = None
        if scorer not in bm25_ops.LM_SCORERS:
            plan = self._wand_plan_cached(store, tids, min(k, max(
                self.num_docs, 1)), avgdl, scorer, idf_of)
        if plan is None:
            # no safe threshold (tiny result set / LM scorer): exhaustive
            docs = self._union_postings([int(t) for t in tids])
            return self._cpu_score(docs, tids, k, scorer, idf_of,
                                   avgdl_override)
        theta = plan.theta
        non_ess = _maxscore_split(plan)
        ess = [int(t) for t in tids if int(t) not in non_ess]
        if not ess:
            ess = [int(t) for t in tids]
        parts = []
        for tid in ess:
            if store.heavy[tid] and tid in plan.kept:
                # block-max pruning: plan.kept already dropped rows that
                # can't reach θ together with the other terms' bounds
                s = int(store.offsets[tid])
                b0 = int(store.block_offsets[tid])
                e = int(store.offsets[tid + 1])
                loc = plan.kept[tid] - b0
                if len(loc) == 0:
                    continue
                spans = [store.flat_docs[s + i * bm25_ops.BLOCK:
                                         min(s + (i + 1) * bm25_ops.BLOCK, e)]
                         for i in loc]
                parts.append(np.concatenate(spans))
            else:
                pd = fi.postings(tid)[0]
                parts.append(pd)
        cand = np.unique(np.concatenate(parts)) if parts \
            else np.empty(0, dtype=np.int32)
        scores, dd = self._cpu_score(cand, tids, k, scorer, idf_of,
                                     avgdl_override)
        keep = scores > 0.0
        return scores[keep][:k], dd[keep][:k]

    def _cpu_score(self, docs: np.ndarray, tids: list[int], k: int,
                   scorer: str = "bm25", idf_of=None,
                   avgdl_override=None) -> tuple[np.ndarray, np.ndarray]:
        scores = np.zeros(len(docs), dtype=np.float64)
        tid_arr = np.asarray(tids, dtype=np.int64)
        if idf_of is not None:
            idf = idf_of(tid_arr)
        elif scorer in bm25_ops.LM_SCORERS:
            idf = bm25_ops.term_weight_for(
                scorer, self.num_docs, None, self.index.ctf[tid_arr],
                float(self.index.total_tokens))
        else:
            idf = bm25_ops.idf_for(scorer, self.num_docs,
                                   self.index.doc_freq[tid_arr])
        dl = self.index.norms[docs].astype(np.float64)
        avgdl = max(avgdl_override if avgdl_override is not None
                    else self.index.avgdl, 1e-9)
        for qi, tid in enumerate(tids):
            pd, pt = self.index.postings(tid)
            ix = np.searchsorted(pd, docs)
            ix = np.clip(ix, 0, max(len(pd) - 1, 0))
            hit = (len(pd) > 0) & (pd[ix] == docs)
            tf = np.where(hit, pt[np.clip(ix, 0, max(len(pd) - 1, 0))],
                          0).astype(np.float64)
            w = float(idf[qi])
            if scorer == "tfidf":
                scores += w * np.sqrt(tf)
            elif scorer == "lm_dirichlet":
                mu = bm25_ops.LM_MU
                c = np.log1p(tf / (mu * w)) + np.log(mu / (dl + mu))
                scores += np.where(
                    tf > 0, np.maximum(c, 0.0) + bm25_ops.MATCH_EPS, 0.0)
            elif scorer == "jelinek_mercer":
                lam = bm25_ops.JM_LAMBDA
                scores += np.log1p(((1 - lam) * tf / np.maximum(dl, 1.0)) /
                                   (lam * w))
            elif scorer == "dfi":
                e = w * dl
                excess = (tf - e) / np.sqrt(np.maximum(e, 1e-9))
                scores += np.where(
                    tf > 0,
                    np.where(tf > e, np.log2(1.0 + excess), 0.0) +
                    bm25_ops.MATCH_EPS, 0.0)
            else:
                denom = tf + K1 * (1 - B + B * dl / avgdl)
                scores += w * (K1 + 1) * tf / np.maximum(denom, 1e-9)
        order = np.argsort(-scores, kind="stable")[:k]
        return (scores[order].astype(np.float32),
                docs[order].astype(np.int32))


def _run_segment_shards(run_segment, segments: list, cap: int) -> list:
    """Drive the per-segment collectors, one result per segment in
    SEGMENT ORDER. With `serene_shards` > 1 the segment set partitions
    round-robin into per-shard groups (exec/shard.py's partitioning
    function) and each shard's group runs as ONE pool task — the
    sharded-tier unit of work — otherwise each segment is its own task.
    Either way the caller's single-heap merge consumes the identical
    per-segment outputs, so results are bit-identical at any shard or
    worker count."""
    from ..exec import shard as shard_mod
    from ..parallel.pool import get_pool
    if cap <= 1 or len(segments) <= 1:
        return [run_segment(sb) for sb in segments]
    n_shards = shard_mod.shard_count(None)
    if n_shards > 1:
        groups = shard_mod.group_round_robin(
            list(enumerate(segments)), n_shards)

        def run_group(entries):
            return [(i, run_segment(sb)) for i, sb in entries]

        parts = shard_mod.run_shard_tasks(None, run_group, groups)
        outs: list = [None] * len(segments)
        for chunk in parts:
            for i, out in chunk:
                outs[i] = out
        return outs
    return get_pool().ensure_started().map_ordered(
        run_segment, list(segments), cap)


def merge_segment_topk(seg_outs: list, bases: list[int], n_queries: int,
                       k: int) -> list[tuple[np.ndarray, np.ndarray]]:
    """Single-heap merge of per-segment top-k collector outputs.

    seg_outs[si][qi] = (scores, local doc ids) for segment si. Ordering
    is (score desc, global doc id asc) — the doc-id tie-break makes the
    merged ranking a pure function of the data, independent of segment
    count, arrival order, or worker scheduling."""
    import heapq
    results = []
    for qi in range(n_queries):
        entries: list[tuple[float, int]] = []
        for out, base in zip(seg_outs, bases):
            sc, dd = out[qi]
            entries.extend(zip(sc.tolist(),
                               (dd.astype(np.int64) + base).tolist()))
        cand = heapq.nlargest(k, entries, key=lambda t: (t[0], -t[1]))
        results.append((
            np.asarray([c[0] for c in cand], dtype=np.float32),
            np.asarray([c[1] for c in cand], dtype=np.int64)))
    return results


def _combine_topk(seg_outs: list, bases: list[int], n_queries: int,
                  k: int) -> list[tuple[np.ndarray, np.ndarray]]:
    """Cross-segment top-k combine dispatcher: the host single-heap
    merge (the parity oracle), or — when the sharded tier is active
    with `serene_shard_combine` resolving to device — an IN-PROGRAM
    merge: each shard's candidate set reduces with an exact per-shard
    top-k inside one shard_map program and the shards meet in a single
    `all_gather` hop (exec/shard.py's round-robin segment grouping).
    Selection is a pure (score desc, doc asc) order on the candidate
    union, so both combines pick the identical entries in the identical
    order — bit-identity by construction, asserted by the
    tests/test_multichip.py parity matrix."""
    from ..exec import shard as shard_mod
    if len(seg_outs) > 1 and k > 0 and n_queries > 0:
        n_shards = shard_mod.shard_count(None)
        if n_shards > 1 and shard_mod.combine_mode(None) == "device":
            out = _device_merge_topk(seg_outs, bases, n_queries, k,
                                     n_shards)
            if out is not None:
                return out
    return merge_segment_topk(seg_outs, bases, n_queries, k)


# compiled shard_map merge programs live in the obs/device compile
# ledger keyed by (padded candidate width, padded k, padded query
# count, mesh width) — pow2 padding keeps the compile-shape population
# bounded under varied query mixes, the ledger LRU bounds it hard

#: padding doc sentinel: sorts after every real doc at equal score and
#: is trimmed host-side; real global doc ids must stay below it
_PAD_DOC = (1 << 31) - 1


def _merge_program(mesh, lp: int, kp: int, qp: int):
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import AXIS
    m_width = mesh.shape[AXIS]
    key = (lp, kp, qp, m_width)
    kcut = min(kp, lp)

    def srt(kk, dd, ss):
        return jax.lax.sort((kk, dd, ss), num_keys=2)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS, None, None), P(AXIS, None, None)),
        out_specs=(P(), P()), check_rep=False)
    def step(sc, dc):
        # per-(shard, query) exact top-k: lexicographic two-key sort on
        # (score desc, doc asc). `+ 0.0` canonicalizes -0.0 so equal
        # scores tie exactly like the host heap's float compare; the
        # original score bits travel as a passenger operand. The whole
        # query batch merges in THIS one dispatch (vmap over the query
        # axis), the many-queries-per-dispatch discipline of the
        # batched serving tier.
        keys = -(sc + 0.0)
        k2, d2, s2 = jax.vmap(jax.vmap(srt))(keys, dc, sc)
        k2, d2, s2 = (k2[:, :, :kcut], d2[:, :, :kcut], s2[:, :, :kcut])
        # ONE all_gather hop: every device sees every shard's top-k
        k2 = jax.lax.all_gather(k2, AXIS, tiled=True)
        d2 = jax.lax.all_gather(d2, AXIS, tiled=True)
        s2 = jax.lax.all_gather(s2, AXIS, tiled=True)
        # (S, Q, kcut) → per query one final exact selection
        k2 = jnp.moveaxis(k2, 0, 1).reshape(qp, -1)
        d2 = jnp.moveaxis(d2, 0, 1).reshape(qp, -1)
        s2 = jnp.moveaxis(s2, 0, 1).reshape(qp, -1)
        _, dfin, sfin = jax.vmap(srt)(k2, d2, s2)
        return sfin[:, :kp], dfin[:, :kp]

    from ..obs import device as obs_device
    return obs_device.compiled("search_merge", key, lambda: step)


def _device_merge_topk(seg_outs: list, bases: list[int], n_queries: int,
                       k: int, n_shards: int):
    """In-program sharded top-k merge — the WHOLE query batch in one
    collective dispatch (queries stack on a vmapped axis, pow2-padded);
    None → caller falls back to the host heap (doc ids past int32, NaN
    scores, degenerate grouping, no candidates at all)."""
    import time

    import jax

    from ..exec import shard as shard_mod
    from ..obs.trace import current_trace
    from ..parallel import mesh as mesh_mod
    from ..utils import metrics

    groups = shard_mod.group_round_robin(
        list(range(len(seg_outs))), n_shards)
    if len(groups) <= 1:
        return None
    # admission: every global doc id must fit below the int32 padding
    # sentinel, and scores must be NaN-free (NaN breaks the sort/heap
    # order equivalence)
    for out, base in zip(seg_outs, bases):
        for sc, dd in out:
            if len(dd) and int(np.asarray(dd).max()) + base >= _PAD_DOC:
                return None
            if len(sc) and np.isnan(np.asarray(sc)).any():
                return None
    S = len(groups)
    mesh = mesh_mod.data_mesh(S)
    m_width = mesh.shape[mesh_mod.AXIS]
    s_pad = -(-S // m_width) * m_width
    # per-(shard, query) candidate lists, one shared padded width
    cands: list[list[tuple[np.ndarray, np.ndarray]]] = []
    lmax = 0
    for idxs in groups:
        row = []
        for qi in range(n_queries):
            sc = np.concatenate(
                [np.asarray(seg_outs[si][qi][0], dtype=np.float32)
                 for si in idxs])
            dd = np.concatenate(
                [np.asarray(seg_outs[si][qi][1]).astype(np.int64) +
                 bases[si] for si in idxs])
            lmax = max(lmax, len(sc))
            row.append((sc, dd))
        cands.append(row)
    if lmax == 0:
        return [(np.empty(0, dtype=np.float32),
                 np.empty(0, dtype=np.int64))] * n_queries
    lp = 1 << (lmax - 1).bit_length()
    kp = 1 << (max(k, 1) - 1).bit_length()
    qp = 1 << (max(n_queries, 1) - 1).bit_length()
    scores = np.full((s_pad, qp, lp), -np.inf, dtype=np.float32)
    docs = np.full((s_pad, qp, lp), _PAD_DOC, dtype=np.int32)
    for i, row in enumerate(cands):
        for qi, (sc, dd) in enumerate(row):
            scores[i, qi, :len(sc)] = sc
            docs[i, qi, :len(dd)] = dd.astype(np.int32)
    jitted = _merge_program(mesh, lp, kp, qp)
    sh = mesh_mod.data_sharding(mesh, 3)
    t_d = time.perf_counter_ns()
    metrics.DEVICE_OFFLOADS.add()
    metrics.COLLECTIVE_DISPATCHES.add()
    from ..obs import device as obs_device
    from ..obs.resources import wait_scope
    with wait_scope("Device", "CollectiveCombine"):
        # the candidate planes bypass DEVICE_CACHE (per-dispatch data):
        # commit() keeps their transfer bytes in the device ledger
        ss, dd2 = obs_device.fetch_all(
            jitted(obs_device.commit(scores, sh),
                   obs_device.commit(docs, sh)))
    dt = time.perf_counter_ns() - t_d
    metrics.COLLECTIVE_COMBINE_NS.add(dt)
    metrics.DEVICE_DISPATCH_HIST.observe_ns(dt)
    trace = current_trace()
    if trace is not None:
        trace.add("collective_dispatch", "device", t_d,
                  time.perf_counter_ns(), shards=S, op="topk_merge",
                  queries=n_queries)
    results = []
    for qi in range(n_queries):
        sq, dq = ss[qi][:k], dd2[qi][:k]
        real = dq != _PAD_DOC
        results.append((sq[real].astype(np.float32),
                        dq[real].astype(np.int64)))
    return results


class MultiSearcher:
    """Searches across immutable segments of one column (reference:
    DirectoryReader over segment readers, SURVEY.md §2.7). Doc ids are
    global row indices (segment base + local id); scoring uses GLOBAL
    collection statistics so multi-segment scores equal a single-segment
    build of the same data."""

    def __init__(self, analyzer: Analyzer):
        self.analyzer = analyzer
        self.segments: list[tuple[SegmentSearcher, int]] = []  # (seg, base)

    def add_segment(self, searcher: SegmentSearcher, base_row: int):
        self.segments.append((searcher, base_row))

    @property
    def num_docs(self) -> int:
        return sum(s.num_docs for s, _ in self.segments)

    @property
    def global_avgdl(self) -> float:
        total_tokens = sum(s.index.total_tokens for s, _ in self.segments)
        n = self.num_docs
        return (total_tokens / n) if n else 0.0

    def _global_df(self, term: str) -> int:
        df = 0
        for s, _ in self.segments:
            tid = s.index.term_id(term)
            if tid >= 0:
                df += int(s.index.doc_freq[tid])
        return df

    def _global_ctf(self, term: str) -> int:
        ctf = 0
        for s, _ in self.segments:
            tid = s.index.term_id(term)
            if tid >= 0:
                ctf += int(s.index.ctf[tid])
        return ctf

    def eval_filter(self, node: QNode) -> np.ndarray:
        parts = []
        for s, base in self.segments:
            local = s.eval_filter(node)
            if len(local):
                parts.append(local.astype(np.int64) + base)
        return np.concatenate(parts).astype(np.int64) if parts \
            else np.empty(0, dtype=np.int64)

    def topk(self, node: QNode, k: int, scorer: str = "bm25",
             mesh_n: int = 0) -> tuple[np.ndarray, np.ndarray]:
        return self.topk_batch([node], k, scorer, mesh_n=mesh_n)[0]

    def topk_batch(self, nodes: list[QNode], k: int, scorer: str = "bm25",
                   mesh_n: int = 0, ragged: bool = False,
                   ) -> list[tuple[np.ndarray, np.ndarray]]:
        """Fragments memoize PER QUERY (cache/fragments.cached_batch): a
        coalesced batch probes each member's own (sig, k, scorer) key, the
        misses score together in one segment dispatch, and each result
        stores back under its own key — so a fragment computed inside any
        batch serves the same query arriving alone later and vice versa
        (sound because per-query results are batch-composition-independent,
        the serving parity contract). `ragged` never keys a fragment: the
        ragged host path is bit-identical to the device dispatch by
        construction, same reason serene_search_batch stays out of the
        result cache's settings digest."""
        from ..cache.fragments import FRAGMENTS, qnode_sig
        sigs = [qnode_sig(n) for n in nodes]
        if len(self.segments) == 1:
            seg, base = self.segments[0]
            # single segment: local stats ARE the global stats — the
            # fragment is a pure function of the segment alone
            shapes = [None if s is None else ("topk1", s, k, scorer, mesh_n)
                      for s in sigs]
            out = FRAGMENTS.cached_batch(
                seg, shapes,
                lambda idxs: seg.topk_batch([nodes[i] for i in idxs], k,
                                            scorer, mesh_n=mesh_n,
                                            ragged=ragged))
            return [(s, d.astype(np.int64) + base) for s, d in out]
        idf_factory = self._segment_idf_factory(nodes, scorer)
        avgdl = self.global_avgdl
        # a segment's scored output depends on GLOBAL collection stats
        # (idf/avgdl span every segment), which are a pure function of
        # the segment SET — key the whole membership, so an append
        # recomputes scores exactly as correctness requires while
        # filter fragments (above) survive it
        segset = tuple(FRAGMENTS.segment_uid(s) for s, _ in self.segments)

        def run_segment(seg_base):
            seg, _base = seg_base
            shapes = [None if s is None else ("topk", s, k, scorer, mesh_n,
                                              segset) for s in sigs]
            return FRAGMENTS.cached_batch(
                seg, shapes,
                lambda idxs: seg.topk_batch([nodes[i] for i in idxs], k,
                                            scorer,
                                            idf_of=idf_factory(seg),
                                            avgdl_override=avgdl,
                                            mesh_n=mesh_n, ragged=ragged))

        # segments are independent top-k collectors: search them on the
        # shared worker pool (reference: parallel scored collectors over
        # the search thread pool). With a device mesh active the mesh IS
        # the parallelism — keep the segment loop serial then.
        from ..parallel.pool import get_pool, session_workers
        cap = 1 if mesh_n > 1 else session_workers(None)
        seg_outs = _run_segment_shards(run_segment, self.segments, cap)
        return _combine_topk(seg_outs,
                             [b for _, b in self.segments],
                             len(nodes), k)

    def probe_topk(self, node: QNode, k: int, scorer: str = "bm25",
                   mesh_n: int = 0,
                   ) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """Pure fragment-cache probe: the merged top-k iff EVERY segment's
        fragment for this query is already cached, else None — no scoring,
        no stores. The batcher consults this BEFORE enqueueing so cache
        hits never wait out a coalescing window or occupy a batch slot.
        Hit gauges bump only on full success; partial probes stay silent
        (the batch dispatch re-probes those segments and counts them
        once)."""
        from ..cache.fragments import FRAGMENTS, enabled, qnode_sig
        if not enabled() or not self.segments:
            return None
        sig = qnode_sig(node)
        if sig is None:
            return None
        if len(self.segments) == 1:
            seg, base = self.segments[0]
            hit = FRAGMENTS.probe(seg, ("topk1", sig, k, scorer, mesh_n))
            if hit is None:
                return None
            FRAGMENTS.count_hits(1)
            s, d = hit
            return s, d.astype(np.int64) + base
        segset = tuple(FRAGMENTS.segment_uid(s) for s, _ in self.segments)
        outs = []
        for seg, _base in self.segments:
            hit = FRAGMENTS.probe(seg, ("topk", sig, k, scorer, mesh_n,
                                        segset))
            if hit is None:
                return None
            outs.append([hit])
        FRAGMENTS.count_hits(len(self.segments))
        return merge_segment_topk(outs, [b for _, b in self.segments],
                                  1, k)[0]

    def _segment_idf_factory(self, nodes: list[QNode], scorer: str):
        """seg → idf_of closure over GLOBAL collection stats. One pass:
        global df per query term STRING (terms have different ids per
        segment), shared by every segment's closure."""
        n_total = max(self.num_docs, 1)
        term_strings: set[str] = set()
        for node in nodes:
            for seg, _ in self.segments:
                ts = seg.index.terms_str
                term_strings.update(str(ts[t])
                                    for t in seg.scoring_terms(node))
        global_df = {s: self._global_df(s) for s in term_strings}
        lm = scorer in bm25_ops.LM_SCORERS
        global_ctf = ({s: self._global_ctf(s) for s in term_strings}
                      if lm else {})
        total_tokens = (float(sum(s.index.total_tokens
                                  for s, _ in self.segments)) if lm else 0.0)

        def factory(seg):
            terms_str = seg.index.terms_str

            def idf_of(tids, _ts=terms_str):
                if lm:
                    ctfs = np.asarray(
                        [global_ctf[str(_ts[t])] for t in tids],
                        dtype=np.int64)
                    return bm25_ops.term_weight_for(
                        scorer, n_total, None, ctfs, total_tokens)
                dfs = np.asarray([global_df[str(_ts[t])] for t in tids],
                                 dtype=np.int64)
                return bm25_ops.idf_for(scorer, n_total, dfs)

            return idf_of
        return factory

    def cpu_topk(self, node: QNode, k: int, scorer: str = "bm25",
                 ) -> tuple[np.ndarray, np.ndarray]:
        """Host-only top-k: block-max WAND per segment on the worker
        pool, merged by one heap — the multi-segment analog of
        SegmentSearcher.cpu_topk_wand (reference: ScanMode::TopK parallel
        scored collectors). Exact-match-mask shapes score their match set
        directly; pure negations return zero-scored matches."""
        idf_factory = self._segment_idf_factory([node], scorer)
        avgdl = self.global_avgdl
        from ..cache.fragments import FRAGMENTS, qnode_sig
        sig = qnode_sig(node)
        segset = tuple(FRAGMENTS.segment_uid(s) for s, _ in self.segments)

        def run_segment(seg_base):
            seg, _base = seg_base

            def compute():
                idf_of = idf_factory(seg)
                tids, req, needs_mask, empty = seg._query_shape(node)
                if empty:
                    return (np.empty(0, dtype=np.float32),
                            np.empty(0, dtype=np.int32))
                if not tids:
                    match = seg.eval_filter(node)[:k]
                    return (np.zeros(len(match), dtype=np.float32),
                            match.astype(np.int32))
                if needs_mask:
                    match = seg.eval_filter(node)
                    sc, dd = seg._cpu_score(match, tids, k, scorer,
                                            idf_of, avgdl)
                    keep = sc > 0.0
                    return (sc[keep][:k], dd[keep][:k])
                return seg.cpu_topk_wand(tids, k, scorer, idf_of=idf_of,
                                         avgdl_override=avgdl,
                                         require_all=req)

            shape = None if sig is None else ("wand", sig, k, scorer,
                                              segset)
            return FRAGMENTS.cached(seg, shape, compute)

        from ..parallel.pool import session_workers
        cap = session_workers(None)
        outs = _run_segment_shards(run_segment, self.segments, cap)
        return _combine_topk([[o] for o in outs],
                             [b for _, b in self.segments], 1, k)[0]


@dataclass
class SearchIndex:
    """A built index over one or more text columns of a table provider.
    Each column holds a MultiSearcher over immutable segments; appends add
    segments (incremental refresh), row mutations force full rebuilds."""

    columns: list[str]
    using: str
    options: dict
    analyzer_name: str
    searchers: dict[str, MultiSearcher]   # column → multi-segment searcher
    data_version: int
    mutation_epoch: int = 0
    indexed_rows: int = 0

    def searcher(self, column: str) -> Optional[MultiSearcher]:
        return self.searchers.get(column)

    def analyzer_name_for(self, column: str) -> str:
        """The column's own tokenizer (multi-column indexes may configure
        one per column — reference: USING inverted(text imdb_en, label))."""
        col_toks = (self.options or {}).get("column_tokenizers", {}) or {}
        return col_toks.get(column, self.analyzer_name)
