"""Linear-time regular expression matching (Thompson NFA simulation).

Reference analog: the by_regexp filter's automaton over the term dictionary
(libs/iresearch/search/regexp_filter — backed by a linear-time DFA/NFA, not
a backtracking engine). User-supplied patterns run against every term in
the dictionary, so matching must be O(len(term) * states): a backtracking
engine (Python `re`) would allow catastrophic-backtracking DoS via patterns
like `(a+)+c`.

Supported syntax (Lucene-regexp-lite): literals, `.`, `[...]` classes with
ranges and `^` negation, `\\d \\w \\s` (+ uppercase complements), `\\x`
literal escapes, `* + ?` and `{m}`/`{m,}`/`{m,n}` quantifiers, `|`
alternation, `(...)` grouping, and `^`/`$` start/end assertions
(zero-width, per alternation branch — PG semantics). Matching is
fullmatch; the SQL `~` operators wrap patterns in `(.|\n)*` for
unanchored search, which composes with the assertions.
"""

from __future__ import annotations

MAX_STATES = 10_000
MAX_REPEAT = 256

_CLASS_SHORTHAND = {
    "d": [("0", "9")],
    "w": [("a", "z"), ("A", "Z"), ("0", "9"), ("_", "_")],
    "s": [(" ", " "), ("\t", "\t"), ("\n", "\n"), ("\r", "\r"),
          ("\f", "\f"), ("\v", "\v")],
}


class RegexpError(ValueError):
    pass


# -- pattern AST ------------------------------------------------------------

class _Alt:
    def __init__(self, branches):
        self.branches = branches        # list of lists of (atom, lo, hi)


class _Char:
    def __init__(self, c):
        self.c = c


class _Dot:
    pass


class _Class:
    def __init__(self, ranges, negated):
        self.ranges = ranges            # list of (lo_char, hi_char)
        self.negated = negated


class _Assert:
    def __init__(self, kind):
        self.kind = kind                # "start" | "end"


class _Parser:
    def __init__(self, pat: str):
        self.pat = pat
        self.i = 0

    def error(self, msg: str):
        raise RegexpError(f"{msg} at position {self.i}")

    def peek(self):
        return self.pat[self.i] if self.i < len(self.pat) else None

    def parse(self) -> _Alt:
        node = self.parse_alt()
        if self.peek() is not None:
            self.error(f"unexpected {self.peek()!r}")
        return node

    def parse_alt(self) -> _Alt:
        branches = [self.parse_concat()]
        while self.peek() == "|":
            self.i += 1
            branches.append(self.parse_concat())
        return _Alt(branches)

    def parse_concat(self) -> list:
        out = []
        while True:
            c = self.peek()
            if c is None or c in "|)":
                return out
            atom = self.parse_atom()
            lo, hi = self.parse_quantifier()
            out.append((atom, lo, hi))

    def parse_atom(self):
        c = self.peek()
        if c == "(":
            self.i += 1
            inner = self.parse_alt()
            if self.peek() != ")":
                self.error("missing closing parenthesis")
            self.i += 1
            return inner
        if c == "[":
            return self.parse_class()
        if c == ".":
            self.i += 1
            return _Dot()
        if c == "\\":
            self.i += 1
            e = self.peek()
            if e is None:
                self.error("trailing backslash")
            self.i += 1
            if e.lower() in _CLASS_SHORTHAND:
                return _Class(_CLASS_SHORTHAND[e.lower()], e.isupper())
            return _Char(e)
        if c in "*+?{":
            self.error(f"quantifier {c!r} with nothing to repeat")
        if c == "^":
            self.i += 1
            return _Assert("start")
        if c == "$":
            self.i += 1
            return _Assert("end")
        self.i += 1
        return _Char(c)

    def parse_class(self) -> _Class:
        self.i += 1                     # consume '['
        negated = False
        if self.peek() == "^":
            negated = True
            self.i += 1
        ranges = []
        first = True
        while True:
            c = self.peek()
            if c is None:
                self.error("unterminated character set")
            if c == "]" and not first:
                self.i += 1
                return _Class(ranges, negated)
            first = False
            if c == "\\":
                self.i += 1
                e = self.peek()
                if e is None:
                    self.error("trailing backslash in character set")
                self.i += 1
                if e.lower() in _CLASS_SHORTHAND:
                    if e.isupper():
                        self.error("negated shorthand in character set")
                    ranges.extend(_CLASS_SHORTHAND[e.lower()])
                    continue
                c = e
            else:
                self.i += 1
            if self.peek() == "-" and self.i + 1 < len(self.pat) and \
                    self.pat[self.i + 1] != "]":
                self.i += 1
                hi = self.peek()
                if hi == "\\":
                    self.i += 1
                    hi = self.peek()
                if hi is None:
                    self.error("unterminated range")
                self.i += 1
                if hi < c:
                    self.error(f"bad character range {c}-{hi}")
                ranges.append((c, hi))
            else:
                ranges.append((c, c))

    def parse_quantifier(self) -> tuple[int, int]:
        """(lo, hi); hi = -1 means unbounded. Default (1, 1)."""
        c = self.peek()
        if c == "*":
            self.i += 1
            return 0, -1
        if c == "+":
            self.i += 1
            return 1, -1
        if c == "?":
            self.i += 1
            return 0, 1
        if c == "{":
            start = self.i
            self.i += 1
            digits = ""
            while self.peek() and self.peek().isdigit():
                digits += self.peek()
                self.i += 1
            if not digits:
                self.error("bad repetition count")
            lo = int(digits)
            hi = lo
            if self.peek() == ",":
                self.i += 1
                digits = ""
                while self.peek() and self.peek().isdigit():
                    digits += self.peek()
                    self.i += 1
                hi = int(digits) if digits else -1
            if self.peek() != "}":
                self.i = start
                self.error("unterminated repetition")
            self.i += 1
            if hi != -1 and hi < lo:
                self.i = start
                self.error(f"bad repetition range {{{lo},{hi}}}")
            if lo > MAX_REPEAT or hi > MAX_REPEAT:
                self.i = start
                self.error(f"repetition count over {MAX_REPEAT}")
            return lo, hi
        return 1, 1


# -- NFA construction (epsilon transitions; start/end per fragment) ---------

class _State:
    __slots__ = ("eps", "edges", "asserts")

    def __init__(self):
        self.eps = []                   # epsilon-reachable states
        self.edges = []                 # (matcher_atom, target)
        self.asserts = []               # (kind, target) zero-width


class Regexp:
    """Compiled pattern. `fullmatch(s)` is O(len(s) * states).

    case_fold: lowercase literal atoms and plain class ranges so patterns
    behave like analyzer-folded bare terms (`/Alpha.*/` matches the stored
    term `alpha…` under a lowercasing analyzer). Negated classes and
    shorthand escapes are left verbatim — folding them would change their
    meaning."""

    def __init__(self, pattern: str, case_fold: bool = False):
        self.pattern = pattern
        ast = _Parser(pattern).parse()
        if case_fold:
            _fold_ast(ast)
        self._n_states = 0
        self.start, self.end = self._build_alt(ast)

    def _new_state(self) -> _State:
        self._n_states += 1
        if self._n_states > MAX_STATES:
            raise RegexpError("pattern too large")
        return _State()

    def _build_alt(self, node: _Alt) -> tuple[_State, _State]:
        s, e = self._new_state(), self._new_state()
        for branch in node.branches:
            bs, be = self._build_concat(branch)
            s.eps.append(bs)
            be.eps.append(e)
        return s, e

    def _build_concat(self, factors: list) -> tuple[_State, _State]:
        s = self._new_state()
        cur = s
        for atom, lo, hi in factors:
            fs, fe = self._build_repeat(atom, lo, hi)
            cur.eps.append(fs)
            cur = fe
        return s, cur

    def _build_repeat(self, atom, lo: int, hi: int) -> tuple[_State, _State]:
        s = self._new_state()
        cur = s
        for _ in range(lo):             # mandatory copies
            fs, fe = self._build_atom(atom)
            cur.eps.append(fs)
            cur = fe
        if hi == -1:                    # star over one more copy
            fs, fe = self._build_atom(atom)
            cur.eps.append(fs)
            fe.eps.append(fs)
            end = self._new_state()
            cur.eps.append(end)
            fe.eps.append(end)
            return s, end
        end = self._new_state()
        for _ in range(hi - lo):        # optional copies
            fs, fe = self._build_atom(atom)
            cur.eps.append(fs)
            cur.eps.append(end)
            cur = fe
        cur.eps.append(end)
        return s, end

    def _build_atom(self, atom) -> tuple[_State, _State]:
        if isinstance(atom, _Alt):
            return self._build_alt(atom)
        s, e = self._new_state(), self._new_state()
        if isinstance(atom, _Assert):
            s.asserts.append((atom.kind, e))
        else:
            s.edges.append((atom, e))
        return s, e

    @staticmethod
    def _atom_matches(atom, ch: str) -> bool:
        if isinstance(atom, _Char):
            return ch == atom.c
        if isinstance(atom, _Dot):
            return True
        hit = any(lo <= ch <= hi for lo, hi in atom.ranges)
        return hit != atom.negated

    @staticmethod
    def _closure(states: set, at_start: bool, at_end: bool) -> set:
        """Epsilon closure; assertion edges traverse only when the
        current position satisfies them (zero-width, linear time)."""
        out = set(states)
        stack = list(states)
        while stack:
            st = stack.pop()
            for nxt in st.eps:
                if nxt not in out:
                    out.add(nxt)
                    stack.append(nxt)
            for kind, nxt in st.asserts:
                ok = at_start if kind == "start" else at_end
                if ok and nxt not in out:
                    out.add(nxt)
                    stack.append(nxt)
        return out

    def fullmatch(self, s: str) -> bool:
        return nfa_fullmatch(self.start, self.end, s)


def _fold_ast(node):
    if isinstance(node, _Alt):
        for branch in node.branches:
            for atom, _, _ in branch:
                _fold_ast(atom)
    elif isinstance(node, _Char):
        node.c = node.c.lower()
    elif isinstance(node, _Class) and not node.negated:
        extra = [(lo.lower(), hi.lower()) for lo, hi in node.ranges
                 if (lo.lower(), hi.lower()) != (lo, hi)
                 and lo.lower() <= hi.lower()]
        node.ranges.extend(extra)



def nfa_fullmatch(start: _State, end: _State, s: str) -> bool:
    """Match a whole string against an NFA fragment — shared by
    Regexp.fullmatch and the automaton module's budget fallback so the
    two can never disagree."""
    n = len(s)
    cur = Regexp._closure({start}, True, n == 0)
    for i, ch in enumerate(s):
        nxt = {t for st in cur for atom, t in st.edges
               if Regexp._atom_matches(atom, ch)}
        if not nxt:
            return False
        cur = Regexp._closure(nxt, False, i + 1 == n)
    return end in cur


def compile_regexp(pattern: str, case_fold: bool = False) -> Regexp:
    return Regexp(pattern, case_fold)
