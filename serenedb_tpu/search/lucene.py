"""Full Lucene query-string parser (ES `query_string` surface).

Reference analog: libs/iresearch/include/iresearch/parser/lucene_parser
— the reference parses the full Lucene syntax into its filter tree. Here
the same grammar parses into a small AST that the ES layer lowers to SQL
(text leaves become `field @@ '<engine query>'` claims against the
inverted index; ranges become SQL comparisons; boosts weight the score
expression).

Grammar (Lucene classic query parser):

    query     := or_expr
    or_expr   := and_expr (('OR' | '||') and_expr)*
    and_expr  := clause (('AND' | '&&') clause)*     -- adjacency uses the
                                                        default operator
    clause    := ('+' | '-' | 'NOT' | '!')? primary ('^' NUMBER)?
    primary   := '(' query ')'
               | FIELD ':' primary                    -- field override,
                                                        incl. field groups
               | '"' ... '"' ('~' INT)?               -- phrase [slop]
               | ('[' | '{') val 'TO' val (']' | '}') -- range
               | '/' regex '/'
               | WORD ('~' INT?)?                     -- term [fuzzy]
                 (WORD may contain * and ? wildcards)

`+`/`-` occur semantics follow ES: within one boolean list, if any
required (+) clause exists, plain clauses become optional (scoring-only)
and do not constrain matching; prohibited (-) clauses always exclude.
Escapes: backslash before any special character makes it literal.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as dc_field
from typing import Optional

from .. import errors

__all__ = ["parse_lucene", "LuceneError", "LTerm", "LPhrase", "LRange",
           "LRegex", "LBool", "LMatchAll"]


class LuceneError(errors.SqlError):
    def __init__(self, msg: str):
        super().__init__(errors.SYNTAX_ERROR,
                         f"query_string parse error: {msg}")


# ------------------------------------------------------------------- AST

@dataclass
class LTerm:
    """Single word; may carry * / ? wildcards; fuzzy > 0 means `~N`."""
    text: str
    field: Optional[str] = None
    boost: float = 1.0
    fuzzy: int = 0


@dataclass
class LPhrase:
    text: str
    field: Optional[str] = None
    boost: float = 1.0
    slop: int = 0


@dataclass
class LRange:
    lo: Optional[str]            # None = unbounded (`*`)
    hi: Optional[str]
    incl_lo: bool
    incl_hi: bool
    field: Optional[str] = None
    boost: float = 1.0


@dataclass
class LRegex:
    pattern: str
    field: Optional[str] = None
    boost: float = 1.0


@dataclass
class LMatchAll:
    """Bare `*` is match-all; `field:*` is an existence check (ES exists
    query), recorded via `field`."""
    boost: float = 1.0
    field: Optional[str] = None


@dataclass
class LBool:
    """`occur` runs parallel to `clauses`: '+' must, '-' must_not,
    '' should."""
    clauses: list = dc_field(default_factory=list)
    occur: list = dc_field(default_factory=list)

    def add(self, clause, occ: str) -> None:
        self.clauses.append(clause)
        self.occur.append(occ)


# ----------------------------------------------------------------- lexer

_SPECIAL = set('+-!(){}[]^"~*?:\\/')

_TOK_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<and>AND\b|&&)
  | (?P<or>OR\b|\|\|)
  | (?P<not>NOT\b)
  | (?P<plus>\+)
  | (?P<minus>-)
  | (?P<bang>!)
  | (?P<lp>\()
  | (?P<rp>\))
  | (?P<lb>\[)
  | (?P<lc>\{)
  | (?P<rb>\])
  | (?P<rc>\})
  | (?P<caret>\^)
  | (?P<tilde>~)
  | (?P<colon>:)
  | (?P<quote>"(?:\\.|[^"\\])*"?)
  | (?P<regex>/(?:\\.|[^/\\])*/?)
  | (?P<word>(?:\\.|[^\s+\-!(){}\[\]^"~:\\/])
             (?:\\.|[^\s!(){}\[\]^"~:\\/])*)
""", re.VERBOSE)
# word: '+'/'-' are operators only at clause start — inside a word
# ("state-of-the-art", "2020-01-01", "C++") they are literal, so the
# continuation class re-admits them.


@dataclass
class _Tok:
    kind: str
    text: str


def _lex(q: str) -> list[_Tok]:
    out: list[_Tok] = []
    i = 0
    while i < len(q):
        m = _TOK_RE.match(q, i)
        if m is None:
            raise LuceneError(f"unexpected character {q[i]!r} at {i}")
        i = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append(_Tok(kind, m.group()))
    return out


def _unescape(s: str) -> str:
    return re.sub(r"\\(.)", r"\1", s)


# ---------------------------------------------------------------- parser

class _Parser:
    def __init__(self, toks: list[_Tok], default_operator: str):
        self.toks = toks
        self.i = 0
        self.default_and = default_operator.upper() == "AND"

    def peek(self) -> Optional[_Tok]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> _Tok:
        t = self.peek()
        if t is None:
            raise LuceneError("unexpected end of query")
        self.i += 1
        return t

    # query := or_expr
    def parse(self):
        if not self.toks:
            return LMatchAll()
        node = self.parse_or()
        if self.peek() is not None:
            raise LuceneError(f"unexpected {self.peek().text!r}")
        return node

    def parse_or(self):
        parts = [self.parse_and()]
        while self.peek() is not None and self.peek().kind == "or":
            self.next()
            parts.append(self.parse_and())
        if len(parts) == 1:
            return parts[0]
        b = LBool()
        for p in parts:
            b.add(p, "")
        return b

    def parse_and(self):
        """A run of clauses joined by AND/&& or adjacency (default op)."""
        clauses: list[tuple[object, str, bool]] = []  # (node, occ, and_join)
        first = True
        while True:
            t = self.peek()
            if t is None or t.kind in ("or", "rp"):
                break
            and_join = False
            if t.kind == "and":
                self.next()
                and_join = True
                t = self.peek()
                if t is None or t.kind in ("or", "rp"):
                    raise LuceneError("dangling AND")
            node, occ = self.parse_clause()
            clauses.append((node, occ, and_join and not first))
            first = False
        if not clauses:
            raise LuceneError("empty clause list")
        if len(clauses) == 1 and clauses[0][1] == "":
            return clauses[0][0]
        b = LBool()
        for node, occ, and_join in clauses:
            if occ == "":
                # explicit AND joins force must on both sides; adjacency
                # uses the default operator
                occ = "+" if (and_join or self.default_and) else ""
            b.add(node, occ)
        # Lucene: `a AND b` makes BOTH sides required — patch the clause
        # preceding an and_join
        for k, (node, occ, and_join) in enumerate(clauses):
            if and_join and k > 0 and b.occur[k - 1] == "":
                b.occur[k - 1] = "+"
        return b

    def parse_clause(self):
        occ = ""
        t = self.peek()
        if t is not None and t.kind in ("plus", "minus", "not", "bang"):
            self.next()
            occ = "+" if t.kind == "plus" else "-"
        node = self.parse_primary()
        # boost
        t = self.peek()
        if t is not None and t.kind == "caret":
            self.next()
            w = self.next()
            try:
                boost = float(w.text)
            except ValueError:
                raise LuceneError(f"bad boost {w.text!r}")
            _set_boost(node, boost)
        return node, occ

    def parse_primary(self):
        t = self.next()
        if t.kind == "lp":
            node = self.parse_or()
            if self.peek() is None or self.peek().kind != "rp":
                raise LuceneError("missing ')'")
            self.next()
            return node
        if t.kind == "word":
            # field:primary ?
            nxt = self.peek()
            if nxt is not None and nxt.kind == "colon":
                self.next()
                field = _unescape(t.text)
                sub = self.parse_primary()
                _set_field(sub, field)
                return sub
            return self._word_term(t.text)
        if t.kind == "quote":
            body = t.text[1:]
            if body.endswith('"'):
                body = body[:-1]
            node = LPhrase(_unescape(body))
            nxt = self.peek()
            if nxt is not None and nxt.kind == "tilde":
                self.next()
                n = self._fuzz_number()
                # bare `"..."~` defaults like Lucene; floats truncate
                node.slop = 2 if n is None else int(n)
            return node
        if t.kind == "regex":
            body = t.text[1:]
            if body.endswith("/"):
                body = body[:-1]
            return LRegex(body)
        if t.kind in ("lb", "lc"):
            return self._range(incl_lo=(t.kind == "lb"))
        if t.kind == "minus":
            # a bare interior '-' (e.g. `a - b`) — treat as a literal term
            return self._word_term("-")
        raise LuceneError(f"unexpected {t.text!r}")

    def _fuzz_number(self) -> Optional[float]:
        """Consume a numeric token after '~' (int or legacy float
        fuzziness like 0.8) if present."""
        w = self.peek()
        if w is not None and w.kind == "word" and \
                re.fullmatch(r"\d+(\.\d+)?", w.text):
            self.next()
            return float(w.text)
        return None

    def _word_term(self, raw: str):
        nxt = self.peek()
        fuzzy = 0
        if nxt is not None and nxt.kind == "tilde":
            self.next()
            n = self._fuzz_number()
            if n is None:
                fuzzy = 1
            elif n < 1:      # legacy float similarity (0..1) — AUTO-ish
                fuzzy = 1
            else:
                fuzzy = max(1, min(int(n), 2))
        text = _unescape(raw)
        if text == "*" and fuzzy == 0:
            return LMatchAll()
        return LTerm(text, fuzzy=fuzzy)

    def _range(self, incl_lo: bool):
        def val() -> Optional[str]:
            t = self.next()
            if t.kind == "quote":
                body = t.text[1:]
                return _unescape(body[:-1] if body.endswith('"') else body)
            if t.kind == "word":
                v = _unescape(t.text)
                return None if v == "*" else v
            if t.kind == "minus":      # negative numbers: [-5 TO 5]
                w = self.next()
                if w.kind != "word":
                    raise LuceneError("bad range endpoint")
                return "-" + _unescape(w.text)
            raise LuceneError(f"bad range endpoint {t.text!r}")

        lo = val()
        to = self.next()
        if not (to.kind == "word" and to.text.upper() == "TO"):
            raise LuceneError("range must use 'TO'")
        hi = val()
        closer = self.next()
        if closer.kind not in ("rb", "rc"):
            raise LuceneError("unterminated range")
        return LRange(lo, hi, incl_lo, closer.kind == "rb")


def _set_field(node, field: str) -> None:
    if isinstance(node, LBool):
        for c in node.clauses:
            _set_field(c, field)
    elif node.field is None:
        node.field = field


def _set_boost(node, boost: float) -> None:
    if isinstance(node, LBool):
        for c in node.clauses:
            _set_boost(c, boost)
    else:
        node.boost = boost


def parse_lucene(q: str, default_operator: str = "OR"):
    """Parse a Lucene query string into the L* AST."""
    return _Parser(_lex(q), default_operator).parse()


# ------------------------------------------------- lowering to SQL text

def _engine_escape_term(t: str) -> str:
    """A Lucene word (may contain * / ? wildcards) → a token the engine
    query parser (query.parse_query) understands. Engine metacharacters
    inside the word are dropped to spaces (they cannot appear in analyzed
    terms anyway)."""
    return re.sub(r'[&|!()"/~]', " ", t).strip()


def _sqlq(s: str) -> str:
    return "'" + s.replace("'", "''") + "'"


def lower_to_sql(node, default_field: str, quote_ident) -> tuple[str, list]:
    """AST → (SQL boolean expression,
             [(field, boost, predicate_sql), ...] score claims).

    Text leaves lower to `field @@ '<engine query>'`; ranges to SQL
    comparisons (numeric when both endpoints parse as numbers). The
    claims list carries each scoring text leaf's field, boost and its
    own predicate SQL, so the caller can build either a single score
    expression (one field) or per-field scored passes (multi-field).
    must_not leaves never claim (ES: prohibited clauses don't score)."""
    claims: list[tuple[str, float, str]] = []

    def fld(n) -> str:
        return n.field if getattr(n, "field", None) else default_field

    def num(v: Optional[str]) -> Optional[float]:
        if v is None:
            return None
        try:
            return float(v)
        except ValueError:
            return None

    def rec(n, scoring: bool = True) -> str:
        def claim(f: str, boost: float, pred: str) -> str:
            # must_not clauses never contribute to scoring (ES occur
            # semantics), so their fields stay out of the claims list
            if scoring:
                claims.append((f, boost, pred))
            return pred

        if isinstance(n, LMatchAll):
            if n.field is not None:       # field:* = exists (ES)
                return f"{quote_ident(n.field)} IS NOT NULL"
            return "TRUE"
        if isinstance(n, LTerm):
            f = fld(n)
            term = _engine_escape_term(n.text)
            if not term:
                return "TRUE"
            if n.fuzzy and not ("*" in term or "?" in term):
                # fuzzy cannot combine with wildcards (ES drops it too)
                term = f"{term}~{n.fuzzy}"
            return claim(f, n.boost, f"{quote_ident(f)} @@ {_sqlq(term)}")
        if isinstance(n, LPhrase):
            f = fld(n)
            body = n.text.replace('"', " ")
            q = f'"{body}"' + (f"~{n.slop}" if n.slop else "")
            return claim(f, n.boost, f"{quote_ident(f)} @@ {_sqlq(q)}")
        if isinstance(n, LRegex):
            f = fld(n)
            return claim(f, n.boost,
                         f"{quote_ident(f)} @@ {_sqlq('/' + n.pattern + '/')}")
        if isinstance(n, LRange):
            f = quote_ident(fld(n))
            parts = []
            lo_n, hi_n = num(n.lo), num(n.hi)
            numeric = (n.lo is None or lo_n is not None) and \
                      (n.hi is None or hi_n is not None) and \
                      not (n.lo is None and n.hi is None)
            if n.lo is not None:
                lit = repr(lo_n) if numeric else _sqlq(n.lo)
                parts.append(f"{f} >{'=' if n.incl_lo else ''} {lit}")
            if n.hi is not None:
                lit = repr(hi_n) if numeric else _sqlq(n.hi)
                parts.append(f"{f} <{'=' if n.incl_hi else ''} {lit}")
            return "(" + " AND ".join(parts) + ")" if parts else "TRUE"
        if isinstance(n, LBool):
            musts = [rec(c, scoring) for c, o in zip(n.clauses, n.occur)
                     if o == "+"]
            nots = [rec(c, False) for c, o in zip(n.clauses, n.occur)
                    if o == "-"]
            shoulds = [rec(c, scoring) for c, o in zip(n.clauses, n.occur)
                       if o == ""]
            parts = list(musts)
            if shoulds and not musts:
                parts.append("(" + " OR ".join(shoulds) + ")")
            # ES semantics: with musts present, shoulds are scoring-only
            parts.extend(f"NOT ({x})" for x in nots)
            return "(" + " AND ".join(parts) + ")" if parts else "TRUE"
        raise LuceneError(f"cannot lower {type(n).__name__}")

    sql = rec(node)
    return sql, claims
