"""Query-side text matching semantics.

The brute-force matchers here define the *semantics contract* for un-indexed
columns (and for parity tests against the indexed path): `##` is analyzed
phrase match (consecutive positions), `@@` is an analyzed boolean query in a
Lucene-lite syntax: terms (implicit AND... actually implicit OR per ES
query_string → the reference's `@@` maps to to_tsquery semantics: & | ! and
quoted phrases). Reference: server/connector/functions/ts_*.cpp,
libs/iresearch/parser/lucene_*.
"""

from __future__ import annotations

import re as _re

import numpy as np

from .. import errors
from .analysis import default_analyzer
from .regexp import RegexpError, compile_regexp


def position_groups(tokens) -> list[list[str]]:
    """Tokens → per-position alternative groups, in position order.
    Same-position tokens (synonym expansions) become alternatives of one
    phrase slot."""
    by_pos: dict[int, list[str]] = {}
    for t in tokens:
        by_pos.setdefault(t.position, []).append(t.term)
    return [by_pos[p] for p in sorted(by_pos)]


def match_phrase_brute(texts: np.ndarray, phrases: np.ndarray) -> np.ndarray:
    an = default_analyzer()
    out = np.zeros(len(texts), dtype=bool)
    # common case: constant phrase
    cache: dict[str, list[list[str]]] = {}
    for i, (text, phrase) in enumerate(zip(texts, phrases)):
        groups = cache.get(phrase)
        if groups is None:
            groups = cache[phrase] = position_groups(an.tokenize(phrase))
        out[i] = _phrase_in(an, text, groups)
    return out


def _phrase_in(an, text: str, groups: list[list[str]],
               slop: int = 0) -> bool:
    if not groups:
        return False
    toks = an.tokenize(text)
    pos_of: dict[str, set[int]] = {}
    for t in toks:
        pos_of.setdefault(t.term, set()).add(t.position)

    def positions(alts):
        out: set[int] = set()
        for a in alts:
            out |= pos_of.get(a, set())
        return out

    first = positions(groups[0])
    if len(groups) == 1:
        return bool(first)
    rest = [positions(g) for g in groups[1:]]
    if slop <= 0:
        return any(all((p + k) in ps for k, ps in enumerate(rest, 1))
                   for p in first)
    return _sloppy_match(first, rest, slop)


def _sloppy_match(first: set, rest: list[set], slop: int) -> bool:
    """In-order slot positions with total extra gap <= slop: for each
    start p0, greedily take the smallest admissible position per slot —
    greedy is optimal here because a smaller current position never
    shrinks the set of choices for later slots."""
    for p0 in sorted(first):
        prev = p0
        budget = slop
        ok = True
        for k, ps in enumerate(rest, 1):
            # smallest position > prev; gap beyond +1 eats budget
            best = None
            for p in ps:
                if p > prev and (best is None or p < best):
                    best = p
            if best is None or (best - prev - 1) > budget:
                ok = False
                break
            budget -= best - prev - 1
            prev = best
        if ok:
            return True
    return False


# -- tsquery-style boolean query parsing ----------------------------------

class QNode:
    pass


class QTerm(QNode):
    def __init__(self, term):
        self.term = term


class QPhrase(QNode):
    """Consecutive-position phrase. `groups` holds the alternatives at
    each position (synonym analyzers emit expansions at the same position,
    so one phrase slot may accept several terms); `terms` stays the flat
    list for scoring.

    `slop` relaxes adjacency the Lucene `"..."~N` way (approximated as:
    slots must appear in order, total extra gap <= slop; Lucene's full
    semantics also admit bounded reorders, which we do not)."""

    def __init__(self, terms, groups=None, slop=0):
        self.terms = terms
        self.groups = groups if groups is not None else [[t] for t in terms]
        self.slop = slop


class QNothing(QNode):
    """Matches no documents (e.g. a phrase that analyzed to zero terms —
    PG's to_tsquery('') semantics). Distinct from an unclaimable conjunct:
    this IS claimable, and returns the empty set."""


class QAnd(QNode):
    def __init__(self, args):
        self.args = args


class QOr(QNode):
    def __init__(self, args):
        self.args = args


class QNot(QNode):
    def __init__(self, arg):
        self.arg = arg


class QPrefix(QNode):
    def __init__(self, prefix):
        self.prefix = prefix


class QFuzzy(QNode):
    def __init__(self, term, max_edits=1):
        self.term = term
        self.max_edits = max_edits


class QRegex(QNode):
    """`/pattern/` — anchored full-term regex over analyzed terms
    (reference: the by_regexp filter, libs/iresearch/search/regexp_filter;
    Lucene regexp semantics: the pattern must match the whole term)."""

    def __init__(self, pattern: str, case_fold: bool = False):
        self.pattern = pattern
        try:
            # linear-time NFA, never Python `re`: user patterns run against
            # whole term dictionaries, so backtracking blowup = query DoS
            self.compiled = compile_regexp(pattern, case_fold)
        except RegexpError as e:
            raise errors.SqlError(
                errors.INVALID_REGULAR_EXPRESSION,
                f"invalid regular expression in query: {e}")

    def matches(self, term: str) -> bool:
        return self.compiled.fullmatch(term)


def parse_query(q: str, analyzer=None) -> QNode:
    """`a & b`, `a | b`, `!a`, `"a phrase"`, `pre*`, parens. Bare terms
    separated by whitespace are AND-ed (to_tsquery-ish)."""
    an = analyzer or default_analyzer()
    toks = _qlex(q)
    node, rest = _parse_or(toks, an)
    return node


def _qlex(q: str) -> list[str]:
    out = []
    i = 0
    while i < len(q):
        c = q[i]
        if c.isspace():
            i += 1
        elif c in "&|!()":
            out.append(c)
            i += 1
        elif c == '"':
            j = q.find('"', i + 1)
            j = len(q) if j < 0 else j
            tok = '"' + q[i + 1:j] + '"'
            i = j + 1
            # Lucene proximity: "..."~N
            if i < len(q) and q[i] == "~":
                k = i + 1
                while k < len(q) and q[k].isdigit():
                    k += 1
                if k > i + 1:
                    tok += q[i:k]
                    i = k
            out.append(tok)
        elif c == "/":
            # scan for the closing '/', honoring backslash escapes so
            # patterns may contain literal slashes (`/etc\/[a-z]+/`)
            j = i + 1
            while j < len(q) and q[j] != "/":
                j += 2 if q[j] == "\\" and j + 1 < len(q) else 1
            out.append("/" + q[i + 1:j] + "/")
            i = j + 1
        else:
            j = i
            while j < len(q) and not q[j].isspace() and q[j] not in "&|!()":
                j += 1
            out.append(q[i:j])
            i = j
    return out


def _has_inner_wildcard(t: str) -> bool:
    """Wildcard metachars anywhere but a single trailing `*` (which has a
    faster QPrefix path)."""
    return "?" in t or "*" in t


_RX_META = set("\\^$.[]()*+?{}|/")


def _wildcard_to_regex(t: str) -> str:
    """Lucene wildcard token → anchored regex source: `*` → `.*`,
    `?` → `.`, everything else literal."""
    out = []
    for c in t:
        if c == "*":
            out.append(".*")
        elif c == "?":
            out.append(".")
        elif c in _RX_META:
            out.append("\\" + c)
        else:
            out.append(c)
    return "".join(out)


def _folds_case(an) -> bool:
    """Does this analyzer lowercase its terms? Probed (and memoized on the
    analyzer) so regex literals fold exactly when bare terms do."""
    cached = getattr(an, "_folds_case", None)
    if cached is None:
        toks = an.terms("AB")
        cached = an._folds_case = bool(toks) and \
            all(t == t.lower() for t in toks)
    return cached


def _parse_or(toks, an):
    left, toks = _parse_and(toks, an)
    args = [left]
    while toks and toks[0] == "|":
        nxt, toks = _parse_and(toks[1:], an)
        args.append(nxt)
    return (args[0] if len(args) == 1 else QOr(args)), toks


def _parse_and(toks, an):
    args = []
    while toks and toks[0] not in ("|", ")"):
        if toks[0] == "&":
            toks = toks[1:]
            continue
        node, toks = _parse_unary(toks, an)
        if node is not None:
            args.append(node)
    if not args:
        return QAnd([]), toks
    return (args[0] if len(args) == 1 else QAnd(args)), toks


def _parse_unary(toks, an):
    if not toks:
        return None, toks
    t = toks[0]
    if t == "!":
        node, rest = _parse_unary(toks[1:], an)
        return QNot(node), rest
    if t == "(":
        node, rest = _parse_or(toks[1:], an)
        if rest and rest[0] == ")":
            rest = rest[1:]
        return node, rest
    if t.startswith('"'):
        body = t[1:]
        slop = 0
        close = body.rfind('"')
        if close >= 0:
            tail = body[close + 1:]
            if tail.startswith("~") and tail[1:].isdigit():
                slop = int(tail[1:])
            body = body[:close]
        terms = [tok.term for tok in an.tokenize(body)]
        return QPhrase(terms, slop=slop), toks[1:]
    if t.startswith("/") and t.endswith("/") and len(t) > 1:
        return QRegex(t[1:-1], case_fold=_folds_case(an)), toks[1:]
    if (t.endswith("*") or t.endswith(":*")) and len(t) > 1 and \
            not _has_inner_wildcard(t[:-1]):
        # Lucene-style `pre*` and PG tsquery `pre:*` both spell prefix.
        # Fold only when the analyzer folds bare terms: under keyword/
        # whitespace analyzers stored terms keep their case
        base = t[:-2] if t.endswith(":*") else t[:-1]
        base = base.lower() if _folds_case(an) else base
        if base:
            return QPrefix(base), toks[1:]
    if _has_inner_wildcard(t):
        if set(t) <= {"*", "?"}:
            # a bare `*` would expand the entire term dictionary; keep
            # the pre-wildcard behavior (token contributes nothing)
            return None, toks[1:]
        # Lucene wildcards beyond trailing-star prefix (`te?t`, `t*e`,
        # `*ing`) compile to an anchored term regex (the reference's
        # by_wildcard filter is the same automaton machinery). A fuzzy
        # suffix cannot combine with wildcards — strip it (ES drops it
        # the same way).
        base = _re.sub(r"~\d*$", "", t) or t
        pat = _wildcard_to_regex(base.lower() if _folds_case(an) else base)
        return QRegex(pat, case_fold=_folds_case(an)), toks[1:]
    if "~" in t and len(t) > 1:
        base, _, edits = t.partition("~")
        terms_f = [tok.term for tok in an.tokenize(base)]
        if len(terms_f) == 1:
            try:
                n_edits = max(1, min(int(edits), 2)) if edits else 1
            except ValueError:
                n_edits = 1
            return QFuzzy(terms_f[0], n_edits), toks[1:]
    terms = [tok.term for tok in an.tokenize(t)]
    if not terms:
        return None, toks[1:]
    if len(terms) == 1:
        return QTerm(terms[0]), toks[1:]
    return QPhrase(terms), toks[1:]


def eval_query_on_text(node: QNode, an, text: str) -> bool:
    toks = an.tokenize(text)
    terms = {t.term for t in toks}

    def ev(nd) -> bool:
        if isinstance(nd, QTerm):
            return nd.term in terms
        if isinstance(nd, QPhrase):
            return _phrase_in(an, text, nd.groups, nd.slop)
        if isinstance(nd, QNothing):
            return False
        if isinstance(nd, QAnd):
            return all(ev(a) for a in nd.args)
        if isinstance(nd, QOr):
            return any(ev(a) for a in nd.args)
        if isinstance(nd, QNot):
            return not ev(nd.arg)
        if isinstance(nd, QPrefix):
            return any(t.startswith(nd.prefix) for t in terms)
        if isinstance(nd, QFuzzy):
            return any(edit_distance_at_most(t, nd.term, nd.max_edits)
                       for t in terms)
        if isinstance(nd, QRegex):
            return any(nd.matches(t) for t in terms)
        return False
    return ev(node)


def edit_distance_at_most(a: str, b: str, k: int) -> bool:
    """Banded Levenshtein: True iff distance(a, b) <= k."""
    if abs(len(a) - len(b)) > k:
        return False
    if a == b:
        return True
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        row_min = i
        for j, cb in enumerate(b, 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                         prev[j - 1] + (ca != cb))
            row_min = min(row_min, cur[j])
        if row_min > k:
            return False
        prev = cur
    return prev[-1] <= k


def match_query_brute(texts: np.ndarray, queries: np.ndarray) -> np.ndarray:
    an = default_analyzer()
    out = np.zeros(len(texts), dtype=bool)
    cache: dict[str, QNode] = {}
    for i, (text, q) in enumerate(zip(texts, queries)):
        node = cache.get(q)
        if node is None:
            node = cache[q] = parse_query(q, an)
        out[i] = eval_query_on_text(node, an, text)
    return out
