"""Text analyzers (tokenizer pipelines).

Reference analog: libs/iresearch/analysis/ — 25+ analyzers (SURVEY.md §2.7).
Analysis is pointer-chasing CPU work in any architecture; it stays on host
here too (the reference's design point holds: term matching on CPU, scoring
on the accelerator — SURVEY.md §7 hard part 5).

Implemented: text (lowercase + unicode word split + stopwords + stemming),
whitespace, keyword, ngram, edge_ngram, delimiter. The registry mirrors the
reference's named-tokenizer catalog objects (CREATE ... TOKENIZER options).
"""

from __future__ import annotations

import re
import unicodedata
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from .. import errors

_WORD_RE = re.compile(r"\w+", re.UNICODE)

# minimal english stopword list (reference text analyzer uses snowball lists)
EN_STOPWORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with".split())


def _porter_light(token: str) -> str:
    """Lightweight English stemmer (S-stemmer + common suffixes). The
    reference uses snowball; this approximation keeps index/query symmetric
    (both sides stem identically), which is what parity requires."""
    t = token
    for suf in ("ational", "iveness", "fulness", "ousness"):
        if t.endswith(suf) and len(t) > len(suf) + 2:
            return t[: -len(suf) + 3] if suf == "ational" else t[: -4]
    for suf in ("ing", "edly", "ed", "ly", "ies", "ness"):
        if t.endswith(suf) and len(t) - len(suf) >= 3:
            t = t[: -len(suf)]
            if suf == "ies":
                t += "y"
            return t
    if t.endswith("es") and len(t) >= 5:
        return t[:-2]
    if t.endswith("s") and not t.endswith("ss") and len(t) >= 4:
        return t[:-1]
    return t


@dataclass
class Token:
    term: str
    position: int
    start: int = 0
    end: int = 0


class Analyzer:
    name = "keyword"

    def tokenize(self, text: str) -> list[Token]:
        raise NotImplementedError

    def terms(self, text: str) -> list[str]:
        return [t.term for t in self.tokenize(text)]


class KeywordAnalyzer(Analyzer):
    name = "keyword"

    def tokenize(self, text: str) -> list[Token]:
        return [Token(text, 0, 0, len(text))] if text else []


class WhitespaceAnalyzer(Analyzer):
    name = "whitespace"

    def tokenize(self, text: str) -> list[Token]:
        out = []
        pos = 0
        for m in re.finditer(r"\S+", text):
            out.append(Token(m.group(), pos, m.start(), m.end()))
            pos += 1
        return out


class TextAnalyzer(Analyzer):
    """Locale text analyzer: NFC normalize, lowercase, word split, accent
    fold, optional stopwords + stemming (reference: analysis/text_analyzer)."""

    name = "text"

    def __init__(self, stopwords: Optional[frozenset] = EN_STOPWORDS,
                 stem: bool = True, accent_fold: bool = True):
        self.stopwords = stopwords or frozenset()
        self.stem = stem
        self.accent_fold = accent_fold

    def tokenize(self, text: str) -> list[Token]:
        norm = unicodedata.normalize("NFC", text).lower()
        out = []
        pos = 0
        for m in _WORD_RE.finditer(norm):
            term = m.group()
            if self.accent_fold:
                term = "".join(c for c in unicodedata.normalize("NFD", term)
                               if not unicodedata.combining(c))
            if term in self.stopwords:
                pos += 1
                continue
            if self.stem:
                term = _porter_light(term)
            out.append(Token(term, pos, m.start(), m.end()))
            pos += 1
        return out


class SimpleTextAnalyzer(TextAnalyzer):
    """text without stemming/stopwords — lowercase word split only."""

    name = "simple"

    def __init__(self):
        super().__init__(stopwords=frozenset(), stem=False)


class NgramAnalyzer(Analyzer):
    name = "ngram"

    def __init__(self, min_n: int = 2, max_n: int = 3, edge: bool = False):
        self.min_n, self.max_n, self.edge = min_n, max_n, edge

    def tokenize(self, text: str) -> list[Token]:
        t = text.lower()
        out = []
        pos = 0
        starts = [0] if self.edge else range(len(t))
        for i in starts:
            for n in range(self.min_n, self.max_n + 1):
                if i + n <= len(t):
                    out.append(Token(t[i:i + n], pos, i, i + n))
                    pos += 1
        return out


class DelimiterAnalyzer(Analyzer):
    name = "delimiter"

    def __init__(self, delimiter: str = ","):
        self.delimiter = delimiter

    def tokenize(self, text: str) -> list[Token]:
        out = []
        start = 0
        for pos, part in enumerate(text.split(self.delimiter)):
            out.append(Token(part, pos, start, start + len(part)))
            start += len(part) + len(self.delimiter)
        return out


_BUILTINS: dict[str, Callable[[], Analyzer]] = {
    "keyword": KeywordAnalyzer,
    "whitespace": WhitespaceAnalyzer,
    "text": TextAnalyzer,
    "text_en": TextAnalyzer,
    "simple": SimpleTextAnalyzer,
    "ngram": NgramAnalyzer,
    "edge_ngram": lambda: NgramAnalyzer(edge=True),
    "delimiter": DelimiterAnalyzer,
}

_cache: dict[str, Analyzer] = {}
_custom: dict[str, Analyzer] = {}


_KNOWN_DICT_OPTIONS = {
    # behavioral
    "template", "stemming", "accent", "stopwords", "min", "max",
    "delimiter",
    # accepted reference options that are defaults/no-ops here
    "locale", "case", "frequency", "position", "norm",
}


def register_dictionary(name: str, options: dict,
                        if_not_exists: bool = False,
                        replace: bool = False) -> Analyzer:
    """CREATE TEXT SEARCH DICTIONARY: a named, configured analyzer
    (reference: server/pg/commands/create_tsdictionary.cpp; template/
    case/stemming/accent options as in examples/demo0/demo.sql).

    Dictionaries may not shadow builtin analyzer names, and duplicates
    error unless IF NOT EXISTS / replace (recovery) is given."""
    key = name.lower()
    unknown = set(options) - _KNOWN_DICT_OPTIONS
    if unknown:
        raise errors.SqlError(
            "22023", f"unrecognized dictionary option "
                     f"{sorted(unknown)[0]!r}")
    if key in _BUILTINS:
        raise errors.SqlError(errors.DUPLICATE_OBJECT,
                              f'"{name}" is a builtin tokenizer')
    if key in _custom and not replace:
        if if_not_exists:
            return _custom[key]
        raise errors.SqlError(errors.DUPLICATE_OBJECT,
                              f'text search dictionary "{name}" already '
                              "exists")
    template = str(options.get("template", "text")).lower()
    def truthy(v, default):
        if v is None:
            return default
        if isinstance(v, bool):
            return v
        return str(v).lower() in ("true", "on", "1", "yes")
    if template in ("text", "simple"):
        a = TextAnalyzer(
            stopwords=(EN_STOPWORDS
                       if truthy(options.get("stopwords"), False)
                       else frozenset()),
            stem=truthy(options.get("stemming"), template == "text"),
            accent_fold=truthy(options.get("accent"), True))
    elif template == "whitespace":
        a = WhitespaceAnalyzer()
    elif template == "keyword":
        a = KeywordAnalyzer()
    elif template in ("ngram", "edge_ngram"):
        a = NgramAnalyzer(int(options.get("min", 2)),
                          int(options.get("max", 3)),
                          edge=template == "edge_ngram")
    elif template == "delimiter":
        a = DelimiterAnalyzer(str(options.get("delimiter", ",")))
    else:
        raise errors.SqlError(errors.UNDEFINED_OBJECT,
                              f'tokenizer template "{template}" does not '
                              "exist")
    a.name = name.lower()
    _custom[name.lower()] = a
    return a


def dictionary_exists(name: str) -> bool:
    return name.lower() in _custom


def drop_dictionary(name: str) -> bool:
    return _custom.pop(name.lower(), None) is not None


def get_analyzer(name: str) -> Analyzer:
    key = (name or "text").lower()
    a = _custom.get(key)
    if a is not None:
        return a
    a = _cache.get(key)
    if a is None:
        ctor = _BUILTINS.get(key)
        if ctor is None:
            raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                  f'tokenizer "{name}" does not exist')
        a = _cache[key] = ctor()
    return a


def default_analyzer() -> Analyzer:
    return get_analyzer("text")
