"""Text analyzers (tokenizer pipelines).

Reference analog: libs/iresearch/analysis/ — 53 files / 25+ analyzers
(SURVEY.md §2.7). Analysis is pointer-chasing CPU work in any architecture;
it stays on host here too (the reference's design point holds: term matching
on CPU, scoring on the accelerator — SURVEY.md §7 hard part 5).

Implemented: locale text analyzers (unicode word split + per-language
stopwords + snowball-family stemming + CJK bigrams), whitespace, keyword,
ngram, edge_ngram, delimiter, multi_delimiter, segmentation, normalizing,
collation, stem, pattern, path_hierarchy, synonyms, pipeline, union,
minhash. The registry mirrors the reference's named-tokenizer catalog
objects (CREATE ... TOKENIZER options; analysis/pipeline_tokenizer.cpp,
solr_synonyms_tokenizer.cpp, minhash_tokenizer.cpp, ...).
"""

from __future__ import annotations

import re
import unicodedata
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from .. import errors
from .stemmers import lang_of as _lang_of
from .stemmers import porter2, stemmer_for

_WORD_RE = re.compile(r"\w+", re.UNICODE)

# CJK codepoint runs are split into overlapping bigrams (the standard
# segmentation approximation the reference gets from ICU break iterators)
_CJK_RE = re.compile(
    "[\u3400-\u4dbf\u4e00-\u9fff\uf900-\ufaff"
    "\u3040-\u30ff\uac00-\ud7af]")

# per-language stopword lists (reference: snowball lists via libstemmer;
# compact high-frequency subsets keep index/query symmetric)
EN_STOPWORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with".split())
DE_STOPWORDS = frozenset(
    "aber als am an auch auf aus bei bin bis das dass dem den der des die "
    "durch ein eine einem einen einer es für hat ich im in ist mit nach "
    "nicht noch nur oder sich sie sind so über um und von vor war wie wird "
    "zu zum zur".split())
FR_STOPWORDS = frozenset(
    "au aux avec ce ces dans de des du elle en et eux il ils je la le les "
    "leur lui ma mais me même mes moi mon ne nos notre nous on ou par pas "
    "pour qu que qui sa se ses son sur ta te tes toi ton tu un une vos "
    "votre vous".split())
ES_STOPWORDS = frozenset(
    "a al algo como con de del desde donde el ella ellas ellos en entre "
    "era es esta este ha hay la las le les lo los me mi muy más ni no nos "
    "o para pero por que se sin sobre su sus te tiene un una uno y ya".split())
RU_STOPWORDS = frozenset(
    "и в во не что он на я с со как а то все она так его но да ты к у же "
    "вы за бы по только ее мне было вот от меня еще нет о из ему".split())
IT_STOPWORDS = frozenset(
    "a ad al alla alle anche che chi ci come con da dal de del della di "
    "e ed è era gli ha hanno il in io la le lo ma mi nel nella non o per "
    "più quella questo se si sono su un una uno".split())
PT_STOPWORDS = frozenset(
    "a ao aos as com da das de dele do dos e ela elas ele eles em entre "
    "essa esse esta este eu foi há isso já mais mas me mesmo na nas não "
    "no nos nós o os ou para pela pelo por qual quando que se sem seu sua "
    "também te um uma você".split())
NL_STOPWORDS = frozenset(
    "aan als bij dan dat de der die dit een en er haar had heeft het hij "
    "hoe ik in is je kan maar me met mijn naar niet nog nu of om onder "
    "ook op over te tot uit van voor was wat we wel wij zal ze zich zij "
    "zijn zo".split())
SV_STOPWORDS = frozenset(
    "alla att av blev bli den det detta dig din du där då efter ej eller "
    "en er ett från för ha hade han hans har hon i icke inte jag kan man "
    "med mig min mot mycket ni nu när och om oss på samma sedan sig sin "
    "som så till under upp vad var vara varför vi vid är".split())
FI_STOPWORDS = frozenset(
    "ei en että he hän ja jo jos kanssa kun me mikä minä mutta myös ne "
    "niin nyt ole oli on ovat se sen siellä sinä tai tämä vain voi".split())
DA_STOPWORDS = frozenset(
    "af alle at blev da de dem den denne der deres det dette dig din dog "
    "du efter eller en end er et for fra ham han hans har havde have hun "
    "hvad hvis hvor i ikke ind jeg jer kan man med meget men mig min "
    "mine mit nogle nu når og også om op os over på selv sig skal skulle "
    "som sådan thi til ud under var vi vil ville vor være været".split())
NO_STOPWORDS = frozenset(
    "alle at av da de deg den denne der dere deres det dette du eller en "
    "er et etter for fra ha hadde han hans har hun hva hvis hvor i ikke "
    "jeg kan man med meg men mer min mitt mot noe noen nå og også om opp "
    "oss over på seg selv sin sitt skal skulle som så til ut var vi vil "
    "ville vår være vært".split())
RO_STOPWORDS = frozenset(
    "acea aceasta această al ale am ar are as au că ce cel cu da dar de "
    "din dintre doar după ei el ele este eu fi fie fost iar în între la "
    "le lor lui mai mult nu o ori pe pentru prin sa să sau se si și sunt "
    "tot un una unei unui va voi vor".split())
TR_STOPWORDS = frozenset(
    "acaba ama ancak bana bazı belki ben beni bir biri birkaç biz bu "
    "çok çünkü da daha de defa diye en gibi hem hep hepsi her hiç için "
    "ile ise kez ki kim mi mu mü nasıl ne neden nerde nerede nereye niye "
    "o sanki şey siz şu tüm ve veya ya yani".split())
HU_STOPWORDS = frozenset(
    "a az abban ahhoz ahogy aki akik akkor amely amelyek ami amit arra "
    "azok azonban be csak de e egy egyéb egyik el ez ezek ezen ezt fel "
    "hogy ha hanem hiszen igen ill illetve is ki le lehet maga más meg "
    "mert mi mint mintha nem nincs olyan ott össze pedig s saját sem "
    "semmi sok szerint szinte talán úgy új vagy van volt".split())

STOPWORDS_BY_LANG = {
    "en": EN_STOPWORDS, "de": DE_STOPWORDS, "fr": FR_STOPWORDS,
    "es": ES_STOPWORDS, "ru": RU_STOPWORDS, "it": IT_STOPWORDS,
    "pt": PT_STOPWORDS, "nl": NL_STOPWORDS, "sv": SV_STOPWORDS,
    "fi": FI_STOPWORDS, "da": DA_STOPWORDS, "no": NO_STOPWORDS,
    "nb": NO_STOPWORDS, "nn": NO_STOPWORDS, "ro": RO_STOPWORDS,
    "tr": TR_STOPWORDS, "hu": HU_STOPWORDS,
}


def _porter_light(token: str) -> str:
    """English stemmer — full Porter2 (stemmers.py). The name survives as
    the historical seam used across the index/query sides."""
    return porter2(token)


def _cjk_split(term: str, pos: int, start: int) -> list["Token"]:
    """Split a \\w+ run containing CJK into script-run tokens: non-CJK runs
    stay whole, CJK runs become overlapping bigrams (unigram when length
    1) — the ICU-segmentation approximation for unspaced scripts."""
    out = []
    i = 0
    n = len(term)
    while i < n:
        if _CJK_RE.match(term[i]):
            j = i
            while j < n and _CJK_RE.match(term[j]):
                j += 1
            run = term[i:j]
            if len(run) == 1:
                out.append(Token(run, pos, start + i, start + i + 1))
                pos += 1
            else:
                for k in range(len(run) - 1):
                    out.append(Token(run[k:k + 2], pos, start + i + k,
                                     start + i + k + 2))
                    pos += 1
            i = j
        else:
            j = i
            while j < n and not _CJK_RE.match(term[j]):
                j += 1
            out.append(Token(term[i:j], pos, start + i, start + j))
            pos += 1
            i = j
    return out


@dataclass
class Token:
    term: str
    position: int
    start: int = 0
    end: int = 0


class Analyzer:
    name = "keyword"

    def tokenize(self, text: str) -> list[Token]:
        raise NotImplementedError

    def terms(self, text: str) -> list[str]:
        return [t.term for t in self.tokenize(text)]


class KeywordAnalyzer(Analyzer):
    name = "keyword"

    def tokenize(self, text: str) -> list[Token]:
        return [Token(text, 0, 0, len(text))] if text else []


class WhitespaceAnalyzer(Analyzer):
    name = "whitespace"

    def tokenize(self, text: str) -> list[Token]:
        out = []
        pos = 0
        for m in re.finditer(r"\S+", text):
            out.append(Token(m.group(), pos, m.start(), m.end()))
            pos += 1
        return out


class TextAnalyzer(Analyzer):
    """Locale text analyzer: NFC normalize, lowercase, word split (CJK
    runs → bigrams), accent fold, per-language stopwords + stemming
    (reference: analysis/text_tokenizer.cpp; locale handling mirrors its
    ICU locale option)."""

    name = "text"

    def __init__(self, stopwords: Optional[frozenset] = None,
                 stem: bool = True, accent_fold: bool = True,
                 locale: str = "en"):
        lang = _lang_of(locale)
        if stopwords is None:
            stopwords = STOPWORDS_BY_LANG.get(lang, frozenset())
        self.stopwords = stopwords or frozenset()
        self.stem = stem
        self.accent_fold = accent_fold
        self.locale = lang
        self._stemmer = stemmer_for(lang) if stem else None

    def tokenize(self, text: str) -> list[Token]:
        norm = unicodedata.normalize("NFC", text).lower()
        out = []
        pos = 0
        for m in _WORD_RE.finditer(norm):
            raw = m.group()
            if _CJK_RE.search(raw):
                toks = _cjk_split(raw, pos, m.start())
                out.extend(toks)
                pos += len(toks) if toks else 1
                continue
            term = raw
            if term in self.stopwords:
                pos += 1
                continue
            if self.accent_fold:
                term = "".join(c for c in unicodedata.normalize("NFD", term)
                               if not unicodedata.combining(c))
            if term in self.stopwords:
                pos += 1
                continue
            if self._stemmer is not None:
                term = self._stemmer(term)
            out.append(Token(term, pos, m.start(), m.end()))
            pos += 1
        return out


class SimpleTextAnalyzer(TextAnalyzer):
    """text without stemming/stopwords — lowercase word split only."""

    name = "simple"

    def __init__(self):
        super().__init__(stopwords=frozenset(), stem=False)


class NgramAnalyzer(Analyzer):
    name = "ngram"

    def __init__(self, min_n: int = 2, max_n: int = 3, edge: bool = False):
        self.min_n, self.max_n, self.edge = min_n, max_n, edge

    def tokenize(self, text: str) -> list[Token]:
        t = text.lower()
        out = []
        pos = 0
        starts = [0] if self.edge else range(len(t))
        for i in starts:
            for n in range(self.min_n, self.max_n + 1):
                if i + n <= len(t):
                    out.append(Token(t[i:i + n], pos, i, i + n))
                    pos += 1
        return out


class DelimiterAnalyzer(Analyzer):
    name = "delimiter"

    def __init__(self, delimiter: str = ","):
        self.delimiter = delimiter

    def tokenize(self, text: str) -> list[Token]:
        out = []
        start = 0
        for pos, part in enumerate(text.split(self.delimiter)):
            out.append(Token(part, pos, start, start + len(part)))
            start += len(part) + len(self.delimiter)
        return out


class MultiDelimiterAnalyzer(Analyzer):
    """Split on any of several delimiters (reference:
    analysis/multi_delimited_tokenizer.cpp)."""

    name = "multi_delimiter"

    def __init__(self, delimiters: Iterable[str] = (",", ";")):
        ds = [re.escape(d) for d in delimiters if d]
        self._re = re.compile("|".join(ds)) if ds else None

    def tokenize(self, text: str) -> list[Token]:
        if self._re is None:
            return [Token(text, 0, 0, len(text))] if text else []
        out = []
        start = pos = 0
        for m in self._re.finditer(text):
            if m.start() > start:
                out.append(Token(text[start:m.start()], pos, start,
                                 m.start()))
                pos += 1
            start = m.end()
        if start < len(text):
            out.append(Token(text[start:], pos, start, len(text)))
        return out


class SegmentationAnalyzer(Analyzer):
    """Unicode word-boundary segmentation with case control (reference:
    analysis/segmentation_tokenizer.cpp; break='word'|'alpha'|'graphic',
    case='lower'|'upper'|'none')."""

    name = "segmentation"

    def __init__(self, break_mode: str = "alpha", case: str = "lower"):
        if break_mode not in ("word", "alpha", "graphic"):
            raise errors.SqlError("22023",
                                  f"unknown break option {break_mode!r}")
        if case not in ("lower", "upper", "none"):
            raise errors.SqlError("22023", f"unknown case option {case!r}")
        self.break_mode = break_mode
        self.case = case

    def tokenize(self, text: str) -> list[Token]:
        if self.case == "lower":
            text = text.lower()
        elif self.case == "upper":
            text = text.upper()
        pat = {"word": r"\w+", "alpha": r"\w+",
               "graphic": r"\S+"}[self.break_mode]
        out = []
        pos = 0
        for m in re.finditer(pat, text, re.UNICODE):
            raw = m.group()
            if self.break_mode == "alpha" and raw.isdigit():
                continue
            if _CJK_RE.search(raw):
                toks = _cjk_split(raw, pos, m.start())
                out.extend(toks)
                pos += len(toks) if toks else 1
                continue
            out.append(Token(raw, pos, m.start(), m.end()))
            pos += 1
        return out


class NormalizingAnalyzer(Analyzer):
    """Whole-input normalization, no split (reference:
    analysis/normalizing_tokenizer.cpp): case fold + optional accent
    removal, emits one token."""

    name = "norm"

    def __init__(self, case: str = "lower", accent: bool = False):
        self.case = case
        self.accent = accent

    def tokenize(self, text: str) -> list[Token]:
        t = unicodedata.normalize("NFC", text)
        if self.case == "lower":
            t = t.lower()
        elif self.case == "upper":
            t = t.upper()
        if not self.accent:
            t = "".join(c for c in unicodedata.normalize("NFD", t)
                        if not unicodedata.combining(c))
        return [Token(t, 0, 0, len(text))] if t else []


class CollationAnalyzer(Analyzer):
    """Collation sort-key token (reference:
    analysis/collation_tokenizer.cpp): emits a locale-insensitive sort key
    so ORDER BY / range filters over the index agree with a case/accent
    -insensitive collation. Approximated as NFKD casefold with marks
    stripped — correct for the Latin-script locales this build targets."""

    name = "collation"

    def __init__(self, locale: str = "en"):
        self.locale = _lang_of(locale)

    def tokenize(self, text: str) -> list[Token]:
        key = "".join(c for c in unicodedata.normalize("NFKD",
                                                       text.casefold())
                      if not unicodedata.combining(c))
        return [Token(key, 0, 0, len(text))]


class StemAnalyzer(Analyzer):
    """Whole-input stemmer (reference: analysis/stemming_tokenizer.cpp):
    lowercases and stems the input as a single token."""

    name = "stem"

    def __init__(self, locale: str = "en"):
        self.locale = _lang_of(locale)
        self._stemmer = stemmer_for(self.locale) or (lambda w: w)

    def tokenize(self, text: str) -> list[Token]:
        t = self._stemmer(text.strip().lower())
        return [Token(t, 0, 0, len(text))] if t else []


class PatternAnalyzer(Analyzer):
    """Regex tokenizer (reference: analysis/pattern_tokenizer.cpp):
    mode='match' emits every match of the pattern (group 1 if present),
    mode='split' uses the pattern as a separator."""

    name = "pattern"

    def __init__(self, pattern: str, mode: str = "match",
                 case: str = "none"):
        if mode not in ("match", "split"):
            raise errors.SqlError("22023", f"unknown pattern mode {mode!r}")
        try:
            self._re = re.compile(pattern)
        except re.error as e:
            raise errors.SqlError("2201B", f"invalid regex: {e}")
        self.mode = mode
        self.case = case

    def tokenize(self, text: str) -> list[Token]:
        if self.case == "lower":
            text = text.lower()
        elif self.case == "upper":
            text = text.upper()
        out = []
        if self.mode == "match":
            for pos, m in enumerate(self._re.finditer(text)):
                term = m.group(1) if self._re.groups else m.group()
                if term:
                    out.append(Token(term, pos, m.start(), m.end()))
        else:
            start = pos = 0
            for m in self._re.finditer(text):
                if m.end() == m.start():
                    continue   # zero-width separators split nothing
                if m.start() > start:
                    out.append(Token(text[start:m.start()], pos, start,
                                     m.start()))
                    pos += 1
                start = m.end()
            if start < len(text):
                out.append(Token(text[start:], pos, start, len(text)))
        return out


class PathHierarchyAnalyzer(Analyzer):
    """Path prefixes (reference: analysis/path_hierarchy_tokenizer.cpp):
    '/a/b/c' → '/a', '/a/b', '/a/b/c' (all at position 0, like the
    reference — a path filter matches any ancestor)."""

    name = "path_hierarchy"

    def __init__(self, delimiter: str = "/", reverse: bool = False):
        self.delimiter = delimiter
        self.reverse = reverse

    def tokenize(self, text: str) -> list[Token]:
        d = self.delimiter
        parts = [p for p in text.split(d) if p != ""]
        if not parts:
            return []
        out = []
        if not self.reverse:
            lead = d if text.startswith(d) else ""
            for i in range(1, len(parts) + 1):
                term = lead + d.join(parts[:i])
                out.append(Token(term, 0, 0, len(term)))
        else:
            trail = d if text.endswith(d) else ""
            for i in range(len(parts)):
                term = d.join(parts[i:]) + trail
                out.append(Token(term, 0, len(text) - len(term), len(text)))
        return out


class SynonymAnalyzer(Analyzer):
    """Synonym expansion over an inner analyzer (reference:
    analysis/solr_synonyms_tokenizer.cpp / wordnet_synonyms_tokenizer.cpp).
    Mapping 'a => b,c' (solr style) or symmetric groups 'a,b,c'; expansions
    are emitted AT THE SAME POSITION so phrase queries still line up."""

    name = "synonyms"

    def __init__(self, rules: Iterable[str],
                 inner: Optional[Analyzer] = None):
        self.inner = inner or SimpleTextAnalyzer()
        self.map: dict[str, list[str]] = {}
        for rule in rules:
            rule = rule.strip()
            if not rule or rule.startswith("#"):
                continue
            if "=>" in rule:
                lhs, rhs = rule.split("=>", 1)
                targets = [t.strip().lower() for t in rhs.split(",")
                           if t.strip()]
                for src in lhs.split(","):
                    src = src.strip().lower()
                    if src:
                        self.map.setdefault(src, []).extend(
                            t for t in targets
                            if t not in self.map.get(src, []))
            else:
                group = [t.strip().lower() for t in rule.split(",")
                         if t.strip()]
                for src in group:
                    self.map.setdefault(src, []).extend(
                        t for t in group
                        if t != src and t not in self.map.get(src, []))

    def tokenize(self, text: str) -> list[Token]:
        out = []
        for tok in self.inner.tokenize(text):
            out.append(tok)
            for syn in self.map.get(tok.term.lower(), ()):
                out.append(Token(syn, tok.position, tok.start, tok.end))
        return out


class PipelineAnalyzer(Analyzer):
    """Chain analyzers: each stage re-tokenizes the previous stage's terms
    (reference: analysis/pipeline_tokenizer.cpp). Positions compose so a
    delimiter → text pipeline keeps phrase semantics."""

    name = "pipeline"

    def __init__(self, stages: list[Analyzer]):
        if not stages:
            raise errors.SqlError("22023", "pipeline requires stages")
        self.stages = stages

    def tokenize(self, text: str) -> list[Token]:
        toks = self.stages[0].tokenize(text)
        for stage in self.stages[1:]:
            nxt: list[Token] = []
            pos = 0
            for t in toks:
                subs = stage.tokenize(t.term)
                for s in subs:
                    nxt.append(Token(s.term, pos, t.start, t.end))
                    pos += 1
                if not subs:
                    pos += 1
            toks = nxt
        return toks


class UnionAnalyzer(Analyzer):
    """Union of several analyzers' outputs, deduplicated by (term,
    position) (reference: analysis/union_tokenizer.cpp — e.g. exact +
    stemmed forms indexed together)."""

    name = "union"

    def __init__(self, parts: list[Analyzer]):
        if not parts:
            raise errors.SqlError("22023", "union requires analyzers")
        self.parts = parts

    def tokenize(self, text: str) -> list[Token]:
        seen = set()
        out = []
        for a in self.parts:
            for t in a.tokenize(text):
                key = (t.term, t.position)
                if key not in seen:
                    seen.add(key)
                    out.append(t)
        return out


class MinHashAnalyzer(Analyzer):
    """MinHash signature tokens (reference: analysis/minhash_tokenizer.cpp):
    k minimal 64-bit hashes over the inner analyzer's term shingles —
    near-duplicate detection with |sig∩sig'|/k ≈ Jaccard similarity."""

    name = "minhash"

    def __init__(self, k: int = 32, inner: Optional[Analyzer] = None,
                 shingle: int = 3):
        self.k = int(k)
        self.inner = inner or SimpleTextAnalyzer()
        self.shingle = max(1, int(shingle))

    def tokenize(self, text: str) -> list[Token]:
        import hashlib
        terms = [t.term for t in self.inner.tokenize(text)]
        if not terms:
            return []
        n = self.shingle
        shingles = ({" ".join(terms[i:i + n])
                     for i in range(max(1, len(terms) - n + 1))}
                    if len(terms) >= 1 else set())
        hashes = sorted(
            int.from_bytes(
                hashlib.blake2b(s.encode(), digest_size=8).digest(),
                "big")
            for s in shingles)[: self.k]
        return [Token(format(h, "016x"), i, 0, 0)
                for i, h in enumerate(hashes)]


class ClassificationAnalyzer(Analyzer):
    """Model-backed classification analyzer (reference:
    analysis/classification_stream.cpp — fastText emits the model's
    top-k predicted labels as tokens). The model here is a centroid
    classifier over the deterministic local char-trigram embedding
    (functions/embedfns.local_embed): each label's centroid is the mean
    embedding of its example texts (the label name itself is always
    included, so querying by label is stable). tokenize() emits the
    top-k label names as tokens."""

    name = "classification"

    def __init__(self, labels: dict[str, str], top: int = 1,
                 dim: int = 64):
        import numpy as _np

        from ..functions.embedfns import local_embed
        if not labels:
            raise errors.SqlError(
                "22023", "classification tokenizer needs labels")
        self._embed = local_embed
        self.top = max(1, int(top))
        self.dim = int(dim)
        self.label_names = sorted(labels)
        cents = []
        for lab in self.label_names:
            examples = [lab] + [w for w in str(labels[lab]).split() if w]
            m = _np.stack([local_embed(e, self.dim) for e in examples])
            c = m.mean(axis=0)
            n = float((c * c).sum()) ** 0.5
            cents.append(c / n if n > 0 else c)
        self._centroids = _np.stack(cents)

    def classify(self, text: str) -> list[str]:
        sims = self._centroids @ self._embed(text, self.dim)
        order = sims.argsort()[::-1][: self.top]
        return [self.label_names[i] for i in order]

    def tokenize(self, text: str) -> list[Token]:
        if not text or not text.strip():
            return []
        return [Token(lab, i, 0, len(text))
                for i, lab in enumerate(self.classify(text))]


class NearestNeighborsAnalyzer(Analyzer):
    """Model-backed term-expansion analyzer (reference:
    analysis/nearest_neighbors_stream.cpp — fastText emits each token's
    nearest model terms). Vocabulary words are embedded with the local
    char-trigram model; each input token (tokenized by `inner`) is
    replaced by its top-k nearest vocabulary terms, emitted at the
    token's position (synonym-style expansion)."""

    name = "nearest_neighbors"

    def __init__(self, vocab: list[str], top: int = 2, dim: int = 64,
                 inner: Optional[Analyzer] = None):
        import numpy as _np

        from ..functions.embedfns import local_embed
        vocab = [w for w in vocab if w]
        if not vocab:
            raise errors.SqlError(
                "22023", "nearest_neighbors tokenizer needs a vocabulary")
        self._embed = local_embed
        self.top = max(1, int(top))
        self.dim = int(dim)
        self.inner = inner or SimpleTextAnalyzer()
        self.vocab = sorted(set(w.lower() for w in vocab))
        self._matrix = _np.stack(
            [local_embed(w, self.dim) for w in self.vocab])
        self._memo: dict[str, list[str]] = {}

    def neighbors(self, term: str) -> list[str]:
        # terms repeat heavily (Zipf) and this sits on the ingest hot
        # path — memoize per distinct term
        hit = self._memo.get(term)
        if hit is not None:
            return hit
        sims = self._matrix @ self._embed(term, self.dim)
        order = sims.argsort()[::-1][: self.top]
        out = [self.vocab[i] for i in order]
        if len(self._memo) < 1_000_000:
            self._memo[term] = out
        return out

    def tokenize(self, text: str) -> list[Token]:
        out = []
        for t in self.inner.tokenize(text):
            for nb in self.neighbors(t.term):
                out.append(Token(nb, t.position, t.start, t.end))
        return out


_BUILTINS: dict[str, Callable[[], Analyzer]] = {
    "keyword": KeywordAnalyzer,
    "whitespace": WhitespaceAnalyzer,
    "text": TextAnalyzer,
    "simple": SimpleTextAnalyzer,
    "ngram": NgramAnalyzer,
    "edge_ngram": lambda: NgramAnalyzer(edge=True),
    "delimiter": DelimiterAnalyzer,
    "multi_delimiter": MultiDelimiterAnalyzer,
    "segmentation": SegmentationAnalyzer,
    "norm": NormalizingAnalyzer,
    "collation": CollationAnalyzer,
    "stem": StemAnalyzer,
    "path_hierarchy": PathHierarchyAnalyzer,
    "minhash": MinHashAnalyzer,
}
# locale text analyzers: text_en … text_fi (reference registers per-locale
# text tokenizers the same way)
for _lang in ("en", "de", "fr", "es", "it", "pt", "nl", "ru", "sv", "fi",
              "da", "no", "ro", "tr", "hu"):
    _BUILTINS[f"text_{_lang}"] = (
        lambda _l=_lang: TextAnalyzer(locale=_l))

_cache: dict[str, Analyzer] = {}
_custom: dict[str, Analyzer] = {}


_KNOWN_DICT_OPTIONS = {
    # behavioral
    "template", "stemming", "accent", "stopwords", "min", "max",
    "delimiter", "delimiters", "locale", "case", "break", "pattern",
    "mode", "synonyms", "stages", "analyzers", "hashes", "shingle",
    "reverse", "analyzer",
    # model-backed analyzers
    "labels", "top", "vocab", "dim",
    # accepted reference options that are defaults/no-ops here
    "frequency", "position", "norm",
}


def register_dictionary(name: str, options: dict,
                        if_not_exists: bool = False,
                        replace: bool = False) -> Analyzer:
    """CREATE TEXT SEARCH DICTIONARY: a named, configured analyzer
    (reference: server/pg/commands/create_tsdictionary.cpp; template/
    case/stemming/accent options as in examples/demo0/demo.sql).

    Dictionaries may not shadow builtin analyzer names, and duplicates
    error unless IF NOT EXISTS / replace (recovery) is given."""
    key = name.lower()
    unknown = set(options) - _KNOWN_DICT_OPTIONS
    if unknown:
        raise errors.SqlError(
            "22023", f"unrecognized dictionary option "
                     f"{sorted(unknown)[0]!r}")
    if key in _BUILTINS:
        raise errors.SqlError(errors.DUPLICATE_OBJECT,
                              f'"{name}" is a builtin tokenizer')
    if key in _custom and not replace:
        if if_not_exists:
            return _custom[key]
        raise errors.SqlError(errors.DUPLICATE_OBJECT,
                              f'text search dictionary "{name}" already '
                              "exists")
    template = str(options.get("template", "text")).lower()
    def truthy(v, default):
        if v is None:
            return default
        if isinstance(v, bool):
            return v
        return str(v).lower() in ("true", "on", "1", "yes")
    locale = str(options.get("locale", "en"))
    if template in ("text", "simple"):
        want_stop = truthy(options.get("stopwords"), False)
        # reference contract (text_tokenizer.hpp:61, normalizing_
        # tokenizer.hpp:49): accent=true KEEPS accents, accent=false /
        # unset removes them
        a = TextAnalyzer(
            stopwords=(None if want_stop else frozenset()),
            stem=truthy(options.get("stemming"), template == "text"),
            accent_fold=not truthy(options.get("accent"), False),
            locale=locale)
    elif template == "whitespace":
        a = WhitespaceAnalyzer()
    elif template == "keyword":
        a = KeywordAnalyzer()
    elif template in ("ngram", "edge_ngram"):
        a = NgramAnalyzer(int(options.get("min", 2)),
                          int(options.get("max", 3)),
                          edge=template == "edge_ngram")
    elif template == "delimiter":
        a = DelimiterAnalyzer(str(options.get("delimiter", ",")))
    elif template == "multi_delimiter":
        ds = options.get("delimiters", ",;")
        if isinstance(ds, str):
            ds = list(ds)
        a = MultiDelimiterAnalyzer(ds)
    elif template == "segmentation":
        a = SegmentationAnalyzer(
            break_mode=str(options.get("break", "alpha")).lower(),
            case=str(options.get("case", "lower")).lower())
    elif template == "norm":
        a = NormalizingAnalyzer(
            case=str(options.get("case", "lower")).lower(),
            accent=truthy(options.get("accent"), False))
    elif template == "collation":
        a = CollationAnalyzer(locale)
    elif template == "stem":
        a = StemAnalyzer(locale)
    elif template == "pattern":
        a = PatternAnalyzer(str(options.get("pattern", r"\w+")),
                            mode=str(options.get("mode", "match")).lower(),
                            case=str(options.get("case", "none")).lower())
    elif template == "path_hierarchy":
        a = PathHierarchyAnalyzer(
            str(options.get("delimiter", "/")),
            reverse=truthy(options.get("reverse"), False))
    elif template == "synonyms":
        rules = options.get("synonyms", "")
        if isinstance(rules, str):
            rules = [r for r in re.split(r"[\n;]", rules) if r.strip()]
        inner = get_analyzer(str(options.get("analyzer", "simple")))
        a = SynonymAnalyzer(rules, inner)
    elif template == "pipeline":
        names = options.get("stages", "")
        stage_names = ([s.strip() for s in names.split(",") if s.strip()]
                       if isinstance(names, str) else list(names))
        a = PipelineAnalyzer([get_analyzer(s) for s in stage_names])
    elif template == "union":
        names = options.get("analyzers", "")
        part_names = ([s.strip() for s in names.split(",") if s.strip()]
                      if isinstance(names, str) else list(names))
        a = UnionAnalyzer([get_analyzer(s) for s in part_names])
    elif template == "minhash":
        a = MinHashAnalyzer(
            k=int(options.get("hashes", 32)),
            inner=get_analyzer(str(options.get("analyzer", "simple"))),
            shingle=int(options.get("shingle", 3)))
    elif template == "classification":
        raw = options.get("labels", "")
        labels: dict[str, str] = {}
        if isinstance(raw, dict):
            labels = {str(k).strip().lower(): str(v)
                      for k, v in raw.items()}
        else:
            # "sports: football goal; tech: compiler kernel"
            for part in re.split(r"[;\n]", str(raw)):
                lab, _, examples = part.partition(":")
                if lab.strip():
                    labels[lab.strip().lower()] = examples.strip()
        a = ClassificationAnalyzer(labels,
                                   top=int(options.get("top", 1)),
                                   dim=int(options.get("dim", 64)))
    elif template == "nearest_neighbors":
        raw = options.get("vocab", "")
        vocab = ([str(w) for w in raw] if isinstance(raw, (list, tuple))
                 else re.split(r"[\s,;]+", str(raw)))
        a = NearestNeighborsAnalyzer(
            vocab, top=int(options.get("top", 2)),
            dim=int(options.get("dim", 64)),
            inner=get_analyzer(str(options.get("analyzer", "simple"))))
    else:
        raise errors.SqlError(errors.UNDEFINED_OBJECT,
                              f'tokenizer template "{template}" does not '
                              "exist")
    a.name = name.lower()
    _custom[name.lower()] = a
    return a


def dictionary_exists(name: str) -> bool:
    return name.lower() in _custom


def drop_dictionary(name: str) -> bool:
    return _custom.pop(name.lower(), None) is not None


def get_analyzer(name: str) -> Analyzer:
    key = (name or "text").lower()
    a = _custom.get(key)
    if a is not None:
        return a
    a = _cache.get(key)
    if a is None:
        ctor = _BUILTINS.get(key)
        if ctor is None:
            raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                  f'tokenizer "{name}" does not exist')
        a = _cache[key] = ctor()
    return a


def default_analyzer() -> Analyzer:
    return get_analyzer("text")
