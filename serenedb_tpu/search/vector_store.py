"""Device-resident vector store: a paged HBM region for IVF / MaxSim.

The PR 16 posting-pool pattern applied to dense vectors: vectors live
CLUSTER-MAJOR in a paged f32 region (`serene_vector_pages` 16 KiB
pages, carved out of the `serene_device_cache_mb` envelope and traded
against the column cache / posting pool under
`serene_device_cache_trade`), one pool entry per index segment with
LRU eviction and weakref reclamation. A query probes the top-nprobe
centroid lists and exact-rescores their contiguous logical slices
through a slot map (logical position → region row), so warm coalesced
knn batches run as ONE jitted dispatch with ZERO host→device vector
bytes — only the query block uploads.

Layout: an index's logical order is cluster-major across its segments
(cluster c = seg₀'s c-rows ++ seg₁'s c-rows ++ …); each segment's rows
sit row-padded in whole pages (rows-per-page = PAGE_F32 / pow2(dim)),
so a segment append writes ONLY the new segment's pages — the base
segments stay hot (the zone-map tail trick, device edition).

Bit-parity: resident, cold (pool off / starved / dim > page) and
brute-oracle paths all run the same `ops.vector` program bodies whose
distance expression is a fixed f32 add chain mirrored by
`ops.vector.host_dist`, and selection is an exact two-key sort — so
`serene_vector_pool` is NOT result-affecting and `nprobe=lists` is
bit-identical to the host brute-force oracle.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from collections import OrderedDict
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..obs import device as obs_device
from ..obs.trace import current_trace
from ..ops import vector as vops
from ..utils import faults, metrics
from ..utils.config import REGISTRY as _settings

#: f32 slots per page (pow2): 16 KiB/page — rows-per-page stays whole
#: for every pow2-padded dim up to 4096
PAGE_F32 = 4096

_PAD_ROW = vops._PAD_ROW

#: scan-chunk lanes: bounds the rescore temp at (Qp, 1024, dp) however
#: large N or nprobe·M grow (the memory-blowup guard)
_CHUNK_LANES = 1024

#: MaxSim docs per scan chunk (the (B, dc, tmax, S) similarity block is
#: the program's large temp)
_MAXSIM_DOCS = 128

#: per-index descriptor memo entries (committed slot/offset/rowid
#: tables of one (region seq, segment stamps) composition — the
#: warm-repeat zero-upload path)
_DESC_MEMO_CAP = 8

#: committed probe-grid chunk maps kept pool-wide, keyed
#: (nprobe, max-count, lanes)
_MAP_MEMO_CAP = 32


def enabled() -> bool:
    try:
        return bool(_settings.get_global("serene_vector_pool"))
    except KeyError:  # pragma: no cover — registry declares it
        return False


def maxsim_device(settings=None) -> bool:
    try:
        if settings is not None:
            return bool(settings.get("serene_maxsim"))
        return bool(_settings.get_global("serene_maxsim"))
    except KeyError:  # pragma: no cover — registry declares it
        return True


def effective_nprobe(settings) -> int:
    """`serene_nprobe` when set (> 0), else the legacy `sdb_nprobe` —
    one result-affecting knob with a compatibility alias."""
    try:
        n = int(settings.get("serene_nprobe"))
    except KeyError:
        n = 0
    if n > 0:
        return n
    try:
        return max(1, int(settings.get("sdb_nprobe")))
    except KeyError:  # pragma: no cover — registry declares it
        return 8


def _effective_pages() -> int:
    """Page budget: `serene_vector_pages`, never exceeding the
    `serene_device_cache_mb` byte cap (the pool is carved out of that
    budget, not added)."""
    try:
        pages = max(4, int(_settings.get_global("serene_vector_pages")))
    except KeyError:  # pragma: no cover — registry declares it
        pages = 4096
    try:
        cap_mb = int(_settings.get_global("serene_device_cache_mb"))
        pages = min(pages, max(4, (cap_mb << 20) // (PAGE_F32 * 4)))
    except KeyError:  # pragma: no cover
        pass
    return pages


def _pow2(n: int, floor: int = 1) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


def note_publication(idx, provider, pin) -> None:
    """Stamp the scan's publication identity onto the index so pool
    entries written for its segments report which table/version/epoch
    occupies the pages (sdb_vector_pool rows)."""
    try:
        from ..exec.device_pipeline import _pub
        pub = _pub(provider, pin)
    except Exception:  # noqa: BLE001 — stats identity only, never fatal
        return
    obs_device.note_provider(pub[0], getattr(provider, "name", ""))
    if getattr(idx, "_pool_pub", None) != pub:
        idx._pool_pub = pub


def _write_program(region, slots, stage):
    """Staged page write: ONE scatter-set produces the next region
    snapshot. Pad rows repeat the last page with identical content —
    deterministic."""
    return region.at[slots].set(stage)


class _Entry:
    """One resident index segment: its page list, row count, padded
    width, write stamp (descriptor-validity token) and the hit/idle
    signals the LRU and sdb_vector_pool read."""

    __slots__ = ("key", "slots", "n", "dp", "stamp", "pub", "hits",
                 "last_ns")

    def __init__(self, key, slots, n, dp, stamp, pub):
        self.key = key
        self.slots = slots
        self.n = n
        self.dp = dp
        self.stamp = stamp
        self.pub = pub
        self.hits = 0
        self.last_ns = time.perf_counter_ns()


class VectorPool:
    def __init__(self):
        self._lock = threading.RLock()
        self._entries: "OrderedDict[int, _Entry]" = OrderedDict()
        self._region_arr = None
        self._n_pages = 0
        self._free: list[int] = []
        self._seq = 0                  # region generation (budget change)
        self._stamp = itertools.count(1)
        self._uids = itertools.count(1)
        self._maps: "OrderedDict[tuple, tuple]" = OrderedDict()

    # -- identity ---------------------------------------------------------

    def seg_uid(self, seg) -> int:
        """Process-unique id for an index segment; the finalizer frees
        the dead segment's pages. Rebuilt indexes get fresh segments,
        hence fresh uids — 'writes move the key'. Appends REUSE the
        base segments, so their pages stay hot across the refresh."""
        uid = getattr(seg, "_vpool_uid", None)
        if uid is None:
            with self._lock:
                uid = getattr(seg, "_vpool_uid", None)
                if uid is None:
                    uid = seg._vpool_uid = next(self._uids)
                    weakref.finalize(seg, self.release_segment, uid)
        return uid

    def release_segment(self, uid: int) -> None:
        with self._lock:
            e = self._entries.pop(uid, None)
            if e is not None:
                self._free.extend(e.slots.tolist())
                if self._n_pages:
                    used = self._n_pages - len(self._free)
                    metrics.VECTOR_BYTES_RESIDENT.set(
                        used * PAGE_F32 * 4)

    # -- region -----------------------------------------------------------

    def _region(self) -> None:
        """Caller holds the lock. (Re)build the paged region to the
        current budget; a budget change drops every entry (operator
        action, rare)."""
        budget = _effective_pages()
        if self._region_arr is None or self._n_pages != budget:
            self._region_arr = jnp.zeros((budget, PAGE_F32), jnp.float32)
            self._n_pages = budget
            self._entries.clear()
            self._free = list(range(budget - 1, -1, -1))
            self._seq += 1
            metrics.VECTOR_BYTES_RESIDENT.set(0)

    def clear(self) -> None:
        """Drop the region and every entry (tests / budget
        experiments). The next search rebuilds lazily."""
        with self._lock:
            self._region_arr = None
            self._n_pages = 0
            self._entries.clear()
            self._free = []
            self._seq += 1
            self._maps.clear()
            metrics.VECTOR_BYTES_RESIDENT.set(0)

    def _alloc(self, need: int, busy: set) -> Optional[np.ndarray]:
        """Caller holds the lock: pop `need` free pages, evicting
        least-recently-used segments (never ones this batch pinned).
        None when the budget cannot fit the segment at all."""
        if need > self._n_pages:
            return None
        while len(self._free) < need:
            victim = None
            for key in list(self._entries):
                if key not in busy:
                    victim = key
                    break
            if victim is None:
                return None
            e = self._entries.pop(victim)
            self._free.extend(e.slots.tolist())
            metrics.VECTOR_POOL_EVICTIONS.add()
        return np.asarray([self._free.pop() for _ in range(need)],
                          dtype=np.int32)

    def _write(self, writes) -> None:
        """Caller holds the lock: batch every new segment's pages into
        ONE staged upload + scatter-set program producing the next
        region snapshot. Rows pad to pow2(dim) width and pages zero-pad
        past the segment tail, so reused pages never leak a prior
        tenant's vectors."""
        slots = np.concatenate([w[0] for w in writes])
        n_new = len(slots)
        stage = np.zeros((n_new, PAGE_F32), np.float32)
        row = 0
        for pages, vals, dp in writes:
            npg = len(pages)
            rpp = PAGE_F32 // dp
            buf = np.zeros((npg * rpp, dp), np.float32)
            buf[:len(vals), :vals.shape[1]] = vals
            stage[row:row + npg] = buf.reshape(npg, PAGE_F32)
            row += npg
        n_pad = _pow2(n_new, 4)
        if n_pad > n_new:
            pad = n_pad - n_new
            slots = np.concatenate(
                [slots, np.full(pad, slots[-1], np.int32)])
            stage = np.concatenate(
                [stage, np.repeat(stage[-1:], pad, axis=0)])
        t0 = time.perf_counter_ns()
        from ..columnar.device import commit_host_array
        prog = obs_device.compiled(
            "vector_pool_write", (self._n_pages, n_pad),
            lambda: _write_program)
        self._region_arr = prog(
            self._region_arr, commit_host_array(slots),
            commit_host_array(stage))
        tr = current_trace()
        if tr is not None:
            tr.add("vector_upload", "device", t0, time.perf_counter_ns(),
                   pages=n_new)

    # -- residency --------------------------------------------------------

    def _ensure(self, idx):
        """Try to make every segment of `idx` resident (all-or-nothing:
        partial vector residency buys little — a missing segment would
        force a host merge — so a segment that cannot fit sends the
        whole query to the cold path). Returns
        (region, seq, n_pages, entries) or None."""
        dp = _pow2(int(idx.dim), 1)
        if dp > PAGE_F32 or not idx.segs:
            return None
        rpp = PAGE_F32 // dp
        pub = getattr(idx, "_pool_pub", None)
        with self._lock:
            self._region()
            busy: set = set()
            writes = []
            ents: list[_Entry] = []
            now = time.perf_counter_ns()
            for seg in idx.segs:
                uid = self.seg_uid(seg)
                e = self._entries.get(uid)
                if e is None:
                    n = len(seg.vals)
                    pages = self._alloc(max(1, -(-n // rpp)), busy)
                    if pages is None:
                        return None
                    e = _Entry(uid, pages, n, dp, next(self._stamp), pub)
                    self._entries[uid] = e
                    writes.append((pages, seg.vals, dp))
                    metrics.VECTOR_POOL_MISSES.add()
                else:
                    metrics.VECTOR_POOL_HITS.add()
                    e.hits += 1
                e.last_ns = now
                if pub is not None:
                    e.pub = pub
                self._entries.move_to_end(uid)
                busy.add(uid)
                ents.append(e)
            if writes:
                self._write(writes)
            used = self._n_pages - len(self._free)
            metrics.VECTOR_BYTES_RESIDENT.set(used * PAGE_F32 * 4)
            # snapshot capture: immutable arrays stay consistent for
            # the dispatch below even if another thread evicts pages
            return (self._region_arr, self._seq, self._n_pages, ents)

    def _slotmap(self, idx, ents, npos_pad: int) -> np.ndarray:
        """Logical position → region row, through each segment's page
        list. Pad positions point at row 0 (dead lanes never read them
        live)."""
        lay = idx.layout()
        seg_of, within = lay["seg_of"], lay["within"]
        slot = np.zeros(npos_pad, np.int32)
        for si, e in enumerate(ents):
            mask = seg_of == si
            if not mask.any():
                continue
            w = within[mask].astype(np.int64)
            rpp = PAGE_F32 // e.dp
            shift = rpp.bit_length() - 1
            slot[np.nonzero(mask)[0]] = (
                e.slots[w >> shift].astype(np.int64) * rpp
                + (w & (rpp - 1))).astype(np.int32)
        return slot

    def _descriptor(self, idx, ents, seq: int, kind: str) -> dict:
        """Committed device descriptor tables for one index
        composition, memoized on the index keyed by (region seq,
        segment write stamps): a warm repeat uploads ZERO descriptor
        bytes."""
        key = (kind, seq, tuple(e.stamp for e in ents))
        memo = getattr(idx, "_vpool_desc", None)
        if memo is None:
            memo = idx._vpool_desc = OrderedDict()
        hit = memo.get(key)
        if hit is not None:
            memo.move_to_end(key)
            return hit
        hit = self._build_descriptor(idx, ents, kind)
        memo[key] = hit
        while len(memo) > _DESC_MEMO_CAP:
            memo.popitem(last=False)
        return hit

    def _build_descriptor(self, idx, ents, kind: str,
                          region: Optional[np.ndarray] = None) -> dict:
        """The committed tables themselves. With `region` given (cold
        path) the slot map is the identity over the logical matrix."""
        from ..columnar.device import commit_host_array
        lay = idx.layout()
        ntot = lay["ntot"]
        l_real = lay["nlists"]
        dp = _pow2(int(idx.dim), 1)
        # maxsim pads one extra zero-count slot so pad docs in the scan
        # chunks have a dead cluster to point at
        lp = _pow2(max(l_real, 1) + (1 if kind == "maxsim" else 0), 1)
        npos_pad = _pow2(max(ntot, 1), 8)
        off = np.zeros(lp, np.int32)
        off[:l_real] = lay["offsets"][:l_real].astype(np.int32)
        cnt = np.zeros(lp, np.int32)
        cnt[:l_real] = lay["counts"][:l_real].astype(np.int32)
        rows = np.full(npos_pad, _PAD_ROW, np.int32)
        rows[:ntot] = lay["rowids"]
        if region is None:
            slot = self._slotmap(idx, ents, npos_pad)
        else:
            slot = np.arange(npos_pad, dtype=np.int32)
        d = {"dp": dp, "lp": lp, "npos_pad": npos_pad,
             "slotmap": commit_host_array(slot),
             "offsets": commit_host_array(off),
             "counts": commit_host_array(cnt),
             "rowids": commit_host_array(rows)}
        if kind == "ivf":
            cents = np.zeros((lp, dp), np.float32)
            c = idx.centroids
            cents[:c.shape[0], :c.shape[1]] = c
            d["cents"] = commit_host_array(cents)
        else:
            # maxsim: per-cluster (= per-doc) row ids, pad-docs dead
            crows = np.full(lp, _PAD_ROW, np.int32)
            crows[:l_real] = lay["cluster_rowids"]
            d["cluster_rowids"] = commit_host_array(crows)
        if region is not None:
            pad = np.zeros((npos_pad, dp), np.float32)
            pad[:region.shape[0], :region.shape[1]] = region
            d["region"] = commit_host_array(pad)
        return d

    def _cold_descriptor(self, idx, kind: str) -> dict:
        """Pool off / starved / dim too wide: commit the logical matrix
        as a temporary region, fresh per call (unaccounted residency
        would dodge the budget). Same program bodies → same bits."""
        return self._build_descriptor(idx, [], kind,
                                      region=idx.host_logical())

    def _chunk_maps(self, nprobe: int, m: int, mc: int):
        """Committed probe-grid chunk maps, memoized pool-wide."""
        key = (nprobe, m, mc)
        with self._lock:
            hit = self._maps.get(key)
            if hit is not None:
                self._maps.move_to_end(key)
                return hit
        from ..columnar.device import commit_host_array
        tm, jm = vops.chunk_maps(nprobe, m, mc)
        hit = (commit_host_array(tm), commit_host_array(jm), tm.shape[0])
        with self._lock:
            self._maps[key] = hit
            while len(self._maps) > _MAP_MEMO_CAP:
                self._maps.popitem(last=False)
        return hit

    # -- search -----------------------------------------------------------

    def search(self, idx, queries: np.ndarray, k: int, nprobe: int):
        """Batched IVF probe: centroid top-nprobe → slot-map gather →
        exact rescore → exact (dist asc, row asc) top-k, ONE dispatch.
        Returns (dists (nq, kk) f32, rows (nq, kk) i32) numpy; dead
        lanes carry (+inf, _PAD_ROW) — callers filter non-finite."""
        lay = idx.layout()
        l_real = lay["nlists"]
        nprobe = max(1, min(int(nprobe), l_real))
        m = int(lay["max_count"])
        return self._dispatch_probe(idx, queries, k, nprobe, m, "ivf",
                                    resident=enabled())

    def brute(self, idx, queries: np.ndarray, k: int):
        """Brute-force oracle: the SAME probe program over a trivial
        one-cluster descriptor (every logical row in list 0), scanned
        in the SAME lane chunks — per-(query,row) distance bits are the
        probe path's bits by construction, which is what makes the
        `nprobe=lists` parity contract checkable bit-for-bit."""
        return self._dispatch_probe(idx, queries, k, 1,
                                    int(idx.layout()["ntot"]), "brute",
                                    resident=False)

    def _dispatch_probe(self, idx, queries, k, nprobe, m, kind,
                        resident):
        from ..columnar.device import commit_host_array
        faults.if_failure("vector_dispatch")
        lay = idx.layout()
        ntot = lay["ntot"]
        metric = idx.metric
        nq = queries.shape[0]
        kk = min(max(int(k), 1), max(ntot, 1))
        kkp = _pow2(kk, 8)
        mc = min(_CHUNK_LANES, _pow2(max(m, 1), 8))
        res = self._ensure(idx) if (resident and kind == "ivf") else None
        if res is not None:
            region, seq, n_pages, ents = res
            desc = self._descriptor(idx, ents, seq, "ivf")
            shape_tag = ("pool", n_pages)
        else:
            if kind == "brute":
                # the oracle's one-cluster layout: every logical row in
                # list 0 of the identity slot map
                desc = self._brute_descriptor(idx)
            else:
                desc = self._cold_descriptor(idx, "ivf")
            region = desc["region"]
            shape_tag = ("cold", desc["npos_pad"])
        dp = desc["dp"]
        l_real = 1 if kind == "brute" else lay["nlists"]
        qp = _pow2(nq, 1)
        q = np.zeros((qp, dp), np.float32)
        q[:nq, :queries.shape[1]] = queries
        tmap, jmap, nchunks = self._chunk_maps(nprobe, max(m, 1), mc)
        fam = "vector_brute" if kind == "brute" else "vector_probe"
        prog = obs_device.compiled(
            fam,
            (metric, dp, desc["lp"], l_real, nprobe, kkp, mc, nchunks,
             qp, shape_tag),
            lambda: vops.probe_program(metric, dp, l_real, nprobe, kkp,
                                       mc))
        t0 = time.perf_counter_ns()
        outs = prog(region, desc["slotmap"], desc["offsets"],
                    desc["counts"], desc["rowids"], desc["cents"],
                    commit_host_array(q), tmap, jmap)
        d, r = obs_device.fetch_all(outs)
        tr = current_trace()
        if tr is not None:
            tr.add("vector_dispatch", "device", t0,
                   time.perf_counter_ns(), queries=nq, nprobe=nprobe,
                   kind=kind, resident=res is not None)
        metrics.VECTOR_SEARCH_QUERIES.add(nq)
        metrics.VECTOR_SEARCH_DISPATCHES.add()
        metrics.VECTOR_PROBED_CLUSTERS.add(nq * nprobe)
        return d[:nq, :kk], r[:nq, :kk]

    def _brute_descriptor(self, idx) -> dict:
        """One cluster holding the whole logical matrix, memoized on
        the (immutable) index — the oracle is a test/bench surface, not
        a serving path, but the bench calls it in a loop."""
        hit = getattr(idx, "_vpool_brute_desc", None)
        if hit is not None:
            return hit
        from ..columnar.device import commit_host_array
        x = idx.host_logical()
        lay = idx.layout()
        ntot = lay["ntot"]
        dp = _pow2(int(idx.dim), 1)
        npos_pad = _pow2(max(ntot, 1), 8)
        rows = np.full(npos_pad, _PAD_ROW, np.int32)
        rows[:ntot] = lay["rowids"]
        pad = np.zeros((npos_pad, dp), np.float32)
        pad[:x.shape[0], :x.shape[1]] = x
        hit = {"dp": dp, "lp": 1, "npos_pad": npos_pad,
               "region": commit_host_array(pad),
               "slotmap": commit_host_array(
                   np.arange(npos_pad, dtype=np.int32)),
               "offsets": commit_host_array(np.zeros(1, np.int32)),
               "counts": commit_host_array(
                   np.asarray([ntot], np.int32)),
               "rowids": commit_host_array(rows),
               "cents": commit_host_array(np.zeros((1, dp),
                                                   np.float32))}
        idx._vpool_brute_desc = hit
        return hit

    # -- MaxSim -----------------------------------------------------------

    def maxsim_search(self, idx, qtoks: np.ndarray, k: int):
        """Batched MaxSim: docs are the clusters (one token matrix
        each); scores are Σ_s max_t <q_s, d_t>, selected with the exact
        (score desc, doc asc) contract. qtoks: (B, S, dim) f32 (token
        rows zero-padded across the batch — an exact no-op). Returns
        (keys (B, kk) f32 = NEGATED scores, rows (B, kk) i32)."""
        from ..columnar.device import commit_host_array
        faults.if_failure("vector_dispatch")
        lay = idx.layout()
        ndocs = lay["nlists"]
        ntot = lay["ntot"]
        b, s = qtoks.shape[0], qtoks.shape[1]
        kk = min(max(int(k), 1), max(ndocs, 1))
        kkp = _pow2(kk, 8)
        tmax = _pow2(max(int(lay["max_count"]), 1), 1)
        dc = min(_MAXSIM_DOCS, _pow2(max(ndocs, 1), 1))
        res = self._ensure(idx) if enabled() else None
        if res is not None:
            region, seq, n_pages, ents = res
            desc = self._descriptor(idx, ents, seq, "maxsim")
            shape_tag = ("pool", n_pages)
        else:
            desc = self._cold_descriptor(idx, "maxsim")
            region = desc["region"]
            shape_tag = ("cold", desc["npos_pad"])
        dp = desc["dp"]
        tile = min(dp, 32)
        sp = _pow2(max(s, 1), 1)
        bp = _pow2(max(b, 1), 1)
        q = np.zeros((bp, sp, dp), np.float32)
        q[:b, :s, :qtoks.shape[2]] = qtoks
        # doc chunks: pad docs point at the extra zero-count slot the
        # maxsim descriptor reserves at index ndocs (dead lanes)
        dmap, nchunks = self._doc_maps(ndocs, dc)
        prog = obs_device.compiled(
            "vector_maxsim",
            (dp, tile, tmax, kkp, dc, nchunks, bp, sp, desc["lp"],
             shape_tag),
            lambda: vops.maxsim_program(dp, tile, tmax, kkp, dc))
        t0 = time.perf_counter_ns()
        outs = prog(region, desc["slotmap"], desc["offsets"],
                    desc["counts"], desc["cluster_rowids"],
                    commit_host_array(q), dmap)
        keys, rows = obs_device.fetch_all(outs)
        tr = current_trace()
        if tr is not None:
            tr.add("vector_dispatch", "device", t0,
                   time.perf_counter_ns(), queries=b, kind="maxsim",
                   resident=res is not None)
        metrics.VECTOR_SEARCH_QUERIES.add(b)
        metrics.VECTOR_SEARCH_DISPATCHES.add()
        metrics.VECTOR_PROBED_CLUSTERS.add(b * ndocs)
        return keys[:b, :kk], rows[:b, :kk]

    def _doc_maps(self, ndocs: int, dc: int):
        """Committed MaxSim doc-chunk map (pad = index ndocs, the
        reserved zero-count slot), memoized pool-wide."""
        key = ("dmap", ndocs, dc)
        with self._lock:
            hit = self._maps.get(key)
            if hit is not None:
                self._maps.move_to_end(key)
                return hit
        from ..columnar.device import commit_host_array
        nchunks = max(1, -(-ndocs // dc))
        dm = np.full(nchunks * dc, ndocs, np.int32)
        dm[:ndocs] = np.arange(ndocs, dtype=np.int32)
        hit = (commit_host_array(dm.reshape(nchunks, dc)), nchunks)
        with self._lock:
            self._maps[key] = hit
            while len(self._maps) > _MAP_MEMO_CAP:
                self._maps.popitem(last=False)
        return hit

    # -- observability ----------------------------------------------------

    def device_bytes(self) -> dict[int, int]:
        """Region HBM bytes per holding device — merged into the
        sdb_device() hbm_bytes_est column (obs/device.device_rows)."""
        with self._lock:
            if self._region_arr is None:
                return {}
            ids = obs_device.array_device_ids(self._region_arr) or (0,)
            total = self._n_pages * PAGE_F32 * 4
            return {int(i): total // len(ids) for i in ids}

    def snapshot(self) -> list[dict]:
        """sdb_vector_pool() rows: per (publication, segment) resident
        pages, bytes, hits and idle time."""
        with self._lock:
            now = time.perf_counter_ns()
            rows = []
            for uid, e in self._entries.items():
                pub = e.pub or (0, 0, 0)
                rows.append({
                    "token": int(pub[0]),
                    "data_version": int(pub[1]),
                    "mutation_epoch": int(pub[2]),
                    "segment": uid,
                    "vectors": int(e.n),
                    "pages": len(e.slots),
                    "bytes": len(e.slots) * PAGE_F32 * 4,
                    "hits": int(e.hits),
                    "idle_ms": round((now - e.last_ns) / 1e6, 3)})
        rows.sort(key=lambda r: (r["token"], r["segment"]))
        return rows

    # -- budget trade with the device column cache (§19) -------------------

    def live_bytes(self) -> int:
        """HBM bytes of LIVE (allocated) pages — this pool's claim on
        the shared serene_device_cache_mb envelope."""
        with self._lock:
            if self._region_arr is None:
                return 0
            return (self._n_pages - len(self._free)) * PAGE_F32 * 4

    def tail_idle_ns(self) -> Optional[int]:
        """Idle time of the LRU tail entry (the next eviction victim),
        or None when the pool is empty."""
        with self._lock:
            for e in self._entries.values():
                return time.perf_counter_ns() - e.last_ns
            return None

    def shed_colder(self, idle_ns: int, need_bytes: int) -> int:
        """Evict LRU-tail segments idle LONGER than `idle_ns` until
        `need_bytes` of pages free; stops at the first tail entry
        warmer than the threshold. Returns bytes freed (the column
        cache calls this when IT is over cap and this pool's tail is
        the coldest claimant)."""
        freed = 0
        with self._lock:
            now = time.perf_counter_ns()
            while freed < need_bytes:
                victim = None
                for key, e in self._entries.items():
                    if now - e.last_ns > idle_ns:
                        victim = key
                    break           # LRU head only: warmer head ends it
                if victim is None:
                    break
                e = self._entries.pop(victim)
                self._free.extend(e.slots.tolist())
                freed += len(e.slots) * PAGE_F32 * 4
                metrics.VECTOR_POOL_EVICTIONS.add()
            if freed and self._n_pages:
                used = self._n_pages - len(self._free)
                metrics.VECTOR_BYTES_RESIDENT.set(used * PAGE_F32 * 4)
        return freed

    def stats(self) -> dict:
        """The `/_stats` / `GET /device` vector_pool section."""
        with self._lock:
            used = (self._n_pages - len(self._free)) \
                if self._region_arr is not None else 0
            return {"pages": self._n_pages,
                    "pages_used": used,
                    "page_bytes": PAGE_F32 * 4,
                    "resident_segments": len(self._entries),
                    "hits": int(metrics.VECTOR_POOL_HITS.value),
                    "misses": int(metrics.VECTOR_POOL_MISSES.value),
                    "evictions": int(
                        metrics.VECTOR_POOL_EVICTIONS.value),
                    "queries": int(
                        metrics.VECTOR_SEARCH_QUERIES.value),
                    "dispatches": int(
                        metrics.VECTOR_SEARCH_DISPATCHES.value)}


#: process-wide pool (indexes and their segments are process-wide)
VPOOL = VectorPool()
