"""Automaton ∩ sorted-term-dictionary intersection.

Reference analog: the burst-trie term dictionary intersected with openfst
automata (levenshtein/wildcard/regexp —
libs/iresearch/include/iresearch/formats/index/burst_trie.cpp). The TPU
build's term dictionary is a SORTED string array, so the equivalent is
the classic sorted-seek walk (Lucene's TermsEnum.seekCeil pattern):

    walk the current term through the automaton; if rejected, compute a
    SEEK TARGET — the smallest string greater than the term that could
    still be accepted given the shared prefix — and binary-search the
    dictionary to it, skipping every term in between.

Soundness of the skip: the target is t[:j] + c where j is the DEEPEST
position with a transition on some c > t[j] (or an extension char when t
walked fully). Any term u strictly between t and the target either dies
at the same failed transition as t, or diverges at a depth where no
transition above t's char exists — both rejected. No completion of the
target is needed (and none is computed: lexicographically-minimal
completions need not exist under cycles); the next loop iteration walks
whatever real term the seek lands on.

Works over the regexp module's NFA states (subset construction memoized
per state-set), and over a Levenshtein NFA built here from the same
_State/_Char/_Dot atoms — one intersection routine serves regex,
prefix/wildcard and fuzzy expansion.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .regexp import Regexp, _Char, _Class, _Dot, _State, nfa_fullmatch

_MAXCHAR = 0x10FFFF

#: cap on interned DFA state sets: adversarial patterns (counting
#: constructs like `.*a.{20}`) blow up subset construction exponentially;
#: past the cap the walk degrades to per-term NFA matching, which is
#: O(pattern states) memory like the pre-automaton scan
MAX_DFA_STATES = 10_000


class _DfaBudget(Exception):
    pass


class _Dfa:
    """On-the-fly subset construction over an NFA with transition and
    min-successor memoization."""

    def __init__(self, start: _State, end: _State):
        self.end = end
        self._ids: dict[frozenset, int] = {}
        self._sets: list[frozenset] = []
        self._trans: dict[tuple[int, str], int] = {}
        self._accept: dict[int, bool] = {}
        s0 = frozenset(Regexp._closure({start}, True, False))
        self.start_id = self._intern(s0)
        # acceptance must also consider the empty string (at_start=True)
        self._accept_start = end in Regexp._closure({start}, True, True)

    def _intern(self, ss: frozenset) -> int:
        sid = self._ids.get(ss)
        if sid is None:
            if len(self._sets) >= MAX_DFA_STATES:
                raise _DfaBudget
            sid = len(self._sets)
            self._ids[ss] = sid
            self._sets.append(ss)
        return sid

    def step(self, sid: int, ch: str) -> int:
        """Transition; -1 = dead."""
        key = (sid, ch)
        hit = self._trans.get(key)
        if hit is not None:
            return hit
        nxt = {t for st in self._sets[sid] for atom, t in st.edges
               if Regexp._atom_matches(atom, ch)}
        out = -1 if not nxt \
            else self._intern(frozenset(Regexp._closure(nxt, False, False)))
        self._trans[key] = out
        return out

    def accepts(self, sid: int) -> bool:
        hit = self._accept.get(sid)
        if hit is None:
            hit = self._accept[sid] = self.end in Regexp._closure(
                set(self._sets[sid]), False, True)
        return hit

    def min_char_above(self, sid: int, bound: Optional[str]) -> Optional[str]:
        """Smallest char strictly greater than `bound` (None = any) with
        an outgoing transition from this state set."""
        lo = -1 if bound is None else ord(bound)
        best = None
        for st in self._sets[sid]:
            for atom, _t in st.edges:
                c = _atom_min_above(atom, lo)
                if c is not None and (best is None or c < best):
                    best = c
        return best


def _atom_min_above(atom, lo: int) -> Optional[str]:
    """Smallest char with code > lo that the atom matches."""
    if isinstance(atom, _Char):
        return atom.c if ord(atom.c) > lo else None
    if isinstance(atom, _Dot):
        return chr(lo + 1) if lo + 1 <= _MAXCHAR else None
    # character class
    if not atom.negated:
        best = None
        for a, b in atom.ranges:
            if ord(b) <= lo:
                continue
            c = chr(max(ord(a), lo + 1))
            if best is None or c < best:
                best = c
        return best
    # negated class: first code > lo not inside any range
    code = lo + 1
    while code <= _MAXCHAR:
        for a, b in atom.ranges:
            if ord(a) <= code <= ord(b):
                code = ord(b) + 1
                break
        else:
            return chr(code)
    return None


def intersect_sorted(start: _State, end: _State,
                     terms: np.ndarray) -> list[int]:
    """Ids of sorted `terms` accepted by the NFA, via seek-skipping.
    Patterns whose subset construction exceeds MAX_DFA_STATES finish
    with a plain per-term NFA scan of the REMAINING band — matches the
    DFA already confirmed are kept, not recomputed."""
    out: list[int] = []
    resume = [0]
    try:
        _intersect_dfa(start, end, terms, out, resume)
    except _DfaBudget:
        out.extend(i for i in range(resume[0], len(terms))
                   if nfa_fullmatch(start, end, str(terms[i])))
    return out


def _intersect_dfa(start: _State, end: _State, terms: np.ndarray,
                   out: list, resume: list) -> list[int]:
    dfa = _Dfa(start, end)
    n = len(terms)
    i = 0
    while i < n:
        resume[0] = i
        t = str(terms[i])
        # walk as deep as transitions allow, keeping the state at each depth
        states = [dfa.start_id]
        sid = dfa.start_id
        d = len(t)
        for j, ch in enumerate(t):
            nxt = dfa.step(sid, ch)
            if nxt < 0:
                d = j
                break
            sid = nxt
            states.append(sid)
        else:
            accepted = dfa.accepts(sid) if t else dfa._accept_start
            if accepted:
                out.append(i)
                i += 1
                continue
        # rejected: seek target = deepest divergence with a live transition
        target = None
        for j in range(d, -1, -1):
            bound = t[j] if j < len(t) else None
            c = dfa.min_char_above(states[j], bound)
            if c is not None:
                target = t[:j] + c
                break
        if target is None:
            break
        # max(..., i+1): numpy's fixed-width unicode comparison pads with
        # NULs, so a target like "abc\x00" compares EQUAL to "abc" and
        # the seek could stall; the current term is rejected, so
        # advancing one slot is always sound
        i = max(int(np.searchsorted(terms, target, side="left")), i + 1)
    resume[0] = n
    return out


# -- Levenshtein NFA ---------------------------------------------------------

def levenshtein_nfa(term: str, max_edits: int) -> tuple[_State, _State]:
    """NFA accepting strings within `max_edits` edits of `term`
    (insert/delete/substitute), built from the regexp module's state
    atoms so intersect_sorted serves fuzzy expansion too (reference:
    levenshtein parametric automata over the burst trie)."""
    m = len(term)
    grid = [[_State() for _ in range(max_edits + 1)] for _ in range(m + 1)]
    end = _State()
    for i in range(m + 1):
        for e in range(max_edits + 1):
            st = grid[i][e]
            if i < m:
                # match
                st.edges.append((_Char(term[i]), grid[i + 1][e]))
                if e < max_edits:
                    # substitution
                    st.edges.append((_Dot(), grid[i + 1][e + 1]))
                    # deletion of term[i] (consume no input)
                    st.eps.append(grid[i + 1][e + 1])
            if e < max_edits:
                # insertion (consume one input char, stay at i)
                st.edges.append((_Dot(), grid[i][e + 1]))
            if i == m:
                st.eps.append(end)
    return grid[0][0], end
