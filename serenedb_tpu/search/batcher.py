"""Search query batcher: coalesce concurrent top-k queries into one
ragged scoring dispatch per (searcher, k, scorer, mesh) group.

Production search traffic is thousands of concurrent SMALL queries over
the SAME index data — each paying full scoring-dispatch overhead alone.
This module is the serving-side fix (ROADMAP "batched ragged search
serving"; the shape of Ragged Paged Attention's ragged-batch kernel and
GPUSparse's parallel inverted indices): queries arriving within a short
window fold into one `MultiSearcher.topk_batch` call, which scores them
in a single vectorized pass per segment over the shared postings/norms
(ragged per-query term lists — search/searcher._ragged_resolve on the
host backend, the batched plane kernel on devices). With
`serene_posting_pool` on, the coalesced dispatch is the one that never
leaves the device: page-resident batches score as ONE jitted
gather-and-accumulate program over the pool's HBM page tables
(search/posting_pool.py), and a warm repeat uploads zero posting bytes.

Coalescing is group-commit shaped, so an idle system never waits:

- a query that is the only active submitter of its group dispatches
  IMMEDIATELY (zero added latency for serial workloads — tier-1 runs
  with batching on and pays nothing);
- while a dispatch is in flight, arrivals queue behind it and fold into
  the next dispatch the moment it completes — the in-flight dispatch IS
  the batching window under sustained load;
- `serene_search_batch_window_ms` bounds how long a query may wait for
  company when other submitters are active but not yet queued, and
  `serene_search_batch_max` caps queries per dispatch.

Parity contract: per-query results are BIT-IDENTICAL to serial dispatch
(scores, doc ids, tie order) — per-query scoring is batch-composition-
independent in every kernel path (asserted by tests/test_search_batch.py
across batched on/off × workers × cache states), so `serene_search_batch
= off` remains a pure serial oracle, the serene_join_vectorized=off
pattern, and the setting stays OUT of the result cache's
RESULT_AFFECTING_SETTINGS digest.

Error isolation: a dispatch that raises marks every member for SERIAL
RETRY on its own submitter thread — a poisoned query fails only its own
caller (with its own context/cancellation), never its batch siblings.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Optional

from ..utils import metrics
from ..utils.config import REGISTRY as _settings_registry

#: process-wide dispatch sequence: every coalesced dispatch gets one id,
#: stamped into each member's batch_dispatch span so a timeline reader
#: (or test) can see WHICH queries shared a scoring pass
_DISPATCH_SEQ = itertools.count(1)


class _Entry:
    __slots__ = ("node", "done", "retry", "result", "n_batch",
                 "window_ns", "scoring_ns", "t_submit_ns", "trace")

    def __init__(self, node):
        self.node = node
        self.done = False
        self.retry = False
        self.result = None
        self.n_batch = 1
        self.window_ns = 0
        self.scoring_ns = 0
        self.t_submit_ns = time.perf_counter_ns()
        # the submitter's timeline (None when tracing is off): a
        # coalesced dispatch stamps its window/scoring spans under
        # EVERY member query's trace, so each member's timeline shows
        # both the wait it paid and the shared dispatch it rode
        from ..obs.trace import current_trace
        self.trace = current_trace()


class _Group:
    """Transient per-(searcher, k, scorer, mesh) coalescing state. Holds
    the searcher STRONGLY while live, so the id() in the group key can
    never alias a dead searcher's recycled address. Each group waits on
    its OWN condition (sharing the batcher lock), so a dispatch
    completing wakes only its group's waiters — with dozens of
    submitter threads a single shared condition turns every completion
    into an O(waiters) GIL stampede."""

    __slots__ = ("searcher", "queue", "dispatching", "active", "cv")

    def __init__(self, searcher, lock):
        self.searcher = searcher
        self.queue: list[_Entry] = []
        self.dispatching = False
        self.active = 0
        self.cv = threading.Condition(lock)


class SearchBatcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._groups: dict[tuple, _Group] = {}

    def submit(self, searcher, node, k: int, scorer: str, mesh_n: int,
               window_s: float, batch_max: int,
               ) -> tuple[tuple, Optional[dict]]:
        """Coalesce-and-score one query; blocks until its result is ready.
        Returns ((scores, docs), stats) with stats carrying the batch
        span counters for the profiler."""
        key = (id(searcher), int(k), scorer, int(mesh_n))
        e = _Entry(node)
        deadline = time.monotonic() + window_s
        batch = None
        with self._lock:
            g = self._groups.get(key)
            if g is None or g.searcher is not searcher:
                g = self._groups[key] = _Group(searcher, self._lock)
            g.active += 1
            g.queue.append(e)
            try:
                while not e.done and not e.retry:
                    now = time.monotonic()
                    if not g.dispatching and (
                            len(g.queue) >= batch_max or
                            now >= deadline or
                            g.active <= len(g.queue)):
                        # claim the dispatch: this entry plus the oldest
                        # queued others (up to the cap) score in one
                        # ragged pass on THIS thread. Own entry ALWAYS
                        # rides its own claim — leaving it queued while
                        # falling back serially would orphan it (scored
                        # twice by a later claimer, or pinning the group
                        # forever if nobody else arrives).
                        g.queue.remove(e)
                        batch = [e] + g.queue[:batch_max - 1]
                        del g.queue[:batch_max - 1]
                        g.dispatching = True
                        break
                    # bounded waits only: re-check conditions even if a
                    # wakeup is lost, and honor the window deadline.
                    # The wait publishes live into the session's
                    # pg_stat_activity row (the batch_wait span's live
                    # counterpart).
                    from ..obs.resources import wait_scope
                    with wait_scope("IPC", "SearchBatchWait"):
                        if g.dispatching:
                            g.cv.wait(0.25)
                        else:
                            g.cv.wait(
                                min(max(deadline - now, 0.0002), 0.05))
            finally:
                if batch is None:
                    self._release(key, g)
        if batch is not None:
            try:
                self._dispatch(g, batch, k, scorer, mesh_n)
            finally:
                with self._lock:
                    self._release(key, g)
        if e.retry or (batch is not None and not e.done):
            # dispatch failed (every member lands here, each on its own
            # thread): serial fallback, so the caller's context/
            # cancellation apply and a poisoned sibling can't take this
            # query down
            out = searcher.topk_batch([node], k, scorer, mesh_n=mesh_n)[0]
            return out, {"queries": 1, "window_ns": 0, "scoring_ns": 0}
        return e.result, {"queries": e.n_batch, "window_ns": e.window_ns,
                          "scoring_ns": e.scoring_ns}

    def _release(self, key, g: _Group) -> None:
        """Caller MUST hold the lock: retire one submitter and drop the
        group when idle. Queued waiters' dispatch-eligibility may have
        changed (`active` shrank toward the queue length) — wake them;
        with nothing queued there is nobody to wake."""
        g.active -= 1
        if g.active <= 0 and not g.queue and not g.dispatching:
            cur = self._groups.get(key)
            if cur is g:
                del self._groups[key]
        elif g.queue:
            g.cv.notify_all()

    def _dispatch(self, g: _Group, batch: list[_Entry], k: int,
                  scorer: str, mesh_n: int) -> None:
        """Score one claimed batch and hand each member its result. On
        ANY failure every member retries serially on its own thread."""
        t0 = time.perf_counter_ns()
        outs = None
        try:
            outs = g.searcher.topk_batch([x.node for x in batch], k,
                                         scorer, mesh_n=mesh_n,
                                         ragged=True)
        except BaseException:
            outs = None   # members retry serially; the bad one re-raises
        t1 = time.perf_counter_ns()
        wait_ns = 0
        seq = next(_DISPATCH_SEQ) if outs is not None else 0
        with self._lock:
            g.dispatching = False
            for i, x in enumerate(batch):
                if outs is not None:
                    x.result = outs[i]
                    x.n_batch = len(batch)
                    x.window_ns = max(t0 - x.t_submit_ns, 0)
                    x.scoring_ns = t1 - t0
                    wait_ns += x.window_ns
                    if x.trace is not None:
                        # per-member timeline: how long THIS query
                        # waited queued, then the shared scoring
                        # dispatch it rode. Stamped from the
                        # dispatching thread BEFORE x.done releases the
                        # member — its statement cannot finalize its
                        # trace until these spans are in the rings
                        if x.window_ns:
                            x.trace.add("batch_wait", "search",
                                        x.t_submit_ns, t0)
                        x.trace.add("batch_dispatch", "search", t0, t1,
                                    queries=len(batch), dispatch=seq)
                    x.done = True
                else:
                    x.retry = True
            g.cv.notify_all()
        if outs is not None:
            metrics.SEARCH_BATCH_DISPATCHES.add()
            metrics.SEARCH_BATCH_QUERIES.add(len(batch))
            metrics.SEARCH_BATCH_WINDOW_WAIT_NS.add(wait_ns)
            if len(batch) > 1:
                metrics.SEARCH_BATCH_COALESCED.add(len(batch))
            for x in batch:
                metrics.SEARCH_BATCH_WINDOW_HIST.observe_ns(x.window_ns)


#: process-wide batcher (searcher groups are process-wide objects)
BATCHER = SearchBatcher()


def batched_topk(searcher, node, k: int, scorer: str = "bm25",
                 mesh_n: int = 0, settings=None,
                 ) -> tuple[tuple, Optional[dict]]:
    """Serving entry point for every top-k consumer (SQL `@@@`/bm25()
    scans, ES `_search`/`_msearch` via those scans): route one query
    through the batcher when `serene_search_batch` is on, else dispatch
    serially (the parity oracle). Fragment-cache hits are probed FIRST
    and returned immediately — a cached query never waits out a window or
    occupies a batch slot; misses store per-query after the batch scores.
    Returns ((scores, docs), batch-stats-or-None)."""
    try:
        if settings is not None:
            on = bool(settings.get("serene_search_batch"))
        else:
            on = bool(_settings_registry.get_global("serene_search_batch"))
    except KeyError:                                   # pragma: no cover
        on = False
    if not on:
        return searcher.topk(node, k, scorer, mesh_n=mesh_n), None
    hit = searcher.probe_topk(node, k, scorer, mesh_n)
    if hit is not None:
        return hit, None
    window_s = max(float(_settings_registry.get_global(
        "serene_search_batch_window_ms")), 0.0) / 1000.0
    batch_max = max(int(_settings_registry.get_global(
        "serene_search_batch_max")), 1)
    return BATCHER.submit(searcher, node, k, scorer, mesh_n, window_s,
                          batch_max)
