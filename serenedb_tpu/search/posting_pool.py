"""Device-resident paged posting pool: batched ragged search in HBM.

PR 8's batched ragged scorer (search/searcher._ragged_resolve) is host
numpy end to end — every coalesced dispatch re-flattens WAND-kept
postings on the CPU while the device tier sits idle. This module is the
accelerator analog of Ragged Paged Attention's paged KV pool (PAPERS.md)
applied to inverted lists (GPUSparse's parallel index traversal): one
paged HBM region holds posting blocks, uploaded ONCE, and a coalesced
batch whose terms are page-resident scores as ONE jitted
gather-and-segment-accumulate program over page tables — zero
host→device posting bytes on the warm path.

Layout and keying
-----------------
The pool owns a fixed region pair `(docs, tfs)` of shape
`(serene_posting_pages, PAGE)` int32 — pow2 page size, budget
coordinated with `serene_device_cache_mb` (the region never exceeds the
column-cache byte cap). A pool entry is ONE term's full posting range
chunked into pages, keyed `(segment uid, term id)` where the uid is
pinned to the segment's immutable BlockStore (fragment-cache idiom:
attach + weakref finalizer). The serving publication
`(provider token, data_version, mutation_epoch)` — stamped by
exec/search_scan via `note_publication` — rides on entries for the
`sdb_posting_pool()` operator view. The append-tail ("zone-map tail")
trick falls out of segment immutability: a pure append creates NEW
segments whose terms allocate new tail pages while every old segment's
pages stay valid and hot; a mutation rebuilds segments, so writes move
the key and the dead uids' pages are reclaimed by their finalizers.

Scoring and parity
------------------
Residency is PREFIX-shaped per query: slices (the (plane, term) flatten
order of `_ragged_resolve`) are ensured in order, and the first
non-resident slice cuts the device portion. Fully resident queries run
the `posting_pool` program (gather pages → `ops/bm25.contrib_expr` —
THE same expression tree the host ragged path traces — → scatter-add
over the query's candidate slots → exact two-key lax.sort top-k);
partially resident queries run `posting_pool_partial`, which returns
the raw accumulator so the host adds the non-resident suffix slices in
the SAME order — an identical f32 addition sequence to the all-host
path, which stays on as the bit-exact parity oracle behind
`serene_posting_pool = off`. Query/page/candidate axes pad to powers of
two so coalesced batches of every size reuse a handful of programs
(compile-ledger hygiene), and pad entries carry weight 0 into a dead
dump slot — contributing exactly +0.0 nowhere visible.

Concurrency: region arrays are immutable jax values; page writes build
NEW arrays via one staged scatter-set program, so an in-flight dispatch
keeps scoring its captured snapshot even while another thread evicts or
rewrites pages. Residency ensure + descriptor capture happen under one
lock hold; dispatches run outside it.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from collections import OrderedDict
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import device as obs_device
from ..obs.trace import current_trace
from ..utils import faults, metrics
from ..utils.config import REGISTRY as _settings

#: postings per page (pow2): 8 KiB/page across the (docs, tfs) pair —
#: small enough that short tails waste little, large enough that a
#: million-posting term is ~1k page table entries
PAGE = 1024
PAGE_SHIFT = 10

#: doc-id pad sentinel in sort keys (matches the device merge pads)
_PAD_DOC = (1 << 31) - 1

#: device-side batch descriptor memo entries kept per store (each holds
#: the uploaded slot/weight/scatter matrices of one batch composition —
#: the warm-repeat zero-upload path)
_BATCH_MEMO_CAP = 16


def enabled() -> bool:
    try:
        return bool(_settings.get_global("serene_posting_pool"))
    except KeyError:  # pragma: no cover — registry declares it
        return False


def _effective_pages() -> int:
    """Page budget: `serene_posting_pages`, never exceeding the
    `serene_device_cache_mb` byte cap the operator already granted the
    device tier (the pool is carved out of that budget, not added)."""
    try:
        pages = max(8, int(_settings.get_global("serene_posting_pages")))
    except KeyError:  # pragma: no cover — registry declares it
        pages = 4096
    try:
        cap_mb = int(_settings.get_global("serene_device_cache_mb"))
        pages = min(pages, max(8, (cap_mb << 20) // (PAGE * 8)))
    except KeyError:  # pragma: no cover
        pass
    return pages


def note_publication(searcher, provider, pin) -> None:
    """Stamp the scan's publication identity onto the (multi)searcher's
    segments so pool entries written for them report which
    table/version/epoch occupies the pages (sdb_posting_pool rows).
    First write wins per distinct publication; cheap enough to call per
    scan."""
    try:
        from ..exec.device_pipeline import _pub
        pub = _pub(provider, pin)
    except Exception:  # noqa: BLE001 — stats identity only, never fatal
        return
    obs_device.note_provider(pub[0], getattr(provider, "name", ""))
    segs = getattr(searcher, "segments", None)
    targets = [s for s, _ in segs] if segs else [searcher]
    for seg in targets:
        if getattr(seg, "_pool_pub", None) != pub:
            seg._pool_pub = pub


def _write_program(docs_pg, tfs_pg, slots, stage_docs, stage_tfs):
    """Staged page write: ONE scatter-set pair produces the next region
    snapshot. Pad rows repeat the last page with identical content, so
    duplicate slots write the same bytes — deterministic."""
    return (docs_pg.at[slots].set(stage_docs),
            tfs_pg.at[slots].set(stage_tfs))


def _accumulate(c, posm, cp):
    """Candidate-lane accumulator, scatter-free: `posm[q, t, lane]` is
    the ep-axis position of term t's contribution to that lane (the
    host-built inverse of the scatter map), with ep itself as the
    sentinel pointing at an appended zero column. The term loop unrolls
    statically left-to-right, so every lane sums its terms in slice
    order — the host ragged path's exact add sequence — while lowering
    to pure gathers, which vectorize on every backend where a ragged
    scatter-add serializes (an order of magnitude on host XLA, worse
    on TPUs)."""
    qp = c.shape[0]
    cpad = jnp.concatenate([c, jnp.zeros((qp, 1), jnp.float32)], axis=1)
    acc = jnp.zeros((qp, cp), jnp.float32)
    for t in range(posm.shape[1]):
        acc = acc + jnp.take_along_axis(cpad, posm[:, t, :], axis=1)
    return acc


def _pool_program(scorer: str, kk: int):
    """Builder for the fully-resident batch program: page-table gather →
    contrib_expr (bit-identical tree to the host ragged path) →
    per-term gather-accumulate → exact top-k selection."""
    from ..ops import bm25 as bm25_ops

    def run(docs_pg, tfs_pg, norms, si, w, posm, cand, nc, k1, b, avgdl):
        ft = tfs_pg.reshape(-1)[si]
        fd = docs_pg.reshape(-1)[si]
        dl = norms[fd]
        c = bm25_ops.contrib_expr(ft, dl, w, k1, b, avgdl, scorer)
        qp, cp = cand.shape
        acc = _accumulate(c, posm, cp)
        live = jnp.arange(cp, dtype=jnp.int32)[None, :] < nc[:, None]
        sc = jnp.where(live, acc, -jnp.inf)
        dk = jnp.where(live, cand, _PAD_DOC)
        # exact (score desc, doc asc) — the topk_tie_exact order:
        # top_k breaks score ties by LOWER lane index, and each row's
        # candidate lanes are doc-id ascending (np.unique), so index
        # order IS doc order. O(cp·log kk), vs a full-width variadic
        # sort which is ~200x slower on the host backend and
        # sort-lowered on TPUs. Dead lanes sink on -inf and are sliced
        # off by the caller (it keeps only len(cand) rows).
        vals_s, sel = jax.lax.top_k(sc, kk)
        docs_s = jnp.take_along_axis(dk, sel, axis=1)
        return vals_s, docs_s

    return run


def _pool_partial_program(scorer: str, cp: int):
    """Builder for the partial-residency batch: same gather/accumulate,
    but the RAW accumulator returns to the host, which continues the
    non-resident suffix slices in order (identical add sequence)."""
    from ..ops import bm25 as bm25_ops

    def run(docs_pg, tfs_pg, norms, si, w, posm, k1, b, avgdl):
        ft = tfs_pg.reshape(-1)[si]
        fd = docs_pg.reshape(-1)[si]
        dl = norms[fd]
        c = bm25_ops.contrib_expr(ft, dl, w, k1, b, avgdl, scorer)
        return _accumulate(c, posm, cp)

    return run


class _Entry:
    """One resident term: its page table, posting count, write stamp
    (descriptor-validity token — changes iff the key is rewritten) and
    the hit/idle signals the LRU and sdb_posting_pool read."""

    __slots__ = ("key", "slots", "n", "stamp", "pub", "hits", "last_ns")

    def __init__(self, key, slots, n, stamp, pub):
        self.key = key
        self.slots = slots
        self.n = n
        self.stamp = stamp
        self.pub = pub
        self.hits = 0
        self.last_ns = time.perf_counter_ns()


def _slice_slots(entry: _Entry, sl) -> np.ndarray:
    """Global region slots of one slice's kept postings: the term's page
    table expanded at the slice's within-term positions (all of them for
    light/full-range slices, the WAND-kept subset for masked ones)."""
    pos = sl.idx if sl.idx is not None \
        else np.arange(entry.n, dtype=np.int64)
    return (entry.slots[pos >> PAGE_SHIFT].astype(np.int64) * PAGE
            + (pos & (PAGE - 1))).astype(np.int32)


class PostingPool:
    def __init__(self):
        self._lock = threading.RLock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._docs = None
        self._tfs = None
        self._n_pages = 0
        self._free: list[int] = []
        self._seq = 0                  # region generation (budget change)
        self._stamp = itertools.count(1)
        self._uids = itertools.count(1)

    # -- identity ---------------------------------------------------------

    def store_uid(self, store) -> int:
        """Process-unique id for a segment's BlockStore; the finalizer
        frees the dead segment's pages (fragment-cache segment_uid
        idiom). Rebuilt segments get fresh stores, hence fresh uids —
        'writes move the key'."""
        uid = getattr(store, "_pool_uid", None)
        if uid is None:
            with self._lock:
                uid = getattr(store, "_pool_uid", None)
                if uid is None:
                    uid = store._pool_uid = next(self._uids)
                    weakref.finalize(store, self.release_segment, uid)
        return uid

    def release_segment(self, uid: int) -> None:
        """Weakref finalizer target: reclaim every page the dead
        segment's terms held."""
        with self._lock:
            dead = [k for k in self._entries if k[0] == uid]
            for k in dead:
                e = self._entries.pop(k)
                self._free.extend(e.slots.tolist())
            if dead and self._n_pages:
                used = self._n_pages - len(self._free)
                metrics.POSTING_POOL_PAGES_USED.set(used)
                metrics.POSTING_POOL_BYTES.set(used * PAGE * 8)

    # -- region -----------------------------------------------------------

    def _region(self) -> None:
        """Caller holds the lock. (Re)build the paged region to the
        current budget; a budget change drops every entry (operator
        action, rare)."""
        budget = _effective_pages()
        if self._docs is None or self._n_pages != budget:
            self._docs = jnp.zeros((budget, PAGE), jnp.int32)
            self._tfs = jnp.zeros((budget, PAGE), jnp.int32)
            self._n_pages = budget
            self._entries.clear()
            self._free = list(range(budget - 1, -1, -1))
            self._seq += 1
            metrics.POSTING_POOL_PAGES_USED.set(0)
            metrics.POSTING_POOL_BYTES.set(0)

    def clear(self) -> None:
        """Drop the region and every entry (tests / budget experiments).
        The next scoring call rebuilds lazily."""
        with self._lock:
            self._docs = self._tfs = None
            self._n_pages = 0
            self._entries.clear()
            self._free = []
            self._seq += 1
            metrics.POSTING_POOL_PAGES_USED.set(0)
            metrics.POSTING_POOL_BYTES.set(0)

    def _alloc(self, need: int, busy: set) -> Optional[np.ndarray]:
        """Caller holds the lock: pop `need` free pages, evicting
        least-recently-used entries (never ones this batch pinned) to
        make room. None when the budget cannot fit the term at all."""
        if need > self._n_pages:
            return None
        while len(self._free) < need:
            victim = None
            # LRU order; iterate a copy — a GC-triggered segment
            # finalizer re-entering on this thread may mutate the dict
            for key in list(self._entries):
                if key not in busy:
                    victim = key
                    break
            if victim is None:
                return None
            e = self._entries.pop(victim)
            self._free.extend(e.slots.tolist())
            metrics.POSTING_POOL_EVICTIONS.add()
        return np.asarray([self._free.pop() for _ in range(need)],
                          dtype=np.int32)

    def _write(self, writes) -> None:
        """Caller holds the lock: batch every new entry's pages into ONE
        staged upload + scatter-set program producing the next region
        snapshot. Short tails zero-pad to the page boundary, so reused
        pages never leak a prior tenant's postings past `entry.n`."""
        slots = np.concatenate([w[0] for w in writes])
        n_new = len(slots)
        sd = np.zeros((n_new, PAGE), np.int32)
        st = np.zeros((n_new, PAGE), np.int32)
        row = 0
        for pages, d, t in writes:
            npg = len(pages)
            sd[row:row + npg].reshape(-1)[:len(d)] = d
            st[row:row + npg].reshape(-1)[:len(t)] = t
            row += npg
        from ..ops.bm25 import _pow2
        n_pad = _pow2(n_new, 8)
        if n_pad > n_new:
            pad = n_pad - n_new
            slots = np.concatenate(
                [slots, np.full(pad, slots[-1], np.int32)])
            sd = np.concatenate([sd, np.repeat(sd[-1:], pad, axis=0)])
            st = np.concatenate([st, np.repeat(st[-1:], pad, axis=0)])
        t0 = time.perf_counter_ns()
        from ..columnar.device import commit_host_array
        prog = obs_device.compiled(
            "posting_pool_write", (self._n_pages, n_pad),
            lambda: _write_program)
        self._docs, self._tfs = prog(
            self._docs, self._tfs, commit_host_array(slots),
            commit_host_array(sd), commit_host_array(st))
        tr = current_trace()
        if tr is not None:
            tr.add("posting_upload", "device", t0, time.perf_counter_ns(),
                   pages=n_new)

    # -- scoring ----------------------------------------------------------

    def score_queries(self, searcher, store, per_q, k: int, scorer: str,
                      avgdl: float, k1: float, b: float, cand_fn) -> dict:
        """Device tier of `_ragged_resolve`: ensure residency for each
        admitted query's slices (in slice order — prefix semantics),
        then score fully resident queries to final top-k and partially
        resident ones to raw accumulators in at most two batched
        dispatches. Returns {qi: ("full", scores, docs) |
        ("partial", acc, n_resident_slices)}; queries absent from the
        result stay entirely on the host oracle path."""
        faults.if_failure("posting_pool_dispatch")
        # plan-free queries (all-light terms, or θ=0 plans) are admitted
        # too: their slices are a pure function of (store, tids), so the
        # entry-stamp tuple in the batch-memo key still identifies the
        # composition exactly even though id(plan) is id(None) for all
        reqs = [(qi, plan, slices) for qi, plan, slices in per_q if slices]
        if not reqs:
            return {}
        uid = self.store_uid(store)
        pub = getattr(searcher, "_pool_pub", None)
        with self._lock:
            self._region()
            busy: set = set()
            writes = []
            prefixes: list[list[_Entry]] = []
            now = time.perf_counter_ns()
            for qi, plan, slices in reqs:
                ents: list[_Entry] = []
                blocked = False
                for sl in slices:
                    key = (uid, sl.tid)
                    e = self._entries.get(key)
                    if e is not None:
                        metrics.POSTING_POOL_HITS.add()
                        e.hits += 1
                    elif not blocked:
                        n = sl.e - sl.s
                        pages = self._alloc(-(-n // PAGE), busy)
                        if pages is None:
                            blocked = True
                        else:
                            e = _Entry(key, pages, n, next(self._stamp),
                                       pub)
                            self._entries[key] = e
                            writes.append((pages, store.flat_docs[sl.s:sl.e],
                                           store.flat_tfs[sl.s:sl.e]))
                            metrics.POSTING_POOL_MISSES.add()
                    if e is None:
                        break    # prefix ends at first non-resident slice
                    e.last_ns = now
                    if pub is not None:
                        e.pub = pub
                    self._entries.move_to_end(key)
                    busy.add(key)
                    ents.append(e)
                prefixes.append(ents)
            if writes:
                self._write(writes)
            used = self._n_pages - len(self._free)
            metrics.POSTING_POOL_PAGES_USED.set(used)
            metrics.POSTING_POOL_BYTES.set(used * PAGE * 8)
            # snapshot capture: these immutable arrays stay consistent
            # for the dispatch below even if another thread evicts or
            # rewrites pages concurrently
            docs_pg, tfs_pg = self._docs, self._tfs
            seq, n_pages = self._seq, self._n_pages
        out: dict = {}
        full_items, part_items = [], []
        for (qi, plan, slices), ents in zip(reqs, prefixes):
            if not ents:
                continue
            cand, ixs = cand_fn(store, plan, slices)
            if not len(cand):
                continue
            item = (qi, plan, slices, ents, cand, ixs)
            (full_items if len(ents) == len(slices)
             else part_items).append(item)
        if full_items:
            rows = self._dispatch(store, full_items, k, scorer, avgdl, k1,
                                  b, docs_pg, tfs_pg, seq, n_pages, True)
            for (qi, _p, _s, _e, cand, _i), (vals, docs) in zip(full_items,
                                                                rows):
                m = min(k, len(cand))
                out[qi] = ("full", vals[:m], docs[:m])
            metrics.POSTING_POOL_DEVICE_QUERIES.add(len(full_items))
        if part_items:
            rows = self._dispatch(store, part_items, k, scorer, avgdl, k1,
                                  b, docs_pg, tfs_pg, seq, n_pages, False)
            for (qi, _p, _s, ents, cand, _i), acc in zip(part_items, rows):
                out[qi] = ("partial", acc[:len(cand)].copy(), len(ents))
            metrics.POSTING_POOL_PARTIAL.add(len(part_items))
        return out

    def _dispatch(self, store, items, k, scorer, avgdl, k1, b, docs_pg,
                  tfs_pg, seq, n_pages, topk: bool):
        """One batched device program over captured region snapshots.
        The per-batch descriptor matrices (slot/weight/scatter/candidate
        tables) memoize on the store keyed by batch composition + entry
        write stamps, so a warm repeat of the same coalesced batch
        uploads ZERO bytes and performs exactly ONE dispatch."""
        from ..ops import bm25 as bm25_ops
        memo = getattr(store, "_pool_batch_memo", None)
        if memo is None:
            memo = store._pool_batch_memo = OrderedDict()
        kk_want = min(bm25_ops.pad_k(k), 1 << 30) if topk else 0
        mkey = (topk, kk_want, scorer, seq, n_pages,
                tuple((id(plan), len(ents),
                       tuple(e.stamp for e in ents))
                      for _q, plan, _s, ents, _c, _i in items))
        hit = memo.get(mkey)
        if hit is None:
            nq = len(items)
            qp = bm25_ops._pow2(nq, 1)
            ep = bm25_ops._pow2(
                max(sum(len(ix) for ix in ixs[:len(ents)])
                    for _q, _p, _s, ents, _c, ixs in items), 8)
            cp = bm25_ops._pow2(
                max(len(cand) for _q, _p, _s, _e, cand, _i in items) + 1,
                16)
            tp = bm25_ops._pow2(
                max(len(ents) for _q, _p, _s, ents, _c, _i in items), 1)
            si = np.zeros((qp, ep), np.int32)
            wm = np.zeros((qp, ep), np.float32)
            # inverse of the scatter map: ep-axis position of term t's
            # contribution to each candidate lane; sentinel ep gathers
            # the program's appended zero column (exact no-op add)
            posm = np.full((qp, tp, cp), ep, np.int32)
            cm = np.full((qp, cp), _PAD_DOC, np.int32)
            ncv = np.zeros((qp,), np.int32)
            for i, (_q, _p, slices, ents, cand, ixs) in enumerate(items):
                pos = 0
                for t, (sl, e, ix) in enumerate(zip(slices, ents, ixs)):
                    g = _slice_slots(e, sl)
                    si[i, pos:pos + len(g)] = g
                    wm[i, pos:pos + len(g)] = sl.w
                    posm[i, t, ix] = pos + np.arange(len(g), dtype=np.int32)
                    pos += len(g)
                cm[i, :len(cand)] = cand
                ncv[i] = len(cand)
            from ..columnar.device import commit_host_array
            hit = {"qp": qp, "ep": ep, "cp": cp, "tp": tp,
                   "kk": min(kk_want, cp) if topk else 0,
                   "si": commit_host_array(si),
                   "w": commit_host_array(wm),
                   "posm": commit_host_array(posm),
                   "cand": commit_host_array(cm) if topk else None,
                   "nc": commit_host_array(ncv) if topk else None,
                   # strong plan refs pin the id()s in mkey
                   "plans": [p for _q, p, _s, _e, _c, _i in items]}
            memo[mkey] = hit
            while len(memo) > _BATCH_MEMO_CAP:
                memo.popitem(last=False)
        else:
            memo.move_to_end(mkey)
        cp = hit["cp"]
        if topk:
            prog = obs_device.compiled(
                "posting_pool",
                (n_pages, hit["qp"], hit["ep"], cp, hit["tp"], hit["kk"],
                 scorer),
                lambda: _pool_program(scorer, hit["kk"]))
            args = (docs_pg, tfs_pg, store.norms, hit["si"], hit["w"],
                    hit["posm"], hit["cand"], hit["nc"],
                    np.float32(k1), np.float32(b), np.float32(avgdl))
        else:
            prog = obs_device.compiled(
                "posting_pool_partial",
                (n_pages, hit["qp"], hit["ep"], cp, hit["tp"], scorer),
                lambda: _pool_partial_program(scorer, cp))
            args = (docs_pg, tfs_pg, store.norms, hit["si"], hit["w"],
                    hit["posm"], np.float32(k1), np.float32(b),
                    np.float32(avgdl))
        t0 = time.perf_counter_ns()
        outs = prog(*args)
        fetched = obs_device.fetch_all(outs if topk else [outs])
        tr = current_trace()
        if tr is not None:
            tr.add("posting_dispatch", "device", t0,
                   time.perf_counter_ns(), queries=len(items),
                   partial=not topk)
        if topk:
            vals, docs = fetched
            return [(vals[i], docs[i]) for i in range(len(items))]
        return [fetched[0][i] for i in range(len(items))]

    # -- observability ----------------------------------------------------

    def device_bytes(self) -> dict[int, int]:
        """Region HBM bytes per holding device — merged into the
        sdb_device() hbm_bytes_est column (obs/device.device_rows)."""
        with self._lock:
            if self._docs is None:
                return {}
            ids = obs_device.array_device_ids(self._docs) or (0,)
            total = self._n_pages * PAGE * 8
            return {int(i): total // len(ids) for i in ids}

    def snapshot(self) -> list[dict]:
        """sdb_posting_pool() rows: per (publication, segment) resident
        terms, page occupancy, bytes, hits and idle time — the live data
        operators size `serene_posting_pages` from."""
        with self._lock:
            now = time.perf_counter_ns()
            agg: dict = {}
            for (uid, _tid), e in self._entries.items():
                pub = e.pub or (0, 0, 0)
                r = agg.get((pub, uid))
                if r is None:
                    r = agg[(pub, uid)] = {
                        "token": int(pub[0]),
                        "data_version": int(pub[1]),
                        "mutation_epoch": int(pub[2]),
                        "segment": uid, "terms": 0, "pages": 0,
                        "bytes": 0, "hits": 0, "last_ns": 0}
                r["terms"] += 1
                r["pages"] += len(e.slots)
                r["bytes"] += e.n * 8
                r["hits"] += e.hits
                r["last_ns"] = max(r["last_ns"], e.last_ns)
        rows = []
        for r in agg.values():
            r["idle_ms"] = round((now - r.pop("last_ns")) / 1e6, 3)
            rows.append(r)
        rows.sort(key=lambda r: (r["token"], r["segment"]))
        return rows

    # -- budget trade with the device column cache (§19) -------------------

    def live_bytes(self) -> int:
        """HBM bytes of LIVE (allocated) pages — the pool's claim on the
        shared serene_device_cache_mb envelope. Free pages of the region
        don't count: they cost HBM but the trade is about who gets to
        KEEP data resident, and an idle region re-shrinks only on a
        budget change (rebuilds drop every entry, so resizing per query
        would thrash)."""
        with self._lock:
            if self._docs is None:
                return 0
            return (self._n_pages - len(self._free)) * PAGE * 8

    def tail_idle_ns(self) -> Optional[int]:
        """Idle time of the LRU tail entry (the next eviction victim),
        or None when the pool is empty."""
        with self._lock:
            for e in self._entries.values():
                return time.perf_counter_ns() - e.last_ns
            return None

    def shed_colder(self, idle_ns: int, need_bytes: int) -> int:
        """Evict LRU-tail entries that have sat idle LONGER than
        `idle_ns` until `need_bytes` of pages are freed; stops at the
        first tail entry warmer than the threshold. Returns bytes
        freed. Called by the column cache when IT is over cap and the
        pool's tail is colder than its own — lock order is strictly
        cache-side-unlocked → pool, so this can never deadlock against
        a concurrent score/alloc holding the pool lock."""
        freed = 0
        with self._lock:
            now = time.perf_counter_ns()
            while freed < need_bytes:
                victim = None
                for key, e in self._entries.items():
                    if now - e.last_ns > idle_ns:
                        victim = key
                    break           # LRU head only: warmer head ends it
                if victim is None:
                    break
                e = self._entries.pop(victim)
                self._free.extend(e.slots.tolist())
                freed += len(e.slots) * PAGE * 8
                metrics.POSTING_POOL_EVICTIONS.add()
            if freed and self._n_pages:
                used = self._n_pages - len(self._free)
                metrics.POSTING_POOL_PAGES_USED.set(used)
                metrics.POSTING_POOL_BYTES.set(used * PAGE * 8)
        return freed

    def stats(self) -> dict:
        """The `/_stats` / `GET /device` posting_pool section."""
        with self._lock:
            used = (self._n_pages - len(self._free)) if self._docs \
                is not None else 0
            return {"pages": self._n_pages,
                    "pages_used": used,
                    "page_bytes": PAGE * 8,
                    "resident_terms": len(self._entries),
                    "hits": int(metrics.POSTING_POOL_HITS.value),
                    "misses": int(metrics.POSTING_POOL_MISSES.value),
                    "evictions": int(
                        metrics.POSTING_POOL_EVICTIONS.value)}


#: process-wide pool (segments and their stores are process-wide objects)
POOL = PostingPool()
