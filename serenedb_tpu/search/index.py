"""Index build: CREATE INDEX ... USING inverted backfill.

Reference analog: duckdb_physical_create_index.* (backfill scan feeding an
irs::IndexWriter; SURVEY.md §2.5). V1 builds one segment over the current
table contents; the storage layer adds incremental segments + WAL.
"""

from __future__ import annotations

import contextlib
import threading
import time

import numpy as np

from .. import errors
from ..utils import log, metrics
from .analysis import get_analyzer
from .searcher import MultiSearcher, SearchIndex, SegmentSearcher
# build_field_index stays re-exported: callers that want the serial
# oracle unconditionally (tests, parity harnesses) import it from here.
from .segment import build_field_index  # noqa: F401
from .segment import build_field_index_auto


@contextlib.contextmanager
def _span(name: str, **detail):
    """Record a segment_build/segment_merge span on the executing
    statement's timeline (read-repair inside a query) when one exists;
    maintenance-thread builds run outside any trace and skip it."""
    from ..obs.trace import current_trace
    tr = current_trace()
    if tr is None:
        yield
        return
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        tr.add(name, "ingest", t0, time.perf_counter_ns(), **detail)


def _build_field(texts, an, settings=None):
    """One field-segment build: the parallel-chunk builder (bit-identical
    to serial), counted and traced."""
    metrics.SEGMENT_BUILDS.add()
    with _span("segment_build", docs=len(texts)):
        return build_field_index_auto(texts, an, settings)


class BtreeIndex:
    """Sorted-array point/range lookup index over one column (reference:
    `USING btree`/`secondary` DuckDB bound indexes, server_engine.cpp:
    290-299). Values sort as (dictionary codes | numerics); lookups are
    binary searches returning row ids."""

    def __init__(self, column: str, using: str, options: dict,
                 sort_vals, row_ids, data_version: int):
        self.column = column
        self.columns = (column,)
        self.using = using
        self.options = dict(options)
        self.sort_vals = sort_vals   # sorted values (codes for strings)
        self.row_ids = row_ids       # row id of each sorted value
        self.data_version = data_version
        self.analyzer_name = ""

    def lookup_eq(self, value) -> "np.ndarray":
        lo = np.searchsorted(self.sort_vals, value, side="left")
        hi = np.searchsorted(self.sort_vals, value, side="right")
        return np.sort(self.row_ids[lo:hi])

def build_btree_index(provider, column: str, using: str,
                      options: dict) -> BtreeIndex:
    col = provider.full_batch([column]).column(column)
    valid = col.valid_mask()
    rows = np.flatnonzero(valid)
    vals = col.data[rows]
    order = np.argsort(vals, kind="stable")
    return BtreeIndex(column, using, options, vals[order],
                      rows[order].astype(np.int64), provider.data_version)


_rebuild_guard = threading.Lock()


def _index_lock(provider) -> threading.Lock:
    """Per-provider rebuild lock (lazily attached) — read-repair rebuilds
    must not run concurrently (racy duplicate builds) or stamp a version
    that doesn't match the batch they were built from."""
    lk = getattr(provider, "_index_rebuild_lock", None)
    if lk is None:
        with _rebuild_guard:
            lk = getattr(provider, "_index_rebuild_lock", None)
            if lk is None:
                lk = threading.Lock()
                provider._index_rebuild_lock = lk
    return lk


def _repair(provider, name, idx, rebuild, force=False):
    """Read-repair `idx` under the provider's rebuild lock. The version is
    captured BEFORE the data is read: if a concurrent fast-path publish
    lands mid-build the new index carries the older stamp, so the next
    reader repairs again instead of trusting an index that may be missing
    the published rows (an index with EXTRA rows is harmless — those rows
    exist in the table). `force` rebuilds even at a current version — the
    maintenance ticker's merge-ladder leg compacts segment tiers whose
    data is perfectly fresh."""
    with _index_lock(provider):
        cur = provider.indexes.get(name, idx)
        if cur.data_version == provider.data_version and not force:
            return cur
        ver = provider.data_version
        new = rebuild(cur)
        new.data_version = ver
        provider.indexes[name] = new
        return new


def find_btree_index(provider, column: str):
    for name, idx in getattr(provider, "indexes", {}).items():
        if isinstance(idx, BtreeIndex) and idx.column == column:
            if idx.data_version != provider.data_version:
                idx = _repair(provider, name, idx,
                              lambda cur: build_btree_index(
                                  provider, cur.column, cur.using,
                                  cur.options))
            return idx
    return None


def build_index_for_table(provider, columns, using, options) -> SearchIndex:
    if using not in ("inverted", "btree", "secondary", "ivf", "maxsim",
                     "geo"):
        raise errors.unsupported(f"index type {using}")
    if using in ("btree", "secondary"):
        if len(columns) != 1:
            raise errors.unsupported("multi-column btree index")
        return build_btree_index(provider, columns[0], using, options)
    if using == "geo":
        if len(columns) != 1:
            raise errors.unsupported("geo index over multiple columns")
        return build_geo_index(provider, columns[0], options)
    analyzer_name = str(options.get("tokenizer", options.get("analyzer",
                                                             "text")))
    if using == "ivf":
        from .ivf import build_ivf_index
        if len(columns) != 1:
            raise errors.unsupported("ivf index over multiple columns")
        return build_ivf_index(provider, columns[0], options)
    if using == "maxsim":
        from .ivf import build_maxsim_index
        if len(columns) != 1:
            raise errors.unsupported("maxsim index over multiple columns")
        return build_maxsim_index(provider, columns[0], options)
    searchers = {}
    n_rows = provider.row_count()
    col_toks = options.get("column_tokenizers", {}) or {}
    if using == "inverted":
        for col_name in columns:
            an = get_analyzer(col_toks.get(col_name, analyzer_name))
            col = provider.full_batch([col_name]).column(col_name)
            if not col.type.is_string:
                raise errors.SqlError(
                    errors.DATATYPE_MISMATCH,
                    f'inverted index requires a text column, "{col_name}" '
                    f"is {col.type}")
            texts = col.to_pylist()
            fi = _build_field(texts, an)
            ms = MultiSearcher(an)
            ms.add_segment(SegmentSearcher(fi, an, len(texts)), 0)
            searchers[col_name] = ms
    return SearchIndex(list(columns), using, dict(options), analyzer_name,
                       searchers, provider.data_version,
                       mutation_epoch=getattr(provider, "mutation_epoch", 0),
                       indexed_rows=n_rows)


MAX_SEGMENTS = 8   # default merge-ladder threshold (serene_max_segments)


def _max_segments() -> int:
    from ..utils.config import REGISTRY
    try:
        return max(2, int(REGISTRY.get_global("serene_max_segments")))
    except KeyError:
        return MAX_SEGMENTS


def _background_merge() -> bool:
    from ..utils.config import REGISTRY
    try:
        return bool(REGISTRY.get_global("serene_background_merge"))
    except KeyError:
        return True


def _merge_tier(provider, col_name, an, segs: list, cap: int) -> list:
    """Tiered merge ladder over one field's [(SegmentSearcher, base)] list:
    while at/over the cap, rebuild the SMALLEST adjacent pair into one
    segment re-read from the provider's columnstore — O(run docs) per
    merge, never a full rebuild. Same epoch is a precondition (appends
    only), so stored rows [base, base+docs) still hold each segment's
    text."""
    segs = list(segs)
    col = None
    while len(segs) >= cap:
        sizes = [s.num_docs + segs[i + 1][0].num_docs
                 for i, (s, _) in enumerate(segs[:-1])]
        i = int(np.argmin(sizes))
        lo_base = segs[i][1]
        n_docs = segs[i][0].num_docs + segs[i + 1][0].num_docs
        if col is None:
            col = provider.full_batch([col_name]).column(col_name)
        texts = col.slice(lo_base, lo_base + n_docs).to_pylist()
        metrics.SEGMENT_MERGES.add()
        with _span("segment_merge", docs=n_docs, segments=2):
            fi = _build_field(texts, an)
        segs[i:i + 2] = [(SegmentSearcher(fi, an, n_docs), lo_base)]
    return segs


def refresh_index(provider, idx, *,
                  merge: bool = True) -> "SearchIndex | BtreeIndex":
    """Refresh one index (reference RefreshLoop leg). Inverted indexes:
    - rows appended since the last refresh → ONE new segment over the delta
      (O(new docs), the real-time path)
    - row mutations (delete/update/truncate) → full rebuild, with the
      reason logged (a silent compaction storm is undiagnosable)
    - at/over the segment cap → the tiered merge ladder compacts the
      smallest adjacent runs (replacing the old full-rebuild cliff).
      `merge=False` skips the ladder — the query-path read-repair leg
      under background maintenance, which pays only the bounded delta
      tail and leaves compaction to the maintenance ticker."""
    if idx.using == "ivf":
        # IVF has its own incremental leg: a pure append assigns only
        # the tail rows to the existing centroids (one new cluster-major
        # segment); everything else re-clusters with the reason logged
        from .ivf import refresh_ivf_index
        return refresh_ivf_index(provider, idx)
    if idx.using != "inverted":
        return build_index_for_table(provider, idx.columns, idx.using,
                                     idx.options)
    same_epoch = idx.mutation_epoch == getattr(provider, "mutation_epoch", 0)
    n_rows = provider.row_count()
    if not same_epoch or n_rows < idx.indexed_rows:
        reason = ("mutation epoch advanced (delete/update/truncate)"
                  if not same_epoch else
                  f"row count shrank ({n_rows} < {idx.indexed_rows}) "
                  "without an epoch bump (truncate/rollback)")
        log.info("maintenance",
                 f"full index rebuild on \"{provider.name}\" "
                 f"({', '.join(idx.columns)}): {reason}")
        return build_index_for_table(provider, idx.columns, idx.using,
                                     idx.options)
    col_toks = idx.options.get("column_tokenizers", {}) or {}
    base = idx.indexed_rows
    cap = _max_segments()
    # build-new-then-swap: assemble fresh MultiSearchers (reusing the old
    # immutable SegmentSearcher objects) and return a NEW SearchIndex the
    # caller publishes with one assignment — in-flight queries keep their
    # consistent snapshot, and a failure mid-build publishes nothing
    new_searchers = {}
    for col_name in idx.columns:
        an = get_analyzer(col_toks.get(col_name, idx.analyzer_name))
        segs = list(idx.searchers[col_name].segments)
        if n_rows > base:
            col = provider.full_batch([col_name]).column(col_name)
            delta = col.slice(base, n_rows).to_pylist()  # O(new docs)
            fi = _build_field(delta, an)
            segs.append((SegmentSearcher(fi, an, len(delta)), base))
        if merge and len(segs) >= cap:
            segs = _merge_tier(provider, col_name, an, segs, cap)
        ms = MultiSearcher(an)
        for seg, seg_base in segs:
            ms.add_segment(seg, seg_base)
        new_searchers[col_name] = ms
    return SearchIndex(list(idx.columns), idx.using, dict(idx.options),
                       idx.analyzer_name, new_searchers,
                       provider.data_version,
                       mutation_epoch=idx.mutation_epoch,
                       indexed_rows=n_rows)


def needs_merge(idx) -> bool:
    """True when an inverted index's segment tier is at/over the merge
    ladder's cap — the maintenance ticker's compaction trigger (data may
    be perfectly fresh; the ladder is about read amplification, not
    staleness)."""
    if getattr(idx, "using", "") != "inverted":
        return False
    searchers = getattr(idx, "searchers", None) or {}
    return max((len(ms.segments) for ms in searchers.values()),
               default=0) >= _max_segments()


def find_index(provider, column: str):
    """The inverted index covering `column`, or None. A stale index
    (data_version behind the provider) is refreshed IN PLACE before use —
    read-repair. Skipping it instead would silently fall back to a brute
    scan with the DEFAULT analyzer, diverging from the column's tokenizer
    (and the maintenance loop only narrows, never closes, that window)."""
    for name, idx in getattr(provider, "indexes", {}).items():
        if idx.using == "inverted" and column in idx.columns:
            if idx.data_version != provider.data_version:
                # under background maintenance the query path pays only
                # the bounded delta-tail build; the merge ladder runs on
                # the maintenance ticker (refresh_index merge=True there)
                fg = not _background_merge()
                idx = _repair(provider, name, idx,
                              lambda cur: refresh_index(provider, cur,
                                                        merge=fg))
            return idx
    return None


class GeoIndex:
    """Cell-term geo index over one geometry (text) column (reference:
    geo_filter_builder.cpp + iresearch GeoFilter — S2 cell terms; here
    the quadtree of geo/cells.py). Candidates come from posting lists
    keyed by packed cell ids; exact predicates post-verify them."""

    def __init__(self, column: str, options: dict, postings: dict,
                 n_rows: int, data_version: int):
        self.column = column
        self.columns = (column,)
        self.using = "geo"
        self.options = dict(options)
        self.postings = postings       # cell id -> np.int64 row ids
        self.indexed_rows = n_rows
        self.data_version = data_version
        self.analyzer_name = ""

    def candidates(self, probe_terms) -> np.ndarray:
        hits = [self.postings[t] for t in probe_terms
                if t in self.postings]
        if not hits:
            return np.empty(0, dtype=np.int64)
        return np.unique(np.concatenate(hits))


def build_geo_index(provider, column: str, options: dict) -> GeoIndex:
    from ..geo import cells as geo_cells
    from ..geo import shapes as geo_shapes
    col = provider.full_batch([column]).column(column)
    if not col.type.is_string:
        raise errors.SqlError(
            errors.DATATYPE_MISMATCH,
            f'geo index requires a geometry text column, "{column}" is '
            f"{col.type}")
    texts = col.to_pylist()
    valid = col.valid_mask()
    lists: dict = {}
    import re as _re
    point_rx = _re.compile(
        r"^\s*POINT\s*\(\s*(-?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?)\s+"
        r"(-?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?)\s*\)\s*$", _re.IGNORECASE)
    for i, t in enumerate(texts):
        if t is None or (valid is not None and not valid[i]):
            continue
        m = point_rx.match(t) if isinstance(t, str) else None
        if m:
            # fast path: POINT(x y) terms without a full WKT parse —
            # same scheme function as every other geometry
            terms = geo_cells.point_terms(float(m.group(1)),
                                          float(m.group(2)))
        else:
            # unparseable geometry FAILS the build (like a functional
            # index in PG): silently skipping the row would make index
            # presence flip the query outcome — the unindexed path
            # raises on that row, the indexed one would exclude it
            terms = geo_cells.geometry_terms(geo_shapes.parse_any(t))
        for term in terms:
            lists.setdefault(term, []).append(i)
    postings = {t: np.asarray(rs, dtype=np.int64)
                for t, rs in lists.items()}
    return GeoIndex(column, options, postings, len(texts),
                    provider.data_version)


def find_geo_index(provider, column: str):
    for name, idx in getattr(provider, "indexes", {}).items():
        if isinstance(idx, GeoIndex) and idx.column == column:
            if idx.data_version != provider.data_version:
                idx = _repair(provider, name, idx,
                              lambda cur: build_geo_index(
                                  provider, cur.column, cur.options))
            return idx
    return None
