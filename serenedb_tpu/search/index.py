"""Index build: CREATE INDEX ... USING inverted backfill.

Reference analog: duckdb_physical_create_index.* (backfill scan feeding an
irs::IndexWriter; SURVEY.md §2.5). V1 builds one segment over the current
table contents; the storage layer adds incremental segments + WAL.
"""

from __future__ import annotations

import numpy as np

from .. import errors
from .analysis import get_analyzer
from .searcher import SearchIndex, SegmentSearcher
from .segment import build_field_index


def build_index_for_table(provider, columns, using, options) -> SearchIndex:
    if using not in ("inverted", "btree", "secondary", "ivf"):
        raise errors.unsupported(f"index type {using}")
    analyzer_name = str(options.get("tokenizer", options.get("analyzer",
                                                             "text")))
    if using == "ivf":
        from .ivf import build_ivf_index
        if len(columns) != 1:
            raise errors.unsupported("ivf index over multiple columns")
        return build_ivf_index(provider, columns[0], options)
    searchers = {}
    if using == "inverted":
        an = get_analyzer(analyzer_name)
        for col_name in columns:
            col = provider.full_batch([col_name]).column(col_name)
            if not col.type.is_string:
                raise errors.SqlError(
                    errors.DATATYPE_MISMATCH,
                    f'inverted index requires a text column, "{col_name}" '
                    f"is {col.type}")
            texts = col.to_pylist()
            fi = build_field_index(texts, an)
            searchers[col_name] = SegmentSearcher(fi, an, len(texts))
    return SearchIndex(list(columns), using, dict(options), analyzer_name,
                       searchers, provider.data_version)


def find_index(provider, column: str):
    """The freshest inverted index covering `column`, or None (stale indexes
    — data_version behind the provider — are skipped, not used wrongly)."""
    for idx in getattr(provider, "indexes", {}).values():
        if idx.using == "inverted" and column in idx.columns and \
                idx.data_version == provider.data_version:
            return idx
    return None
