"""Inverted-index build entry point (placeholder until the segment layer).

Reference analog: CREATE INDEX ... USING inverted backfill
(server/connector/duckdb_physical_create_index.*). The real segmented index
with posting blocks lands with the search core; this records index metadata
so DDL round-trips."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class IndexDef:
    columns: list[str]
    using: str
    options: dict = field(default_factory=dict)


def build_index_for_table(provider, columns, using, options) -> IndexDef:
    return IndexDef(list(columns), using, dict(options))
