"""Highlighting: byte offsets of query matches + headline rendering.

Reference analog: server/connector/highlight/memory_index.* — highlights are
computed by re-analyzing the row's text against the query (SURVEY.md §2.5
"Highlight") rather than storing offsets in the index.
"""

from __future__ import annotations

from .query import (QAnd, QFuzzy, QNode, QNot, QOr, QPhrase, QPrefix, QRegex,
                    QTerm, edit_distance_at_most, parse_query)


def _positive_terms(node: QNode) -> tuple[set[str], set[str], list, list]:
    """(exact terms, prefixes, fuzzy specs, regexes) contributing to
    highlights."""
    terms: set[str] = set()
    prefixes: set[str] = set()
    fuzzies: list[tuple[str, int]] = []
    regexes: list[QRegex] = []

    def rec(nd):
        if isinstance(nd, QTerm):
            terms.add(nd.term)
        elif isinstance(nd, QPhrase):
            terms.update(nd.terms)
        elif isinstance(nd, QPrefix):
            prefixes.add(nd.prefix)
        elif isinstance(nd, QFuzzy):
            fuzzies.append((nd.term, nd.max_edits))
        elif isinstance(nd, QRegex):
            regexes.append(nd)
        elif isinstance(nd, (QAnd, QOr)):
            for a in nd.args:
                rec(a)
        # QNot: negated terms never highlight
    rec(node)
    return terms, prefixes, fuzzies, regexes


def token_matches(term: str, terms: set, prefixes: set, fuzzies: list,
                  regexes: list = ()) -> bool:
    return term in terms or \
        any(term.startswith(p) for p in prefixes) or \
        any(edit_distance_at_most(term, f, k) for f, k in fuzzies) or \
        any(r.matches(term) for r in regexes)


def match_offsets(analyzer, text: str, query: str) -> list[list[int]]:
    """[[start, end], ...] character ranges of matching tokens."""
    node = parse_query(query, analyzer)
    terms, prefixes, fuzzies, regexes = _positive_terms(node)
    out = []
    for tok in analyzer.tokenize(text):
        if token_matches(tok.term, terms, prefixes, fuzzies, regexes):
            out.append([tok.start, tok.end])
    return out


def headline(analyzer, text: str, query: str, start_sel: str = "<b>",
             stop_sel: str = "</b>", spans=None) -> str:
    """PG ts_headline-style rendering: matched tokens wrapped in markers.
    Pre-computed spans (from a cached parsed query) skip the re-parse."""
    if spans is None:
        spans = match_offsets(analyzer, text, query)
    if not spans:
        return text
    parts = []
    prev = 0
    for s, e in spans:
        parts.append(text[prev:s])
        parts.append(start_sel)
        parts.append(text[s:e])
        parts.append(stop_sel)
        prev = e
    parts.append(text[prev:])
    return "".join(parts)
