"""Language stemmers for the text analyzers.

Reference analog: libs/iresearch/analysis/stemming_tokenizer.cpp +
text_tokenizer.cpp delegate to libstemmer (snowball). No snowball binding
exists in this image, so English gets a full Porter2 implementation and the
other languages get snowball-derived suffix strippers. What parity actually
requires is that index-side and query-side stem identically and that
morphological variants collapse — both hold for these.
"""

from __future__ import annotations

_VOWELS = set("aeiouy")
_DOUBLES = ("bb", "dd", "ff", "gg", "mm", "nn", "pp", "rr", "tt")
_LI_ENDING = set("cdeghkmnrt")

_P2_EXCEPTIONS = {
    "skis": "ski", "skies": "sky", "dying": "die", "lying": "lie",
    "tying": "tie", "idly": "idl", "gently": "gentl", "ugly": "ugli",
    "early": "earli", "only": "onli", "singly": "singl", "sky": "sky",
    "news": "news", "howe": "howe", "atlas": "atlas", "cosmos": "cosmos",
    "bias": "bias", "andes": "andes",
}
_P2_EXCEPTIONS1A = {"inning", "outing", "canning", "herring", "earring",
                    "proceed", "exceed", "succeed"}


def _is_vowel(word: str, i: int) -> bool:
    return word[i] in _VOWELS


def _regions(word: str) -> tuple[int, int]:
    """Porter2 R1/R2 start offsets."""
    if word.startswith(("gener", "commun", "arsen")):
        r1 = 6 if word.startswith("commun") else 5
    else:
        r1 = len(word)
        for i in range(1, len(word)):
            if not _is_vowel(word, i) and _is_vowel(word, i - 1):
                r1 = i + 1
                break
    r2 = len(word)
    for i in range(r1 + 1, len(word)):
        if not _is_vowel(word, i) and _is_vowel(word, i - 1):
            r2 = i + 1
            break
    return r1, r2


def _short_syllable_end(word: str) -> bool:
    """word ends in a short syllable (porter2 definition)."""
    n = len(word)
    if n >= 3:
        a, b, c = word[n - 3], word[n - 2], word[n - 1]
        if (c not in _VOWELS and c not in "wxY" and b in _VOWELS
                and a not in _VOWELS):
            return True
    if n == 2 and word[0] in _VOWELS and word[1] not in _VOWELS:
        return True
    return False


def _is_short(word: str, r1: int) -> bool:
    return r1 >= len(word) and _short_syllable_end(word)


def porter2(word: str) -> str:
    """Snowball English (Porter2) stemmer."""
    w = word.lower()
    if len(w) <= 2:
        return w
    if w in _P2_EXCEPTIONS:
        return _P2_EXCEPTIONS[w]
    w = w.replace("’", "'")
    if w.startswith("'"):
        w = w[1:]
    # mark consonant-y as Y
    if w.startswith("y"):
        w = "Y" + w[1:]
    w = "".join("Y" if (c == "y" and i > 0 and w[i - 1] in _VOWELS) else c
                for i, c in enumerate(w))
    r1, r2 = _regions(w)

    # step 0
    for suf in ("'s'", "'s", "'"):
        if w.endswith(suf):
            w = w[: -len(suf)]
            break
    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith(("ied", "ies")):
        w = w[:-2] if len(w) > 4 else w[:-1]
    elif w.endswith(("us", "ss")):
        pass
    elif w.endswith("s") and any(c in _VOWELS for c in w[:-2]):
        w = w[:-1]
    if w in _P2_EXCEPTIONS1A:
        return w.lower()
    # step 1b
    if w.endswith(("eed", "eedly")):
        suf = "eedly" if w.endswith("eedly") else "eed"
        if len(w) - len(suf) >= r1:
            w = w[: -len(suf)] + "ee"
    else:
        for suf in ("ingly", "edly", "ing", "ed"):
            if w.endswith(suf):
                stem = w[: -len(suf)]
                if any(c in _VOWELS for c in stem):
                    w = stem
                    if w.endswith(("at", "bl", "iz")):
                        w += "e"
                    elif w.endswith(_DOUBLES):
                        w = w[:-1]
                    elif _is_short(w, r1):
                        w += "e"
                break
    # step 1c
    if len(w) > 2 and w[-1] in "yY" and w[-2] not in _VOWELS:
        w = w[:-1] + "i"

    # step 2 (longest suffix, in R1)
    step2 = [("ational", "ate"), ("fulness", "ful"), ("iveness", "ive"),
             ("ization", "ize"), ("ousness", "ous"), ("biliti", "ble"),
             ("lessli", "less"), ("tional", "tion"), ("alism", "al"),
             ("aliti", "al"), ("ation", "ate"), ("entli", "ent"),
             ("fulli", "ful"), ("iviti", "ive"), ("ousli", "ous"),
             ("abli", "able"), ("alli", "al"), ("anci", "ance"),
             ("ator", "ate"), ("enci", "ence"), ("izer", "ize"),
             ("bli", "ble"), ("ogi", "og"), ("li", "")]
    for suf, rep in step2:
        if w.endswith(suf):
            if len(w) - len(suf) >= r1:
                if suf == "ogi":
                    if w[-4:-3] == "l":
                        w = w[:-3] + rep
                elif suf == "li":
                    if len(w) >= 3 and w[-3] in _LI_ENDING:
                        w = w[:-2]
                else:
                    w = w[: -len(suf)] + rep
            break
    # step 3 (in R1; ative needs R2)
    step3 = [("ational", "ate"), ("tional", "tion"), ("alize", "al"),
             ("icate", "ic"), ("iciti", "ic"), ("ative", ""),
             ("ical", "ic"), ("ness", ""), ("ful", "")]
    for suf, rep in step3:
        if w.endswith(suf):
            if len(w) - len(suf) >= r1:
                if suf == "ative":
                    if len(w) - len(suf) >= r2:
                        w = w[: -len(suf)]
                else:
                    w = w[: -len(suf)] + rep
            break
    # step 4 (in R2)
    step4 = ["ement", "ance", "ence", "able", "ible", "ment", "ant", "ent",
             "ism", "ate", "iti", "ous", "ive", "ize", "ion", "al", "er",
             "ic"]
    for suf in step4:
        if w.endswith(suf):
            if len(w) - len(suf) >= r2:
                if suf == "ion":
                    if len(w) >= 4 and w[-4] in "st":
                        w = w[:-3]
                else:
                    w = w[: -len(suf)]
            break
    # step 5
    if w.endswith("e"):
        if len(w) - 1 >= r2:
            w = w[:-1]
        elif len(w) - 1 >= r1 and not _short_syllable_end(w[:-1]):
            w = w[:-1]
    elif w.endswith("l") and len(w) - 1 >= r2 and len(w) >= 2 and \
            w[-2] == "l":
        w = w[:-1]
    return w.lower()


def _strip_suffixes(word: str, suffixes, min_stem: int) -> str:
    """Strip the longest matching suffix, keeping at least min_stem chars."""
    for suf in suffixes:
        if word.endswith(suf) and len(word) - len(suf) >= min_stem:
            return word[: -len(suf)]
    return word


def stem_de(w: str) -> str:
    w = (w.replace("ä", "a").replace("ö", "o").replace("ü", "u")
          .replace("ß", "ss"))
    w = _strip_suffixes(w, ("ungen", "heiten", "keiten", "erung", "ern",
                            "ung", "heit", "keit", "isch", "lich", "en",
                            "er", "em", "es", "e", "s"), 4)
    return w


def stem_fr(w: str) -> str:
    import unicodedata
    w = "".join(c for c in unicodedata.normalize("NFD", w)
                if not unicodedata.combining(c))
    # suffixes are accent-folded to match the folded input
    return _strip_suffixes(
        w, ("issements", "issement", "issantes", "issante", "issants",
            "issant", "atrices", "atrice", "ateurs", "ateur", "logies",
            "logie", "emment", "amment", "ements", "ement", "euses",
            "ments", "ment", "euse", "eux", "ives", "ive", "ifs", "if",
            "ables", "able", "istes", "iste", "ances", "ance", "ences",
            "ence", "ites", "ite", "aient", "erent", "erons", "eront",
            "antes", "ante", "ants", "ant", "ees", "ee", "er",
            "ez", "ent", "ais", "ait", "ons", "ion", "es", "s", "e"), 4)


def stem_es(w: str) -> str:
    import unicodedata
    w = "".join(c for c in unicodedata.normalize("NFD", w)
                if not unicodedata.combining(c))
    return _strip_suffixes(
        w, ("amientos", "imientos", "amiento", "imiento", "aciones",
            "uciones", "adoras", "adores", "ancias", "logias", "encias",
            "idades", "acion", "ucion", "adora", "ador", "ancia", "logia",
            "encia", "antes", "anzas", "ismos", "ables", "ibles", "istas",
            "osos", "osas", "ivas", "ivos", "anza", "icos", "icas", "ismo",
            "able", "ible", "ista", "oso", "osa", "iva", "ivo", "idad",
            "ante", "arse", "iendo", "ando", "aria", "eria", "iria",
            "aron", "ieron", "ando", "aban", "amos", "emos", "imos",
            "ar", "er", "ir", "as", "es", "os", "a", "e", "o", "s"), 4)


def stem_it(w: str) -> str:
    import unicodedata
    w = "".join(c for c in unicodedata.normalize("NFD", w)
                if not unicodedata.combining(c))
    return _strip_suffixes(
        w, ("azioni", "azione", "amenti", "imenti", "amento", "imento",
            "atrici", "atrice", "abili", "ibili", "ismi", "ismo", "iste",
            "isti", "ista", "osi", "ose", "osa", "oso", "ivi", "ive",
            "iva", "ivo", "anza", "anze", "ichi", "iche", "logia",
            "logie", "mente", "ando", "endo", "are", "ere", "ire",
            "ato", "ata", "ati", "ate", "uto", "uta", "uti", "ute",
            "ito", "ita", "iti", "ite", "ano", "ono", "i", "e", "a",
            "o"), 4)


def stem_pt(w: str) -> str:
    import unicodedata
    w = "".join(c for c in unicodedata.normalize("NFD", w)
                if not unicodedata.combining(c))
    return _strip_suffixes(
        w, ("amentos", "imentos", "amento", "imento", "adoras", "adores",
            "acoes", "ancias", "logias", "encias", "idades", "issimo",
            "acao", "ancia", "logia", "encia", "adora", "ador", "antes",
            "ismos", "istas", "aveis", "iveis", "osos", "osas", "ivas",
            "ivos", "ismo", "avel", "ivel", "ista", "oso", "osa", "iva",
            "ivo", "idade", "ante", "ando", "endo", "indo", "aram",
            "eram", "iram", "amos", "emos", "imos", "ar", "er", "ir",
            "as", "es", "os", "a", "e", "o", "s"), 4)


def stem_nl(w: str) -> str:
    return _strip_suffixes(
        w, ("heden", "ingen", "erend", "end", "ing", "tje", "pje", "je",
            "en", "se", "s", "e"), 4)


def stem_ru(w: str) -> str:
    # noun/adjective/verb endings, longest-first (snowball russian order)
    return _strip_suffixes(
        w, ("ированиями", "ованиями", "ированием", "ирование", "ирования",
            "ированию", "ировании", "ованием", "ованиям", "ованиях",
            "ировани", "ностью", "ениями", "ование", "ением", "ениях",
            "ениям", "ывание", "ивание", "ность", "ости", "ение", "ость",
            "ними", "ыми", "ими", "ого", "его", "ому", "ему", "ями",
            "ами", "ует", "уют", "ишь", "ешь", "ить", "ать", "ять",
            "еть", "ала", "ила", "ыла", "ела", "ях", "ям", "ах", "ам",
            "ие", "ия", "ий", "ые", "ый", "ое", "ой", "ая", "яя", "ью",
            "ов", "ев", "ей", "ом", "ем", "ан", "ен", "ут", "ют", "ат",
            "ят", "ы", "и", "а", "я", "о", "е", "у", "ю", "ь", "й"), 3)


def stem_sv(w: str) -> str:
    w = w.replace("å", "a").replace("ä", "a").replace("ö", "o")
    return _strip_suffixes(
        w, ("heterna", "heten", "heter", "arna", "erna", "orna", "ande",
            "ende", "aste", "arne", "are", "ast", "ade", "ad", "arnas",
            "ernas", "or", "ar", "er", "en", "an", "et", "na", "a", "e",
            "s"), 3)


def stem_fi(w: str) -> str:
    w = w.replace("ä", "a").replace("ö", "o")
    return _strip_suffixes(
        w, ("isuudet", "isuuden", "immat", "impia", "sti", "ssa", "sta",
            "lla", "lta", "lle", "ksi", "tta", "ista", "issa", "iin",
            "ihin", "iden", "ien", "it", "et", "at", "in", "an", "en",
            "na", "a", "i", "t", "n"), 3)


def stem_da(w: str) -> str:
    w = w.replace("æ", "a").replace("ø", "o").replace("å", "a")
    return _strip_suffixes(
        w, ("hederne", "erende", "hedens", "heder", "heden", "endes",
            "erede", "ernes", "erens", "erets", "ande", "ende", "erne",
            "eres", "eren", "eret", "enes", "ene", "ens", "ers", "ets",
            "en", "er", "es", "et", "e", "s"), 3)


def stem_no(w: str) -> str:
    w = w.replace("æ", "a").replace("ø", "o").replace("å", "a")
    return _strip_suffixes(
        w, ("hetene", "hetens", "endes", "heter", "heten", "ande",
            "ende", "edes", "enes", "erte", "ede", "ane", "ene", "ens",
            "ers", "ets", "ert", "et", "en", "ar", "er", "as", "es",
            "a", "e", "s"), 3)


def stem_ro(w: str) -> str:
    import unicodedata
    w = "".join(c for c in unicodedata.normalize("NFD", w)
                if not unicodedata.combining(c))
    return _strip_suffixes(
        w, ("abilitate", "ibilitate", "ivitate", "atoare", "urilor",
            "itate", "atori", "iune", "iuni", "ator", "ilor", "elor",
            "ism", "ist", "ului", "uri", "ul", "ea", "ele", "ie", "ii",
            "le", "a", "e", "i", "u"), 4)


def stem_tr(w: str) -> str:
    # Turkish-specific letters fold for matching (ı has no combining
    # mark, so the analyzer's NFD accent fold does not catch it)
    w = (w.replace("ı", "i").replace("ğ", "g").replace("ş", "s")
          .replace("ç", "c").replace("ö", "o").replace("ü", "u"))
    return _strip_suffixes(
        w, ("larindan", "lerinden", "larinda", "lerinde", "larin",
            "lerin", "lardan", "lerden", "larda", "lerde", "lari",
            "leri", "lar", "ler", "dan", "den", "tan", "ten", "nin",
            "nun", "da", "de", "ta", "te", "in", "un", "i", "u", "a",
            "e"), 3)


def stem_hu(w: str) -> str:
    import unicodedata
    w = "".join(c for c in unicodedata.normalize("NFD", w)
                if not unicodedata.combining(c))
    return _strip_suffixes(
        w, ("sagok", "segek", "saga", "sege", "eket", "akat", "okat",
            "knak", "knek", "sag", "seg", "val", "vel", "ban", "ben",
            "nak", "nek", "bol", "tol", "rol", "hoz", "hez", "ott",
            "ok", "ek", "ak", "at", "et", "ot", "ni", "va", "ve", "k",
            "t", "a", "e", "o"), 3)


STEMMERS = {
    "en": porter2, "english": porter2,
    "de": stem_de, "german": stem_de,
    "fr": stem_fr, "french": stem_fr,
    "es": stem_es, "spanish": stem_es,
    "it": stem_it, "italian": stem_it,
    "pt": stem_pt, "portuguese": stem_pt,
    "nl": stem_nl, "dutch": stem_nl,
    "ru": stem_ru, "russian": stem_ru,
    "sv": stem_sv, "swedish": stem_sv,
    "fi": stem_fi, "finnish": stem_fi,
    "da": stem_da, "danish": stem_da,
    "no": stem_no, "nb": stem_no, "nn": stem_no, "norwegian": stem_no,
    "ro": stem_ro, "romanian": stem_ro,
    "tr": stem_tr, "turkish": stem_tr,
    "hu": stem_hu, "hungarian": stem_hu,
}


def lang_of(locale: str) -> str:
    """'de_DE.utf-8' / 'de-AT' / 'german' → normalized language key."""
    return (locale or "en").lower().split("_")[0].split("-")[0].split(".")[0]


def stemmer_for(locale: str):
    """locale → stemmer fn (None = no stemmer for that language)."""
    return STEMMERS.get(lang_of(locale))
