"""IVF vector index over a table column.

Reference analog: the IVF ANN index (IvfBuilder/centroids/quantizer,
libs/iresearch/formats/ivf/ivf_writer.hpp:44-100) with the session knobs
sdb_nprobe / sdb_rerank_factor (reference: config_variables.cpp).

Vectors live in a VARCHAR column as JSON arrays ('[0.1, 0.2, ...]'); the
index parses them once at build into an HBM-resident (N, D) f32 matrix plus
k-means cluster codes. Queries batch through ops/vector.ivf_topk.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
import numpy as np

from .. import errors
from ..ops import vector as vops

DEFAULT_LISTS = 64
KMEANS_ITERS = 8


def parse_vector(text: Optional[str], dim: Optional[int] = None,
                 ) -> Optional[np.ndarray]:
    if text is None:
        return None
    try:
        v = np.asarray(json.loads(text), dtype=np.float32)
    except (json.JSONDecodeError, ValueError):
        raise errors.SqlError(errors.INVALID_TEXT_REPRESENTATION,
                              f"invalid vector literal: {text[:40]!r}")
    if v.ndim != 1:
        raise errors.SqlError(errors.INVALID_TEXT_REPRESENTATION,
                              "vector literal must be a flat array")
    if dim is not None and len(v) != dim:
        raise errors.SqlError(errors.DATATYPE_MISMATCH,
                              f"expected {dim} dimensions, got {len(v)}")
    return v


@dataclass
class IvfIndex:
    column: str
    dim: int
    lists: int
    metric: str                 # l2 | ip | cos
    centroids: np.ndarray       # (lists, dim) f32
    codes: jnp.ndarray          # (N_pad,) int32 device
    vectors: jnp.ndarray        # (N_pad, dim) f32 (or dequant-ready) device
    valid: jnp.ndarray          # (N_pad,) bool device
    num_rows: int
    data_version: int
    using: str = "ivf"
    columns: tuple = ()
    options: dict = None
    # SQ8 (reference: ivf scalar quantizer + sdb_rerank_factor knob):
    # HBM holds int8-quantized vectors; originals stay host-side for the
    # exact rerank of the approximate top candidates
    quantized: bool = False
    host_vectors: object = None   # np (N, dim) f32 originals (sq8 only)

    def __post_init__(self):
        self.columns = (self.column,)
        if self.options is None:
            self.options = {}

    def search(self, queries: np.ndarray, k: int, nprobe: int,
               rerank_factor: int = 4) -> tuple[np.ndarray, np.ndarray]:
        """Batched: queries (Q, dim) → (distances (Q,k), row indices)."""
        q = jnp.asarray(np.ascontiguousarray(queries, dtype=np.float32))
        nprobe = max(1, min(nprobe, self.lists))
        kk = min(max(k, 1), max(self.num_rows, 1))
        fetch = min(kk * max(rerank_factor, 1), max(self.num_rows, 1)) \
            if self.quantized else kk
        d, idx = vops.ivf_topk(q, self.vectors, self.valid,
                               jnp.asarray(self.centroids),
                               self.codes, fetch, nprobe, self.metric)
        d, idx = np.asarray(d), np.asarray(idx)
        if not self.quantized:
            return d, idx
        # exact rerank over the approximate candidates (host originals)
        out_d = np.full((len(idx), kk), np.inf, dtype=np.float32)
        out_i = np.zeros((len(idx), kk), dtype=np.int64)
        for qi in range(len(idx)):
            cand = idx[qi][np.isfinite(d[qi])]
            if not len(cand):
                continue
            vecs = self.host_vectors[cand]
            qv = np.asarray(queries[qi], dtype=np.float32)
            if self.metric == "l2":
                dd = ((vecs - qv) ** 2).sum(axis=1)
            elif self.metric == "ip":
                dd = -(vecs @ qv)
            else:
                nv = np.linalg.norm(vecs, axis=1)
                dd = 1.0 - (vecs @ qv) / np.maximum(
                    nv * max(np.linalg.norm(qv), 1e-9), 1e-9)
            order = np.argsort(dd, kind="stable")[:kk]
            out_d[qi, :len(order)] = dd[order]
            out_i[qi, :len(order)] = cand[order]
        return out_d, out_i


def build_ivf_index(provider, column: str, options: dict) -> IvfIndex:
    col = provider.full_batch([column]).column(column)
    if not col.type.is_string:
        raise errors.SqlError(errors.DATATYPE_MISMATCH,
                              "ivf index requires a JSON-array vector column")
    texts = col.to_pylist()
    dim = int(options.get("dim", 0)) or None
    vecs = []
    valid = []
    for t in texts:
        v = parse_vector(t, dim) if t is not None else None
        if v is None:
            vecs.append(None)
            valid.append(False)
        else:
            if dim is None:
                dim = len(v)
            vecs.append(v)
            valid.append(True)
    if dim is None:
        dim = 1
    n = len(texts)
    mat = np.zeros((max(n, 1), dim), dtype=np.float32)
    for i, v in enumerate(vecs):
        if v is not None:
            mat[i] = v
    valid_arr = np.asarray(valid if n else [False], dtype=bool)
    lists = int(options.get("lists", options.get("nlist", DEFAULT_LISTS)))
    lists = max(1, min(lists, max(int(valid_arr.sum()), 1)))
    metric = str(options.get("metric", "l2")).lower()
    if metric not in ("l2", "ip", "cos"):
        raise errors.unsupported(f"ivf metric {metric}")
    train = mat[valid_arr] if valid_arr.any() else mat[:1]
    init = vops.init_centroids(train, lists)
    centroids = np.asarray(vops.kmeans_fit(
        jnp.asarray(train), jnp.asarray(init), lists, KMEANS_ITERS))
    mat_p = vops.pad_rows(mat)
    valid_p = np.zeros(len(mat_p), dtype=bool)
    valid_p[:n] = valid_arr[:n] if n else False
    codes = np.zeros(len(mat_p), dtype=np.int32)
    codes[:len(mat)] = np.asarray(vops.assign_clusters(
        jnp.asarray(mat), jnp.asarray(centroids)))
    quant = str(options.get("quantization",
                            options.get("quantizer", ""))).lower()
    if quant in ("sq8", "int8"):
        # per-dim affine SQ8: stats come from VALID rows only (zero padding
        # must not widen the range and wreck precision); HBM stores the
        # dequantized f32, originals stay host-side for exact rerank
        stats_src = mat[valid_arr] if valid_arr.any() else mat[:1]
        _, lo, scale = vops.sq8_quantize(stats_src)
        q = np.clip(np.round((mat_p - lo) / scale * 255.0),
                    0, 255).astype(np.uint8)
        dq = vops.sq8_dequantize(q, lo, scale)
        return IvfIndex(
            column=column, dim=dim, lists=lists, metric=metric,
            centroids=centroids, codes=jnp.asarray(codes),
            vectors=jnp.asarray(dq), valid=jnp.asarray(valid_p),
            num_rows=n, data_version=provider.data_version,
            options=dict(options), quantized=True, host_vectors=mat)
    return IvfIndex(
        column=column, dim=dim, lists=lists, metric=metric,
        centroids=centroids, codes=jnp.asarray(codes),
        vectors=jnp.asarray(mat_p), valid=jnp.asarray(valid_p),
        num_rows=n, data_version=provider.data_version,
        options=dict(options))


def find_ivf_index(provider, column: str) -> Optional[IvfIndex]:
    for idx in getattr(provider, "indexes", {}).values():
        if isinstance(idx, IvfIndex) and idx.column == column and \
                idx.data_version == provider.data_version:
            return idx
    return None
