"""IVF vector index and MaxSim late-interaction index over table columns.

Reference analog: the IVF ANN index (IvfBuilder/centroids/quantizer,
libs/iresearch/formats/ivf/ivf_writer.hpp:44-100) with the session knobs
sdb_nprobe / sdb_rerank_factor (reference: config_variables.cpp), plus a
ColBERT-style multi-vector MaxSim index (FLASH-MAXSIM kernel shape).

Vectors live in a VARCHAR column as JSON arrays ('[0.1, 0.2, ...]'; a
MaxSim column holds '[[...], [...]]' token matrices). The index parses
them once at build into immutable CLUSTER-MAJOR segments — `VecSegment`
slabs sorted (cluster asc, row asc) — which the device vector pool
(search/vector_store.py) pages into HBM. Queries batch through the
pool's probe/maxsim programs; `nprobe=lists` is bit-identical to the
host brute-force oracle (ops/vector.host_dist + exact two-key
selection).

Write handling (the orphaning fix): a pure append assigns ONLY the tail
rows to the existing centroids and publishes a new tail segment (the
zone-map tail trick — base segments stay resident); destructive
mutations log a rebuild-reason on the maintenance topic and leave the
rebuild to the ticker.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict
from typing import Optional

import jax.numpy as jnp
import numpy as np

from .. import errors
from ..ops import vector as vops
from ..utils import log
from .vector_store import VPOOL

DEFAULT_LISTS = 64
KMEANS_ITERS = 8

#: tail-segment cap: one more pure append past this forces a logged
#: full rebuild (re-clustering) instead of growing the segment chain
MAX_VEC_SEGMENTS = 8

#: per-index fragment-probe memo entries (batcher probe_topk)
_FRAG_CAP = 64


def parse_vector(text: Optional[str], dim: Optional[int] = None,
                 ) -> Optional[np.ndarray]:
    if text is None:
        return None
    try:
        v = np.asarray(json.loads(text), dtype=np.float32)
    except (json.JSONDecodeError, ValueError):
        raise errors.SqlError(errors.INVALID_TEXT_REPRESENTATION,
                              f"invalid vector literal: {text[:40]!r}")
    if v.ndim != 1:
        raise errors.SqlError(errors.INVALID_TEXT_REPRESENTATION,
                              "vector literal must be a flat array")
    if dim is not None and len(v) != dim:
        raise errors.SqlError(errors.DATATYPE_MISMATCH,
                              f"expected {dim} dimensions, got {len(v)}")
    return v


def parse_multi_vector(text: Optional[str], dim: Optional[int] = None,
                       ) -> Optional[np.ndarray]:
    """A MaxSim document: '[[...], [...]]' → (T, dim) f32 token matrix
    (a flat '[...]' is accepted as a single token). None / empty → None
    (the doc simply has no tokens to score)."""
    if text is None:
        return None
    try:
        raw = json.loads(text)
        v = np.asarray(raw, dtype=np.float32)
    except (json.JSONDecodeError, ValueError):
        raise errors.SqlError(errors.INVALID_TEXT_REPRESENTATION,
                              f"invalid multi-vector literal: {text[:40]!r}")
    if v.ndim == 1:
        if v.size == 0:
            return None
        v = v.reshape(1, -1)
    if v.ndim != 2:
        raise errors.SqlError(errors.INVALID_TEXT_REPRESENTATION,
                              "multi-vector literal must be a 2-D array")
    if v.shape[0] == 0:
        return None
    if dim is not None and v.shape[1] != dim:
        raise errors.SqlError(errors.DATATYPE_MISMATCH,
                              f"expected {dim} dimensions, got {v.shape[1]}")
    return v


class VecSegment:
    """One immutable cluster-major slab: `vals[i]` is the vector at
    segment-local position i, `rows[i]` its table row, `codes[i]` its
    cluster — sorted (cluster asc, row asc). The device pool keys page
    residency on the segment OBJECT (weakref-reclaimed), so appends
    that reuse base segments keep their pages hot."""

    __slots__ = ("vals", "rows", "codes", "counts", "__weakref__",
                 "_vpool_uid")

    def __init__(self, vals: np.ndarray, rows: np.ndarray,
                 codes: np.ndarray, lists: int):
        order = np.lexsort((rows, codes))
        self.vals = np.ascontiguousarray(vals[order], dtype=np.float32)
        self.rows = np.ascontiguousarray(rows[order], dtype=np.int32)
        self.codes = np.ascontiguousarray(codes[order], dtype=np.int32)
        self.counts = np.bincount(self.codes, minlength=lists)[:lists] \
            .astype(np.int64)


class _VecIndexBase:
    """Shared layout/pool plumbing + the SearchBatcher adapter contract
    (`topk` / `topk_batch` / `probe_topk`)."""

    def __init__(self):
        self._layout = None
        self._hostmat = None
        self._frag: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._frag_lock = threading.Lock()

    # -- layout -----------------------------------------------------------

    def layout(self) -> dict:
        """Cluster-major logical layout across segments (cluster c =
        seg₀'s c-rows ++ seg₁'s c-rows ++ …): per-cluster offsets and
        counts, per-position row ids and (segment, within) coordinates
        for the pool's slot map. Cached; the index is immutable."""
        lay = self._layout
        if lay is None:
            nl = self.nlists()
            if self.segs:
                counts = np.zeros(nl, np.int64)
                for s in self.segs:
                    counts += s.counts
                all_codes = np.concatenate([s.codes for s in self.segs])
                all_seg = np.concatenate(
                    [np.full(len(s.codes), si, np.int32)
                     for si, s in enumerate(self.segs)])
                all_within = np.concatenate(
                    [np.arange(len(s.codes), dtype=np.int32)
                     for s in self.segs])
                all_rows = np.concatenate([s.rows for s in self.segs])
                order = np.lexsort((all_within, all_seg, all_codes))
            else:
                counts = np.zeros(nl, np.int64)
                order = np.zeros(0, np.int64)
                all_seg = all_within = all_rows = np.zeros(0, np.int32)
            offsets = np.zeros(nl + 1, np.int64)
            np.cumsum(counts, out=offsets[1:])
            lay = {"ntot": int(counts.sum()),
                   "nlists": nl,
                   "offsets": offsets[:-1],
                   "counts": counts,
                   "max_count": int(counts.max(initial=0)),
                   "seg_of": all_seg[order] if len(order) else all_seg,
                   "within": all_within[order] if len(order)
                   else all_within,
                   "rowids": all_rows[order] if len(order) else all_rows}
            lay.update(self._layout_extra(lay))
            self._layout = lay
        return lay

    def _layout_extra(self, lay) -> dict:
        return {}

    def host_logical(self) -> np.ndarray:
        """The logical-order (ntot, dim) f32 matrix — the cold path's
        temporary region and the brute oracle's corpus. Cached."""
        mat = self._hostmat
        if mat is None:
            lay = self.layout()
            mat = np.zeros((max(lay["ntot"], 1), self.dim), np.float32)
            for si, seg in enumerate(self.segs):
                mask = lay["seg_of"] == si
                if mask.any():
                    mat[np.nonzero(mask)[0]] = seg.vals[
                        lay["within"][mask]]
            self._hostmat = mat
        return mat

    # -- SearchBatcher adapter --------------------------------------------

    def topk(self, node, k: int, scorer: str, mesh_n: int = 0):
        return self.topk_batch([node], k, scorer, mesh_n=mesh_n)[0]

    def probe_topk(self, node, k: int, scorer: str, mesh_n: int):
        """Fragment probe: a repeated (query, k, scorer) pair returns
        its cached per-query result without occupying a batch slot."""
        key = self._frag_key(node, k, scorer)
        with self._frag_lock:
            hit = self._frag.get(key)
            if hit is not None:
                self._frag.move_to_end(key)
            return hit

    def _frag_store(self, node, k: int, scorer: str, result) -> None:
        key = self._frag_key(node, k, scorer)
        with self._frag_lock:
            self._frag[key] = result
            while len(self._frag) > _FRAG_CAP:
                self._frag.popitem(last=False)

    def _frag_key(self, node, k: int, scorer: str) -> tuple:
        a = np.ascontiguousarray(node, np.float32)
        return (a.shape, a.tobytes(), int(k), scorer)


class IvfIndex(_VecIndexBase):
    using = "ivf"

    def __init__(self, *, column: str, dim: int, lists: int, metric: str,
                 centroids: np.ndarray, segs: list, num_rows: int,
                 data_version: int, mutation_epoch: int = 0,
                 options: dict = None, quantized: bool = False,
                 host_vectors=None, sq8_lo=None, sq8_scale=None):
        super().__init__()
        self.column = column
        self.dim = dim
        self.lists = lists
        self.metric = metric
        self.centroids = centroids
        self.segs = list(segs)
        self.num_rows = num_rows
        self.data_version = data_version
        self.mutation_epoch = mutation_epoch
        self.columns = (column,)
        self.options = dict(options or {})
        self.quantized = quantized
        # SQ8: HBM pages hold the dequantized f32; originals stay
        # host-side for the exact rerank; lo/scale are FROZEN at build
        # so existing rows' dequantized bits never change across appends
        self.host_vectors = host_vectors
        self.sq8_lo = sq8_lo
        self.sq8_scale = sq8_scale

    def nlists(self) -> int:
        return self.lists

    # -- search -----------------------------------------------------------

    def search(self, queries: np.ndarray, k: int, nprobe: int,
               rerank_factor: int = 4) -> tuple[np.ndarray, np.ndarray]:
        """Batched: queries (Q, dim) → (distances (Q, kk), row ids
        (Q, kk)); dead lanes carry (+inf, pad) — callers filter
        non-finite distances."""
        q = np.ascontiguousarray(queries, dtype=np.float32)
        lay = self.layout()
        ntot = lay["ntot"]
        if ntot == 0:
            return (np.full((len(q), 1), np.inf, np.float32),
                    np.zeros((len(q), 1), np.int64))
        kk = min(max(k, 1), ntot)
        if not self.quantized:
            d, r = VPOOL.search(self, q, kk, nprobe)
            return d, r.astype(np.int64)
        # SQ8: over-fetch in the dequantized space, exact-rerank the
        # candidates against the host originals
        fetch = min(kk * max(rerank_factor, 1), ntot)
        d, r = VPOOL.search(self, q, fetch, nprobe)
        out_d = np.full((len(q), kk), np.inf, dtype=np.float32)
        out_i = np.zeros((len(q), kk), dtype=np.int64)
        for qi in range(len(q)):
            cand = r[qi][np.isfinite(d[qi])].astype(np.int64)
            if not len(cand):
                continue
            vecs = self.host_vectors[cand]
            qv = q[qi]
            if self.metric == "l2":
                dd = ((vecs - qv) ** 2).sum(axis=1)
            elif self.metric == "ip":
                dd = -(vecs @ qv)
            else:
                nv = np.linalg.norm(vecs, axis=1)
                dd = 1.0 - (vecs @ qv) / np.maximum(
                    nv * max(np.linalg.norm(qv), 1e-9), 1e-9)
            order = np.argsort(dd, kind="stable")[:kk]
            out_d[qi, :len(order)] = dd[order]
            out_i[qi, :len(order)] = cand[order]
        return out_d, out_i

    def brute_search(self, queries: np.ndarray, k: int):
        """Device brute-force oracle (test/bench surface): same program
        body and distance bits as the probe path, one all-rows list."""
        q = np.ascontiguousarray(queries, dtype=np.float32)
        return VPOOL.brute(self, q, k)

    # -- batcher adapter ---------------------------------------------------

    def topk_batch(self, nodes, k: int, scorer: str, mesh_n: int = 0,
                   ragged: bool = False):
        nprobe, rerank = _parse_knn_scorer(scorer)
        q = np.stack([np.ascontiguousarray(n, np.float32)
                      for n in nodes])
        d, r = self.search(q, k, nprobe, rerank)
        outs = [(d[i], r[i]) for i in range(len(nodes))]
        for node, out in zip(nodes, outs):
            self._frag_store(node, k, scorer, out)
        return outs


def _parse_knn_scorer(scorer: str) -> tuple[int, int]:
    """'knn:<nprobe>:<rerank>' → (nprobe, rerank). The settings ride in
    the scorer string so the batcher's (searcher, k, scorer, mesh)
    group key keeps queries with different knobs in separate
    dispatches."""
    try:
        _, a, b = scorer.split(":")
        return max(1, int(a)), max(1, int(b))
    except ValueError:
        return 8, 4


class MaxSimIndex(_VecIndexBase):
    using = "maxsim"
    metric = "maxsim"
    quantized = False

    def __init__(self, *, column: str, dim: int, segs: list,
                 doc_rows: np.ndarray, num_rows: int, data_version: int,
                 mutation_epoch: int = 0, options: dict = None):
        super().__init__()
        self.column = column
        self.dim = dim
        self.segs = list(segs)
        #: table row of each doc ordinal (docs = rows with ≥1 token)
        self.doc_rows = doc_rows.astype(np.int32)
        self.num_rows = num_rows
        self.data_version = data_version
        self.mutation_epoch = mutation_epoch
        self.columns = (column,)
        self.options = dict(options or {})

    def nlists(self) -> int:
        return len(self.doc_rows)

    def _layout_extra(self, lay) -> dict:
        return {"cluster_rowids": self.doc_rows}

    def search(self, qtoks: np.ndarray, k: int):
        """One query's MaxSim top-k: (scores desc (kk,), rows (kk,)).
        qtoks: (S, dim) f32."""
        keys, rows = self.search_batch(qtoks[None, ...], k)
        return -keys[0], rows[0]

    def search_batch(self, qtoks: np.ndarray, k: int):
        """Batched: qtoks (B, S, dim) → (keys = NEGATED scores
        (B, kk), rows (B, kk)); dead lanes carry (+inf, pad)."""
        ndocs = len(self.doc_rows)
        if ndocs == 0 or self.layout()["ntot"] == 0:
            return (np.full((len(qtoks), 1), np.inf, np.float32),
                    np.zeros((len(qtoks), 1), np.int32))
        return VPOOL.maxsim_search(self, qtoks, k)

    def host_scores(self, qtoks: np.ndarray) -> np.ndarray:
        """f64 host oracle (the `serene_maxsim = off` path): exact
        Σ_s max_t <q_s, d_t> per doc, in float64."""
        lay = self.layout()
        mat = self.host_logical().astype(np.float64)
        q = np.asarray(qtoks, np.float64)
        out = np.zeros(len(self.doc_rows), np.float64)
        for di in range(len(self.doc_rows)):
            a = int(lay["offsets"][di])
            b = a + int(lay["counts"][di])
            sim = q @ mat[a:b].T                  # (S, T)
            out[di] = sim.max(axis=1).sum()
        return out

    # -- batcher adapter ---------------------------------------------------

    def topk_batch(self, nodes, k: int, scorer: str, mesh_n: int = 0,
                   ragged: bool = False):
        s_max = max(n.shape[0] for n in nodes)
        q = np.zeros((len(nodes), s_max, self.dim), np.float32)
        for i, n in enumerate(nodes):
            q[i, :n.shape[0]] = n
        keys, rows = self.search_batch(q, k)
        outs = [(keys[i], rows[i]) for i in range(len(nodes))]
        for node, out in zip(nodes, outs):
            self._frag_store(node, k, scorer, out)
        return outs


# -- builders -----------------------------------------------------------------


def _parse_column(provider, column: str, dim, parse):
    col = provider.full_batch([column]).column(column)
    if not col.type.is_string:
        raise errors.SqlError(errors.DATATYPE_MISMATCH,
                              "vector index requires a JSON-array vector "
                              "column")
    texts = col.to_pylist()
    vecs, rows = [], []
    for i, t in enumerate(texts):
        v = parse(t, dim) if t is not None else None
        if v is not None:
            if dim is None:
                dim = v.shape[-1]
            vecs.append(v)
            rows.append(i)
    return texts, vecs, np.asarray(rows, np.int64), dim


def build_ivf_index(provider, column: str, options: dict) -> IvfIndex:
    dim = int(options.get("dim", 0)) or None
    texts, vecs, rows, dim = _parse_column(provider, column, dim,
                                           parse_vector)
    n = len(texts)
    dim = dim or 1
    nv = len(vecs)
    mat = np.stack(vecs).astype(np.float32) if nv \
        else np.zeros((0, dim), np.float32)
    lists = int(options.get("lists", options.get("nlist", DEFAULT_LISTS)))
    lists = max(1, min(lists, max(nv, 1)))
    metric = str(options.get("metric", "l2")).lower()
    if metric not in ("l2", "ip", "cos"):
        raise errors.unsupported(f"ivf metric {metric}")
    train = mat if nv else np.zeros((1, dim), np.float32)
    init = vops.init_centroids(train, lists)
    centroids = np.asarray(vops.kmeans_fit(
        jnp.asarray(vops.pad_rows(train)), jnp.asarray(init), lists,
        KMEANS_ITERS))
    host = np.zeros((max(n, 1), dim), np.float32)
    if nv:
        host[rows] = mat
    quant = str(options.get("quantization",
                            options.get("quantizer", ""))).lower()
    quantized = quant in ("sq8", "int8")
    lo = scale = None
    vals = mat
    if quantized:
        # per-dim affine SQ8: stats come from the VALID rows at build
        # time and stay FROZEN across appends; pages hold the
        # dequantized f32, originals stay host-side for exact rerank
        stats_src = mat if nv else np.zeros((1, dim), np.float32)
        _, lo, scale = vops.sq8_quantize(stats_src)
        q8 = np.clip(np.round((mat - lo) / scale * 255.0),
                     0, 255).astype(np.uint8)
        vals = vops.sq8_dequantize(q8, lo, scale)
    segs = []
    if nv:
        codes = np.asarray(vops.assign_clusters(
            jnp.asarray(vops.pad_rows(mat)),
            jnp.asarray(centroids)))[:nv]
        segs.append(VecSegment(vals, rows, codes, lists))
    return IvfIndex(
        column=column, dim=dim, lists=lists, metric=metric,
        centroids=centroids, segs=segs, num_rows=n,
        data_version=provider.data_version,
        mutation_epoch=getattr(provider, "mutation_epoch", 0),
        options=dict(options), quantized=quantized,
        host_vectors=host if quantized else None,
        sq8_lo=lo, sq8_scale=scale)


def build_maxsim_index(provider, column: str, options: dict,
                       ) -> MaxSimIndex:
    dim = int(options.get("dim", 0)) or None
    texts, vecs, rows, dim = _parse_column(provider, column, dim,
                                           parse_multi_vector)
    n = len(texts)
    dim = dim or 1
    if vecs:
        vals = np.concatenate(vecs, axis=0).astype(np.float32)
        codes = np.concatenate(
            [np.full(len(v), di, np.int32) for di, v in enumerate(vecs)])
        tok_rows = np.concatenate(
            [np.full(len(v), i, np.int32)
             for v, i in zip(vecs, np.arange(len(vecs)))])
        segs = [VecSegment(vals, tok_rows, codes, len(vecs))]
    else:
        segs = []
    return MaxSimIndex(
        column=column, dim=dim, segs=segs,
        doc_rows=rows.astype(np.int32), num_rows=n,
        data_version=provider.data_version,
        mutation_epoch=getattr(provider, "mutation_epoch", 0),
        options=dict(options))


# -- refresh / lookup ---------------------------------------------------------


def refresh_ivf_index(provider, idx: IvfIndex) -> IvfIndex:
    """The ticker/read-repair leg for IVF: pure appends assign ONLY the
    tail rows to the existing centroids and publish one new tail
    segment; everything else (mutation, shrink, segment-cap overflow)
    is a logged full rebuild (re-clustering)."""
    n_rows = provider.row_count()
    epoch = getattr(provider, "mutation_epoch", 0)
    reason = None
    if idx.mutation_epoch != epoch:
        reason = "mutation epoch advanced (delete/update/truncate)"
    elif n_rows < idx.num_rows:
        reason = (f"row count shrank ({n_rows} < {idx.num_rows}) "
                  "without an epoch bump (truncate/rollback)")
    elif len(idx.segs) >= MAX_VEC_SEGMENTS and n_rows > idx.num_rows:
        reason = (f"tail-segment cap reached ({len(idx.segs)} >= "
                  f"{MAX_VEC_SEGMENTS}); re-clustering")
    if reason is not None:
        log.info("maintenance",
                 f"full ivf rebuild on \"{provider.name}\" "
                 f"({idx.column}): {reason}")
        return build_ivf_index(provider, idx.column, idx.options)
    if n_rows == idx.num_rows:
        return _clone_ivf(idx, n_rows, epoch)
    # pure append: parse the tail only, keep centroids and segments
    col = provider.full_batch([idx.column]).column(idx.column)
    texts = col.slice(idx.num_rows, n_rows).to_pylist()
    vecs, rows = [], []
    for i, t in enumerate(texts):
        v = parse_vector(t, idx.dim) if t is not None else None
        if v is not None:
            vecs.append(v)
            rows.append(idx.num_rows + i)
    new = _clone_ivf(idx, n_rows, epoch)
    if vecs:
        mat = np.stack(vecs).astype(np.float32)
        rows = np.asarray(rows, np.int64)
        vals = mat
        if idx.quantized:
            q8 = np.clip(np.round((mat - idx.sq8_lo) / idx.sq8_scale
                                  * 255.0), 0, 255).astype(np.uint8)
            vals = vops.sq8_dequantize(q8, idx.sq8_lo, idx.sq8_scale)
            host = np.zeros((n_rows, idx.dim), np.float32)
            host[:len(idx.host_vectors)] = idx.host_vectors
            host[rows] = mat
            new.host_vectors = host
        codes = np.asarray(vops.assign_clusters(
            jnp.asarray(vops.pad_rows(mat)),
            jnp.asarray(idx.centroids)))[:len(mat)]
        new.segs.append(VecSegment(vals, rows, codes, idx.lists))
    return new


def _clone_ivf(idx: IvfIndex, n_rows: int, epoch: int) -> IvfIndex:
    return IvfIndex(
        column=idx.column, dim=idx.dim, lists=idx.lists,
        metric=idx.metric, centroids=idx.centroids, segs=idx.segs,
        num_rows=n_rows, data_version=idx.data_version,
        mutation_epoch=epoch, options=idx.options,
        quantized=idx.quantized, host_vectors=idx.host_vectors,
        sq8_lo=idx.sq8_lo, sq8_scale=idx.sq8_scale)


def find_ivf_index(provider, column: str) -> Optional[IvfIndex]:
    """Current IVF index for the column, read-repairing pure appends
    in place (incremental tail segment). Destructive mutations return
    None — the knn degrades to a scored scan — but LOG the reason once
    per stale index so the degradation is diagnosable; the maintenance
    ticker rebuilds it."""
    for name, idx in getattr(provider, "indexes", {}).items():
        if not (isinstance(idx, IvfIndex) and idx.column == column):
            continue
        if idx.data_version == provider.data_version:
            return idx
        epoch = getattr(provider, "mutation_epoch", 0)
        n_rows = provider.row_count()
        if idx.mutation_epoch == epoch and n_rows >= idx.num_rows \
                and len(idx.segs) < MAX_VEC_SEGMENTS:
            from .index import _repair
            return _repair(provider, name, idx,
                           lambda cur: refresh_ivf_index(provider, cur))
        if not getattr(idx, "_orphan_logged", False):
            idx._orphan_logged = True
            why = ("mutation epoch advanced"
                   if idx.mutation_epoch != epoch else
                   "row count shrank" if n_rows < idx.num_rows else
                   "tail-segment cap reached")
            log.info("maintenance",
                     f"ivf index on \"{provider.name}\" ({column}) "
                     f"stale ({why}); queries fall back to a scored "
                     "scan until the maintenance ticker rebuilds it")
        return None
    return None


def find_maxsim_index(provider, column: str) -> Optional[MaxSimIndex]:
    for idx in getattr(provider, "indexes", {}).values():
        if isinstance(idx, MaxSimIndex) and idx.column == column and \
                idx.data_version == provider.data_version:
            return idx
    return None
