"""Sorted primary-key index over memcomparable key bytes.

Reference analog: the iresearch PK terms written by the sink writer
(server/connector/search_sink_writer.cpp PK encoding +
key_encoding.cpp) — here a sorted (keys, row_ids) pair per table,
version-stamped like every other index so lock-free readers repair on
staleness instead of trusting a stale structure. Appends extend the
index incrementally (O(k log n) merge); mutations rebuild.

Serves three consumers:
- uniqueness checks for INSERT / upsert (engine)
- PK point lookups and leading-column range scans (PkScanNode)
- PK-based remove filters: WAL delete_pk records resolve key bytes to
  physical rows at apply/replay time, so recovery no longer depends on
  positional row identity for PK tables
"""

from __future__ import annotations

import threading

import numpy as np

from ..columnar import keyenc

_attach_guard = threading.Lock()


class PkIndex:
    def __init__(self, pk_cols: list, keys: np.ndarray, rows: np.ndarray,
                 data_version: int):
        self.pk_cols = pk_cols          # column names, declared order
        self.keys = keys                # sorted object array of bytes
        self.rows = rows                # int64 row ids, aligned with keys
        self.data_version = data_version

    # -- lookups -----------------------------------------------------------

    def get(self, key: bytes) -> int:
        """Row id for an exact key, or -1."""
        i = int(np.searchsorted(self.keys, key))
        if i < len(self.keys) and self.keys[i] == key:
            return int(self.rows[i])
        return -1

    def contains_any(self, keys: np.ndarray) -> np.ndarray:
        """Boolean mask: which of `keys` exist in the index."""
        if len(self.keys) == 0:
            return np.zeros(len(keys), dtype=bool)
        idx = np.searchsorted(self.keys, keys)
        idx = np.clip(idx, 0, len(self.keys) - 1)
        return self.keys[idx] == keys

    def lookup_rows(self, keys) -> np.ndarray:
        """Row ids for exact keys; missing keys are skipped."""
        out = []
        for k in keys:
            r = self.get(k)
            if r >= 0:
                out.append(r)
        return np.asarray(out, dtype=np.int64)

    def range_rows(self, lo, hi) -> np.ndarray:
        """Row ids whose key is in [lo, hi) — None bounds are open."""
        start = 0 if lo is None else int(np.searchsorted(self.keys, lo))
        end = len(self.keys) if hi is None else \
            int(np.searchsorted(self.keys, hi))
        return np.sort(self.rows[start:end].astype(np.int64))


def _build(provider, pk_cols: list) -> PkIndex:
    batch, ver, _ = provider.pinned()
    cols = [batch.column(c) for c in pk_cols]
    keys = keyenc.encode_key_columns(cols)
    order = np.argsort(keys, kind="stable")
    return PkIndex(list(pk_cols), keys[order],
                   order.astype(np.int64), ver)


def pk_index(provider) -> "PkIndex | None":
    """The provider's PK index, rebuilt if stale (version-stamped; same
    repair discipline as search/index.py)."""
    meta = getattr(provider, "table_meta", None) or {}
    pk = meta.get("primary_key") or []
    if not pk:
        return None
    lk = getattr(provider, "_pk_index_lock", None)
    if lk is None:
        with _attach_guard:
            lk = getattr(provider, "_pk_index_lock", None)
            if lk is None:
                lk = threading.Lock()
                provider._pk_index_lock = lk
    with lk:
        idx = getattr(provider, "_pk_index", None)
        if idx is not None and idx.data_version == provider.data_version \
                and idx.pk_cols == list(pk):
            return idx
        idx = _build(provider, pk)
        provider._pk_index = idx
        return idx


def pk_extend(provider, appended_keys: np.ndarray, n_before: int,
              base_version: int):
    """After an append of len(appended_keys) rows starting at row
    n_before: merge the new keys in instead of rebuilding. Caller holds
    the table's write_lock and passes the data_version it observed
    BEFORE publishing — if the cached index is not exactly at that
    version, a concurrent lock-free reader already rebuilt it over the
    published batch (merging again would duplicate the keys) or it is
    stale in some other way; skip and let pk_index() repair."""
    lk = getattr(provider, "_pk_index_lock", None)
    if lk is None:
        return
    with lk:
        idx = getattr(provider, "_pk_index", None)
        if idx is None or idx.data_version != base_version:
            return
        new_rows = np.arange(n_before, n_before + len(appended_keys),
                             dtype=np.int64)
        order = np.argsort(appended_keys, kind="stable")
        ak, ar = appended_keys[order], new_rows[order]
        pos = np.searchsorted(idx.keys, ak)
        keys = np.insert(idx.keys, pos, ak)
        rows = np.insert(idx.rows, pos, ar)
        provider._pk_index = PkIndex(idx.pk_cols, keys, rows,
                                     provider.data_version)
