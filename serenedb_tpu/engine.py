"""Engine facade: Database (catalog of tables) + Connection (session).

Reference analog: the serened process + per-socket session driving one
DuckDB connection (SURVEY.md §3.2). Here a Database owns the table
namespace; Connections carry session settings and execute statements.
The storage/catalog layers (WAL-backed search tables, versioned snapshots,
RBAC) progressively replace the in-memory structures in this module.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

import numpy as np

from . import errors
from .columnar import dtypes as dt
from .columnar.column import Batch, Column, concat_batches
from .exec.plan import ExecContext, PlanNode
from .exec.tables import MemTable, ParquetTable, TableProvider
from .sql import ast, parser
from .sql.binder import ExprBinder, Scope, cast_column
from .sql.planner import Planner, TableResolver
from .utils import faults, log, metrics
from .utils.config import SessionSettings


@dataclass
class QueryResult:
    """One statement's result: rows (maybe empty) + a PG command tag."""
    batch: Batch
    command_tag: str

    @property
    def names(self) -> list[str]:
        return self.batch.names

    def rows(self) -> list[tuple]:
        return self.batch.rows()

    def scalar(self):
        rs = self.rows()
        return rs[0][0] if rs else None


@dataclass
class ViewDef:
    name: str
    query: ast.Select
    sql: str


class SchemaObj:
    def __init__(self, name: str):
        self.name = name
        self.tables: dict[str, TableProvider] = {}
        self.views: dict[str, ViewDef] = {}


class Database(TableResolver):
    """The process-wide database: schema → tables/views. Thread-safe for
    DDL/DML via a coarse lock (fine-grained MVCC comes with the catalog
    layer)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.lock = threading.RLock()
        self.schemas: dict[str, SchemaObj] = {"main": SchemaObj("main")}
        # parquet providers are cached by path so repeated queries reuse the
        # provider's HBM column cache and compiled XLA programs
        self._parquet_cache: dict[str, ParquetTable] = {}

    # -- resolution (TableResolver) ---------------------------------------

    def _split(self, parts: list[str]) -> tuple[str, str]:
        if len(parts) == 1:
            return "main", parts[0]
        if len(parts) == 2:
            return parts[0], parts[1]
        # database.schema.table — single-database process, ignore the first
        return parts[-2], parts[-1]

    def resolve_table(self, parts: list[str]) -> TableProvider:
        schema, name = self._split(parts)
        with self.lock:
            s = self.schemas.get(schema)
            if s is None:
                raise errors.SqlError(errors.UNDEFINED_TABLE,
                                      f'schema "{schema}" does not exist')
            t = s.tables.get(name.lower())
            if t is not None:
                return t
            v = s.views.get(name.lower())
            if v is not None:
                raise _ViewRef(v)  # unwound by the planner wrapper below
        from .pgcatalog import system_table
        st = system_table(self, parts)
        if st is not None:
            return st
        raise errors.SqlError(errors.UNDEFINED_TABLE,
                              f'relation "{".".join(parts)}" does not exist')

    def resolve_table_function(self, name: str, args: list) -> TableProvider:
        if name in ("read_parquet", "parquet_scan"):
            path = str(args[0])
            with self.lock:
                p = self._parquet_cache.get(path)
                if p is None:
                    p = self._parquet_cache[path] = ParquetTable(path)
            return p
        if name == "sdb_log":
            from .pgcatalog import log_table
            return log_table()
        if name == "sdb_metrics":
            from .pgcatalog import metrics_table
            return metrics_table()
        raise errors.SqlError(errors.UNDEFINED_FUNCTION,
                              f"table function {name} does not exist")

    # -- DDL ---------------------------------------------------------------

    def create_schema(self, name: str, if_not_exists: bool):
        with self.lock:
            if name in self.schemas:
                if if_not_exists:
                    return
                raise errors.SqlError(errors.DUPLICATE_OBJECT,
                                      f'schema "{name}" already exists')
            self.schemas[name] = SchemaObj(name)

    def create_table(self, schema: str, name: str, provider: TableProvider,
                     if_not_exists: bool):
        with self.lock:
            s = self._schema(schema)
            key = name.lower()
            if key in s.tables or key in s.views:
                if if_not_exists:
                    return False
                raise errors.SqlError(errors.DUPLICATE_TABLE,
                                      f'relation "{name}" already exists')
            s.tables[key] = provider
            return True

    def create_view(self, schema: str, name: str, view: ViewDef,
                    or_replace: bool):
        with self.lock:
            s = self._schema(schema)
            key = name.lower()
            if key in s.tables:
                raise errors.SqlError(errors.DUPLICATE_TABLE,
                                      f'"{name}" is already a table')
            if key in s.views and not or_replace:
                raise errors.SqlError(errors.DUPLICATE_TABLE,
                                      f'relation "{name}" already exists')
            s.views[key] = view

    def drop(self, kind: str, parts: list[str], if_exists: bool,
             cascade: bool):
        schema, name = self._split(parts)
        with self.lock:
            if kind == "schema":
                target = parts[-1]
                if target not in self.schemas:
                    if if_exists:
                        return
                    raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                          f'schema "{target}" does not exist')
                if target == "main":
                    raise errors.SqlError(errors.FEATURE_NOT_SUPPORTED,
                                          "cannot drop schema main")
                if self.schemas[target].tables and not cascade:
                    raise errors.SqlError("2BP01",
                                          f'schema "{target}" is not empty')
                del self.schemas[target]
                return
            s = self._schema(schema, if_exists)
            if s is None:
                return
            key = name.lower()
            store = s.views if kind == "view" else s.tables
            if key not in store:
                if if_exists:
                    return
                raise errors.SqlError(errors.UNDEFINED_TABLE,
                                      f'{kind} "{name}" does not exist')
            del store[key]

    def _schema(self, name: str, if_exists_ok: bool = False):
        s = self.schemas.get(name)
        if s is None and not if_exists_ok:
            raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                  f'schema "{name}" does not exist')
        return s

    def table_list(self) -> list[tuple[str, str, str]]:
        with self.lock:
            out = []
            for sname, s in self.schemas.items():
                for t in s.tables:
                    out.append((sname, t, "table"))
                for v in s.views:
                    out.append((sname, v, "view"))
            return sorted(out)

    def connect(self) -> "Connection":
        return Connection(self)


class _ViewRef(Exception):
    def __init__(self, view: ViewDef):
        self.view = view


class _ResolverShim(TableResolver):
    """Expands views inline during planning."""

    def __init__(self, db: Database, planner_params):
        self.db = db
        self.params = planner_params

    def resolve_table(self, parts: list[str]) -> TableProvider:
        return self.db.resolve_table(parts)

    def resolve_table_function(self, name, args):
        return self.db.resolve_table_function(name, args)


class Connection:
    def __init__(self, db: Database):
        self.db = db
        self.settings = SessionSettings()
        self.in_txn = False
        self.txn_failed = False

    # -- public API --------------------------------------------------------

    def execute(self, sql: str, params: Optional[list] = None) -> QueryResult:
        results = self.execute_all(sql, params)
        return results[-1] if results else QueryResult(Batch([], []), "")

    def execute_all(self, sql: str,
                    params: Optional[list] = None) -> list[QueryResult]:
        stmts = parser.parse(sql)
        out = []
        for st in stmts:
            out.append(self.execute_statement(st, params or []))
        return out

    def execute_statement(self, st: ast.Statement,
                          params: list) -> QueryResult:
        if self.txn_failed and not isinstance(st, ast.Transaction):
            raise errors.SqlError(
                errors.IN_FAILED_TRANSACTION,
                "current transaction is aborted, commands ignored until "
                "end of transaction block")
        try:
            with metrics.QUERIES_ACTIVE.scoped():
                return self._dispatch(st, params)
        except errors.SqlError:
            if self.in_txn:
                self.txn_failed = True
            raise

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, st: ast.Statement, params: list) -> QueryResult:
        if isinstance(st, ast.Select):
            batch = self._run_select(st, params)
            return QueryResult(batch, f"SELECT {batch.num_rows}")
        if isinstance(st, ast.CreateTable):
            return self._create_table(st, params)
        if isinstance(st, ast.CreateSchema):
            self.db.create_schema(st.name, st.if_not_exists)
            return QueryResult(Batch([], []), "CREATE SCHEMA")
        if isinstance(st, ast.CreateView):
            schema, name = self.db._split(st.name)
            self.db.create_view(schema, name,
                                ViewDef(name, st.query, ""), st.or_replace)
            return QueryResult(Batch([], []), "CREATE VIEW")
        if isinstance(st, ast.CreateIndex):
            return self._create_index(st)
        if isinstance(st, ast.Drop):
            self.db.drop(st.kind, st.name, st.if_exists, st.cascade)
            return QueryResult(Batch([], []), f"DROP {st.kind.upper()}")
        if isinstance(st, ast.Insert):
            return self._insert(st, params)
        if isinstance(st, ast.Delete):
            return self._delete(st, params)
        if isinstance(st, ast.Update):
            return self._update(st, params)
        if isinstance(st, ast.Truncate):
            return self._truncate(st)
        if isinstance(st, ast.SetStmt):
            return self._set(st)
        if isinstance(st, ast.ShowStmt):
            return self._show(st)
        if isinstance(st, ast.Transaction):
            return self._txn(st)
        if isinstance(st, ast.Explain):
            return self._explain(st, params)
        if isinstance(st, ast.VacuumStmt):
            return self._vacuum(st)
        if isinstance(st, ast.CopyStmt):
            return self._copy(st, params)
        raise errors.unsupported(f"statement {type(st).__name__}")

    # -- SELECT ------------------------------------------------------------

    def _plan(self, sel: ast.Select, params: list) -> PlanNode:
        from .sql.search_rewrite import rewrite_search
        planner = Planner(_ResolverShim(self.db, params), params)
        while True:
            try:
                return rewrite_search(planner.plan_select(sel))
            except _ViewRef as vr:
                sel = _inline_view(sel, vr.view)

    def _run_select(self, sel: ast.Select, params: list) -> Batch:
        plan = self._plan(sel, params)
        ctx = ExecContext(self.settings, params)
        return plan.execute(ctx)

    # -- DDL/DML -----------------------------------------------------------

    def _create_table(self, st: ast.CreateTable, params: list) -> QueryResult:
        schema, name = self.db._split(st.name)
        if st.as_query is not None:
            batch = self._run_select(st.as_query, params)
            provider = MemTable(name, batch)
        else:
            cols = []
            names = []
            for cd in st.columns:
                t = dt.type_from_name(cd.type_name)
                names.append(cd.name)
                cols.append(Column(t, np.empty(0, dtype=t.np_dtype), None,
                                   np.empty(0, dtype=object)
                                   if t.is_string else None))
            provider = MemTable(name, Batch(names, cols))
        provider.table_meta = {
            "engine": st.engine,
            "primary_key": st.primary_key,
            "not_null": [c.name for c in st.columns if c.not_null],
            "defaults": {c.name: c.default for c in st.columns if c.default},
            "tokenizers": {c.name: c.tokenizer for c in st.columns
                           if c.tokenizer},
            "options": st.options,
        }
        created = self.db.create_table(schema, name, provider,
                                       st.if_not_exists)
        if st.as_query is not None and created:
            return QueryResult(Batch([], []),
                               f"SELECT {provider.row_count()}")
        return QueryResult(Batch([], []), "CREATE TABLE")

    def _create_index(self, st: ast.CreateIndex) -> QueryResult:
        provider = self.db.resolve_table(st.table)
        if not hasattr(provider, "indexes"):
            provider.indexes = {}
        idx_name = st.name or f"{st.table[-1]}_{'_'.join(st.columns)}_idx"
        from .search.index import build_index_for_table
        provider.indexes[idx_name] = build_index_for_table(
            provider, st.columns, st.using, st.options)
        return QueryResult(Batch([], []), "CREATE INDEX")

    def _table_for_dml(self, parts: list[str]) -> MemTable:
        provider = self.db.resolve_table(parts)
        if not isinstance(provider, MemTable):
            raise errors.SqlError(errors.FEATURE_NOT_SUPPORTED,
                                  "cannot modify this table")
        return provider

    def _insert(self, st: ast.Insert, params: list) -> QueryResult:
        table = self._table_for_dml(st.table)
        target_names = st.columns or table.column_names
        for c in target_names:
            if c not in table.column_names:
                raise errors.SqlError(errors.UNDEFINED_COLUMN,
                                      f'column "{c}" does not exist')
        if st.query is not None:
            incoming = self._run_select(st.query, params)
        else:
            binder = ExprBinder(Scope([]), params)
            one = Batch(["__dummy"], [Column.from_pylist([0])])
            cols_vals: list[list] = [[] for _ in target_names]
            for row in st.values:
                if len(row) != len(target_names):
                    raise errors.SqlError(
                        "42601", "INSERT has more expressions than columns"
                        if len(row) > len(target_names)
                        else "INSERT has more target columns than expressions")
                for k, e in enumerate(row):
                    b = binder.bind(e)
                    cols_vals[k].append(b.eval(one).decode(0))
            incoming = Batch(list(target_names),
                             [Column.from_pylist(v) for v in cols_vals])
        self._insert_batch(table, incoming)
        return QueryResult(Batch([], []), f"INSERT 0 {incoming.num_rows}")

    def _delete(self, st: ast.Delete, params: list) -> QueryResult:
        table = self._table_for_dml(st.table)
        with self.db.lock:
            full = table.full_batch()
            if st.where is None:
                n = full.num_rows
                table.replace(full.slice(0, 0))
                return QueryResult(Batch([], []), f"DELETE {n}")
            scope = Scope.of(list(full.names), [c.type for c in full.columns],
                             st.table[-1])
            pred = ExprBinder(scope, params).bind(st.where)
            c = pred.eval(full)
            mask = c.data.astype(bool) & c.valid_mask()
            n = int(mask.sum())
            table.replace(full.filter(~mask))
        return QueryResult(Batch([], []), f"DELETE {n}")

    def _update(self, st: ast.Update, params: list) -> QueryResult:
        table = self._table_for_dml(st.table)
        with self.db.lock:
            full = table.full_batch()
            scope = Scope.of(list(full.names), [c.type for c in full.columns],
                             st.table[-1])
            binder = ExprBinder(scope, params)
            if st.where is not None:
                c = binder.bind(st.where).eval(full)
                mask = c.data.astype(bool) & c.valid_mask()
            else:
                mask = np.ones(full.num_rows, dtype=bool)
            n = int(mask.sum())
            new_cols = {}
            for col_name, e in st.assignments:
                if col_name not in full:
                    raise errors.SqlError(errors.UNDEFINED_COLUMN,
                                          f'column "{col_name}" does not exist')
                target_t = full.column(col_name).type
                val = _coerce(binder.bind(e).eval(full), target_t)
                cur = full.column(col_name)
                merged_vals = [
                    val.decode(i) if mask[i] else cur.decode(i)
                    for i in range(full.num_rows)]
                new_cols[col_name] = Column.from_pylist(merged_vals, target_t)
            cols = [new_cols.get(nm, c)
                    for nm, c in zip(full.names, full.columns)]
            table.replace(Batch(list(full.names), cols))
        return QueryResult(Batch([], []), f"UPDATE {n}")

    def _truncate(self, st: ast.Truncate) -> QueryResult:
        table = self._table_for_dml(st.table)
        with self.db.lock:
            table.replace(table.full_batch().slice(0, 0))
        return QueryResult(Batch([], []), "TRUNCATE TABLE")

    # -- session statements ------------------------------------------------

    def _set(self, st: ast.SetStmt) -> QueryResult:
        if st.value == "DEFAULT":
            self.settings.reset(st.name)
        else:
            self.settings.set(st.name, st.value)
            if st.name == "sdb_faults":
                faults.arm_from_spec(str(st.value))
        return QueryResult(Batch([], []), "SET")

    def _show(self, st: ast.ShowStmt) -> QueryResult:
        if st.name == "tables":
            rows = self.db.table_list()
            b = Batch.from_pydict({
                "schema": [r[0] for r in rows],
                "name": [r[1] for r in rows],
                "kind": [r[2] for r in rows]})
            return QueryResult(b, f"SELECT {b.num_rows}")
        if st.name == "all":
            names = self.settings._registry.names()
            b = Batch.from_pydict({
                "name": names,
                "setting": [str(self.settings.get(n)) for n in names]})
            return QueryResult(b, f"SELECT {b.num_rows}")
        v = self.settings.get(st.name)
        b = Batch.from_pydict({st.name: [_setting_text(v)]})
        return QueryResult(b, "SHOW")

    def _txn(self, st: ast.Transaction) -> QueryResult:
        # single-statement autocommit engine for now: BEGIN/COMMIT tracked
        # for wire-protocol status; ROLLBACK clears failure state.
        if st.action == "begin":
            self.in_txn = True
            self.txn_failed = False
            return QueryResult(Batch([], []), "BEGIN")
        self.in_txn = False
        self.txn_failed = False
        return QueryResult(Batch([], []),
                           "COMMIT" if st.action == "commit" else "ROLLBACK")

    def _explain(self, st: ast.Explain, params: list) -> QueryResult:
        if not isinstance(st.inner, ast.Select):
            raise errors.unsupported("EXPLAIN of non-SELECT")
        plan = self._plan(st.inner, params)
        lines = plan.explain()
        b = Batch.from_pydict({"QUERY PLAN": lines})
        return QueryResult(b, f"SELECT {len(lines)}")

    def _vacuum(self, st: ast.VacuumStmt) -> QueryResult:
        return QueryResult(Batch([], []), "VACUUM")

    def _copy(self, st: ast.CopyStmt, params: list) -> QueryResult:
        fmt = str(st.options.get("format", "csv")).lower()
        if st.direction == "from":
            table = self._table_for_dml(st.table)
            if fmt == "parquet":
                incoming = ParquetTable(st.target).full_batch()
            elif fmt in ("csv", "text"):
                incoming = _read_csv(st.target, table, st.options)
            else:
                raise errors.unsupported(f"COPY format {fmt}")
            names = st.columns or list(incoming.names)
            sub = Batch(names, [incoming.columns[i]
                                for i in range(len(names))])
            self._insert_batch(table, sub)
            return QueryResult(Batch([], []), f"COPY {incoming.num_rows}")
        # COPY TO
        provider = self.db.resolve_table(st.table)
        full = provider.full_batch(st.columns)
        if fmt == "parquet":
            _write_parquet(st.target, full)
        else:
            _write_csv(st.target, full, st.options)
        return QueryResult(Batch([], []), f"COPY {full.num_rows}")

    def _insert_batch(self, table: MemTable, incoming: Batch):
        with self.db.lock:
            current = table.full_batch()
            new_cols = []
            for name, cur in zip(table.column_names, current.columns):
                if name in incoming.names:
                    add = _coerce(incoming.column(name), cur.type)
                else:
                    add = Column.from_pylist([None] * incoming.num_rows,
                                             cur.type)
                merged = concat_batches(
                    [Batch([name], [cur]), Batch([name], [add])]).columns[0]
                new_cols.append(merged)
            table.replace(Batch(list(table.column_names), new_cols))


def _coerce(col: Column, target: dt.SqlType) -> Column:
    if col.type == target or col.type.id is dt.TypeId.NULL:
        if col.type.id is dt.TypeId.NULL and target.id is not dt.TypeId.NULL:
            return Column.from_pylist([None] * len(col), target)
        return col
    return cast_column(col, target)


def _setting_text(v) -> str:
    if isinstance(v, bool):
        return "on" if v else "off"
    return str(v)


def _inline_view(sel: ast.Select, view: ViewDef) -> ast.Select:
    """Replace references to the view with a subquery ref."""
    def rewrite(ref: ast.TableRef) -> ast.TableRef:
        if isinstance(ref, ast.NamedTable) and \
                ref.parts[-1].lower() == view.name.lower():
            return ast.SubqueryRef(view.query, ref.alias or view.name)
        if isinstance(ref, ast.JoinRef):
            ref.left = rewrite(ref.left)
            ref.right = rewrite(ref.right)
        return ref
    import copy
    sel2 = copy.deepcopy(sel)
    if sel2.from_ is not None:
        sel2.from_ = rewrite(sel2.from_)
    return sel2


def _read_csv(path: str, table: MemTable, options: dict) -> Batch:
    import csv as _csv
    delim = str(options.get("delimiter", ","))
    header = str(options.get("header", "false")).lower() in ("true", "on", "1")
    with open(path, newline="") as f:
        rows = list(_csv.reader(f, delimiter=delim))
    if header and rows:
        rows = rows[1:]
    names = table.column_names
    cols = []
    for k, (nm, t) in enumerate(zip(names, table.column_types)):
        vals = []
        for r in rows:
            raw = r[k] if k < len(r) else ""
            if raw == "" or raw == "\\N":
                vals.append(None)
            else:
                from .sql.binder import _cast_text_to
                vals.append(raw if t.is_string else _cast_text_to(raw, t))
        cols.append(Column.from_pylist(vals, t))
    return Batch(list(names), cols)


def _write_csv(path: str, batch: Batch, options: dict):
    import csv as _csv
    delim = str(options.get("delimiter", ","))
    header = str(options.get("header", "false")).lower() in ("true", "on", "1")
    with open(path, "w", newline="") as f:
        w = _csv.writer(f, delimiter=delim)
        if header:
            w.writerow(batch.names)
        for row in batch.rows():
            w.writerow(["" if v is None else v for v in row])


def _write_parquet(path: str, batch: Batch):
    import pyarrow as pa
    import pyarrow.parquet as pq
    arrays = []
    for c in batch.columns:
        vals = c.to_pylist()
        arrays.append(pa.array(vals))
    pq.write_table(pa.table(dict(zip(batch.names, arrays))), path)
