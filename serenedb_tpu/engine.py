"""Engine facade: Database (catalog of tables) + Connection (session).

Reference analog: the serened process + per-socket session driving one
DuckDB connection (SURVEY.md §3.2). Here a Database owns the table
namespace; Connections carry session settings and execute statements.
The storage/catalog layers (WAL-backed search tables, versioned snapshots,
RBAC) progressively replace the in-memory structures in this module.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

import numpy as np

from . import errors
from .columnar import dtypes as dt
from .columnar.column import Batch, Column, concat_batches
from .exec.plan import ExecContext, PlanNode
from .exec.tables import MemTable, ParquetTable, TableProvider
from .sql import ast, parser
from .sql.binder import ExprBinder, Scope, ScopeColumn, cast_column
from .sql.planner import Planner, TableResolver
from .utils import faults, log, metrics
from .utils.config import SessionSettings


# current connection for context-dependent functions (nextval/currval —
# the reference threads ClientContext through DuckDB function binding)
CURRENT_CONNECTION: contextvars.ContextVar = contextvars.ContextVar(
    "serene_current_connection", default=None)


@dataclass
class QueryResult:
    """One statement's result: rows (maybe empty) + a PG command tag."""
    batch: Batch
    command_tag: str

    @property
    def names(self) -> list[str]:
        return self.batch.names

    def rows(self) -> list[tuple]:
        return self.batch.rows()

    def scalar(self):
        rs = self.rows()
        return rs[0][0] if rs else None


#: statement types the timeline tracer skips: pure session bookkeeping
#: with no execution work — recording their empty timelines would churn
#: the bounded flight recorder (obs/trace.FLIGHT) out of the slow-query
#: entries it exists to preserve
_UNTRACED_STATEMENTS = (ast.SetStmt, ast.ShowStmt, ast.SetRole,
                        ast.Transaction, ast.ListenStmt, ast.NotifyStmt)


def _result_rows(res: "QueryResult") -> int:
    """Rows a statement produced/affected, for statement stats: result
    rows when any came back, else the count off the PG command tag
    ('INSERT 0 5' → 5, 'DELETE 3' → 3, 'SET' → 0)."""
    n = res.batch.num_rows
    if n:
        return n
    parts = res.command_tag.split()
    return int(parts[-1]) if parts and parts[-1].isdigit() else 0


@dataclass
class ViewDef:
    name: str
    query: ast.Select
    sql: str


def _view_references(node, schema: str, table_key: str,
                     depth: int = 0) -> bool:
    """Does a view's AST reference relation (schema, name)? Unqualified
    references resolve to schema "main" (the engine's _split rule), so a
    view over s1.dup never blocks dropping s2.dup. Generic dataclass
    walk."""
    import dataclasses
    if depth > 200 or node is None:
        return False
    if isinstance(node, ast.NamedTable):
        parts = node.parts
        ref_schema = parts[-2].lower() if len(parts) >= 2 else "main"
        return (parts[-1].lower() == table_key and
                ref_schema == schema.lower())
    if isinstance(node, (list, tuple)):
        return any(_view_references(v, schema, table_key, depth + 1)
                   for v in node)
    if isinstance(node, dict):
        return any(_view_references(v, schema, table_key, depth + 1)
                   for v in node.values())
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        return any(_view_references(getattr(node, f.name), schema,
                                    table_key, depth + 1)
                   for f in dataclasses.fields(node))
    return False


class SchemaObj:
    def __init__(self, name: str):
        self.name = name
        self.tables: dict[str, TableProvider] = {}
        self.views: dict[str, ViewDef] = {}


class StoredTable(MemTable):
    """A durable columnar table: in-memory working set + WAL write-through +
    parquet checkpoint snapshots (reference analog: a Search-engine table's
    columnstore + SearchDbWal leg, SURVEY.md §2.6)."""

    def __init__(self, name: str, batch: Batch, key: str, table_id: int):
        super().__init__(name, batch)
        self.key = key
        self.table_id = table_id


class Database(TableResolver):
    """The process-wide database: schema → tables/views. Thread-safe for
    DDL/DML via a coarse lock (fine-grained MVCC comes with the catalog
    layer). With `path`, all DDL/DML is durable: definitions in
    catalog.json, data as parquet snapshots + WAL delta (storage/)."""

    #: sequence counters persist in batches of this many values — a crash
    #: skips at most one batch, never repeats (reference: batched counter
    #: persistence, server/catalog/sequence.cpp)
    SEQ_BATCH = 32

    def __init__(self, path: Optional[str] = None):
        self.path = path
        #: guards the CATALOG (schemas/tables/views dicts), the session
        #: registry and LISTEN/NOTIFY wiring — NOT data-plane execution.
        #: Table data is guarded per-table: writers serialize on
        #: MemTable.write_lock, readers pin the atomic (batch, version,
        #: epoch) publication without any lock, so concurrent SELECTs and
        #: DML on different tables never contend process-wide (reference:
        #: morsel-parallel execution, server_engine.cpp:225-244).
        self.lock = threading.RLock()
        self.schemas: dict[str, SchemaObj] = {"main": SchemaObj("main")}
        self.sequences: dict[str, dict] = {}
        #: user-defined types: name -> {"kind": "enum"|"domain",
        #: "labels": [...], "base": str} (reference: catalog UserType,
        #: server/catalog/object.h:82-94)
        self.types: dict[str, dict] = {}
        # parquet providers are cached by path so repeated queries reuse the
        # provider's HBM column cache and compiled XLA programs
        self._parquet_cache: dict[str, ParquetTable] = {}
        from .auth import Roles
        self.roles = Roles()
        #: dictionaries registered by THIS database; released on close so
        #: process-global analyzer state never leaks across Databases
        self._tsdict_names: set[str] = set()
        # live sessions for pg_stat_activity (id → info dict); entries
        # are removed by Connection.close()/finalizer
        self.sessions: dict[int, dict] = {}
        self._session_seq = 0
        # LISTEN/NOTIFY bus: channel → {Connection}; notifications land in
        # each listener's thread-safe deque and drain at statement
        # boundaries (pgwire sends NotificationResponse before ready)
        self._listeners: dict[str, set] = {}
        # stable in-process OIDs for pg_catalog introspection: assigned
        # lazily per (kind, schema, name), never reused within a process
        # (reference: catalog object ids, server/pg/pg_catalog/)
        self._oids: dict[tuple, int] = {}
        self._oid_rev: dict[int, tuple] = {}
        self._oid_next = 16384
        self.store = None
        self.maintenance = None
        if path is not None:
            from .storage.store import Store
            self.store = Store(path)
            self._boot()
            from .storage.maintenance import MaintenanceManager
            self.maintenance = MaintenanceManager(self)
            self.maintenance.start()

    @contextlib.contextmanager
    def quiesced(self, tables):
        """Exclusive writer section over `tables` with fast-path inserts
        drained: raises the quiesce gate on EVERY table first (so an
        insert cannot slip onto an already-drained table while a later one
        is still draining), waits each table's in-flight publishes out
        holding only THAT table's lock (a publisher needs its table's
        write_lock — waiting while holding another table's lock would
        deadlock), then acquires every write_lock in a global order. On
        exit, locks release and gates lower. Mutating ops and checkpoint
        capture run inside this so a committed-but-unpublished insert can
        never order between a commit's WAL tick and its publish (which
        would make live state diverge from replayed state)."""
        tables = sorted(set(tables), key=id)
        for t in tables:
            with t.write_lock:
                t._quiesce_waiters = getattr(t, "_quiesce_waiters", 0) + 1
        try:
            for t in tables:
                with t.write_lock:
                    while getattr(t, "_inflight", 0):
                        t.pub_cond.wait(timeout=5)
            with contextlib.ExitStack() as stack:
                for t in tables:
                    stack.enter_context(t.write_lock)
                yield
        finally:
            for t in tables:
                with t.write_lock:
                    t._quiesce_waiters -= 1
                    t.pub_cond.notify_all()

    def crash(self):
        """Abandon this Database as if the process was killed: stop loops
        without any further checkpoint/refresh pass, release the datadir
        lock, write nothing else. Recovery harnesses reopen the datadir
        afterwards (reference: recovery tests kill serened and restart,
        tests/sqllogic/recovery/)."""
        self._crashed = True
        if self.maintenance is not None:
            self.maintenance.stop()
        if self.store is not None:
            import os
            try:
                os.remove(self.store._lockfile)
            except OSError:
                pass
        from .search.analysis import drop_dictionary
        for name in self._tsdict_names:
            drop_dictionary(name)
        self._tsdict_names.clear()

    def close(self):
        if self.maintenance is not None:
            self.maintenance.stop()
        if self.store is not None:
            # clean shutdown persists exact sequence counters so a restart
            # continues without a gap (PG semantics); only a crash skips
            # ahead to the batched high-water mark
            with self.lock:
                dirty = False
                for seq in self.sequences.values():
                    if seq["hwm"] != seq["value"]:
                        seq["hwm"] = seq["value"]
                        dirty = True
                if dirty:
                    self._persist_sequences()
            self.store.release()
        from .search.analysis import drop_dictionary
        for name in self._tsdict_names:
            drop_dictionary(name)
        self._tsdict_names.clear()

    # -- boot / recovery ---------------------------------------------------

    def _boot(self):
        """Load definitions, table snapshots, then WAL delta replay
        (reference startup order: store → catalog → search recovery,
        serened.cpp:133-150)."""
        from .sql import parser as _parser
        meta = self.store.load_meta()
        for s in meta.get("schemas", ["main"]):
            self.schemas.setdefault(s, SchemaObj(s))
        for key, tdef in meta.get("tables", {}).items():
            schema, name = key.split(".", 1)
            names = [c["name"] for c in tdef["columns"]]
            types = [dt.type_from_name(c["type"]) for c in tdef["columns"]]
            batch = self.store.read_snapshot(tdef["id"], names, types)
            t = StoredTable(name, batch, key, tdef["id"])
            import base64
            import pickle
            t.table_meta = {
                "engine": tdef.get("engine", "columnar"),
                "primary_key": tdef.get("primary_key", []),
                "not_null": tdef.get("not_null", []),
                "defaults": {n: pickle.loads(base64.b64decode(b))
                             for n, b in
                             (tdef.get("defaults") or {}).items()},
                "tokenizers": tdef.get("tokenizers", {}),
                "enums": tdef.get("enums", {}),
                "options": tdef.get("options", {}),
            }
            self.schemas[schema].tables[name.lower()] = t
        for key, vdef in meta.get("views", {}).items():
            schema, name = key.split(".", 1)
            import base64
            import pickle
            q = pickle.loads(base64.b64decode(vdef["ast_b64"]))
            self.schemas[schema].views[name.lower()] = ViewDef(name, q, "")

        self.types = dict(meta.get("types", {}))
        self.roles.load_meta(meta.get("auth", {}))
        from .search.analysis import register_dictionary
        for dname, dopts in meta.get("tsdicts", {}).items():
            register_dictionary(dname, dopts, replace=True)
            self._tsdict_names.add(dname.lower())
        for name, sdef in meta.get("sequences", {}).items():
            # resume at the persisted high-water mark: crash skips at most
            # one batch of values, never repeats
            self.sequences[name] = {"value": sdef["hwm"],
                                    "increment": sdef["increment"],
                                    "start": sdef["start"],
                                    "hwm": sdef["hwm"]}

        def committed_of(key: str) -> int:
            tdef = meta.get("tables", {}).get(key)
            if tdef is None:
                return 1 << 62  # dropped table: skip its records
            return tdef.get("checkpoint_tick", 0)

        max_tick = self.store.wal.recover(committed_of, self._apply_wal_op)
        # checkpoint cursors can be ahead of every surviving WAL record
        # (post-GC); ticks must never restart below them or fresh commits
        # would be skipped by a later delta replay
        cursor_ticks = [t.get("checkpoint_tick", 0)
                        for t in meta.get("tables", {}).values()]
        self.store.ticks.advance_to(max(max_tick, *cursor_ticks)
                                    if cursor_ticks else max_tick)
        # rebuild persisted index definitions (backfill from recovered data)
        from .search.index import build_index_for_table
        for idx_name, idef in meta.get("indexes", {}).items():
            t = self._table_by_key(idef["table"])
            if t is None:
                continue
            if not hasattr(t, "indexes"):
                t.indexes = {}
            try:
                t.indexes[idx_name] = build_index_for_table(
                    t, idef["columns"], idef["using"], idef["options"])
            except errors.SqlError:
                log.warn("boot", f"index {idx_name} rebuild failed")

    # -- sequences ---------------------------------------------------------

    def _seq_key(self, name: str) -> str:
        """Sequences are schema-scoped like tables: bare names live in
        main, qualified names ('s2.seq') are used verbatim."""
        return name if "." in name else f"main.{name}"

    def create_sequence(self, name: str, start: int, increment: int,
                        if_not_exists: bool):
        name = self._seq_key(name)
        with self.lock:
            if name in self.sequences:
                if if_not_exists:
                    return
                raise errors.SqlError(errors.DUPLICATE_OBJECT,
                                      f'sequence "{name}" already exists')
            self.sequences[name] = {"value": start - increment,
                                    "increment": increment, "start": start,
                                    "hwm": start - increment}
            self._persist_sequences()

    def drop_sequence(self, name: str, if_exists: bool):
        name = self._seq_key(name)
        with self.lock:
            if name not in self.sequences:
                if if_exists:
                    return
                raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                      f'sequence "{name}" does not exist')
            del self.sequences[name]
            self._persist_sequences()

    def sequence_nextval(self, name: str) -> int:
        name = self._seq_key(name)
        with self.lock:
            seq = self.sequences.get(name)
            if seq is None:
                raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                      f'sequence "{name}" does not exist')
            seq["value"] += seq["increment"]
            if (seq["increment"] > 0 and seq["value"] > seq["hwm"]) or \
                    (seq["increment"] < 0 and seq["value"] < seq["hwm"]):
                seq["hwm"] = seq["value"] + seq["increment"] * self.SEQ_BATCH
                self._persist_sequences()
            return seq["value"]

    def sequence_setval(self, name: str, value: int) -> int:
        name = self._seq_key(name)
        with self.lock:
            seq = self.sequences.get(name)
            if seq is None:
                raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                      f'sequence "{name}" does not exist')
            seq["value"] = value
            seq["hwm"] = value
            self._persist_sequences()
            return value

    def _persist_sequences(self):
        if self.store is None:
            return
        snap = {n: {"hwm": s["hwm"], "increment": s["increment"],
                    "start": s["start"]}
                for n, s in self.sequences.items()}
        self.store.update_meta(
            lambda m: m.__setitem__("sequences", snap))

    def _table_by_key(self, key: str):
        schema, name = key.split(".", 1)
        s = self.schemas.get(schema)
        return s.tables.get(name.lower()) if s else None

    def _apply_wal_op(self, tick: int, op) -> None:
        t = self._table_by_key(op.table)
        if t is None:
            return
        batch = op.batch
        if batch is not None:
            # arrow WAL serde can't carry logical types the physical
            # layout doesn't (ARRAY/RECORD ride as text payloads,
            # INTERVAL as int64 micros) — re-stamp from the catalog
            # schema so replayed appends don't degrade column types
            for name, ct in zip(t.column_names, t.column_types):
                if name in batch and batch.column(name).type != ct and \
                        ct.id in (dt.TypeId.ARRAY, dt.TypeId.RECORD,
                                  dt.TypeId.INTERVAL, dt.TypeId.OID,
                                  dt.TypeId.REGCLASS, dt.TypeId.REGTYPE,
                                  dt.TypeId.REGPROC,
                                  dt.TypeId.REGNAMESPACE):
                    batch.column(name).type = ct
        _apply_ops(t, [(op.kind, batch, op.rows)])

    def _persist_catalog(self):
        if self.store is not None:
            self.store.save_meta()

    # -- resolution (TableResolver) ---------------------------------------

    def _split(self, parts: list[str]) -> tuple[str, str]:
        if len(parts) == 1:
            return "main", parts[0]
        if len(parts) == 2:
            return parts[0], parts[1]
        # database.schema.table — single-database process, ignore the first
        return parts[-2], parts[-1]

    def _acl_check(self, schema: str, name: str, privilege: str = "select"):
        """ACL applies to user tables only; system catalogs stay open
        (reference surfaces introspection to all roles)."""
        conn = CURRENT_CONNECTION.get()
        if conn is not None:
            self.roles.require(conn.current_role,
                               f"{schema}.{name.lower()}", privilege)

    def resolve_table(self, parts: list[str],
                      privilege: str = "select") -> TableProvider:
        schema, name = self._split(parts)
        if schema in ("pg_catalog", "information_schema", "sdb_catalog"):
            from .pgcatalog import system_table
            st = system_table(self, parts)
            if st is not None:
                return st
            raise errors.SqlError(errors.UNDEFINED_TABLE,
                                  f'relation "{".".join(parts)}" does not '
                                  "exist")
        with self.lock:
            s = self.schemas.get(schema)
            if s is None:
                raise errors.SqlError(errors.UNDEFINED_TABLE,
                                      f'schema "{schema}" does not exist')
            t = s.tables.get(name.lower())
        if t is not None:
            self._acl_check(schema, name, privilege)
            return t
        with self.lock:
            v = s.views.get(name.lower())
            if v is not None:
                raise _ViewRef(v)  # unwound by the planner wrapper below
        from .pgcatalog import system_table
        st = system_table(self, parts)
        if st is not None:
            return st
        raise errors.SqlError(errors.UNDEFINED_TABLE,
                              f'relation "{".".join(parts)}" does not exist')

    def resolve_table_function(self, name: str, args: list) -> TableProvider:
        if name in ("read_parquet", "parquet_scan"):
            from .exec.filesource import parquet_source
            pinned = len(args) > 1 and \
                str(args[1]).lower() in ("pinned", "snapshot")
            return parquet_source(self, str(args[0]), pinned=pinned)
        if name in ("read_csv", "read_csv_auto", "csv_scan"):
            from .exec.filesource import csv_source
            header = None
            delim = ","
            if len(args) > 1 and args[1] is not None:
                header = (str(args[1]).lower() in ("true", "t", "1")
                          if not isinstance(args[1], bool) else args[1])
            if len(args) > 2 and args[2] is not None:
                delim = str(args[2])
            return csv_source(self, str(args[0]), header, delim)
        if name == "unnest":
            # set-returning: one row per element; multiple arrays zip with
            # NULL padding (PG: FROM unnest(a, b)); arrays are JSON text
            import json as _json
            lists = []
            for a in args:
                if a is None:
                    lists.append([])
                    continue
                try:
                    elems = _json.loads(str(a))
                except _json.JSONDecodeError:
                    raise errors.SqlError(
                        errors.INVALID_TEXT_REPRESENTATION,
                        f"invalid array literal: {str(a)[:40]!r}")
                if not isinstance(elems, list):
                    raise errors.SqlError(
                        errors.INVALID_TEXT_REPRESENTATION,
                        "unnest expects a JSON array")
                lists.append([
                    _json.dumps(e) if isinstance(e, (list, dict)) else e
                    for e in elems])
            if not lists:
                lists = [[]]
            n = max(len(ls) for ls in lists)
            cols = {}
            for i, ls in enumerate(lists):
                cols["unnest" if i == 0 else f"unnest_{i}"] = \
                    ls + [None] * (n - len(ls))
            return MemTable("unnest", Batch.from_pydict(cols))
        if name == "generate_series":
            # set-returning integer series (PG: generate_series(a, b[, s]))
            if len(args) < 2:
                raise errors.SqlError(
                    "42883", "generate_series requires start and stop")
            if any(a is None for a in args[:3]):
                return MemTable("generate_series", Batch(
                    ["generate_series"],
                    [Column.from_numpy(np.empty(0, dtype=np.int64))]))
            try:
                start, stop = int(args[0]), int(args[1])
                step = int(args[2]) if len(args) > 2 else 1
            except (TypeError, ValueError, OverflowError):
                raise errors.SqlError(
                    errors.INVALID_TEXT_REPRESENTATION,
                    "generate_series arguments must be integers")
            if step == 0:
                raise errors.SqlError(
                    "22023", "step size cannot equal zero")
            n = max(0, (stop - start) // step + 1)
            if n > 50_000_000:
                raise errors.SqlError(
                    "54000", "generate_series result set too large")
            vals = np.arange(start, start + n * step, step, dtype=np.int64)
            return MemTable("generate_series", Batch(
                ["generate_series"], [Column.from_numpy(vals)]))
        if name == "sdb_terms":
            # term-enumeration scan over an inverted index (reference:
            # the TsDict full-scan mode of
            # server/connector/duckdb_search_full_scan.hpp:54-76 — the
            # dictionary itself is a queryable relation)
            if len(args) < 2:
                raise errors.SqlError(
                    "42883", "sdb_terms(table, column) requires a table "
                             "and column name")
            provider = self.resolve_table([str(args[0])])
            col = str(args[1])
            from .search.index import find_index
            idx = find_index(provider, col)
            if idx is None:
                raise errors.SqlError(
                    errors.UNDEFINED_OBJECT,
                    f'no inverted index on "{args[0]}"."{col}"')
            # find_index read-repaired above, so segments carry no
            # deleted docs (mutations rebuild; appends add segments)
            terms: dict[str, int] = {}
            for seg, _base in idx.searchers[col].segments:
                fi = seg.index
                for t, df in zip(fi.terms_str.tolist(),
                                 fi.doc_freq.tolist()):
                    terms[t] = terms.get(t, 0) + int(df)
            items = sorted(terms.items())
            return MemTable("sdb_terms", Batch.from_pydict({
                "term": Column.from_pylist([t for t, _ in items],
                                           dt.VARCHAR),
                "doc_freq": Column.from_pylist([d for _, d in items],
                                               dt.BIGINT),
            }))
        if name == "sdb_log":
            from .pgcatalog import log_table
            return log_table()
        if name == "sdb_metrics":
            from .pgcatalog import metrics_table
            return metrics_table()
        if name == "sdb_stat_statements":
            from .pgcatalog import stat_statements_table
            return stat_statements_table()
        if name == "sdb_cache":
            from .pgcatalog import cache_table
            return cache_table()
        if name == "sdb_trace":
            from .pgcatalog import trace_table
            return trace_table(args)
        if name == "sdb_query_progress":
            from .pgcatalog import query_progress_table
            return query_progress_table()
        if name == "sdb_admission":
            from .pgcatalog import admission_table
            return admission_table()
        if name == "sdb_connections":
            from .pgcatalog import connections_table
            return connections_table()
        if name == "sdb_device":
            from .pgcatalog import device_table
            return device_table()
        if name == "sdb_programs":
            from .pgcatalog import programs_table
            return programs_table()
        if name == "sdb_device_cache":
            from .pgcatalog import device_cache_table
            return device_cache_table()
        if name == "sdb_posting_pool":
            from .pgcatalog import posting_pool_table
            return posting_pool_table()
        raise errors.SqlError(errors.UNDEFINED_FUNCTION,
                              f"table function {name} does not exist")

    # -- DDL ---------------------------------------------------------------

    def create_schema(self, name: str, if_not_exists: bool):
        with self.lock:
            if name in self.schemas:
                if if_not_exists:
                    return
                raise errors.SqlError(errors.DUPLICATE_OBJECT,
                                      f'schema "{name}" already exists')
            self.schemas[name] = SchemaObj(name)

    def create_table(self, schema: str, name: str, provider: TableProvider,
                     if_not_exists: bool):
        with self.lock:
            s = self._schema(schema)
            key = name.lower()
            if key in s.tables or key in s.views:
                if if_not_exists:
                    return False
                raise errors.SqlError(errors.DUPLICATE_TABLE,
                                      f'relation "{name}" already exists')
            s.tables[key] = provider
            return True

    def create_view(self, schema: str, name: str, view: ViewDef,
                    or_replace: bool):
        with self.lock:
            s = self._schema(schema)
            key = name.lower()
            if key in s.tables:
                raise errors.SqlError(errors.DUPLICATE_TABLE,
                                      f'"{name}" is already a table')
            if key in s.views and not or_replace:
                raise errors.SqlError(errors.DUPLICATE_TABLE,
                                      f'relation "{name}" already exists')
            s.views[key] = view

    def drop(self, kind: str, parts: list[str], if_exists: bool,
             cascade: bool):
        schema, name = self._split(parts)
        with self.lock:
            if kind == "schema":
                target = parts[-1]
                if target not in self.schemas:
                    if if_exists:
                        return
                    raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                          f'schema "{target}" does not exist')
                if target == "main":
                    raise errors.SqlError(errors.FEATURE_NOT_SUPPORTED,
                                          "cannot drop schema main")
                if self.schemas[target].tables and not cascade:
                    raise errors.SqlError("2BP01",
                                          f'schema "{target}" is not empty')
                del self.schemas[target]
                return
            s = self._schema(schema, if_exists)
            if s is None:
                return
            key = name.lower()
            if kind == "index":
                from .search.index import _index_lock
                removed = False
                for t in s.tables.values():
                    idxs = getattr(t, "indexes", {})
                    for iname in list(idxs):
                        if iname.lower() == key:
                            with _index_lock(t):
                                idxs.pop(iname, None)
                            removed = True
                if removed or if_exists:
                    return
                raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                      f'index "{name}" does not exist')
            store = s.views if kind == "view" else s.tables
            if kind in ("table", "view") and key in store:
                deps = self._dependent_views(schema, key,
                                             exclude=(schema, key)
                                             if kind == "view" else None)
                if deps and not cascade:
                    dn = deps[0][1]
                    raise errors.SqlError(
                        "2BP01",
                        f'cannot drop {kind} "{name}" because view '
                        f'"{dn}" depends on it')
                for dschema, dname in deps:     # CASCADE: drop dependents
                    self.schemas[dschema].views.pop(dname, None)
            if key not in store:
                if if_exists:
                    return
                raise errors.SqlError(errors.UNDEFINED_TABLE,
                                      f'{kind} "{name}" does not exist')
            del store[key]

    def _dependent_views(self, schema: str, key: str,
                         exclude=None) -> list[tuple[str, str]]:
        """Transitive closure of views depending on relation (schema,
        key) — view-on-view chains included, so CASCADE never dangles a
        second-level view. Caller holds self.lock."""
        out: list[tuple[str, str]] = []
        frontier = [(schema, key)]
        seen = {(schema.lower(), key)}
        while frontier:
            tschema, tkey = frontier.pop()
            for sname2, s2 in self.schemas.items():
                for vname, vdef in s2.views.items():
                    ident = (sname2.lower(), vname)
                    if ident in seen or ident == exclude:
                        continue
                    if _view_references(vdef.query, tschema, tkey):
                        seen.add(ident)
                        out.append((sname2, vname))
                        frontier.append((sname2, vname))
        return out

    def _schema(self, name: str, if_exists_ok: bool = False):
        s = self.schemas.get(name)
        if s is None and not if_exists_ok:
            raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                  f'schema "{name}" does not exist')
        return s

    def table_list(self) -> list[tuple[str, str, str]]:
        with self.lock:
            out = []
            for sname, s in self.schemas.items():
                for t in s.tables:
                    out.append((sname, t, "table"))
                for v in s.views:
                    out.append((sname, v, "view"))
            return sorted(out)

    def catalog_key_of(self, provider) -> Optional[str]:
        """schema.table key when this provider is a user table currently
        registered in the catalog: StoredTable `key` fast path (verified
        against the live catalog — a dropped/replaced table must not
        resolve), else an identity scan. Shared by the transaction
        machinery (Connection._txn_key_of) and the result cache
        (cache/result.py) so provider identity can never diverge
        between them."""
        key = getattr(provider, "key", None)      # StoredTable fast path
        with self.lock:
            if key is not None and self._table_by_key(key) is provider:
                return key
            for sname, sch in self.schemas.items():
                for tname, t in sch.tables.items():
                    if t is provider:
                        return f"{sname}.{tname}"
        return None

    def oid_of(self, kind: str, schema: str, name: str) -> int:
        """Stable per-process OID for a catalog object (lazily assigned).
        kind ∈ {schema, table, view, index, sequence}."""
        key = (kind, schema, name)
        with self.lock:
            oid = self._oids.get(key)
            if oid is None:
                oid = self._oid_next
                self._oid_next += 1
                self._oids[key] = oid
                self._oid_rev[oid] = key
            return oid

    def oid_lookup(self, oid: int):
        """(kind, schema, name) for an OID assigned by oid_of, else None."""
        with self.lock:
            return self._oid_rev.get(int(oid))

    def resolve_relation_oid(self, text: str) -> int:
        """'schema.table' / 'table' → OID, PG ::regclass semantics."""
        parts = [p.strip().strip('"') for p in text.split(".")]
        with self.lock:
            cands = ([(parts[0], parts[1])] if len(parts) == 2
                     else [(sn, parts[0]) for sn in ("main",
                                                     *sorted(self.schemas))])
            for sn, tn in cands:
                s = self.schemas.get(sn)
                if s is None:
                    continue
                tl = tn.lower()
                if tl in s.tables:
                    return self.oid_of("table", sn, tl)
                if tl in s.views:
                    return self.oid_of("view", sn, tl)
                for t in s.tables.values():
                    if tl in getattr(t, "indexes", {}):
                        return self.oid_of("index", sn, tl)
        raise errors.SqlError(errors.UNDEFINED_TABLE,
                              f'relation "{text}" does not exist')

    def resolve_type_name(self, name: str):
        """(SqlType, enum_labels|None) for a declared column/cast type,
        consulting user-defined types (enums store as validated text,
        domains alias their base)."""
        tdef = self.types.get(name.lower())
        if tdef is None:
            try:
                return dt.type_from_name(name), None
            except ValueError:
                raise errors.SqlError(
                    errors.UNDEFINED_OBJECT,
                    f'type "{name}" does not exist')
        if tdef["kind"] == "enum":
            return dt.VARCHAR, list(tdef["labels"])
        # domains may stack over other user types (incl. enums): recurse
        # so the base's physical type AND its labels carry through
        return self.resolve_type_name(tdef["base"])

    def connect(self) -> "Connection":
        return Connection(self)


class _ViewRef(Exception):
    def __init__(self, view: ViewDef):
        self.view = view


class _UpsertScope(Scope):
    """Scope for DO UPDATE SET: unqualified names resolve to the TARGET
    table only (never ambiguous with excluded.*), qualified names see
    both the target alias and `excluded`."""

    def __init__(self, base_cols, exc_cols):
        super().__init__(base_cols + exc_cols)
        self._base = Scope(base_cols)

    def resolve(self, parts):
        if len(parts) == 1:
            return self._base.resolve(parts)
        return super().resolve(parts)


class _ResolverShim(TableResolver):
    """Expands views inline during planning; inside a transaction, reads
    resolve to the connection's pinned snapshot (snapshot isolation)."""

    def __init__(self, db: Database, planner_params, conn=None):
        self.db = db
        self.params = planner_params
        self.conn = conn

    def resolve_table(self, parts: list[str]) -> TableProvider:
        p = self.db.resolve_table(parts)
        if self.conn is not None and self.conn.in_txn:
            return self.conn._txn_read_provider(p)
        return p

    def resolve_table_function(self, name, args):
        return self.db.resolve_table_function(name, args)


class Connection:
    def __init__(self, db: Database, role: str = None):
        from .auth import SUPERUSER
        self.db = db
        self.settings = SessionSettings()
        self.in_txn = False
        self.txn_failed = False
        # snapshot-isolation state: pinned read snapshots + buffered writes
        # (key → {"real", "work", "version", "ops"}), live only in a txn
        self._txn_pins: dict[str, MemTable] = {}
        self._txn_writes: dict[str, dict] = {}
        self._txn_savepoints: list[tuple] = []   # (name, {key: ops_len}, actions_len)
        from collections import deque
        self._listen_channels: set[str] = set()
        #: bounded: a never-draining idle listener must not grow without
        #: limit (oldest notifications drop past the cap)
        self._notifications = deque(maxlen=8192)
        #: set by the wire session to wake an idle client (thread-safe)
        self.notify_hook = None
        #: mid-query cancel: set from ANY thread (CancelRequest socket),
        #: polled cooperatively at executor batch boundaries
        self._cancel_event = threading.Event()
        #: LISTEN/UNLISTEN/NOTIFY deferred to COMMIT inside a txn (PG
        #: queues them transactionally; ROLLBACK discards)
        self._txn_actions: list[tuple] = []
        #: authenticated identity — SET ROLE can never escalate beyond it
        self.session_role = (role or SUPERUSER).lower()
        self.current_role = self.session_role
        #: set by the result cache when the CURRENT statement was served
        #: without executing (cache/result.py); read by the statement-end
        #: observability hook for sdb_stat_statements cache_hits
        self._cache_hit = False
        #: set by _plan when view inlining ran: view identity is not in
        #: the result-cache key, so such statements never cache
        self._plan_inlined_views = False
        #: last executed plan + its span profile (serene_profile on):
        #: read by the statement-end observability hook for the
        #: slow-query log's annotated tree. Best effort — a suspended
        #: streaming portal interleaved with another statement may
        #: overwrite it; the stats/stat_statements path never depends
        #: on it.
        self._active_profile = None
        self._active_plan = None
        #: the executing statement's timeline trace (serene_trace on);
        #: finalized into the flight recorder at statement end
        self._active_trace = None
        #: the executing statement's memory accountant
        #: (serene_mem_account on; obs/resources.py) — read by the
        #: statement-end observability hook for peak-bytes attribution
        self._active_mem = None
        #: workload governor state (sched/governor.py): admission slots
        #: this connection currently holds (nested statements on a
        #: slot-holding connection bypass admission — a session cannot
        #: deadlock itself), the executing statement's enforced
        #: serene_work_mem ceiling in bytes (0 = unlimited), and its
        #: fair-share scheduling identity (tag, serene_priority weight)
        #: read by the worker pool at task-submit time
        self._admission_held = 0
        self._work_mem_limit = 0
        self._sched = None
        import weakref
        with db.lock:
            db._session_seq += 1
            self._session_id = db._session_seq
            db.sessions[self._session_id] = {
                "pid": self._session_id, "usename": self.session_role,
                "application_name": "", "state": "idle", "query": "",
                "backend_start": time.time(), "query_start": None,
                "wait_event_type": None, "wait_event": None}
        weakref.finalize(self, db.sessions.pop, self._session_id, None)

    # -- public API --------------------------------------------------------

    def execute(self, sql: str, params: Optional[list] = None) -> QueryResult:
        results = self.execute_all(sql, params)
        return results[-1] if results else QueryResult(Batch([], []), "")

    def execute_all(self, sql: str,
                    params: Optional[list] = None) -> list[QueryResult]:
        stmts = parser.parse(sql)  # cached copy-on-read in the parser
        out = []
        for st in stmts:
            out.append(self.execute_statement(st, params or [],
                                              sql_text=sql))
        return out

    def execute_streaming(self, st: ast.Statement, params: Optional[list] = None,
                          sql_text: Optional[str] = None):
        """Streaming SELECT execution: (names, types, batch iterator).

        The iterator yields result batches as the executor produces them,
        so the wire session can encode and flush incrementally — bounding
        session memory and time-to-first-row instead of materializing the
        whole result before the first DataRow (reference: the wire
        collector streams rows to the socket DURING execution,
        server/network/pg/wire_collector.h:20-60).

        Only Select/SetOp are streamable; anything else raises ValueError
        (callers route other statements through execute_statement)."""
        if not isinstance(st, (ast.Select, ast.SetOp)):
            raise ValueError("execute_streaming handles SELECT only")
        if self.txn_failed:
            raise errors.SqlError(
                errors.IN_FAILED_TRANSACTION,
                "current transaction is aborted, commands ignored until "
                "end of transaction block")
        params = params or []
        import time as _time
        self.stmt_now_us = int(_time.time() * 1e6)  # now() stability
        from .cache.result import RESULT_CACHE
        self._cache_hit = False
        probe = RESULT_CACHE.begin(self, st, params, sql_text)
        token = CURRENT_CONNECTION.set(self)
        try:
            hit = probe.fast_lookup() if probe is not None else None
            if hit is None:
                plan = self._plan(st, params)  # binding enforces ACLs here
                if probe is not None:
                    probe.prepare(plan)
                    hit = probe.lookup()
        finally:
            CURRENT_CONNECTION.reset(token)
        if hit is not None:
            def run_hit(b=hit):
                t0 = time.perf_counter_ns()
                with self._session_scope(sql_text if sql_text is not None
                                         else "SELECT"):
                    yield b
                    # re-pin the hit flag at drain time: a statement
                    # interleaved with this suspended portal may have
                    # overwritten the connection-level attribution
                    self._cache_hit = True
                    self._obs_record(sql_text, t0, b.num_rows, None, None)
            return (hit.names, [c.type for c in hit.columns], run_hit())
        # streaming memory accounting: the accountant is created here
        # (so the plan's operator wrappers see it on the context) but —
        # like the trace — its contextvar pins per generator step, and
        # its ACTIVE progress row registers at first resume and retires
        # on every exit path
        from .obs.resources import MemoryAccountant
        acct = MemoryAccountant(sql_text or "SELECT",
                                pid=self._session_id) \
            if self._mem_enabled() else None
        self._active_mem = acct
        ctx = self._exec_ctx(params)
        # a cacheable streaming statement accumulates its batches for a
        # post-drain store — bounded: accumulation stops past the cache
        # byte cap, exactly the point where the store would refuse it
        store_cap = (int(self.settings._registry.get_global(
            "serene_result_cache_mb")) << 20) \
            if probe is not None and probe.cacheable else -1

        def run():
            from .cache.result import _batch_nbytes
            from .obs.resources import ACTIVE, CURRENT_MEM
            from .obs.trace import CURRENT_TRACE, FLIGHT, QueryTrace
            t0 = time.perf_counter_ns()
            nrows = 0
            acc: Optional[list] = [] if store_cap >= 0 else None
            acc_bytes = 0
            # streaming trace: the generator resumes on arbitrary
            # threads, so the trace pins CURRENT_TRACE around every
            # step (same-thread set/reset pairs) instead of holding one
            # token across suspensions
            trace = QueryTrace(sql_text or "SELECT") \
                if self._trace_enabled() else None
            if acct is not None:
                ACTIVE.register(acct)
            with self._session_scope(sql_text if sql_text is not None
                                     else "SELECT"):
                from .sched.governor import GOVERNOR, admission_exempt
                ticket = None
                try:
                    # admission gates the first step, not portal OPEN:
                    # the slot is taken when execution actually starts
                    # and held until the portal drains or drops
                    if GOVERNOR.enabled() and not admission_exempt(st):
                        ticket = GOVERNOR.admit(self, sql_text or "SELECT",
                                                trace)
                    it = plan.batches(ctx)
                    while True:
                        # the caller may resume this generator from any
                        # worker thread: pin the connection contextvar
                        # around every underlying step (scalar functions
                        # read it), and the trace + accountant
                        # contextvars with it
                        tok = CURRENT_CONNECTION.set(self)
                        tok_tr = CURRENT_TRACE.set(trace) \
                            if trace is not None else None
                        tok_mem = CURRENT_MEM.set(acct) \
                            if acct is not None else None
                        try:
                            b = next(it)
                        except StopIteration:
                            if acc is not None:
                                out = concat_batches(acc) if acc else \
                                    Batch(list(plan.names),
                                          [Column.from_pylist([], t)
                                           for t in plan.types])
                                probe.store(out)
                            # this generator IS the miss path — re-pin
                            # the flag in case an interleaved statement
                            # on this connection flipped it while we
                            # were suspended
                            self._cache_hit = False
                            entry = None
                            if trace is not None:
                                entry = trace.finish()
                                if acct is not None:
                                    entry["peak_bytes"] = \
                                        acct.totals()[1]
                                entry = FLIGHT.record(entry)
                            trace = None
                            ACTIVE.retire(acct)
                            self._obs_record(sql_text, t0, nrows,
                                             ctx.profile, plan, entry,
                                             mem=acct)
                            return
                        finally:
                            if tok_mem is not None:
                                CURRENT_MEM.reset(tok_mem)
                            if tok_tr is not None:
                                CURRENT_TRACE.reset(tok_tr)
                            CURRENT_CONNECTION.reset(tok)
                        nrows += b.num_rows
                        if acc is not None:
                            acc_bytes += _batch_nbytes(b)
                            if acc_bytes > store_cap:
                                acc = None
                            else:
                                acc.append(b)
                        yield b
                except BaseException as e:  # noqa: BLE001 — re-raised
                    # error/early-close paths (incl. GeneratorExit from
                    # a dropped portal) still dump the timeline and
                    # retire the progress row
                    ACTIVE.retire(acct)
                    if trace is not None:
                        entry = trace.finish(
                            error=f"{type(e).__name__}: {e}")
                        if acct is not None:
                            entry["peak_bytes"] = acct.totals()[1]
                        FLIGHT.record(entry)
                    raise
                finally:
                    # slot returns on EVERY exit: drained, errored, or
                    # a dropped portal's GeneratorExit
                    GOVERNOR.release(ticket)

        return plan.names, plan.types, run()

    def close(self):
        """Deterministically retire this session from pg_stat_activity
        (the weakref finalizer is only the GC backstop)."""
        self.db.sessions.pop(self._session_id, None)
        with self.db.lock:
            for ch in list(self._listen_channels):
                lst = self.db._listeners.get(ch)
                if lst is not None:
                    lst.discard(self)
                    if not lst:
                        del self.db._listeners[ch]
        self._listen_channels.clear()

    def _apply_listen(self, action: str, channel: str):
        with self.db.lock:
            if action == "listen":
                self._listen_channels.add(channel)
                self.db._listeners.setdefault(channel, set()).add(self)
                return
            chans = [channel] if action == "unlisten" \
                else list(self._listen_channels)
            for ch in chans:
                self._listen_channels.discard(ch)
                lst = self.db._listeners.get(ch)
                if lst is not None:
                    lst.discard(self)
                    if not lst:
                        del self.db._listeners[ch]   # no channel-name leak

    def _apply_notify(self, channel: str, payload: str):
        with self.db.lock:
            targets = list(self.db._listeners.get(channel, ()))
        for conn in targets:
            conn._notifications.append((self._session_id, channel, payload))
            hook = conn.notify_hook
            if hook is not None:
                try:
                    hook()
                except Exception:
                    pass

    def take_notifications(self) -> list[tuple]:
        """Drain pending (sender_pid, channel, payload) notifications."""
        out = []
        while self._notifications:
            out.append(self._notifications.popleft())
        return out

    def execute_statement(self, st: ast.Statement, params: list,
                          sql_text: Optional[str] = None) -> QueryResult:
        if self.txn_failed and not isinstance(st, ast.Transaction):
            raise errors.SqlError(
                errors.IN_FAILED_TRANSACTION,
                "current transaction is aborted, commands ignored until "
                "end of transaction block")
        token = CURRENT_CONNECTION.set(self)
        import time as _time
        # PG: now()/current_timestamp are statement-stable — every call
        # within one statement sees this timestamp
        self.stmt_now_us = int(_time.time() * 1e6)
        try:
            with self._session_scope(sql_text if sql_text is not None
                                     else type(st).__name__):
                self._active_profile = None
                self._active_plan = None
                self._cache_hit = False
                # utility statements (SET/SHOW/txn control/LISTEN/...)
                # are not traced: their zero-span timelines would churn
                # the bounded flight recorder out of exactly the slow
                # statements it exists to preserve — a pgwire client
                # issuing SET per query would halve the ring's reach
                label = sql_text if sql_text is not None \
                    else type(st).__name__
                utility = isinstance(st, _UNTRACED_STATEMENTS)
                trace = None if utility else self._begin_trace(label)
                if trace is None:
                    self._active_trace = None
                # memory accounting + live progress share the trace's
                # utility gate: SET/SHOW bookkeeping materializes
                # nothing worth accounting and would churn the
                # progress registry
                acct = None if utility else self._begin_mem(label)
                if acct is None:
                    self._active_mem = None
                t0 = time.perf_counter_ns()
                ticket = None
                try:
                    # workload governor admission (sched/governor.py):
                    # utility statements and catalog-only introspection
                    # bypass; everything else may queue (state 'queued',
                    # Admission/AdmissionQueue wait event, queue_wait
                    # trace span) or reject with 53300. t0 precedes the
                    # gate so queue time counts in end-to-end latency —
                    # the number the concurrency bench decomposes.
                    if not utility:
                        from .sched.governor import (GOVERNOR,
                                                     admission_exempt)
                        if GOVERNOR.enabled() and not admission_exempt(st):
                            ticket = GOVERNOR.admit(self, label, trace)
                    res = self._dispatch(st, params, sql_text)
                except BaseException as e:  # noqa: BLE001 — re-raised
                    # error paths dump the timeline automatically: the
                    # flight recorder keeps the failed statement's spans
                    # for post-mortem (sdb_trace / GET /trace/<id>)
                    self._finish_trace(trace,
                                       error=f"{type(e).__name__}: {e}")
                    self._finish_mem(acct)
                    raise
                finally:
                    if ticket is not None:
                        from .sched.governor import GOVERNOR
                        GOVERNOR.release(ticket)
                entry = self._finish_trace(trace)
                self._finish_mem(acct)
                self._obs_record(sql_text, t0, _result_rows(res),
                                 self._active_profile, self._active_plan,
                                 entry, utility=utility, mem=acct)
                return res
        finally:
            CURRENT_CONNECTION.reset(token)

    def request_cancel(self):
        """Ask the in-flight statement to stop (PG CancelRequest). Safe
        from any thread; a no-op when the connection is idle — the flag
        clears when the next statement starts."""
        self._cancel_event.set()

    def check_cancel(self):
        """Cooperative cancellation point (reference: the session's
        interrupt check inside DuckDB execution tasks,
        pg_wire_session.h:205-220). Executors call this at batch
        boundaries AND between chunked device dispatches, so cancel and
        statement_timeout fire mid-aggregate within one chunk's
        latency."""
        if self._cancel_event.is_set():
            self._cancel_event.clear()
            raise errors.SqlError(
                errors.QUERY_CANCELED,
                "canceling statement due to user request")
        deadline = getattr(self, "_deadline", None)
        if deadline is not None:
            if time.monotonic() > deadline:
                self._deadline = None
                raise errors.SqlError(
                    errors.QUERY_CANCELED,
                    "canceling statement due to statement timeout")
        # serene_work_mem enforcement (sched/governor.py contract):
        # the budget rides the SAME cooperative drain as cancel and
        # timeout, checked against the accountant's live bytes — free
        # when no ceiling is set (one attribute read), one bucket sum
        # per batch boundary when one is
        limit = self._work_mem_limit
        if limit:
            acct = self._active_mem
            if acct is not None:
                live = acct.totals()[0]
                if live > limit:
                    self._work_mem_limit = 0   # abort once, not per morsel
                    from .obs.resources import fmt_kb
                    raise errors.SqlError(
                        errors.OUT_OF_MEMORY,
                        "out of memory: statement live bytes "
                        f"({fmt_kb(live)}) exceed serene_work_mem "
                        f"({fmt_kb(limit)})",
                        hint="raise serene_work_mem or reduce the "
                             "statement's working set")

    @contextlib.contextmanager
    def _session_scope(self, label: str):
        """pg_stat_activity bookkeeping + active-query metrics + txn-abort
        marking shared by the materializing and streaming paths."""
        self._cancel_event.clear()   # cancel targets the CURRENT statement
        timeout_ms = int(self.settings.get("statement_timeout") or 0)
        # serene_statement_timeout_ms rides the same deadline/drain; the
        # LOWER positive value wins when both are set
        g_ms = int(self.settings.get("serene_statement_timeout_ms") or 0)
        if g_ms > 0 and (timeout_ms <= 0 or g_ms < timeout_ms):
            timeout_ms = g_ms
        # save/restore: a statement interleaved with a SUSPENDED streaming
        # portal (extended protocol) must not clobber the portal's
        # deadline — scopes nest, each restores what it found (same for
        # the work-mem ceiling and the fair-share scheduling identity)
        prev_deadline = getattr(self, "_deadline", None)
        self._deadline = (time.monotonic() + timeout_ms / 1000.0
                          if timeout_ms > 0 else None)
        prev_work_mem = self._work_mem_limit
        self._work_mem_limit = int(self.settings.get("serene_work_mem") or 0)
        prev_sched = self._sched
        from .sched.governor import next_stmt_tag
        self._sched = (next_stmt_tag(),
                       int(self.settings.get("serene_priority") or 100))
        sess = self.db.sessions.get(self._session_id)
        if sess is not None:
            sess["state"] = "active"
            sess["query"] = label
            sess["query_start"] = time.time()
            sess["application_name"] = \
                str(self.settings.get("application_name") or "")
        try:
            with metrics.QUERIES_ACTIVE.scoped():
                yield
        except errors.SqlError:
            if self.in_txn:
                self.txn_failed = True
            raise
        finally:
            self._deadline = prev_deadline
            self._work_mem_limit = prev_work_mem
            self._sched = prev_sched
            if sess is not None:
                sess["state"] = ("idle in transaction"
                                 if self.in_txn else "idle")
                # an abandoned wait (error inside a waiting section)
                # must not linger as this session's live wait event
                sess["wait_event_type"] = None
                sess["wait_event"] = None

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, st: ast.Statement, params: list,
                  sql_text: Optional[str] = None) -> QueryResult:
        if isinstance(st, (ast.Drop, ast.DropRole, ast.AlterTable,
                           ast.CreateRole, ast.AlterRole, ast.GrantRevoke,
                           ast.CreateIndex, ast.VacuumStmt)):
            # destructive/administrative DDL is superuser-only in the
            # ownerless v1 model (PG would check ownership)
            if not self.db.roles.is_superuser(self.current_role):
                raise errors.SqlError(
                    errors.INSUFFICIENT_PRIVILEGE,
                    f"must be superuser to run {type(st).__name__}")
        if isinstance(st, (ast.Select, ast.SetOp)):
            batch = self._run_select(st, params, sql_text=sql_text)
            return QueryResult(batch, f"SELECT {batch.num_rows}")
        if isinstance(st, ast.CreateTable):
            return self._create_table(st, params)
        if isinstance(st, ast.CreateSchema):
            self.db.create_schema(st.name, st.if_not_exists)
            if self.db.store is not None:
                self.db.store.update_meta(
                    lambda m: None if st.name in m["schemas"]
                    else m["schemas"].append(st.name))
            return QueryResult(Batch([], []), "CREATE SCHEMA")
        if isinstance(st, ast.CreateView):
            schema, name = self.db._split(st.name)
            # store the SELECT body: pg_get_viewdef/pg_views.definition
            # return the query, not the CREATE statement (PG semantics —
            # tools wrap it in their own CREATE VIEW). body_sql is sliced
            # from token positions by the parser.
            body = (getattr(st, "body_sql", None) or
                    getattr(st, "source_sql", None) or sql_text or "")
            self.db.create_view(schema, name,
                                ViewDef(name, st.query, body),
                                st.or_replace)
            if self.db.store is not None:
                import base64
                import pickle
                blob = base64.b64encode(pickle.dumps(st.query)).decode()
                self.db.store.update_meta(
                    lambda m: m["views"].__setitem__(
                        f"{schema}.{name.lower()}", {"ast_b64": blob}))
            return QueryResult(Batch([], []), "CREATE VIEW")
        if isinstance(st, ast.CreateIndex):
            return self._create_index(st)
        if isinstance(st, ast.CreateRole):
            self.db.roles.create(st.name, st.password, st.login,
                                 st.superuser, st.if_not_exists)
            self._persist_auth()
            return QueryResult(Batch([], []), "CREATE ROLE")
        if isinstance(st, ast.AlterRole):
            self.db.roles.alter(st.name, st.set_password, st.password,
                                st.login, st.superuser)
            self._persist_auth()
            return QueryResult(Batch([], []), "ALTER ROLE")
        if isinstance(st, ast.DropRole):
            self.db.roles.drop(st.name, st.if_exists)
            self._persist_auth()
            return QueryResult(Batch([], []), "DROP ROLE")
        if isinstance(st, ast.GrantRevoke):
            if st.granted_role is not None:
                self.db.roles.grant_role(st.granted_role, st.role,
                                         revoke=not st.grant)
                self._persist_auth()
                return QueryResult(Batch([], []),
                                   "GRANT ROLE" if st.grant
                                   else "REVOKE ROLE")
            schema, name = self.db._split(st.table)
            try:
                self.db.resolve_table(st.table)  # must exist
            except _ViewRef:
                raise errors.SqlError(
                    "42809", f'"{name}" is not a table')
            self.db.roles.grant(f"{schema}.{name.lower()}", st.role,
                                st.privileges, revoke=not st.grant)
            self._persist_auth()
            return QueryResult(Batch([], []),
                               "GRANT" if st.grant else "REVOKE")
        if isinstance(st, ast.SetRole):
            if st.name is None:
                self.current_role = self.session_role  # RESET → auth role
            else:
                if not self.db.roles.exists(st.name):
                    raise errors.SqlError(errors.UNDEFINED_OBJECT,
                                          f'role "{st.name}" does not exist')
                target = st.name.lower()
                # PG: a session may SET ROLE to itself, to any role it is
                # a member of (transitive), or anything if superuser —
                # never an escalation beyond the membership closure
                if target != self.session_role and \
                        not self.db.roles.is_superuser(self.session_role):
                    with self.db.roles._lock:
                        member_of = self.db.roles._closure(
                            self.session_role)
                    if target not in member_of:
                        raise errors.SqlError(
                            errors.INSUFFICIENT_PRIVILEGE,
                            f'permission denied to set role "{st.name}"')
                self.current_role = target
            return QueryResult(Batch([], []), "SET")
        if isinstance(st, ast.AlterTable):
            return self._alter_table(st)
        if isinstance(st, ast.CreateTsDictionary):
            if not self.db.roles.is_superuser(self.current_role):
                raise errors.SqlError(errors.INSUFFICIENT_PRIVILEGE,
                                      "must be superuser to create "
                                      "dictionaries")
            from .search.analysis import (dictionary_exists,
                                          register_dictionary)
            existed = dictionary_exists(st.name)
            register_dictionary(st.name, st.options,
                                if_not_exists=st.if_not_exists)
            if not existed:
                self.db._tsdict_names.add(st.name.lower())
                if self.db.store is not None:
                    opts = dict(st.options)
                    self.db.store.update_meta(
                        lambda m: m.setdefault("tsdicts", {}).__setitem__(
                            st.name.lower(), opts))
            return QueryResult(Batch([], []), "CREATE TEXT SEARCH DICTIONARY")
        if isinstance(st, ast.CreateType):
            key = st.name.lower()
            builtin = True
            try:
                dt.type_from_name(st.name)
            except Exception:
                builtin = False
            if key in self.db.types or builtin:
                if st.if_not_exists:
                    return QueryResult(Batch([], []), "CREATE TYPE")
                raise errors.SqlError(errors.DUPLICATE_OBJECT,
                                      f'type "{st.name}" already exists')
            if st.kind == "domain":
                self.db.resolve_type_name(st.base)   # base must exist
            tdef = {"kind": st.kind, "labels": list(st.labels),
                    "base": st.base}
            self.db.types[key] = tdef
            if self.db.store is not None:
                self.db.store.update_meta(
                    lambda m: m.setdefault("types", {}).__setitem__(
                        key, tdef))
            return QueryResult(Batch([], []),
                               "CREATE TYPE" if st.kind == "enum"
                               else "CREATE DOMAIN")
        if isinstance(st, ast.CreateSequence):
            self.db.create_sequence(".".join(st.name), st.start,
                                    st.increment, st.if_not_exists)
            return QueryResult(Batch([], []), "CREATE SEQUENCE")
        if isinstance(st, ast.Drop):
            if st.kind == "tsdictionary":
                from .search.analysis import drop_dictionary
                target = st.name[-1].lower()
                with self.db.lock:
                    for s in self.db.schemas.values():
                        for t in s.tables.values():
                            for iname, idx in getattr(t, "indexes",
                                                      {}).items():
                                names = {getattr(idx, "analyzer_name",
                                                 "")} | set(
                                    (getattr(idx, "options", {}) or {})
                                    .get("column_tokenizers", {}).values())
                                if target in {n.lower() for n in names}:
                                    raise errors.SqlError(
                                        "2BP01",
                                        f'cannot drop text search '
                                        f'dictionary "{st.name[-1]}" '
                                        f'because index "{iname}" depends '
                                        "on it")
                if not drop_dictionary(st.name[-1]) and not st.if_exists:
                    raise errors.SqlError(
                        errors.UNDEFINED_OBJECT,
                        f'text search dictionary "{st.name[-1]}" does '
                        "not exist")
                self.db._tsdict_names.discard(target)
                if self.db.store is not None:
                    self.db.store.update_meta(
                        lambda m: m.setdefault("tsdicts", {}).pop(
                            target, None))
                return QueryResult(Batch([], []),
                                   "DROP TEXT SEARCH DICTIONARY")
            if st.kind == "sequence":
                self.db.drop_sequence(".".join(st.name), st.if_exists)
                return QueryResult(Batch([], []), "DROP SEQUENCE")
            if st.kind == "type":
                key = st.name[-1].lower()
                if key not in self.db.types:
                    if st.if_exists:
                        return QueryResult(Batch([], []), "DROP TYPE")
                    raise errors.SqlError(
                        errors.UNDEFINED_OBJECT,
                        f'type "{st.name[-1]}" does not exist')
                def _chain(name):
                    # the type name plus every domain base it resolves
                    # through — dropping ANY link breaks the column
                    out = []
                    seen = set()
                    cur = name
                    while cur and cur not in seen:
                        seen.add(cur)
                        out.append(cur)
                        td = self.db.types.get(cur)
                        cur = (td.get("base") or "").lower() \
                            if td and td["kind"] == "domain" else None
                    return out
                for dname, td in self.db.types.items():
                    if td["kind"] == "domain" and \
                            (td.get("base") or "").lower() == key:
                        raise errors.SqlError(
                            "2BP01",
                            f'cannot drop type "{st.name[-1]}" because '
                            f'type "{dname}" depends on it')
                with self.db.lock:
                    for s_ in self.db.schemas.values():
                        for t in s_.tables.values():
                            used = (getattr(t, "table_meta", None)
                                    or {}).get("enums", {})
                            for uname in used.values():
                                if key in _chain(uname):
                                    raise errors.SqlError(
                                        "2BP01",
                                        f'cannot drop type '
                                        f'"{st.name[-1]}" because column '
                                        f'of table "{t.name}" depends '
                                        "on it")
                del self.db.types[key]
                if self.db.store is not None:
                    self.db.store.update_meta(
                        lambda m: m.setdefault("types", {}).pop(key, None))
                return QueryResult(Batch([], []), "DROP TYPE")
            self.db.drop(st.kind, st.name, st.if_exists, st.cascade)
            if self.db.store is not None:
                schema, name = self.db._split(st.name)
                key = f"{schema}.{name.lower()}"
                store = self.db.store

                def mutate(meta):
                    if st.kind == "table" and key in meta["tables"]:
                        dropped_ids.append(meta["tables"][key]["id"])
                        del meta["tables"][key]
                        meta["indexes"] = {k: v for k, v in
                                           meta["indexes"].items()
                                           if v["table"] != key}
                    elif st.kind == "view":
                        meta["views"].pop(key, None)
                    elif st.kind == "schema":
                        target = st.name[-1]
                        if target in meta["schemas"]:
                            meta["schemas"].remove(target)
                        # cascade: purge the schema's persisted objects too,
                        # or the datadir becomes unopenable on restart
                        prefix = f"{target}."
                        for k in [k for k in meta["tables"]
                                  if k.startswith(prefix)]:
                            dropped_ids.append(meta["tables"][k]["id"])
                            del meta["tables"][k]
                        for k in [k for k in meta["views"]
                                  if k.startswith(prefix)]:
                            del meta["views"][k]
                        meta["indexes"] = {
                            k: v for k, v in meta["indexes"].items()
                            if not v["table"].startswith(prefix)}
                    elif st.kind == "index":
                        target = st.name[-1].lower()
                        for k in [k for k in meta["indexes"]
                                  if k.lower() == target]:
                            del meta["indexes"][k]

                dropped_ids: list[int] = []
                store.update_meta(mutate)
                for tid in dropped_ids:
                    # async drop: tombstone now (O(1) rename), reclaim in
                    # the maintenance loop (reference: drop_task.cpp)
                    store.tombstone_snapshot(tid)
            return QueryResult(Batch([], []), f"DROP {st.kind.upper()}")
        if isinstance(st, ast.Insert):
            return self._insert(st, params)
        if isinstance(st, ast.Delete):
            return self._delete(st, params)
        if isinstance(st, ast.Update):
            return self._update(st, params)
        if isinstance(st, ast.Truncate):
            return self._truncate(st)
        if isinstance(st, ast.SetStmt):
            return self._set(st)
        if isinstance(st, ast.ShowStmt):
            return self._show(st)
        if isinstance(st, ast.ListenStmt):
            if self.in_txn:
                # PG defers LISTEN/UNLISTEN effects to COMMIT
                self._txn_actions.append((st.action, st.channel, None))
            else:
                self._apply_listen(st.action, st.channel)
            return QueryResult(Batch([], []),
                               "LISTEN" if st.action == "listen"
                               else "UNLISTEN")
        if isinstance(st, ast.NotifyStmt):
            if self.in_txn:
                # PG queues NOTIFY until COMMIT; ROLLBACK discards it
                self._txn_actions.append(("notify", st.channel, st.payload))
            else:
                self._apply_notify(st.channel, st.payload)
            return QueryResult(Batch([], []), "NOTIFY")
        if isinstance(st, ast.Transaction):
            return self._txn(st)
        if isinstance(st, ast.Explain):
            return self._explain(st, params, sql_text)
        if isinstance(st, ast.VacuumStmt):
            return self._vacuum(st)
        if isinstance(st, ast.CopyStmt):
            return self._copy(st, params)
        raise errors.unsupported(f"statement {type(st).__name__}")

    # -- SELECT ------------------------------------------------------------

    def _plan(self, sel: ast.Select, params: list) -> PlanNode:
        from .sql.search_rewrite import rewrite_search
        planner = Planner(_ResolverShim(self.db, params, self), params)
        self._plan_inlined_views = False
        while True:
            try:
                return rewrite_search(planner.plan_select(sel))
            except _ViewRef as vr:
                self._plan_inlined_views = True
                sel = _inline_view(sel, vr.view)

    def _profile_enabled(self) -> bool:
        try:
            return bool(self.settings.get("serene_profile"))
        except KeyError:  # pragma: no cover — registry always declares it
            return False

    def _trace_enabled(self) -> bool:
        try:
            return bool(self.settings.get("serene_trace"))
        except KeyError:  # pragma: no cover — registry always declares it
            return False

    def _mem_enabled(self) -> bool:
        try:
            return bool(self.settings.get("serene_mem_account"))
        except KeyError:  # pragma: no cover — registry always declares it
            return False

    def _begin_mem(self, label: str):
        """Start the statement's memory accounting + live progress row
        (serene_mem_account on): allocates the accountant, registers it
        in the ACTIVE query registry (sdb_query_progress / GET
        /progress) and publishes it through CURRENT_MEM so pool tasks,
        device uploads and cache stores charge this query's account.
        Observation only — executors never read the accountant back."""
        if not self._mem_enabled():
            self._active_mem = None
            return None
        from .obs.resources import ACTIVE, CURRENT_MEM, MemoryAccountant
        acct = MemoryAccountant(label, pid=self._session_id)
        acct._cv_token = CURRENT_MEM.set(acct)
        ACTIVE.register(acct)
        self._active_mem = acct
        return acct

    def _finish_mem(self, acct) -> None:
        """Retire the statement's accounting: progress row leaves the
        ACTIVE registry (success AND error paths — a failed statement
        must not linger as a phantom running query) and the contextvar
        resets. The accountant object stays readable for statement-end
        attribution (_obs_record, flight-recorder peak stamp)."""
        if acct is None:
            return
        from .obs.resources import ACTIVE, CURRENT_MEM
        if acct._cv_token is not None:
            CURRENT_MEM.reset(acct._cv_token)
            acct._cv_token = None
        ACTIVE.retire(acct)

    def _begin_trace(self, label: str):
        """Start the statement's timeline trace (serene_trace on):
        allocates the trace id and publishes it through CURRENT_TRACE so
        pool tasks / batcher members / device dispatches stamp spans
        into this query's timeline. Observation only — executors never
        read the trace back."""
        if not self._trace_enabled():
            self._active_trace = None
            return None
        from .obs.trace import CURRENT_TRACE, QueryTrace
        tr = QueryTrace(label)
        tr._cv_token = CURRENT_TRACE.set(tr)
        self._active_trace = tr
        return tr

    def _finish_trace(self, tr, error: Optional[str] = None):
        """Finalize a trace into the flight recorder (success AND error
        paths — a failed statement's timeline is exactly the one worth
        keeping). Returns the recorded entry, or None."""
        if tr is None:
            return None
        from .obs.trace import CURRENT_TRACE, FLIGHT
        if tr._cv_token is not None:
            CURRENT_TRACE.reset(tr._cv_token)
            tr._cv_token = None
        entry = tr.finish(error)
        # accounted peak rides the flight-recorder entry so a
        # memory-heavy query is findable after the fact (sdb_trace
        # listing, GET /trace, /_stats.traces)
        acct = self._active_mem
        if acct is not None:
            entry["peak_bytes"] = acct.totals()[1]
        return FLIGHT.record(entry)

    def _exec_ctx(self, params: list) -> ExecContext:
        """Execution context with a span collector attached when
        `serene_profile` is on (obs/trace.py); the collector observes
        only, so results are identical either way."""
        ctx = ExecContext(self.settings, params)
        if self._profile_enabled():
            from .obs.trace import QueryProfile
            ctx.profile = QueryProfile()
            self._active_profile = ctx.profile
        # the statement-level accountant (begun next to the trace)
        # rides the context so operator wrappers charge without a
        # contextvar read per batch
        ctx.mem = self._active_mem
        return ctx

    def _run_select(self, sel: ast.Select, params: list,
                    sql_text: Optional[str] = None) -> Batch:
        from .cache.result import RESULT_CACHE
        from .obs.trace import current_trace
        tr = current_trace()
        t_probe = time.perf_counter_ns() if tr is not None else 0
        probe = RESULT_CACHE.begin(self, sel, params, sql_text)
        if probe is not None:
            # plan-skipping fast path: the statement's table set was
            # learned at an earlier store — resolve, re-check ACLs,
            # observe publications, serve
            hit = probe.fast_lookup()
            if hit is not None:
                if tr is not None:
                    tr.add("cache_probe", "cache", t_probe,
                           time.perf_counter_ns(), hit=True)
                return hit
        t_plan = time.perf_counter_ns() if tr is not None else 0
        if tr is not None and t_plan - t_probe > 1000:
            # cache digest + publication observation time: part of the
            # statement's wall clock, attributed so plan+execute+probe
            # spans jointly cover the timeline instead of leaving a gap
            tr.add("cache_probe", "cache", t_probe, t_plan)
        plan = self._plan(sel, params)
        t_exec = time.perf_counter_ns() if tr is not None else 0
        if tr is not None:
            tr.add("plan", "plan", t_plan, t_exec)
        ctx = self._exec_ctx(params)
        if ctx.profile is not None:
            self._active_plan = plan
        if probe is not None:
            probe.prepare(plan)
            hit = probe.lookup()
            if hit is not None:
                return hit
        batch = plan.execute(ctx)
        if tr is not None:
            # the timeline's execution envelope: plan-digest probe,
            # execution and result hand-off — so cache_probe + plan +
            # execute jointly account for the statement's wall time
            # even when no finer-grained span fired (tiny serial
            # queries)
            tr.add("execute", "exec", t_exec, time.perf_counter_ns())
        if probe is not None:
            probe.store(batch)
        return batch

    def _obs_record(self, sql_text: Optional[str], t0_ns: int, rows: int,
                    profile, plan, trace_entry=None,
                    utility: bool = False, mem=None) -> None:
        """Statement-end observability hook (begin is _session_scope):
        query gauges + latency histogram, sdb_stat_statements, the
        slow-query log and the session's pg_stat_activity query id.
        Everything is behind `serene_profile`; failures here must never
        fail the statement's own result path, so this is called only
        after success. `trace_entry` is the statement's flight-recorder
        timeline (serene_trace on) — the slow-query log attaches its
        top-5 widest spans next to the annotated plan tree.

        The latency histogram records BEFORE the serene_profile gate:
        the pool/batch/device histograms fill regardless of that
        setting, and query p50/p99 is half of the admission-control
        signal pair — it must not vanish because profiling was turned
        off. `utility` statements (SET/SHOW/txn bookkeeping) stay OUT
        of it: a client issuing SET per query would otherwise drown the
        percentiles in microsecond observations."""
        elapsed_ns = time.perf_counter_ns() - t0_ns
        if not utility:
            metrics.QUERY_LATENCY_HIST.observe_ns(elapsed_ns)
        mem_peak = mem_live = 0
        if mem is not None:
            # the peak histogram records BEFORE the serene_profile gate
            # for the same reason the latency histogram does: the
            # memory axis is its own setting and half of the
            # admission-control signal pair
            mem_live, mem_peak = mem.totals()
            metrics.QUERY_PEAK_BYTES_HIST.observe_ns(mem_peak)
            metrics.MEM_ACCOUNT_EVENTS.add(mem.event_count())
        if not self._profile_enabled():
            return
        metrics.QUERY_TIME_NS.add(elapsed_ns)
        metrics.QUERIES_EXECUTED.add()
        pruned = 0
        if profile is not None:
            t = profile.totals()
            pruned = t.morsels_pruned + t.morsels_jf_pruned
        if sql_text:
            from .obs.statements import STATEMENTS
            cap = int(self.settings.get("serene_stat_statements_max"))
            qid = STATEMENTS.record(sql_text, elapsed_ns, rows, pruned,
                                    cap,
                                    cache_hit=getattr(self, "_cache_hit",
                                                      False),
                                    peak_bytes=mem_peak)
            sess = self.db.sessions.get(self._session_id)
            if sess is not None:
                sess["query_id"] = qid
        thresh = int(self.settings.get("serene_log_min_duration_ms"))
        if thresh >= 0 and elapsed_ns >= thresh * 1_000_000:
            metrics.SLOW_QUERIES.add()
            msg = (f"duration: {elapsed_ns / 1e6:.3f} ms  "
                   f"statement: {sql_text or '<internal>'}")
            if mem is not None:
                from .obs.resources import fmt_kb
                msg += (f"\nmemory: peak={fmt_kb(mem_peak)} "
                        f"live={fmt_kb(max(mem_live, 0))}")
            if profile is not None and plan is not None:
                from .obs.trace import annotate_plan
                msg += "\n" + "\n".join(annotate_plan(plan, profile,
                                                      mem))
            if trace_entry is not None:
                from .obs.trace import format_top_spans
                msg += "\n" + "\n".join(format_top_spans(trace_entry))
            log.info("slow_query", msg)

    # -- DDL/DML -----------------------------------------------------------

    def _create_table(self, st: ast.CreateTable, params: list) -> QueryResult:
        schema, name = self.db._split(st.name)
        enums: dict = {}
        if st.as_query is None:
            cols = []
            names = []
            for cd in st.columns:
                t, labels = self.db.resolve_type_name(cd.type_name)
                if labels is not None:
                    enums[cd.name] = cd.type_name.lower()
                names.append(cd.name)
                cols.append(Column(t, np.empty(0, dtype=t.np_dtype), None,
                                   np.empty(0, dtype=object)
                                   if t.is_string else None))
            batch = Batch(names, cols)
        else:
            batch = self._run_select(st.as_query, params)
        key = f"{schema}.{name.lower()}"
        if self.db.store is not None:
            table_id = self.db.store.new_table_id()
            provider: MemTable = StoredTable(name, batch, key, table_id)
        else:
            provider = MemTable(name, batch)
        provider.table_meta = {
            "engine": st.engine,
            "primary_key": st.primary_key,
            "not_null": [c.name for c in st.columns if c.not_null],
            "defaults": {c.name: c.default for c in st.columns if c.default},
            "tokenizers": {c.name: c.tokenizer for c in st.columns
                           if c.tokenizer},
            "options": st.options,
            "enums": enums,
        }
        created = self.db.create_table(schema, name, provider,
                                       st.if_not_exists)
        if created and not self.db.roles.is_superuser(self.current_role):
            # creator keeps full use of their own table
            self.db.roles.grant(key, self.current_role, ["all"])
        if created and self.db.store is not None:
            from .storage.store import table_def
            start_tick = self.db.store.ticks.current()
            tdef = table_def(key, provider.table_id, provider.column_names,
                             provider.column_types, provider.table_meta,
                             start_tick)
            if batch.num_rows:
                self.db.store.write_snapshot(provider.table_id, batch)
            self.db.store.update_meta(
                lambda m: m["tables"].__setitem__(key, tdef))
        if st.as_query is not None and created:
            return QueryResult(Batch([], []),
                               f"SELECT {provider.row_count()}")
        return QueryResult(Batch([], []), "CREATE TABLE")

    def _create_index(self, st: ast.CreateIndex) -> QueryResult:
        from .utils.progress import REGISTRY as _progress
        provider = self.db.resolve_table(st.table)
        if not hasattr(provider, "indexes"):
            provider.indexes = {}
        idx_name = st.name or f"{st.table[-1]}_{'_'.join(st.columns)}_idx"
        from .search.index import build_index_for_table
        for c in st.columns:
            if c not in provider.column_names:
                raise errors.SqlError(errors.UNDEFINED_COLUMN,
                                      f'column "{c}" does not exist')
        if st.using is None:
            # no USING clause: text columns get the inverted index (this
            # is a search database), anything else a btree — PG's own
            # default method. Decided from the declared schema type, not a
            # full materialization of the column.
            first_type = provider.column_types[
                provider.column_names.index(st.columns[0])]
            st.using = "inverted" if first_type.is_string else "btree"
        options = dict(st.options)
        if st.column_tokenizers:
            # per-column dictionary names; columns WITHOUT one keep the
            # index default ('text' unless WITH tokenizer=... says else)
            options["column_tokenizers"] = dict(st.column_tokenizers)
        with _progress.track("CREATE INDEX", provider.row_count()):
            built = build_index_for_table(provider, st.columns, st.using,
                                          options)
            from .search.index import _index_lock
            with _index_lock(provider):   # serializes registry mutation
                provider.indexes[idx_name] = built
        if self.db.store is not None and isinstance(provider, StoredTable):
            idef = {"table": provider.key, "columns": list(st.columns),
                    "using": st.using, "options": options}
            self.db.store.update_meta(
                lambda m: m["indexes"].__setitem__(idx_name, idef))
        return QueryResult(Batch([], []), "CREATE INDEX")

    def _alter_table(self, st: ast.AlterTable) -> QueryResult:
        try:
            # DDL is autocommit: ALTER must hit the REAL table, never the
            # txn work copy (COMMIT replays only insert/delete/truncate,
            # and RENAME must not publish uncommitted state)
            table = self._table_for_dml(st.table, txn_route=False)
        except errors.SqlError:
            if st.if_exists:
                return QueryResult(Batch([], []), "ALTER TABLE")
            raise
        # LOCK ORDER: write_lock (via quiesced) first, db.lock inner —
        # the same order DML uses when a WHERE subquery resolves tables
        # under the write_lock (resolve_table takes db.lock). db.lock is
        # only taken around the rename's catalog-dict mutation below.
        with self.db.quiesced([table]):
            full = table.full_batch()
            names = list(full.names)
            if st.action == "add_column":
                if st.column in names:
                    if st.if_not_exists:
                        return QueryResult(Batch([], []), "ALTER TABLE")
                    raise errors.SqlError(
                        "42701", f'column "{st.column}" already exists')
                t, labels = self.db.resolve_type_name(st.type_name)
                if labels is not None:
                    meta_t = getattr(table, "table_meta", None)
                    if meta_t is not None:
                        meta_t.setdefault("enums", {})[st.column] = \
                            st.type_name.lower()
                col = Column.from_pylist([None] * full.num_rows, t)
                table.replace(Batch(names + [st.column],
                                    list(full.columns) + [col]),
                              rows_preserved=True)
            elif st.action == "drop_column":
                if st.column not in names:
                    if st.col_if_exists:
                        return QueryResult(Batch([], []), "ALTER TABLE")
                    raise errors.SqlError(
                        errors.UNDEFINED_COLUMN,
                        f'column "{st.column}" does not exist')
                if len(names) == 1:
                    raise errors.SqlError(
                        "0A000", "cannot drop the only column of a table")
                keep = [i for i, n in enumerate(names) if n != st.column]
                # NOT rows_preserved: dropping a column changes column
                # identity — caches keyed per column name under an
                # unchanged epoch (zone maps) must not survive a later
                # re-add of the same name with different values
                table.replace(Batch([names[i] for i in keep],
                                    [full.columns[i] for i in keep]))
            elif st.action == "rename_column":
                if st.column not in names:
                    raise errors.SqlError(
                        errors.UNDEFINED_COLUMN,
                        f'column "{st.column}" does not exist')
                if st.new_name in names:
                    raise errors.SqlError(
                        "42701", f'column "{st.new_name}" already exists')
                new_names = [st.new_name if n == st.column else n
                             for n in names]
                # NOT rows_preserved: renaming moves values under a new
                # name — per-column-name caches (zone maps) must rebuild
                table.replace(Batch(new_names, list(full.columns)))
            elif st.action == "rename_table":
                schema, name = self.db._split(st.table)
                with self.db.lock:   # catalog-dict mutation
                    s = self.db.schemas[schema]
                    new_key = st.new_name.lower()
                    if new_key in s.tables or new_key in s.views:
                        raise errors.SqlError(
                            errors.DUPLICATE_TABLE,
                            f'relation "{st.new_name}" already exists')
                    del s.tables[name.lower()]
                    table.name = st.new_name
                    s.tables[new_key] = table
                    if isinstance(table, StoredTable):
                        old_skey = table.key
                        table.key = f"{schema}.{new_key}"
            # indexes over altered tables rebuild on next refresh; dropped/
            # renamed columns drop their indexes
            if st.action in ("drop_column", "rename_column"):
                for iname, idx in list(getattr(table, "indexes",
                                               {}).items()):
                    if st.column in idx.columns:
                        del table.indexes[iname]
            # persist new shape
            if self.db.store is not None and isinstance(table, StoredTable):
                from .storage.store import table_def
                tick = self.db.store.ticks.current()
                tdef = table_def(table.key, table.table_id,
                                 table.column_names, table.column_types,
                                 getattr(table, "table_meta", {}), tick)
                self.db.store.write_snapshot(table.table_id,
                                             table.full_batch())
                tdef["checkpoint_tick"] = tick
                key = table.key

                def mutate(m):
                    if st.action == "rename_table":
                        m["tables"].pop(old_skey, None)
                        for idef in m["indexes"].values():
                            if idef["table"] == old_skey:
                                idef["table"] = key
                    m["tables"][key] = tdef
                    if st.action in ("drop_column", "rename_column"):
                        m["indexes"] = {
                            k: v for k, v in m["indexes"].items()
                            if not (v["table"] == key and
                                    st.column in v["columns"])}
                self.db.store.update_meta(mutate)
        return QueryResult(Batch([], []), "ALTER TABLE")

    def _table_for_dml(self, parts: list[str],
                       privilege: str = "insert",
                       txn_route: bool = True) -> MemTable:
        try:
            provider = self.db.resolve_table(parts, privilege)
        except _ViewRef:
            raise errors.SqlError(
                "55000", f'cannot modify view "{parts[-1]}"')
        if not isinstance(provider, MemTable):
            raise errors.SqlError(errors.FEATURE_NOT_SUPPORTED,
                                  "cannot modify this table")
        if self.in_txn and txn_route:
            return self._txn_write_provider(provider)
        return provider

    # -- snapshot-isolation transaction machinery -------------------------
    # Reference analog: the versioned catalog snapshot model (SURVEY.md
    # §3.2 "binding pins a catalog::Snapshot") — a txn reads one immutable
    # snapshot and buffers writes; COMMIT is first-committer-wins.

    def _txn_key_of(self, provider) -> Optional[str]:
        """schema.table key when this provider is a user table (system
        tables and table functions are rebuilt per query — never
        pinned). Delegates to the shared catalog resolution (db.lock is
        an RLock, so callers already holding it nest safely)."""
        return self.db.catalog_key_of(provider)

    @staticmethod
    def _txn_copy(provider, batch, share_indexes: bool = False) -> MemTable:
        copy = MemTable(provider.name, batch)
        meta = getattr(provider, "table_meta", None)
        if meta is not None:
            copy.table_meta = meta
        if share_indexes:
            # segments are immutable: a pin over the CURRENT batch can
            # share the provider's search indexes (in-txn indexed search);
            # batch+version+epoch are ONE atomic observation via pinned()
            # so the freshness checks stay honest without any lock
            cur, ver, epoch = provider.pinned()
            if batch is cur:
                copy.data_version = ver
                copy.mutation_epoch = epoch
                # the per-provider rebuild lock serializes every mutation
                # of the index registry (CREATE INDEX / read-repair), so
                # copying under it is deterministic
                from .search.index import _index_lock
                with _index_lock(provider):
                    copy.indexes = dict(getattr(provider, "indexes",
                                                {}) or {})
        return copy

    def _txn_read_provider(self, provider):
        # _txn_key_of scans the catalog dicts — db.lock guards those; the
        # data pin itself is the provider's atomic publication
        with self.db.lock:
            key = self._txn_key_of(provider)
        if key is None:
            return provider
        w = self._txn_writes.get(key)
        if w is not None:
            return w["work"]          # read-your-writes
        pin = self._txn_pins.get(key)
        if pin is None:
            batch, ver, _ = provider.pinned()
            pin = self._txn_copy(provider, batch, share_indexes=True)
            pin._txn_base_version = ver
            self._txn_pins[key] = pin
        return pin

    def _txn_write_provider(self, provider) -> MemTable:
        with self.db.lock:
            key = self._txn_key_of(provider)
        if key is None:
            raise errors.SqlError(errors.FEATURE_NOT_SUPPORTED,
                                  "cannot modify this table in a "
                                  "transaction")
        w = self._txn_writes.get(key)
        if w is not None:
            return w["work"]
        # seed the working copy from the pinned snapshot (or pin now):
        # the txn keeps seeing its own snapshot + its own writes
        pin = self._txn_read_provider(provider)
        work = self._txn_copy(provider, pin.full_batch())
        work._txn_key = key
        self._txn_writes[key] = {
            "real": provider, "work": work,
            "version": getattr(pin, "_txn_base_version",
                               provider.data_version),
            "ops": []}
        return work

    def _txn_clear(self):
        self._txn_pins = {}
        self._txn_writes = {}
        self._txn_savepoints = []
        self._txn_actions = []

    def _txn_commit_writes(self):
        """First-committer-wins publish: conflict check, one atomic WAL
        commit across all written tables, then in-memory apply."""
        if not self._txn_writes:
            return
        from .storage.wal import WalOp
        # Quiesce committed-but-unpublished fast-path inserts first: such
        # an insert holds an earlier WAL tick but is invisible to the
        # data_version conflict check, and publishing txn ops ahead of it
        # would diverge live row order from replay (tick) order,
        # corrupting positional delete/update records on recovery.
        # quiesced() holds every written table's write_lock, so the
        # conflict check + WAL commit + publish are atomic vs other
        # writers of those tables; writers of OTHER tables proceed.
        with self.db.quiesced(
                [w["real"] for w in self._txn_writes.values()]):
            for key, w in self._txn_writes.items():
                if w["real"].data_version != w["version"] or \
                        self.db._table_by_key(key) is not w["real"]:
                    # concurrent update, or the table was dropped/replaced
                    # under the txn
                    self._txn_clear()
                    raise errors.SqlError(
                        "40001", "could not serialize access due to "
                        "concurrent update")
            if self.db.store is not None:
                wal_ops = [WalOp(w["real"].key, kind, batch, rows)
                           for w in self._txn_writes.values()
                           if isinstance(w["real"], StoredTable)
                           for kind, batch, rows in w["ops"]]
                if wal_ops:
                    self.db.store.commit(wal_ops)
            for w in self._txn_writes.values():
                _apply_ops(w["real"], w["ops"])

    def _insert(self, st: ast.Insert, params: list) -> QueryResult:
        table = self._table_for_dml(st.table)
        if st.returning:
            self.db.resolve_table(st.table, "select")   # PG: RETURNING reads
        target_names = st.columns or table.column_names
        seen_targets = set()
        for c in target_names:
            if c not in table.column_names:
                raise errors.SqlError(errors.UNDEFINED_COLUMN,
                                      f'column "{c}" does not exist')
            if c.lower() in seen_targets:
                raise errors.SqlError(
                    "42701",
                    f'column "{c}" specified more than once')
            seen_targets.add(c.lower())
        if st.query is not None:
            incoming = self._run_select(st.query, params)
            if incoming.num_columns != len(target_names):
                raise errors.SqlError(
                    "42601", "INSERT has more expressions than columns"
                    if incoming.num_columns > len(target_names)
                    else "INSERT has more target columns than expressions")
            # PG maps SELECT output to target columns POSITIONALLY —
            # matching by name would silently insert NULLs
            incoming = Batch(list(target_names), list(incoming.columns))
        else:
            binder = ExprBinder(Scope([]), params)
            one = Batch(["__dummy"], [Column.from_pylist([0])])
            cols_vals: list[list] = [[] for _ in target_names]
            # epoch-int types (INTERVAL/DATE/TIMESTAMP) must keep their
            # bound type: re-inferring from the raw int would type interval
            # micros as BIGINT and then refuse the BIGINT→INTERVAL cast
            cols_types: list = [None] * len(target_names)
            for row in st.values:
                if len(row) != len(target_names):
                    raise errors.SqlError(
                        "42601", "INSERT has more expressions than columns"
                        if len(row) > len(target_names)
                        else "INSERT has more target columns than expressions")
                for k, e in enumerate(row):
                    if isinstance(e, ast.DefaultMarker):
                        dv, dvt = _default_typed(table, target_names[k])
                        cols_vals[k].append(dv)
                        if dvt is not None and dvt.id in (
                                dt.TypeId.INTERVAL, dt.TypeId.DATE,
                                dt.TypeId.TIMESTAMP):
                            cols_types[k] = dvt
                        continue
                    b = binder.bind(e)
                    cols_vals[k].append(b.eval(one).decode(0))
                    if b.type.id in (dt.TypeId.INTERVAL, dt.TypeId.DATE,
                                     dt.TypeId.TIMESTAMP):
                        cols_types[k] = b.type
            incoming = Batch(list(target_names),
                             [Column.from_pylist(v, cols_types[k])
                              for k, v in enumerate(cols_vals)])
        if st.on_conflict is not None:
            pk = _pk_of(table)
            return self._insert_with_pk(st, table, incoming, pk, params)
        aligned = self._insert_batch(table, incoming)
        tag = f"INSERT 0 {incoming.num_rows}"
        if st.returning:
            return QueryResult(self._returning_batch(
                st.returning, table, aligned, params), tag)
        return QueryResult(Batch([], []), tag)

    def _insert_with_pk(self, st, table, incoming: Batch, pk: list,
                        params: list) -> QueryResult:
        """INSERT into a table with a PRIMARY KEY: uniqueness enforcement
        (23505) and ON CONFLICT DO NOTHING / DO UPDATE (reference: PG
        upsert; conflict arbitration is the declared primary key)."""
        action, target, assigns = st.on_conflict
        if action == "update" and not target:
            raise errors.SqlError(
                "42601", "ON CONFLICT DO UPDATE requires a conflict "
                "target")
        if target:
            if not pk or sorted(t.lower() for t in target) != \
                    sorted(c.lower() for c in pk):
                raise errors.SqlError(
                    "42P10", "there is no unique or exclusion constraint "
                    "matching the ON CONFLICT specification")
        if not pk:
            # targetless DO NOTHING on an unconstrained table: nothing can
            # conflict (PG accepts this); plain insert
            aligned = self._insert_batch(table, incoming)
            tag = f"INSERT 0 {aligned.num_rows}"
            if st.returning:
                return QueryResult(self._returning_batch(
                    st.returning, table, aligned, params), tag)
            return QueryResult(Batch([], []), tag)
        with table.write_lock:
            aligned = _align_to_schema(table, incoming)
            _check_not_null(table, aligned)
            _check_enums(self.db, table, aligned)
            key_cols_new = [aligned.column(c).to_pylist() for c in pk]
            _check_pk_not_null(pk, key_cols_new, aligned.num_rows)
            from .columnar import keyenc
            from .search.pkindex import pk_index
            idx = pk_index(table)
            enc_new = keyenc.encode_key_columns(
                [aligned.column(c) for c in pk])
            fresh_rows, conflicts, seen = [], [], set()
            for i in range(aligned.num_rows):
                key = enc_new[i]
                if key in seen:
                    # second hit on the same key within one statement
                    if action == "update":
                        raise errors.SqlError(
                            "21000", "ON CONFLICT DO UPDATE command "
                            "cannot affect row a second time")
                    if action is None:
                        raise errors.SqlError(
                            "23505", "duplicate key value violates "
                            "unique constraint "
                            f"(key columns: {', '.join(pk)})")
                    continue              # DO NOTHING drops the duplicate
                hit = idx.get(key)
                if hit >= 0:
                    if action is None:
                        raise errors.SqlError(
                            "23505", "duplicate key value violates "
                            "unique constraint "
                            f"(key columns: {', '.join(pk)})")
                    conflicts.append((i, hit))
                    seen.add(key)
                    continue              # DO NOTHING also lands here: no-op
                fresh_rows.append(i)
                seen.add(key)
            if action == "nothing":
                conflicts = []
            ops = []
            affected = []
            if conflicts and action == "update":
                full = table.full_batch()
                old_rows = np.asarray([o for _, o in conflicts],
                                      dtype=np.int64)
                exc_rows = [i for i, _ in conflicts]
                updated = self._apply_upsert_assignments(
                    table, full.take(old_rows), aligned.take(
                        np.asarray(exc_rows, dtype=np.int64)),
                    assigns, params)
                # PK-based remove filter (not positional rows): replay
                # after a crash resolves the same keys whatever the
                # physical row order (reference: search_remove_filter)
                ops.append(("delete_pk", None,
                            {"cols": list(pk),
                             "keys": [enc_new[i] for i, _ in conflicts]}))
                ops.append(("insert", updated, None))
                affected.append(updated)
            if fresh_rows:
                fresh = aligned.take(np.asarray(fresh_rows,
                                                dtype=np.int64))
                ops.append(("insert", fresh, None))
                affected.append(fresh)
            n_affected = (len(fresh_rows) +
                          (len(conflicts) if action == "update" else 0))
            if ops:
                self._wal_commit(table, ops)
                _apply_ops(table, ops)
        tag = f"INSERT 0 {n_affected}"
        if st.returning:
            out = concat_batches(affected) if affected else Batch(
                list(table.column_names),
                [Column.from_pylist([], t) for t in table.column_types])
            return QueryResult(self._returning_batch(
                st.returning, table, out, params), tag)
        return QueryResult(Batch([], []), tag)

    def _apply_upsert_assignments(self, table, old: Batch, exc: Batch,
                                  assigns, params: list) -> Batch:
        """DO UPDATE SET evaluation: unqualified columns are the existing
        row, excluded.col is the incoming row (PG semantics)."""
        base_cols = [ScopeColumn(table.name, n, c.type, i)
                     for i, (n, c) in enumerate(zip(old.names,
                                                    old.columns))]
        n_base = len(base_cols)
        exc_cols = [ScopeColumn("excluded", n, c.type, n_base + i)
                    for i, (n, c) in enumerate(zip(exc.names,
                                                   exc.columns))]
        scope = _UpsertScope(base_cols, exc_cols)
        combined = Batch(list(old.names) + [f"__exc_{n}"
                                            for n in exc.names],
                         list(old.columns) + list(exc.columns))
        binder = ExprBinder(scope, params)
        new_cols = {}
        for col_name, e in assigns:
            if col_name not in old:
                raise errors.SqlError(
                    errors.UNDEFINED_COLUMN,
                    f'column "{col_name}" does not exist')
            target_t = old.column(col_name).type
            new_cols[col_name] = _coerce(binder.bind(e).eval(combined),
                                         target_t)
        return Batch(list(old.names),
                     [new_cols.get(n, c)
                      for n, c in zip(old.names, old.columns)])

    def _dml_join(self, table: MemTable, tparts: list[str], extra_ref,
                  where_ast, value_exprs: list, params: list):
        """UPDATE ... FROM / DELETE ... USING core: plan a real join of
        the row-numbered target against the extra FROM tables, evaluate
        the value expressions in the joined scope, and keep the FIRST
        match per target row (PG: which match wins is unspecified).
        Returns (rows int64 sorted-unique, [Column per value expr])."""
        full = table.full_batch()
        rowcol = Column.from_numpy(
            np.arange(full.num_rows, dtype=np.int64))
        ext = Batch(list(full.names) + ["__dml_row"],
                    list(full.columns) + [rowcol])
        target = MemTable(tparts[-1], ext)
        base = _ResolverShim(self.db, params, self)
        db = self.db
        # the interception key is the RESOLVED identity — a same-named
        # table in another schema must hit the real catalog, not the
        # row-numbered target copy
        t_ident = db._split(tparts)
        t_ident = (t_ident[0].lower(), t_ident[1].lower())

        class _TargetShim(TableResolver):
            def resolve_table(self, parts):
                schema2, name2 = db._split(parts)
                if (schema2.lower(), name2.lower()) == t_ident:
                    return target
                return base.resolve_table(parts)

            def resolve_table_function(self, name, args):
                return base.resolve_table_function(name, args)

        # qualified: a self-join alias of the target table would carry
        # its own __dml_row copy and make the bare name ambiguous
        items = [ast.SelectItem(
            ast.ColumnRef([tparts[-1], "__dml_row"]), "__dml_row")]
        for k, e in enumerate(value_exprs):
            items.append(ast.SelectItem(e, f"__v{k}"))
        sel = ast.Select(
            items=items,
            from_=ast.JoinRef("cross", ast.NamedTable(list(tparts)),
                              extra_ref),
            where=where_ast)
        plan = Planner(_TargetShim(), params).plan_select(sel)
        out = plan.execute(ExecContext(self.settings, params))
        arr = out.column("__dml_row").data.astype(np.int64)
        uniq, first = np.unique(arr, return_index=True)
        vals = [out.columns[1 + k].take(first)
                for k in range(len(value_exprs))]
        return uniq, vals

    def _delete(self, st: ast.Delete, params: list) -> QueryResult:
        table = self._table_for_dml(st.table, "delete")
        if st.returning:
            self.db.resolve_table(st.table, "select")
        with self.db.quiesced([table]):
            full = table.full_batch()
            if st.using_ref is not None:
                rows, _ = self._dml_join(table, st.table, st.using_ref,
                                         st.where, [], params)
            elif st.where is None:
                rows = np.arange(full.num_rows, dtype=np.int64)
            else:
                scope = Scope.of(list(full.names),
                                 [c.type for c in full.columns],
                                 st.table[-1])
                planner = Planner(_ResolverShim(self.db, params, self),
                                  params)
                pred = ExprBinder(scope, params,
                                  planner=planner).bind(st.where)
                c = pred.eval(full)
                rows = np.flatnonzero(c.data.astype(bool) & c.valid_mask())
            n = len(rows)
            if st.returning:
                self._validate_returning(st.returning, table, params)
            pk = _pk_of(table)
            if pk:
                from .columnar import keyenc
                # encode ONLY the deleted rows' keys (O(k), not O(N))
                deleted_rows = full.take(rows)
                enc_del = keyenc.encode_key_columns(
                    [deleted_rows.column(c) for c in pk])
                del_op = ("delete_pk", None,
                          {"cols": list(pk), "keys": list(enc_del)})
            else:
                del_op = ("delete", None, rows)
            self._wal_commit(table, [del_op])
            mask = np.ones(full.num_rows, dtype=bool)
            mask[rows] = False
            deleted = full.take(rows) if st.returning else None
            table.replace(full.filter(mask))
        tag = f"DELETE {n}"
        if st.returning:
            return QueryResult(self._returning_batch(
                st.returning, table, deleted, params), tag)
        return QueryResult(Batch([], []), tag)

    def _update(self, st: ast.Update, params: list) -> QueryResult:
        """UPDATE = delete + re-append of the affected rows (matching the
        WAL replay transformation exactly, so recovered row order equals
        live row order — the reference does the same remove+insert in its
        search DML, duckdb_physical_search_update.*)."""
        table = self._table_for_dml(st.table, "update")
        if st.returning:
            self.db.resolve_table(st.table, "select")
        with self.db.quiesced([table]):
            full = table.full_batch()
            scope = Scope.of(list(full.names), [c.type for c in full.columns],
                             st.table[-1])
            planner = Planner(_ResolverShim(self.db, params, self), params)
            binder = ExprBinder(scope, params, planner=planner)
            join_vals = None
            if st.from_ref is not None:
                value_exprs = [e for _cn, e in st.assignments
                               if not isinstance(e, ast.DefaultMarker)]
                rows, jv = self._dml_join(table, st.table, st.from_ref,
                                          st.where, value_exprs, params)
                join_vals = iter(jv)
            elif st.where is not None:
                c = binder.bind(st.where).eval(full)
                mask = c.data.astype(bool) & c.valid_mask()
                rows = np.flatnonzero(mask)
            else:
                rows = np.arange(full.num_rows, dtype=np.int64)
            n = len(rows)
            if n == 0 and not st.returning:
                return QueryResult(Batch([], []), "UPDATE 0")
            updated = full.take(rows)
            new_cols = {}
            for col_name, e in st.assignments:
                if col_name not in full:
                    raise errors.SqlError(errors.UNDEFINED_COLUMN,
                                          f'column "{col_name}" does not exist')
                target_t = full.column(col_name).type
                if isinstance(e, ast.DefaultMarker):
                    dv, dvt = _default_typed(table, col_name)
                    new_cols[col_name] = _coerce(
                        Column.from_pylist([dv] * n, dvt), target_t) \
                        if dv is not None else \
                        Column.from_pylist([None] * n, target_t)
                    continue
                if join_vals is not None:
                    # evaluated in the joined scope, first match per row
                    new_cols[col_name] = _coerce(next(join_vals), target_t)
                    continue
                val = _coerce(binder.bind(e).eval(full), target_t)
                new_cols[col_name] = val.take(rows)
            upd_cols = [new_cols.get(nm, c)
                        for nm, c in zip(updated.names, updated.columns)]
            updated = Batch(list(updated.names), upd_cols)
            if st.returning:
                self._validate_returning(st.returning, table, params)
            _check_not_null(table, updated)
            _check_enums(self.db, table, updated)
            pk = _pk_of(table)
            del_op = ("delete", None, rows)
            if pk:
                from .columnar import keyenc
                from .search.pkindex import pk_index
                key_cols_u = [updated.column(c).to_pylist() for c in pk]
                _check_pk_not_null(pk, key_cols_u, updated.num_rows)
                # encode only the touched rows' keys (O(k)); the cached
                # sorted index answers the uniqueness probes in O(log N)
                old_rows = full.take(rows)
                enc_del = keyenc.encode_key_columns(
                    [old_rows.column(c) for c in pk])
                pk_lower = {c.lower() for c in pk}
                if any(a.lower() in pk_lower for a, _ in st.assignments):
                    # keys may change: new keys must be unique among
                    # themselves AND against the untouched rows
                    enc_upd = keyenc.encode_key_columns(
                        [updated.column(c) for c in pk])
                    idx = pk_index(table)
                    touched = set(int(r) for r in rows)
                    seen = set()
                    for i in range(updated.num_rows):
                        key = enc_upd[i]
                        hit = idx.get(key)
                        if (hit >= 0 and hit not in touched) or key in seen:
                            raise errors.SqlError(
                                "23505", "duplicate key value violates "
                                "unique constraint "
                                f"(key columns: {', '.join(pk)})")
                        seen.add(key)
                # PK remove filter: replay-robust against row order
                del_op = ("delete_pk", None,
                          {"cols": list(pk), "keys": list(enc_del)})
            self._wal_commit(table, [del_op, ("insert", updated, None)])
            # single-publish delete+reinsert: lock-free readers never see
            # the intermediate rows-removed state
            _apply_ops(table, [del_op, ("insert", updated, None)])
        tag = f"UPDATE {n}"
        if st.returning:
            return QueryResult(self._returning_batch(
                st.returning, table, updated, params), tag)
        return QueryResult(Batch([], []), tag)

    def _truncate(self, st: ast.Truncate) -> QueryResult:
        table = self._table_for_dml(st.table, "delete")
        with self.db.quiesced([table]):
            self._wal_commit(table, [("truncate", None, None)])
            table.replace(table.full_batch().slice(0, 0))
        return QueryResult(Batch([], []), "TRUNCATE TABLE")

    # -- session statements ------------------------------------------------

    def _persist_auth(self):
        if self.db.store is not None:
            auth = self.db.roles.to_meta()
            self.db.store.update_meta(
                lambda m: m.__setitem__("auth", auth))

    def _set(self, st: ast.SetStmt) -> QueryResult:
        try:
            if st.value == "DEFAULT":
                self.settings.reset(st.name)
            else:
                self.settings.set(st.name, st.value)
                if st.name == "sdb_faults":
                    faults.arm_from_spec(str(st.value))
        except KeyError as e:
            raise errors.SqlError("42704", str(e).strip("'\""))
        except ValueError as e:
            raise errors.SqlError(
                "22023", f'invalid value for parameter "{st.name}": {e}')
        return QueryResult(Batch([], []), "SET")

    def _show(self, st: ast.ShowStmt) -> QueryResult:
        if st.name == "tables":
            rows = self.db.table_list()
            b = Batch.from_pydict({
                "schema": [r[0] for r in rows],
                "name": [r[1] for r in rows],
                "kind": [r[2] for r in rows]})
            return QueryResult(b, f"SELECT {b.num_rows}")
        if st.name == "all":
            names = self.settings._registry.names()
            b = Batch.from_pydict({
                "name": names,
                "setting": [str(self.settings.get(n)) for n in names]})
            return QueryResult(b, f"SELECT {b.num_rows}")
        try:
            v = self.settings.get(st.name)
        except KeyError as e:
            raise errors.SqlError("42704", str(e).strip("'\""))
        b = Batch.from_pydict({st.name: [_setting_text(v)]})
        return QueryResult(b, "SHOW")

    def _txn(self, st: ast.Transaction) -> QueryResult:
        if st.action in ("savepoint", "release", "rollback_to"):
            return self._txn_savepoint_stmt(st)
        if st.action == "begin":
            if self.in_txn:
                # PG: WARNING, there is already a transaction in progress —
                # the open txn (and its failure state) is preserved
                return QueryResult(Batch([], []), "BEGIN")
            self.in_txn = True
            self.txn_failed = False
            self._txn_clear()
            return QueryResult(Batch([], []), "BEGIN")
        was_failed = self.txn_failed
        self.in_txn = False
        self.txn_failed = False
        if st.action == "commit" and not was_failed:
            try:
                self._txn_commit_writes()
                actions = self._txn_actions
            finally:
                self._txn_clear()
            for action, channel, payload in actions:
                if action == "notify":
                    self._apply_notify(channel, payload)
                else:
                    self._apply_listen(action, channel)
            return QueryResult(Batch([], []), "COMMIT")
        # ROLLBACK, or COMMIT of a failed txn (PG answers ROLLBACK)
        self._txn_clear()
        return QueryResult(Batch([], []), "ROLLBACK")

    def _txn_savepoint_stmt(self, st: ast.Transaction) -> QueryResult:
        """SAVEPOINT / RELEASE / ROLLBACK TO over the txn op buffer: a
        savepoint records each written table's op-count; rolling back
        truncates the op streams and rebuilds the working copies from the
        pins (and, per PG, un-fails an aborted transaction)."""
        name = (st.savepoint or "").lower()
        if not self.in_txn:
            raise errors.SqlError(
                "25P01", f"{st.action.upper().replace('_', ' ')} can only "
                "be used in transaction blocks")
        if st.action == "savepoint":
            if self.txn_failed:
                raise errors.SqlError(
                    errors.IN_FAILED_TRANSACTION,
                    "current transaction is aborted, commands ignored "
                    "until end of transaction block")
            self._txn_savepoints.append(
                (name, {k: len(w["ops"])
                        for k, w in self._txn_writes.items()},
                 len(self._txn_actions)))
            return QueryResult(Batch([], []), "SAVEPOINT")
        idx = next((i for i in range(len(self._txn_savepoints) - 1, -1, -1)
                    if self._txn_savepoints[i][0] == name), None)
        if idx is None:
            raise errors.SqlError(
                "3B001", f'savepoint "{st.savepoint}" does not exist')
        if st.action == "release":
            if self.txn_failed:
                # PG: only ROLLBACK TO may run in an aborted txn —
                # RELEASE would destroy the recovery point
                raise errors.SqlError(
                    errors.IN_FAILED_TRANSACTION,
                    "current transaction is aborted, commands ignored "
                    "until end of transaction block")
            # PG: releasing a savepoint also releases everything above it
            del self._txn_savepoints[idx:]
            return QueryResult(Batch([], []), "RELEASE")
        # rollback_to: truncate ops, rebuild working copies, un-fail
        marks = self._txn_savepoints[idx][1]
        self._txn_actions = \
            self._txn_actions[:self._txn_savepoints[idx][2]]
        del self._txn_savepoints[idx + 1:]
        for key, w in list(self._txn_writes.items()):
            keep = marks.get(key, 0)
            if len(w["ops"]) != keep:
                w["ops"] = w["ops"][:keep]
                pin = self._txn_pins[key]
                w["work"].replace(pin.full_batch())
                _apply_ops(w["work"], w["ops"])
            if not w["ops"]:
                # net-zero writes: drop the entry so COMMIT's conflict
                # check never 40001s on a table this txn no longer touches
                # (the pin stays for snapshot reads)
                del self._txn_writes[key]
        self.txn_failed = False
        return QueryResult(Batch([], []), "ROLLBACK")

    def _explain(self, st: ast.Explain, params: list,
                 sql_text: Optional[str] = None) -> QueryResult:
        fmt = getattr(st, "format", "text")
        if isinstance(st.inner, (ast.Select, ast.SetOp)):
            plan = self._plan(st.inner, params)
            if not st.analyze:
                if fmt == "json":
                    import json as _json

                    from .obs.trace import annotate_plan_json
                    lines = [_json.dumps(
                        [{"Plan": annotate_plan_json(plan, None)}],
                        indent=2)]
                    b = Batch.from_pydict({"QUERY PLAN": lines})
                    return QueryResult(b, f"SELECT {len(lines)}")
                lines = plan.explain()
            else:
                # ANALYZE always instruments (PG semantics), independent
                # of the serene_profile session setting. It also always
                # EXECUTES — the result cache is only consulted for the
                # `Result Cache:` report line (would this statement have
                # been served?) and fed by the instrumented run, so
                # EXPLAIN ANALYZE output is never a stale replay.
                from .cache.result import RESULT_CACHE
                from .obs.trace import QueryProfile, annotate_plan
                probe = RESULT_CACHE.begin(self, st.inner, params,
                                           sql_text)
                cache_line = None
                if probe is not None:
                    probe.prepare(plan)
                    if probe.cacheable:
                        cache_line = ("Result Cache: hit" if probe.peek()
                                      else "Result Cache: miss")
                prof = QueryProfile()
                # ANALYZE always accounts memory too (same PG-style
                # always-instrument rule as the profiler): the inner
                # plan gets its own accountant so the Memory lines key
                # on THIS plan's nodes — the statement-level accountant
                # (begun by execute_statement for the EXPLAIN wrapper)
                # keeps feeding stat_statements/progress
                from .obs.resources import MemoryAccountant
                macct = MemoryAccountant(sql_text or "EXPLAIN",
                                         pid=self._session_id)
                t0 = time.perf_counter()
                result = plan.execute(
                    ExecContext(self.settings, params, profile=prof,
                                mem=macct))
                elapsed = (time.perf_counter() - t0) * 1000
                if cache_line == "Result Cache: miss":
                    probe.store(result)
                if fmt == "json":
                    # machine-readable EXPLAIN ANALYZE: the annotated
                    # tree (rows, timings, prune counters, device/shard
                    # keys) as one JSON document, PG's FORMAT JSON shape
                    import json as _json

                    from .obs.trace import annotate_plan_json
                    doc: dict = {
                        "Plan": annotate_plan_json(plan, prof, macct),
                        "Execution Time": round(elapsed, 3),
                        "Rows Returned": result.num_rows,
                        "Peak Memory Bytes": macct.totals()[1],
                    }
                    if cache_line:
                        doc["Result Cache"] = \
                            cache_line.split(": ", 1)[1]
                    lines = [_json.dumps([doc], indent=2)]
                    b = Batch.from_pydict({"QUERY PLAN": lines})
                    return QueryResult(b, f"SELECT {len(lines)}")
                from .obs.resources import fmt_kb
                lines = annotate_plan(plan, prof, macct) + \
                    ([cache_line] if cache_line else []) + [
                    f"Execution Time: {elapsed:.3f} ms",
                    f"Peak Memory: {fmt_kb(macct.totals()[1])}",
                    f"Rows Returned: {result.num_rows}",
                ]
        elif isinstance(st.inner, (ast.Insert, ast.Update, ast.Delete)):
            if fmt == "json":
                raise errors.unsupported(
                    "EXPLAIN (FORMAT JSON) of DML statements")
            lines = self._explain_dml(st, params)
        else:
            raise errors.unsupported(
                f"EXPLAIN of {type(st.inner).__name__}")
        b = Batch.from_pydict({"QUERY PLAN": lines})
        return QueryResult(b, f"SELECT {len(lines)}")

    def _explain_dml(self, st: ast.Explain, params: list) -> list[str]:
        """EXPLAIN [ANALYZE] of INSERT/UPDATE/DELETE, PG's shape: the
        target operator line (`Insert on t`) with the source subplan
        under it when one exists; ANALYZE really executes the DML (side
        effects included, exactly like PG) and stamps the affected-row
        count and wall time on the target line."""
        inner = st.inner
        verb = type(inner).__name__              # Insert / Update / Delete
        schema, name = self.db._split(inner.table)
        target = name if schema == "main" else f"{schema}.{name}"
        lines = [f"{verb} on {target}"]
        if isinstance(inner, ast.Insert):
            if inner.query is not None:
                sub = self._plan(inner.query, params)
                lines += ["  ->  " + sub.explain()[0]] + \
                         ["  " + ln for ln in sub.explain()[1:]]
            elif inner.values is not None:
                lines.append(f"  ->  Values ({len(inner.values)} rows)")
        else:
            # UPDATE/DELETE source: plan the equivalent row-selection
            # SELECT so the subplan shows the real scan + pushed-down
            # filter (PG's shape); statements the planner can't express
            # this way (USING/FROM joins, etc.) keep the one-line plan
            try:
                src = ast.Select(
                    items=[ast.SelectItem(ast.Star())],
                    from_=ast.NamedTable(list(inner.table)),
                    where=inner.where)
                sub = self._plan(src, params)
                lines += ["  ->  " + sub.explain()[0]] + \
                         ["  " + ln for ln in sub.explain()[1:]]
            except errors.SqlError:
                pass
        if st.analyze:
            t0 = time.perf_counter()
            res = self._dispatch(inner, params)
            elapsed = (time.perf_counter() - t0) * 1000
            affected = _result_rows(res)
            lines[0] += (f" (actual time=0.000..{elapsed:.3f} "
                         f"rows={affected} loops=1)")
            lines.append(f"Execution Time: {elapsed:.3f} ms")
        return lines

    def _vacuum(self, st: ast.VacuumStmt) -> QueryResult:
        """VACUUM verbs (reference: SearchTable VACUUM refresh/compact/
        cleanup ops): checkpoint = snapshot + WAL GC; refresh = rebuild
        stale search indexes now."""
        targets: list[MemTable] = []
        if st.table is not None:
            t = self.db.resolve_table(st.table)
            if isinstance(t, MemTable):
                targets.append(t)
        else:
            with self.db.lock:
                for s in self.db.schemas.values():
                    targets.extend(t for t in s.tables.values()
                                   if isinstance(t, MemTable))
        verbs = set(st.verbs) or {"refresh"}
        for t in targets:
            if isinstance(t, StoredTable) and self.db.store is not None:
                # batch+tick must be captured atomically vs writers
                with self.db.quiesced([t]):
                    batch = t.full_batch()
                    tick = self.db.store.ticks.current()
                self.db.store.checkpoint_table(t.key, t.table_id, batch,
                                               tick)
            if verbs & {"refresh", "full"}:
                _refresh_indexes(self.db, t)
        return QueryResult(Batch([], []), "VACUUM")

    def _copy(self, st: ast.CopyStmt, params: list) -> QueryResult:
        from .utils.progress import REGISTRY as _progress
        if st.target in ("STDIN", "STDOUT"):
            raise errors.unsupported(
                f"COPY {st.target} is only available over the wire protocol")
        fmt = str(st.options.get("format", "csv")).lower()
        if st.direction == "from":
            table = self._table_for_dml(st.table)
            with _progress.track("COPY FROM"):
                return self._copy_from(st, table, fmt)
        # COPY TO
        if st.query is not None:
            full = self._run_select(st.query, [])
        else:
            provider = self.db.resolve_table(st.table)
            if self.in_txn:
                provider = self._txn_read_provider(provider)
            full = provider.full_batch(st.columns)
        with _progress.track("COPY TO", full.num_rows):
            if fmt == "parquet":
                # records export as PG (…) text — the physical JSON is a
                # private encoding and must not leak into interchange files
                _write_parquet(st.target, _records_as_text(full))
            elif fmt == "binary":
                from .columnar import pgcopy
                with open(st.target, "wb") as f:
                    for chunk in pgcopy.encode_full(full):
                        f.write(chunk)
            else:
                _write_csv(st.target, _records_as_text(full), st.options)
        return QueryResult(Batch([], []), f"COPY {full.num_rows}")

    def copy_in_data(self, st: ast.CopyStmt, data: bytes) -> QueryResult:
        """COPY ... FROM STDIN: parse the wire-fed payload (PG text format
        by default: tab-delimited, \\N nulls, backslash escapes; or csv)."""
        table = self._table_for_dml(st.table)
        seen = set()
        for c in st.columns or []:
            if c not in table.column_names:
                raise errors.SqlError(errors.UNDEFINED_COLUMN,
                                      f'column "{c}" does not exist')
            if c in seen:
                raise errors.SqlError(
                    "42701", f'column "{c}" specified more than once')
            seen.add(c)
        fmt = str(st.options.get("format", "text")).lower()
        target_names = st.columns or list(table.column_names)
        types = [table.column_types[table.column_names.index(c)]
                 for c in target_names]
        if fmt == "binary":
            from .columnar import pgcopy
            incoming = pgcopy.decode_to_batch(data, target_names, types)
            self._insert_batch(table, incoming)
            return QueryResult(Batch([], []), f"COPY {incoming.num_rows}")
        delim = str(st.options.get("delimiter",
                                   "," if fmt == "csv" else "\t"))
        null_s = str(st.options.get("null", "" if fmt == "csv" else "\\N"))
        text = data.decode("utf-8")
        rows = []
        is_csv = fmt == "csv"
        if is_csv:
            import csv as _csv
            import io as _io
            header = str(st.options.get("header", "false")).lower() in \
                ("true", "on", "1")
            rdr = _csv.reader(_io.StringIO(text), delimiter=delim)
            rows = [r for r in rdr if r]
            if header and rows:
                rows = rows[1:]
        else:
            lines = text.split("\n")
            if lines and lines[-1] == "":
                lines.pop()          # trailing newline, not a row
            for line in lines:
                if line == "\\.":
                    break            # end-of-data marker terminates input
                # raw split: null markers compare BEFORE unescaping so a
                # literal backslash-N value (escaped as \\N) round-trips
                rows.append(_copy_text_split_raw(line, delim))
        from .sql.binder import _cast_text_to

        def parse_chunk(chunk):
            cols_vals: list[list] = [[] for _ in target_names]
            for r in chunk:
                if len(r) != len(target_names):
                    raise errors.SqlError(
                        "22P04", f"row has {len(r)} columns, expected "
                                 f"{len(target_names)}")
                for k, raw in enumerate(r):
                    if raw == null_s:
                        cols_vals[k].append(None)
                        continue
                    val = raw if is_csv else _copy_text_unescape(raw)
                    if types[k].is_string:
                        cols_vals[k].append(val)
                    else:
                        cols_vals[k].append(_cast_text_to(val, types[k]))
            return Batch(list(target_names),
                         [Column.from_pylist(v, t)
                          for v, t in zip(cols_vals, types)])

        incoming = _parse_chunked(rows, parse_chunk, self.settings)
        self._insert_batch(table, incoming)
        return QueryResult(Batch([], []), f"COPY {incoming.num_rows}")

    def copy_out_data(self, st: ast.CopyStmt,
                      ) -> tuple[list[bytes], int, int]:
        """COPY ... TO STDOUT → (encoded rows, row count, column count):
        PG text format by default, or csv with the same options
        copy_in_data honors."""
        if st.query is not None:
            full = self._run_select(st.query, [])
        else:
            provider = self.db.resolve_table(st.table)
            if self.in_txn:
                provider = self._txn_read_provider(provider)
            full = provider.full_batch(st.columns)
        ncols = len(full.columns)
        fmt = str(st.options.get("format", "text")).lower()
        if fmt == "binary":
            from .columnar import pgcopy
            return pgcopy.encode_full(full), full.num_rows, ncols
        full = _records_as_text(full)
        cols = [c.to_pylist() for c in full.columns]
        if fmt == "csv":
            import csv as _csv
            import io as _io
            delim = str(st.options.get("delimiter", ","))
            null_s = str(st.options.get("null", ""))
            out = []
            for i in range(full.num_rows):
                buf = _io.StringIO()
                w = _csv.writer(buf, delimiter=delim, lineterminator="\n")
                w.writerow([null_s if v is None else v
                            for v in (col[i] for col in cols)])
                out.append(buf.getvalue().encode())
            return out, full.num_rows, ncols
        delim = str(st.options.get("delimiter", "\t"))
        null_s = str(st.options.get("null", "\\N"))
        out = []
        for i in range(full.num_rows):
            parts = []
            for v in (col[i] for col in cols):
                if v is None:
                    parts.append(null_s)
                else:
                    s = str(v)
                    s = s.replace("\\", "\\\\").replace("\t", "\\t") \
                         .replace("\n", "\\n").replace("\r", "\\r")
                    parts.append(s)
            out.append((delim.join(parts) + "\n").encode())
        return out, full.num_rows, ncols

    def _copy_from(self, st: ast.CopyStmt, table: MemTable,
                   fmt: str) -> QueryResult:
        if isinstance(st.target, str) and not st.target.startswith(
                ("http://", "https://", "s3://")) and \
                not os.path.exists(st.target):
            raise errors.SqlError(
                "58P01", f'could not open file "{st.target}" for reading: '
                         "No such file or directory")
        seen = set()
        for c in st.columns or []:
            if c not in table.column_names:
                raise errors.SqlError(errors.UNDEFINED_COLUMN,
                                      f'column "{c}" does not exist')
            if c in seen:
                raise errors.SqlError(
                    "42701", f'column "{c}" specified more than once')
            seen.add(c)
        names = st.columns or list(table.column_names)
        types = [table.column_types[table.column_names.index(c)]
                 for c in names]
        if fmt == "parquet":
            # parquet files carry column names: select by NAME so a
            # column-list subset maps correctly, never positionally
            full = ParquetTable(st.target).full_batch()
            missing = [c for c in names if c not in full]
            if missing:
                raise errors.SqlError(
                    errors.UNDEFINED_COLUMN,
                    f'column "{missing[0]}" not present in {st.target}')
            sub = Batch(names, [full.column(c) for c in names])
        elif fmt == "binary":
            from .columnar import pgcopy
            with open(st.target, "rb") as f:
                sub = pgcopy.decode_to_batch(f.read(), names, types)
        elif fmt in ("csv", "text"):
            # csv/text files are headerless positional data over exactly
            # the listed columns (PG COPY semantics)
            sub = _read_csv(st.target, names, types, st.options,
                            self.settings)
        else:
            raise errors.unsupported(f"COPY format {fmt}")
        self._insert_batch(table, sub)
        return QueryResult(Batch([], []), f"COPY {sub.num_rows}")

    def _describe_returning(self, st, params: list):
        """(names, types) of a DML RETURNING clause without executing —
        bound against the target table's schema (Describe support)."""
        provider = self.db.resolve_table(st.table)
        scope = Scope.of(list(provider.column_names),
                         list(provider.column_types), provider.name)
        binder = ExprBinder(scope, params)
        names, types = [], []
        for it in st.returning:
            if isinstance(it.expr, ast.Star):
                for c in scope.columns:
                    names.append(c.name)
                    types.append(c.type)
                continue
            b = binder.bind(it.expr)
            names.append(it.alias or _default_returning_name(it.expr))
            types.append(b.type)
        return names, types

    def _validate_returning(self, items, table: MemTable, params: list):
        """Bind RETURNING against the target schema BEFORE mutating:
        a bad reference must abort the statement atomically, never after
        the WAL commit. (Join-table columns in RETURNING are not
        supported — they fail here, pre-mutation.)"""
        scope = Scope.of(list(table.column_names),
                         list(table.column_types), table.name)
        binder = ExprBinder(scope, params)
        for it in items:
            if not isinstance(it.expr, ast.Star):
                binder.bind(it.expr)

    def _returning_batch(self, items, table: MemTable, affected: Batch,
                         params: list) -> Batch:
        """RETURNING evaluation over the affected rows (PG: the new row
        state for INSERT/UPDATE, the old row for DELETE)."""
        scope = Scope.of(list(affected.names),
                         [c.type for c in affected.columns], table.name)
        binder = ExprBinder(scope, params)
        names, cols = [], []
        for it in items:
            if isinstance(it.expr, ast.Star):
                for c in scope.columns:
                    names.append(c.name)
                    cols.append(affected.columns[c.index])
                continue
            b = binder.bind(it.expr)
            names.append(it.alias or _default_returning_name(it.expr))
            cols.append(b.eval(affected))
        return Batch(names, cols)

    def _insert_batch(self, table: MemTable, incoming: Batch) -> Batch:
        with table.write_lock:
            aligned = _align_to_schema(table, incoming)
            _check_not_null(table, aligned)
            _check_enums(self.db, table, aligned)
            pk = _pk_of(table)
            if pk:
                from .columnar import keyenc
                from .search.pkindex import pk_extend, pk_index
                key_cols = [aligned.column(c).to_pylist() for c in pk]
                _check_pk_not_null(pk, key_cols, aligned.num_rows)
                idx = pk_index(table)
                enc = keyenc.encode_key_columns(
                    [aligned.column(c) for c in pk])
                if len(enc) and (idx.contains_any(enc).any() or
                                 len(set(enc)) != len(enc)):
                    raise errors.SqlError(
                        "23505", "duplicate key value violates "
                        "unique constraint "
                        f"(key columns: {', '.join(pk)})")
                n_before = table.row_count()
                base_ver = table.data_version
                self._wal_commit(table, [("insert", aligned, None)])
                _append_rows(table, aligned)
                pk_extend(table, enc, n_before, base_ver)
                self._ingest_observe(table, aligned)
                return aligned
            # give way to any mutator waiting to quiesce this table —
            # without this gate a sustained insert stream starves it
            while getattr(table, "_quiesce_waiters", 0):
                table.pub_cond.wait(timeout=5)
            table._inflight = getattr(table, "_inflight", 0) + 1
            entry = {"tick": None, "done": False, "ready": False,
                     "batch": None}
            if not hasattr(table, "_pub_entries"):
                table._pub_entries = []
            table._pub_entries.append(entry)
        # parallel-ingest fast path (no PK to reserve): the WAL encode +
        # group-commit fsync run OUTSIDE the DML lock so concurrent bulk
        # INSERTs overlap their compression and share fsyncs (reference:
        # ParallelSink per-thread ChunkWriters,
        # duckdb_physical_search_insert.cpp:107-369). Publishes are
        # SEQUENCED BY TICK below: DELETE/UPDATE WAL records address rows
        # positionally, so live row order must equal replay (tick) order.
        # on_tick runs inside the WAL queue lock, so once this commit
        # knows its tick every earlier tick is already recorded in
        # _pub_entries; still-unticked entries are guaranteed LATER.
        try:
            self._wal_commit(table, [("insert", aligned, None)],
                             on_tick=lambda t: entry.__setitem__("tick", t))
            with table.write_lock:
                if entry["tick"] is None:
                    # no WAL behind this table (in-memory db, txn working
                    # copy): sequence publishes by arrival under the write
                    # lock instead of by WAL tick. A table never mixes the
                    # two domains — it either always logs or never does.
                    table._pub_seq = getattr(table, "_pub_seq", 0) + 1
                    entry["tick"] = table._pub_seq
                entry["batch"] = aligned
                entry["ready"] = True
                table.pub_cond.notify_all()
                if _group_commit_enabled():
                    # coalesced publication: the lowest-ticked committed
                    # entry publishes EVERY contiguous-by-tick ready entry
                    # in one append (one version bump / cache invalidation
                    # per window); followers wake marked done
                    while not entry["done"]:
                        run = _publish_run(table, entry)
                        if run is None:
                            table.pub_cond.wait(timeout=5)
                            continue
                        table.append_batches([e["batch"] for e in run])
                        for e in run:
                            e["done"] = True
                            e["batch"] = None
                        table.pub_cond.notify_all()
                else:
                    while any(e is not entry and not e["done"]
                              and e["tick"] is not None
                              and entry["tick"] is not None
                              and e["tick"] < entry["tick"]
                              for e in table._pub_entries):
                        table.pub_cond.wait(timeout=5)
                    _append_rows(table, aligned)
                    entry["done"] = True
                    table.pub_cond.notify_all()
        finally:
            with table.write_lock:
                entry["done"] = True
                try:
                    table._pub_entries.remove(entry)
                except ValueError:
                    pass
                table._inflight -= 1
                table.pub_cond.notify_all()
        self._ingest_observe(table, aligned)
        return aligned

    def _ingest_observe(self, table: MemTable, aligned: Batch) -> None:
        """Write-path accounting + background-maintenance wakeup: count
        the appended rows/bytes and, when the table carries indexes, wake
        the maintenance ticker so the delta range becomes a segment off
        the query path (the append 'enqueues' its delta implicitly —
        [indexed_rows, n_rows) of every stale index)."""
        metrics.INGEST_BATCHES.add()
        metrics.INGEST_DOCS.add(aligned.num_rows)
        nbytes = 0
        for col in aligned.columns:
            nbytes += int(col.data.nbytes)
            if col.validity is not None:
                nbytes += int(col.validity.nbytes)
            if col.dictionary is not None:
                nbytes += sum(len(str(s)) for s in col.dictionary)
        metrics.INGEST_BYTES.add(nbytes)
        mm = self.db.maintenance
        if mm is not None and getattr(table, "indexes", None):
            mm.notify_append()

    def _wal_commit(self, table: MemTable, ops: list[tuple], on_tick=None):
        """Durably log (kind, batch, rows) ops for a stored table before the
        in-memory publish (WAL-then-apply, reference §3.4). Inside a txn
        the working copy buffers the ops; COMMIT logs them atomically."""
        key = getattr(table, "_txn_key", None)
        if key is not None:
            self._txn_writes[key]["ops"].extend(ops)
            return
        if self.db.store is None or not isinstance(table, StoredTable):
            return
        from .storage.wal import WalOp
        wal_ops = [WalOp(table.key, kind, batch, rows)
                   for kind, batch, rows in ops]
        self.db.store.commit(wal_ops, on_tick=on_tick)


def _group_commit_enabled() -> bool:
    from .utils.config import REGISTRY
    try:
        return bool(REGISTRY.get_global("serene_group_commit"))
    except KeyError:
        return True


def _publish_run(table: MemTable, entry: dict):
    """The group-commit publication window leader election (called under
    the table's write_lock): returns the tick-ordered run of committed
    entries THIS entry must publish — itself plus every later contiguous
    ready entry — or None when a lower-ticked commit is still pending
    (that commit's thread leads, and may publish this entry too).
    Correctness leans on the WAL queue-lock invariant: tick order ==
    enqueue order, and an entry with tick None will be assigned a LATER
    tick than every entry already ticked, so it can never belong before
    this run."""
    pend = [e for e in table._pub_entries
            if not e["done"] and e["tick"] is not None]
    pend.sort(key=lambda e: e["tick"])
    if not pend or pend[0] is not entry:
        return None
    run = []
    for e in pend:
        if not e["ready"]:
            break
        run.append(e)
    return run


def _apply_ops(table: MemTable, ops: list[tuple]) -> None:
    """THE op-replay transformation, shared by WAL recovery, txn commit
    and UPDATE/upsert so committed state always matches recovered state.
    All ops compose on a scratch copy and land in ONE publish: lock-free
    readers can never observe a delete-without-reinsert intermediate
    state of a multi-op statement."""
    scratch = MemTable(table.name, table.full_batch())
    for kind, batch, rows in ops:
        if kind == "insert":
            scratch.append_batch(batch)
        elif kind == "delete":
            full = scratch.full_batch()
            mask = np.ones(full.num_rows, dtype=bool)
            rows = np.asarray(rows, dtype=np.int64)
            mask[rows[rows < full.num_rows]] = False
            scratch.replace(full.filter(mask))
        elif kind == "delete_pk":
            # PK-based remove filter: resolve key bytes against the
            # CURRENT state — identical live and in replay, whatever the
            # physical row order (reference: search_remove_filter.*)
            full = scratch.full_batch()
            mask = np.ones(full.num_rows, dtype=bool)
            idx = None
            if full is table.full_batch():
                # first op of the statement: the provider's cached sorted
                # index covers exactly this batch — O(k log N) resolution
                from .search.pkindex import pk_index
                try:
                    idx = pk_index(table)
                except Exception:
                    idx = None
                if idx is not None and idx.pk_cols != list(rows["cols"]):
                    idx = None
            if idx is not None:
                mask[idx.lookup_rows(rows["keys"])] = False
            else:
                from .columnar import keyenc
                cur = keyenc.encode_key_columns(
                    [full.column(c) for c in rows["cols"]])
                kset = set(rows["keys"])
                mask = np.asarray([k not in kset for k in cur],
                                  dtype=bool)
            scratch.replace(full.filter(mask))
        elif kind == "truncate":
            scratch.replace(scratch.full_batch().slice(0, 0))
    rows_preserved = all(kind == "insert" for kind, _, _ in ops)
    table.replace(scratch.full_batch(), rows_preserved=rows_preserved)


def _pk_of(table) -> list:
    return (getattr(table, "table_meta", None) or {}).get(
        "primary_key") or []


def _check_pk_not_null(pk: list, key_cols: list, n: int):
    for i in range(n):
        for c, kc in zip(pk, key_cols):
            if kc[i] is None:
                raise errors.SqlError(
                    "23502", f'null value in column "{c}" violates '
                    "not-null constraint")


def _default_returning_name(e: ast.Expr) -> str:
    if isinstance(e, ast.ColumnRef):
        return e.parts[-1]
    if isinstance(e, ast.FuncCall):
        return e.name
    return "?column?"


def _default_typed(table: MemTable, name: str):
    """(value, bound SqlType|None) of a column's DEFAULT — the type matters
    for epoch-int families (DATE/TIMESTAMP/INTERVAL) where the raw int
    would otherwise re-infer as BIGINT and then refuse the cast."""
    d = (getattr(table, "table_meta", None) or {}).get("defaults", {})
    e = d.get(name)
    if e is None:
        return None, None
    from .sql.binder import ExprBinder, Scope
    b = ExprBinder(Scope([]), [])
    one = Batch(["__d"], [Column.from_pylist([0])])
    bound = b.bind(e)
    return bound.eval(one).decode(0), bound.type


def _default_column(table: MemTable, name: str, n: int):
    """Evaluate a volatile DEFAULT once per row: bind ONCE, evaluate over
    an n-row dummy batch (row-vectorized impls like nextval() assign per
    row)."""
    d = (getattr(table, "table_meta", None) or {}).get("defaults", {})
    e = d.get(name)
    from .sql.binder import ExprBinder, Scope
    bound = ExprBinder(Scope([]), []).bind(e)
    rows = Batch(["__d"], [Column.from_pylist([0] * n)])
    return bound.eval(rows), bound.type


def _default_is_volatile(table: MemTable, name: str) -> bool:
    """Defaults like nextval()/random() must evaluate once PER ROW (PG);
    constant defaults evaluate once per statement. now() is deliberately
    absent: PG keeps it statement-stable."""
    d = (getattr(table, "table_meta", None) or {}).get("defaults", {})
    e = d.get(name)
    if e is None:
        return False
    _VOLATILE = {"nextval", "random", "gen_random_uuid",
                 "clock_timestamp", "uuid_generate_v4"}

    def walk(n) -> bool:
        if isinstance(n, ast.FuncCall):
            if n.name.lower() in _VOLATILE:
                return True
            return any(walk(a) for a in n.args)
        for attr in ("operand", "left", "right", "expr"):
            c = getattr(n, attr, None)
            if isinstance(c, ast.Expr) and walk(c):
                return True
        args = getattr(n, "args", None)
        if isinstance(args, list) and any(
                isinstance(a, ast.Expr) and walk(a) for a in args):
            return True
        return False
    return walk(e)


def _check_enums(db: "Database", table: MemTable, aligned: Batch):
    """Enum-typed columns accept only their declared labels (22P02).
    Dictionary-encoded columns validate O(unique labels): only the
    dictionary entries actually referenced by valid rows are checked."""
    enums = (getattr(table, "table_meta", None) or {}).get("enums") or {}
    for cname, tname in enums.items():
        if cname not in aligned:
            continue
        try:
            _, labels_list = db.resolve_type_name(tname)
        except Exception:
            continue
        if labels_list is None:
            continue        # domain over a plain base: nothing to check
        labels = set(labels_list)
        col = aligned.column(cname)
        if col.dictionary is not None:
            codes = col.data
            if col.validity is not None:
                codes = codes[col.valid_mask()]
            for code in np.unique(codes):
                v = col.dictionary[int(code)]
                if v not in labels:
                    raise errors.SqlError(
                        "22P02",
                        f'invalid input value for enum {tname}: "{v}"')
            continue
        for v in col.to_pylist():
            if v is not None and v not in labels:
                raise errors.SqlError(
                    "22P02",
                    f'invalid input value for enum {tname}: "{v}"')


def _check_not_null(table: MemTable, aligned: Batch):
    """Enforce NOT NULL column constraints (PG 23502)."""
    nn = (getattr(table, "table_meta", None) or {}).get("not_null", [])
    for name in nn:
        if name not in aligned.names:
            continue
        col = aligned.column(name)
        if col.validity is not None and not col.valid_mask().all():
            raise errors.SqlError(
                "23502", f'null value in column "{name}" of relation '
                         f'"{table.name}" violates not-null constraint')


def _align_to_schema(table: MemTable, incoming: Batch) -> Batch:
    """Project incoming rows onto the table schema: coerce types, fill
    missing columns with their DEFAULT (NULL when none). The aligned batch
    is what goes to the WAL, so replay needs no re-coercion."""
    cols = []
    for name, t in zip(table.column_names, table.column_types):
        if name in incoming.names:
            cols.append(_coerce(incoming.column(name), t))
        elif _default_is_volatile(table, name):
            # nextval()-style defaults: one evaluation PER ROW (PG),
            # bound once and vectorized over the row count
            col, _dvt = _default_column(table, name, incoming.num_rows)
            cols.append(_coerce(col, t))
        else:
            dv, dvt = _default_typed(table, name)
            cols.append(_coerce(
                Column.from_pylist([dv] * incoming.num_rows, dvt), t)
                if dv is not None else
                Column.from_pylist([None] * incoming.num_rows, t))
    return Batch(list(table.column_names), cols)


def _append_rows(table: MemTable, aligned: Batch) -> None:
    table.append_batch(aligned)


def _refresh_indexes(db: Database, table: MemTable) -> None:
    """Refresh any index whose data_version is stale (the refresh leg of
    the reference's RefreshLoop, task.cpp:237-343): appends publish a new
    segment, mutations trigger the rebuild leg, and segment tiers at the
    cap run the merge ladder — this is the maintenance/VACUUM entry, so
    compaction happens HERE (merge=True), off the query path."""
    from .search.index import _repair, needs_merge, refresh_index
    for name, idx in list(getattr(table, "indexes", {}).items()):
        stale = idx.data_version != table.data_version
        if stale or needs_merge(idx):
            # shares the per-provider rebuild lock + pre-build version stamp
            # with the read-repair path so concurrent repairs can't race
            _repair(table, name, idx,
                    lambda cur: refresh_index(table, cur),
                    force=not stale)


def _coerce(col: Column, target: dt.SqlType) -> Column:
    if col.type == target or col.type.id is dt.TypeId.NULL:
        if col.type.id is dt.TypeId.NULL and target.id is not dt.TypeId.NULL:
            return Column.from_pylist([None] * len(col), target)
        return col
    return cast_column(col, target)


def _copy_text_split_raw(line: str, delim: str) -> list[str]:
    """Split one PG COPY text-format line into RAW (still-escaped) fields:
    escape pairs are kept verbatim so the null-marker comparison happens
    before unescaping (PG semantics — a literal backslash-N survives)."""
    out = []
    cur = []
    i = 0
    while i < len(line):
        c = line[i]
        if c == "\\" and i + 1 < len(line):
            cur.append(line[i:i + 2])
            i += 2
            continue
        if c == delim:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _copy_text_unescape(raw: str) -> str:
    out = []
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == "\\" and i + 1 < len(raw):
            nxt = raw[i + 1]
            out.append({"t": "\t", "n": "\n", "r": "\r",
                        "\\": "\\"}.get(nxt, nxt))
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _setting_text(v) -> str:
    if isinstance(v, bool):
        return "on" if v else "off"
    return str(v)


def _inline_view(sel, view: ViewDef):
    """Replace references to the view with a subquery ref — in every
    Select leaf of the statement (set-op arms included) and in CTE
    bodies, so a view used anywhere in the query resolves instead of
    spinning _plan's inline-retry loop."""
    def rewrite(ref: ast.TableRef) -> ast.TableRef:
        if isinstance(ref, ast.NamedTable) and \
                ref.parts[-1].lower() == view.name.lower():
            return ast.SubqueryRef(view.query, ref.alias or view.name)
        if isinstance(ref, ast.JoinRef):
            ref.left = rewrite(ref.left)
            ref.right = rewrite(ref.right)
        if isinstance(ref, ast.SubqueryRef):
            # view-over-view: an earlier inlining produced this subquery;
            # the reference to replace now lives inside it
            _rewrite_leaves(ref.query)
        return ref

    def _rewrite_leaves(q) -> None:
        """Rewrite from_ of every Select leaf under q (Select|SetOp),
        and recurse into WITH bodies."""
        stack = [q]
        while stack:
            node = stack.pop()
            for body in getattr(node, "ctes", {}).values():
                stack.append(body.query if isinstance(body, ast.CteDef)
                             else body)
            if isinstance(node, ast.SetOp):
                stack.append(node.left)
                stack.append(node.right)
            elif getattr(node, "from_", None) is not None:
                node.from_ = rewrite(node.from_)

    import copy
    sel2 = copy.deepcopy(sel)
    _rewrite_leaves(sel2)
    return sel2


#: rows per COPY/CSV parse chunk — fixed (worker-count independent) so
#: the chunk split, and with it every parse error and dictionary merge,
#: is deterministic
COPY_PARSE_CHUNK_ROWS = 16384


def _parse_chunked(rows: list, parse_chunk, settings) -> Batch:
    """Chunk-parallel ingest parsing (reference ParallelSink analog:
    per-thread sink writers building column fragments, merged in order).
    parse_chunk(list-of-raw-rows) → Batch; chunks concatenate in row
    order so the result is identical to one serial parse. With a worker
    cap of 1 the whole input parses in one pass — per-chunk dictionary
    encodes + a merge would be pure overhead with zero parallelism."""
    from .parallel.pool import parallel_map, session_workers
    if len(rows) <= COPY_PARSE_CHUNK_ROWS or session_workers(settings) <= 1:
        return parse_chunk(rows)
    chunks = [rows[i:i + COPY_PARSE_CHUNK_ROWS]
              for i in range(0, len(rows), COPY_PARSE_CHUNK_ROWS)]
    return concat_batches(parallel_map(settings, parse_chunk, chunks))


def _read_csv(path: str, names: list, types: list, options: dict,
              settings=None) -> Batch:
    import csv as _csv
    delim = str(options.get("delimiter", ","))
    header = str(options.get("header", "false")).lower() in ("true", "on", "1")
    with open(path, newline="") as f:
        rows = list(_csv.reader(f, delimiter=delim))
    if header and rows:
        rows = rows[1:]

    def parse_chunk(chunk):
        from .sql.binder import _cast_text_to
        cols = []
        for k, (nm, t) in enumerate(zip(names, types)):
            vals = []
            for r in chunk:
                raw = r[k] if k < len(r) else ""
                if raw == "" or raw == "\\N":
                    vals.append(None)
                else:
                    vals.append(raw if t.is_string
                                else _cast_text_to(raw, t))
            cols.append(Column.from_pylist(vals, t))
        return Batch(list(names), cols)

    return _parse_chunked(rows, parse_chunk, settings)


def _records_as_text(batch: Batch) -> Batch:
    """Record columns render as PG (…) text for text/csv COPY output
    (binary keeps the record codec; reference: record_out)."""
    from .columnar import dtypes as _dt
    from .columnar.pgcopy import record_text
    from .sql.expr import make_string_column
    if not any(c.type.id is _dt.TypeId.RECORD for c in batch.columns):
        return batch
    cols = []
    for c in batch.columns:
        if c.type.id is _dt.TypeId.RECORD:
            vals = [None if v is None else record_text(str(v))
                    for v in c.to_pylist()]
            import numpy as _np
            validity = _np.asarray([v is not None for v in vals])
            cols.append(make_string_column(
                _np.asarray(["" if v is None else v for v in vals],
                            dtype=object),
                None if validity.all() else validity))
        else:
            cols.append(c)
    return Batch(list(batch.names), cols)


def _write_csv(path: str, batch: Batch, options: dict):
    import csv as _csv
    delim = str(options.get("delimiter", ","))
    header = str(options.get("header", "false")).lower() in ("true", "on", "1")
    with open(path, "w", newline="") as f:
        w = _csv.writer(f, delimiter=delim)
        if header:
            w.writerow(batch.names)
        for row in batch.rows():
            w.writerow(["" if v is None else v for v in row])


def _write_parquet(path: str, batch: Batch):
    import pyarrow as pa
    import pyarrow.parquet as pq
    arrays = []
    for c in batch.columns:
        vals = c.to_pylist()
        arrays.append(pa.array(vals))
    pq.write_table(pa.table(dict(zip(batch.names, arrays))), path)
