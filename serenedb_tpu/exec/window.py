"""Window function execution.

Reference analog: DuckDB's physical window operator (the reference gets
window functions from its engine fork; SURVEY.md §1 L3). Semantics follow
PG: with ORDER BY the default frame is RANGE UNBOUNDED PRECEDING..CURRENT
ROW (running aggregates, ties share peaks), without ORDER BY aggregates
cover the whole partition.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Batch, Column, concat_batches
from ..sql.expr import BoundExpr
from .plan import PlanNode

WINDOW_FUNCS = {"row_number", "rank", "dense_rank", "ntile",
                "lag", "lead", "first_value", "last_value",
                "count", "sum", "min", "max", "avg"}


@dataclass
class WindowSpec:
    func: str
    arg: Optional[BoundExpr]           # None for row_number/rank/...
    extra: Optional[int]               # lag/lead offset, ntile buckets
    partition_by: list[BoundExpr]
    order_by: list[tuple[BoundExpr, bool]]   # (expr, desc)
    type: dt.SqlType
    default: Optional[object] = None   # lag/lead 3rd arg (PG default NULL)
    #: ROWS frame (start_off, end_off), None member = unbounded; None =
    #: default frame (RANGE UNBOUNDED PRECEDING .. CURRENT ROW)
    frame: Optional[tuple] = None


def window_result_type(func: str, arg_type: Optional[dt.SqlType]) -> dt.SqlType:
    if func in ("row_number", "rank", "dense_rank", "ntile", "count"):
        return dt.BIGINT
    if func == "avg":
        return dt.DOUBLE
    if func == "sum":
        if arg_type is not None and arg_type.is_integer:
            return dt.BIGINT
        return dt.DOUBLE
    return arg_type or dt.BIGINT


class WindowNode(PlanNode):
    """Appends one #win{i} column per spec to the child's output."""

    def __init__(self, child: PlanNode, specs: list[WindowSpec]):
        self.child = child
        self.specs = specs
        self.names = list(child.names) + [f"#win{i}"
                                          for i in range(len(specs))]
        self.types = list(child.types) + [s.type for s in specs]

    def children(self):
        return [self.child]

    def label(self):
        return f"Window [{', '.join(s.func for s in self.specs)}]"

    def batches(self, ctx):
        full = concat_batches(list(self.child.batches(ctx)))
        n = full.num_rows
        out_cols = list(full.columns)
        for spec in self.specs:
            out_cols.append(self._compute(spec, full, n))
        yield Batch(list(self.names), out_cols)

    def _compute(self, spec: WindowSpec, full: Batch, n: int) -> Column:
        from ..ops.agg import factorize_keys
        if n == 0:
            return Column.from_pylist([], spec.type)
        if spec.partition_by:
            pcols = [e.eval(full) for e in spec.partition_by]
            codes, _, _ = factorize_keys([c.data for c in pcols],
                                         [c.validity for c in pcols])
        else:
            codes = np.zeros(n, dtype=np.int64)
        # rank each ORDER BY key once; reuse for sort keys AND peer groups
        key_ranks = []     # (ranks int64 with NULL=-1, desc)
        for e, desc in spec.order_by:
            c = e.eval(full)
            _, ranks = np.unique(c.data, return_inverse=True)
            ranks = np.where(c.valid_mask(), ranks.astype(np.int64), -1)
            key_ranks.append((ranks, desc))
        sort_keys = [np.arange(n)]  # final tiebreak: input order
        for ranks, desc in reversed(key_ranks):
            nulls = ranks < 0
            v = -ranks if desc else ranks
            sort_keys.append(np.where(nulls, 0, v))
            sort_keys.append(np.where(nulls, 1, -1) if not desc
                             else np.where(nulls, -1, 1))
        sort_keys.append(codes)
        order = np.lexsort(tuple(sort_keys))
        s_codes = codes[order]
        boundaries = np.concatenate(
            [[True], s_codes[1:] != s_codes[:-1]])
        part_start = np.maximum.accumulate(
            np.where(boundaries, np.arange(n), 0))
        idx_in_part = np.arange(n) - part_start

        # peer groups (ties) for rank/running aggregates with ORDER BY
        if spec.order_by:
            same_peer = np.ones(n, dtype=bool)
            if n:
                same_peer[0] = False
                for ranks, _ in key_ranks:
                    k = ranks[order]
                    same_peer[1:] &= k[1:] == k[:-1]
                same_peer[1:] &= ~boundaries[1:]
        else:
            same_peer = np.zeros(n, dtype=bool)

        vals = None
        valid = None
        if spec.arg is not None:
            c = spec.arg.eval(full)
            vals = c.data[order]
            valid = c.valid_mask()[order]
            arg_col = c
        result = np.zeros(n, dtype=np.float64)
        res_valid = np.ones(n, dtype=bool)

        f = spec.func
        if f == "row_number":
            result = idx_in_part + 1
        elif f in ("rank", "dense_rank"):
            if not spec.order_by:
                raise errors.SqlError("42P20",
                                      f"{f}() requires ORDER BY")
            rank = np.zeros(n, dtype=np.int64)
            dense = np.zeros(n, dtype=np.int64)
            for i in range(n):
                if boundaries[i]:
                    rank[i] = 1
                    dense[i] = 1
                elif same_peer[i]:
                    rank[i] = rank[i - 1]
                    dense[i] = dense[i - 1]
                else:
                    rank[i] = idx_in_part[i] + 1
                    dense[i] = dense[i - 1] + 1
            result = rank if f == "rank" else dense
        elif f == "ntile":
            buckets = max(spec.extra or 1, 1)
            # partition sizes → PG ntile: larger buckets first
            part_sizes = np.zeros(n, dtype=np.int64)
            ends = np.flatnonzero(np.concatenate([boundaries[1:], [True]]))
            starts = np.flatnonzero(boundaries)
            result = np.zeros(n, dtype=np.int64)
            for st, en in zip(starts, ends):
                size = en - st + 1
                base = size // buckets
                rem = size % buckets
                pos = 0
                for b in range(1, buckets + 1):
                    cnt = base + (1 if b <= rem else 0)
                    result[st + pos:st + pos + cnt] = b
                    pos += cnt
                    if pos >= size:
                        break
        elif f in ("lag", "lead"):
            off = 1 if spec.extra is None else spec.extra
            shift = -off if f == "lag" else off
            src_idx = np.arange(n) + shift
            ok = (src_idx >= 0) & (src_idx < n)
            same_part = np.zeros(n, dtype=bool)
            clipped = np.clip(src_idx, 0, max(n - 1, 0))
            if n:
                same_part = ok & (s_codes[clipped] == s_codes)
            fill = spec.default if spec.default is not None else 0
            result = np.where(same_part, vals[clipped] if vals is not None
                              else 0, fill)
            res_valid = same_part & (valid[clipped] if valid is not None
                                     else True)
            if spec.default is not None:
                # rows outside the partition take the default VALUE
                res_valid = res_valid | ~same_part
        elif f in ("first_value", "last_value") and spec.frame is not None:
            starts_f, ends_f = _frame_bounds(spec.frame, boundaries, n)
            empty = starts_f > ends_f
            pick = starts_f if f == "first_value" else ends_f
            pick = np.clip(pick, 0, max(n - 1, 0))
            result = vals[pick] if vals is not None else np.zeros(n)
            res_valid = (valid[pick] if valid is not None
                         else np.ones(n, dtype=bool)) & ~empty
        elif f in ("first_value", "last_value"):
            if f == "first_value":
                result = vals[part_start] if vals is not None else None
                res_valid = valid[part_start]
            else:
                # default frame: last_value = current row (with ORDER BY)
                if spec.order_by:
                    result = vals
                    res_valid = valid
                else:
                    part_end = np.zeros(n, dtype=np.int64)
                    ends = np.flatnonzero(
                        np.concatenate([boundaries[1:], [True]]))
                    starts = np.flatnonzero(boundaries)
                    for st, en in zip(starts, ends):
                        part_end[st:en + 1] = en
                    result = vals[part_end]
                    res_valid = valid[part_end]
        elif spec.frame is not None:  # framed count/sum/min/max/avg
            result, res_valid = _window_agg_framed(
                f, vals, valid, boundaries, spec.frame, n,
                integer=spec.type.is_integer)
        else:  # count/sum/min/max/avg, default frame
            running = bool(spec.order_by)
            result, res_valid = _window_agg(
                f, vals, valid, boundaries, same_peer, running, n,
                integer=spec.type.is_integer)

        # scatter back to original row order; integer window results stay
        # in int64 end-to-end (no 2^53 float63 rounding)
        t = spec.type
        result = np.asarray(result)
        dtype = np.int64 if (t.is_integer or t.is_string) else np.float64
        final = np.zeros(n, dtype=dtype)
        final_valid = np.ones(n, dtype=bool)
        final[order] = result.astype(dtype)
        final_valid[order] = res_valid
        if t.is_string and spec.arg is not None:
            # min/max/lag over strings: results are dictionary codes
            data = final.astype(np.int32)
            return Column(t, data,
                          None if final_valid.all() else final_valid,
                          arg_col.dictionary)
        data = final.astype(t.np_dtype)
        return Column(t, data, None if final_valid.all() else final_valid)


def _window_agg(f, vals, valid, boundaries, same_peer,
                running: bool, n: int, integer: bool = False):
    # python-int accumulation keeps integer sums exact past 2^53
    result = np.zeros(n, dtype=np.int64 if integer else np.float64)
    res_valid = np.ones(n, dtype=bool)
    acc_sum = 0 if integer else 0.0
    acc_cnt = 0
    acc_min = None
    acc_max = None
    for i in range(n):
        if boundaries[i]:
            acc_sum = 0 if integer else 0.0
            acc_cnt, acc_min, acc_max = 0, None, None
        if vals is not None and (valid is None or valid[i]):
            v = int(vals[i]) if integer else float(vals[i])
            acc_sum += v
            acc_cnt += 1
            acc_min = v if acc_min is None else min(acc_min, v)
            acc_max = v if acc_max is None else max(acc_max, v)
        elif vals is None:
            acc_cnt += 1
        if f == "count":
            result[i] = acc_cnt
        elif f == "sum":
            result[i] = acc_sum
            res_valid[i] = acc_cnt > 0
        elif f == "avg":
            result[i] = acc_sum / acc_cnt if acc_cnt else 0.0
            res_valid[i] = acc_cnt > 0
        elif f == "min":
            result[i] = acc_min if acc_min is not None else 0
            res_valid[i] = acc_min is not None
        elif f == "max":
            result[i] = acc_max if acc_max is not None else 0
            res_valid[i] = acc_max is not None
    if not running:
        # whole-partition value = the partition's last running value
        ends = np.flatnonzero(np.concatenate([boundaries[1:], [True]]))
        starts = np.flatnonzero(boundaries)
        for st, en in zip(starts, ends):
            result[st:en + 1] = result[en]
            res_valid[st:en + 1] = res_valid[en]
    else:
        # peers share the frame end (RANGE semantics): each peer group
        # takes its LAST member's running value (backward pass)
        i = n - 1
        while i > 0:
            if same_peer[i]:
                j = i
                while j > 0 and same_peer[j]:
                    j -= 1
                result[j:i] = result[i]
                res_valid[j:i] = res_valid[i]
                i = j - 1
            else:
                i -= 1
    return result, res_valid


def _frame_bounds(frame, boundaries, n):
    """Per-row inclusive [start, end] row indexes of a ROWS frame,
    clamped to the row's partition."""
    part_start = np.maximum.accumulate(
        np.where(boundaries, np.arange(n), 0))
    part_end = np.zeros(n, dtype=np.int64)
    ends = np.flatnonzero(np.concatenate([boundaries[1:], [True]]))
    starts = np.flatnonzero(boundaries)
    for st, en in zip(starts, ends):
        part_end[st:en + 1] = en
    idx = np.arange(n)
    s_off, e_off = frame
    lo = part_start if s_off is None else np.maximum(part_start,
                                                     idx + s_off)
    hi = part_end if e_off is None else np.minimum(part_end, idx + e_off)
    return lo, hi


def _window_agg_framed(f, vals, valid, boundaries, frame, n,
                       integer: bool = False):
    """ROWS-framed aggregates: prefix sums give count/sum/avg in O(n);
    min/max reduce each frame slice directly (frames are small in
    practice — bounded by the offsets)."""
    lo, hi = _frame_bounds(frame, boundaries, n)
    empty = lo > hi
    result = np.zeros(n, dtype=np.int64 if integer else np.float64)
    res_valid = np.ones(n, dtype=bool)
    if vals is None:    # count(*)
        result = np.where(empty, 0, hi - lo + 1)
        return result, res_valid
    v_ok = valid if valid is not None else np.ones(n, dtype=bool)
    if f in ("count", "sum", "avg"):
        acc = np.where(v_ok, vals, 0)
        ps = np.concatenate([[0], np.cumsum(
            acc.astype(np.int64 if integer else np.float64))])
        pc = np.concatenate([[0], np.cumsum(v_ok.astype(np.int64))])
        lo_c = np.clip(lo, 0, n)
        hi_c = np.clip(hi + 1, 0, n)
        cnt = np.where(empty, 0, pc[hi_c] - pc[lo_c])
        if f == "count":
            return cnt, res_valid
        ssum = np.where(empty, 0, ps[hi_c] - ps[lo_c])
        if f == "sum":
            return ssum, cnt > 0
        with np.errstate(invalid="ignore", divide="ignore"):
            av = np.where(cnt > 0, ssum / np.maximum(cnt, 1), 0.0)
        return av, cnt > 0
    # min/max. Unbounded sides use per-partition prefix/suffix scans
    # (O(n)); only genuinely bounded two-sided frames take the per-row
    # slice loop, whose width is capped by the constant offsets.
    s_off, e_off = frame
    if integer:   # int64 end-to-end: float64 would round past 2^53
        iv = vals.astype(np.int64)
        info = np.iinfo(np.int64)
        sent_min = np.where(v_ok, iv, info.max)
        sent_max = np.where(v_ok, iv, info.min)
    else:
        fv = vals.astype(np.float64)
        sent_min = np.where(v_ok, fv, np.inf)
        sent_max = np.where(v_ok, fv, -np.inf)
    pc = np.concatenate([[0], np.cumsum(v_ok.astype(np.int64))])
    lo_c = np.clip(lo, 0, n)
    hi_c = np.clip(hi + 1, 0, n)
    any_valid = (pc[hi_c] - pc[lo_c]) > 0
    res_valid = any_valid & ~empty

    def scan_fwd(a, op):
        out = a.copy()
        for i in range(1, n):
            if not boundaries[i]:
                out[i] = op(out[i], out[i - 1])
        return out

    def scan_bwd(a, op):
        out = a.copy()
        part_next = np.concatenate([boundaries[1:], [True]])
        for i in range(n - 2, -1, -1):
            if not part_next[i]:
                out[i] = op(out[i], out[i + 1])
        return out

    npop = np.minimum if f == "min" else np.maximum
    src = sent_min if f == "min" else sent_max
    if s_off is None and e_off is None:
        run = scan_fwd(src, npop)
        ends = np.flatnonzero(np.concatenate([boundaries[1:], [True]]))
        starts = np.flatnonzero(boundaries)
        for st, en in zip(starts, ends):
            run[st:en + 1] = run[en]
        result = run
    elif s_off is None:
        run = scan_fwd(src, npop)          # min/max from partition start
        result = run[np.clip(hi, 0, n - 1)]
    elif e_off is None:
        run = scan_bwd(src, npop)          # min/max to partition end
        result = run[np.clip(lo, 0, n - 1)]
    else:
        result = np.zeros(n, dtype=src.dtype)
        for i in range(n):
            if not res_valid[i]:
                continue
            sl = slice(int(lo[i]), int(hi[i]) + 1)
            result[i] = src[sl].min() if f == "min" else src[sl].max()
    result = np.where(res_valid, result, 0)
    if integer:
        result = result.astype(np.int64)
    return result, res_valid
