"""Device (TPU) compilation of bound expressions over cached HBM columns.

This is the offload seam the reference doesn't have (SURVEY.md §5.8): the
planner's Scan→Filter→Aggregate chains compile to one jitted XLA program per
(table, query) pair — predicate, mask logic, and reduction fuse into a single
HBM pass. Strings participate as sorted-dictionary codes: literal
comparisons are resolved to code thresholds on host at compile time
(code order == string order, columnar/column.py).

Expressions evaluate to (value, valid) pairs — SQL three-valued logic on
device, matching the CPU oracle in sql/expr.py.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.device import DeviceColumn
from ..sql.expr import (BoundColumn, BoundExpr, BoundFunc, BoundLiteral)

_NUMERIC_IDS = {dt.TypeId.BOOL, dt.TypeId.TINYINT, dt.TypeId.SMALLINT,
                dt.TypeId.INT, dt.TypeId.BIGINT, dt.TypeId.FLOAT,
                dt.TypeId.DOUBLE, dt.TypeId.TIMESTAMP, dt.TypeId.DATE}

_CMP = {"op=", "op<>", "op!=", "op<", "op<=", "op>", "op>="}
_ARITH = {"op+", "op-", "op*", "op/", "op%"}


class NotCompilable(Exception):
    """Expression/plan shape the device compiler declines — the caller
    falls back to the host path. `reason` is a short category slug for
    the per-reason decline gauges (never query text)."""

    def __init__(self, msg: str = "", reason: str = "not_compilable"):
        super().__init__(msg)
        self.reason = reason


class DeviceExpr:
    """Compiled closure producing (value, valid) given the env of device
    columns; env maps scan-column index → DeviceColumn."""

    def __init__(self, fn: Callable, inputs: list[int],
                 consts: tuple = ()):
        self.fn = fn          # (list of (data, mask)) -> (value, valid)
        self.inputs = inputs  # scan column indices, order matches fn args
        #: every DATA-DEPENDENT constant the closure bakes into its trace
        #: (today: the dictionary-code thresholds of string comparisons).
        #: A program cache key that drops the publication tuple MUST
        #: include these, or a stale executable could serve a new
        #: dictionary generation with the old thresholds.
        self.consts = consts


def compile_expr(expr: BoundExpr, col_types: list[dt.SqlType],
                 dictionaries: dict[int, np.ndarray]) -> DeviceExpr:
    """Compile a bound expression to a device closure.

    dictionaries: scan column index → sorted dictionary (VARCHAR columns),
    used to resolve string literals to code thresholds at compile time.
    Raises NotCompilable for unsupported shapes (caller falls back to CPU).
    """
    inputs: list[int] = []
    index_of: dict[int, int] = {}
    consts: list = []

    def slot(col_index: int) -> int:
        if col_index not in index_of:
            index_of[col_index] = len(inputs)
            inputs.append(col_index)
        return index_of[col_index]

    def rec(e: BoundExpr):
        if isinstance(e, BoundLiteral):
            if e.value is None:
                return lambda env: (jnp.int32(0), False)
            if isinstance(e.value, bool):
                v = jnp.int32(1 if e.value else 0)
            elif isinstance(e.value, int):
                if not (-2**31 <= e.value < 2**31):
                    raise NotCompilable("int64 literal")
                v = jnp.int32(e.value)
            elif isinstance(e.value, float):
                v = jnp.float32(e.value)
            else:
                raise NotCompilable("string literal outside comparison")
            return lambda env, _v=v: (_v, True)
        if isinstance(e, BoundColumn):
            if e.type.id not in _NUMERIC_IDS and not e.type.is_string:
                raise NotCompilable(f"column type {e.type}")
            s = slot(e.index)
            return lambda env, _s=s: env[_s]
        if isinstance(e, BoundFunc):
            return rec_func(e)
        raise NotCompilable(type(e).__name__)

    def rec_func(e: BoundFunc):
        name = e.name
        if name in _CMP:
            return compile_compare(e)
        if name in _ARITH:
            return compile_arith(e)
        if name in ("and", "or"):
            subs = [rec(a) for a in e.args]
            is_and = name == "and"

            def fn(env, _subs=subs, _and=is_and):
                vals = [s(env) for s in _subs]
                bools = [_as_bool(v) for v, _ in vals]
                oks = [_m(ok) for _, ok in vals]
                any_null = functools.reduce(jnp.logical_or,
                                            [~ok for ok in oks])
                if _and:
                    any_false = functools.reduce(
                        jnp.logical_or,
                        [jnp.logical_and(ok, ~b) for b, ok in zip(bools, oks)])
                    return ~any_false, jnp.logical_or(any_false, ~any_null)
                any_true = functools.reduce(
                    jnp.logical_or,
                    [jnp.logical_and(ok, b) for b, ok in zip(bools, oks)])
                return any_true, jnp.logical_or(any_true, ~any_null)
            return fn
        if name == "not":
            sub = rec(e.args[0])

            def fn(env, _sub=sub):
                v, ok = _sub(env)
                return ~_as_bool(v), ok
            return fn
        if name in ("is_null", "is_not_null"):
            sub = rec(e.args[0])
            neg = name == "is_not_null"

            def fn(env, _sub=sub, _neg=neg):
                v, ok = _sub(env)
                m = _m(ok)
                return (m if _neg else ~m), True
            return fn
        if name == "cast":
            sub = rec(e.args[0])
            if e.type.is_float:
                def fn(env, _sub=sub):
                    v, ok = _sub(env)
                    return v.astype(jnp.float32), ok
                return fn
            if e.type.is_integer:
                def fn(env, _sub=sub):
                    v, ok = _sub(env)
                    if jnp.issubdtype(v.dtype, jnp.floating):
                        # PG: round half away from zero
                        r = jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)
                        return r.astype(jnp.int32), ok
                    return v.astype(jnp.int32), ok
                return fn
            raise NotCompilable("cast target")
        raise NotCompilable(f"function {name}")

    def compile_compare(e: BoundFunc):
        a, b = e.args
        name = e.name
        # string vs literal → code threshold
        for col, lit, flip in ((a, b, False), (b, a, True)):
            if isinstance(col, BoundColumn) and col.type.is_string and \
                    isinstance(lit, BoundLiteral) and isinstance(lit.value, str):
                d = dictionaries.get(col.index)
                if d is None:
                    raise NotCompilable("no dictionary for string column")
                return compile_str_cmp(col, lit.value, name, flip, d)
        if (isinstance(a, BoundColumn) and a.type.is_string) or \
                (isinstance(b, BoundColumn) and b.type.is_string):
            raise NotCompilable("string-string comparison on device")
        fa, fb = rec(a), rec(b)
        op = name[2:]

        def fn(env, _fa=fa, _fb=fb, _op=op):
            (va, oka), (vb, okb) = _fa(env), _fb(env)
            va, vb = _unify(va, vb)
            if _op == "=":
                v = va == vb
            elif _op in ("<>", "!="):
                v = va != vb
            elif _op == "<":
                v = va < vb
            elif _op == "<=":
                v = va <= vb
            elif _op == ">":
                v = va > vb
            else:
                v = va >= vb
            return v, jnp.logical_and(_m(oka), _m(okb))
        return fn

    def compile_str_cmp(col: BoundColumn, s: str, name: str, flip: bool,
                        d: np.ndarray):
        """col OP 'literal' on sorted dictionary codes."""
        op = name[2:]
        if flip:  # 'literal' OP col  →  col FLIP(OP) literal
            op = {"=": "=", "<>": "<>", "!=": "<>", "<": ">", "<=": ">=",
                  ">": "<", ">=": "<="}[op]
        ds = d.astype(str)
        lo = int(np.searchsorted(ds, s, side="left"))
        hi = int(np.searchsorted(ds, s, side="right"))
        exact = lo < len(ds) and ds[lo] == s
        sl = slot(col.index)
        consts.append((col.index, op, lo, hi, exact))

        def fn(env, _sl=sl, _op=op, _lo=lo, _hi=hi, _exact=exact):
            codes, ok = env[_sl]
            if _op == "=":
                v = (codes == _lo) if _exact else jnp.zeros_like(codes, dtype=bool)
            elif _op == "<>":
                v = (codes != _lo) if _exact else jnp.ones_like(codes, dtype=bool)
            elif _op == "<":
                v = codes < _lo
            elif _op == "<=":
                v = codes < _hi
            elif _op == ">":
                v = codes >= _hi
            else:
                v = codes >= _lo
            return v, _m(ok)
        return fn

    def compile_arith(e: BoundFunc):
        fa, fb = rec(e.args[0]), rec(e.args[1])
        op = e.name[2:]
        int_result = e.type.is_integer

        def fn(env, _fa=fa, _fb=fb, _op=op, _int=int_result):
            (va, oka), (vb, okb) = _fa(env), _fb(env)
            va, vb = _unify(va, vb)
            ok = jnp.logical_and(_m(oka), _m(okb))
            if _op == "+":
                return va + vb, ok
            if _op == "-":
                return va - vb, ok
            if _op == "*":
                return va * vb, ok
            raise NotCompilable("device division")  # PG trunc semantics: CPU
        return fn

    top = rec(expr)
    return DeviceExpr(top, inputs, tuple(consts))


def _m(ok):
    return ok if not isinstance(ok, bool) else jnp.bool_(ok)


def _as_bool(v):
    if v.dtype == jnp.bool_:
        return v
    return v != 0


def _unify(va, vb):
    fa = hasattr(va, "dtype") and jnp.issubdtype(va.dtype, jnp.floating)
    fb = hasattr(vb, "dtype") and jnp.issubdtype(vb.dtype, jnp.floating)
    if fa or fb:
        return (va.astype(jnp.float32) if hasattr(va, "astype") else jnp.float32(va),
                vb.astype(jnp.float32) if hasattr(vb, "astype") else jnp.float32(vb))
    return va, vb


# The per-(provider, query-shape) jitted program cache that used to
# live here (an unbounded module dict — one leaked executable per novel
# query shape for process lifetime) is now the obs/device.py compile
# ledger: a BOUNDED LRU (serene_program_cache_entries) with per-family
# compile/hit/miss accounting. Call sites go through
# obs.device.compiled(family, key, builder).
