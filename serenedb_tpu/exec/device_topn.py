"""Device / mesh top-N: ORDER BY <column> LIMIT k over a table scan.

Reference analog: the columnstore top-N pushdown of the reference's
analytics path (DuckDB TopN operator over the iresearch columnstore;
SURVEY.md §1 L3) — re-expressed as one XLA `top_k` over the HBM-resident
key column. Under `SET serene_mesh = N` the key tiles shard across the
mesh, each shard computes its local top-k, and the (N x k) candidates
merge on the host — the same shard-then-merge shape as the sharded BM25
top-k (parallel/mesh.py).

Supported shape: Limit(Sort(Scan | Project(Scan))) with a single sort
key that is a plain numeric column (int / date / float32) with no NULLs
and no filter. Anything else falls back to the exact CPU lexsort
(plan.SortNode). The asc direction uses the bitwise-NOT transform
(~k = -k-1) so int32 min does not overflow under negation.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..columnar import dtypes as dt
from ..columnar.column import Batch
from ..utils import log, metrics
from .device import NotCompilable
from .tables import TableProvider

MAX_TOPN_K = 8192
_I32_MIN = -(1 << 31)
_I32_MAX = (1 << 31) - 1


def try_device_topn(limit_node, ctx) -> Optional[Batch]:
    """Attempt device execution of Limit(Sort(...)); None → CPU path."""
    from .plan import ProjectNode, ScanNode, SortNode

    device = ctx.settings.get("serene_device")
    if device == "cpu":
        return None
    if limit_node.limit is None:
        return None
    k = limit_node.limit + limit_node.offset
    if k == 0 or k > MAX_TOPN_K:
        return None
    sort = limit_node.child
    if not isinstance(sort, SortNode) or len(sort.key_indices) != 1:
        return None
    if sort.nulls_first[0] is not None:
        return None     # explicit NULLS placement: CPU handles it
    proj = None
    inner = sort.child
    if isinstance(inner, ProjectNode):
        proj = inner
        inner = inner.child
    if not isinstance(inner, ScanNode) or inner.filter is not None:
        return None
    scan = inner
    ki = sort.key_indices[0]
    if proj is not None:
        from ..sql.expr import BoundColumn
        key_expr = proj.exprs[ki]
        if not isinstance(key_expr, BoundColumn):
            return None
        col_idx = key_expr.index
    else:
        col_idx = ki
    t = scan.types[col_idx]
    if not (t.is_integer or t.id in (dt.TypeId.DATE, dt.TypeId.FLOAT)):
        return None
    provider = scan.provider
    if device == "auto" and \
            provider.row_count() < ctx.settings.get("serene_device_min_rows"):
        return None
    from ..columnar.device import DeviceNarrowingError
    from ..obs.trace import current_trace
    prof = getattr(ctx, "profile", None)
    trace = current_trace()
    try:
        import time as _time
        t0 = _time.perf_counter_ns()
        idx = _topn_indices(provider, scan, scan.columns[col_idx],
                            bool(sort.descs[0]), k, ctx,
                            prof_key=id(limit_node))
        t1 = _time.perf_counter_ns()
        if prof is not None:
            # device-path time lands on the Limit node that claimed the
            # Sort pipeline (the offload replaced its whole subtree)
            prof.add_device_ns(id(limit_node), t1 - t0)
        if idx is not None:
            # unconditional: the device latency signal survives
            # profiling/tracing being off (None = declined, no dispatch)
            from ..utils import metrics as _metrics
            _metrics.DEVICE_DISPATCH_HIST.observe_ns(t1 - t0)
            if trace is not None:
                trace.add("device_dispatch", "device", t0, t1, op="topn")
    except (NotCompilable, DeviceNarrowingError) as e:
        log.debug("device", f"top-N fell back to CPU: {e}")
        return None
    if idx is None:
        return None
    idx = idx[limit_node.offset:]
    base = provider.full_batch(scan.columns).take(idx)
    if proj is None:
        return base
    cols = [e.eval(base) for e in proj.exprs]
    return Batch(list(proj.names), cols)


def _topn_indices(provider: TableProvider, scan, col_name: str,
                  desc: bool, k: int, ctx,
                  prof_key=None) -> Optional[np.ndarray]:
    import jax
    import jax.numpy as jnp

    pin = provider.try_pin()
    dev_ver = pin[1] if pin is not None else provider.data_version
    host = (pin[0].column(col_name) if pin is not None
            else provider.host_column(col_name))
    n = len(host)
    if n == 0:
        return np.empty(0, dtype=np.int64)
    if not host.valid_mask().all():
        raise NotCompilable("top-N key column has NULLs")
    if host.data.dtype.kind == "f":
        if not np.isfinite(host.data).all():
            # NaN ordering is PG-specific; +/-inf would collide with the
            # -inf padding sentinel in the mesh merge
            raise NotCompilable("top-N float key has NaN/inf")
    else:
        # sentinel-tie gates (see module docstring): the transform must
        # keep every valid key strictly above the invalid sentinel
        lo, hi = int(host.data.min()), int(host.data.max())
        if desc and lo <= _I32_MIN:
            raise NotCompilable("key touches int32 min")
        if not desc and hi >= _I32_MAX:
            raise NotCompilable("key touches int32 max")

    mesh_n = int(ctx.settings.get("serene_mesh") or 0)
    if mesh_n > 1 and len(jax.devices()) < mesh_n:
        mesh_n = 0

    # zone-map skip-scan: block bounds alone can prove a prefix/suffix
    # of blocks holds no top-k candidate (cover k rows with the best
    # blocks' worst values, prune blocks strictly beyond that
    # threshold); only the surviving contiguous range uploads. Pruned
    # rows are strictly outside the top-k, so result AND tie order are
    # untouched — indices just shift by the range start.
    from . import zonemap
    block_rows = int(ctx.settings.get("serene_morsel_rows"))
    zrange = zonemap.topn_block_range(provider, ctx.settings, col_name,
                                      block_rows, desc, k, pin)

    # the range keys the program: a sliced upload's frame-of-reference
    # scheme can differ from the whole column's
    cache_key = ("topn", id(provider), dev_ver, col_name, desc, k, mesh_n,
                 zrange)
    if zrange is None:
        dc = provider.device_columns([col_name], pin)[col_name]
    else:
        from .device_agg import _range_device_columns
        dc = _range_device_columns(provider, [col_name], pin,
                                   zrange)[col_name]
    is_float = dc.data.dtype.kind == "f"

    def build():
        scheme, offset = dc.scheme, dc.offset

        def keys_of(data, mask):
            v = data
            if scheme != "raw":
                v = v.astype(jnp.int32) + jnp.int32(offset)
            if is_float:
                kv = v if desc else -v
                sent = jnp.float32(-jnp.inf)
            else:
                v = v.astype(jnp.int32)
                kv = v if desc else ~v
                sent = jnp.int32(_I32_MIN)
            return jnp.where(mask.ravel(), kv.ravel(), sent)

        if mesh_n > 1:
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            from ..parallel.mesh import AXIS, data_mesh
            mesh = data_mesh(mesh_n)

            def core(data, mask):
                keys = keys_of(data, mask)
                kk, ii = jax.lax.top_k(keys, k)
                shard_rows = data.shape[0] * data.shape[1]
                base = jax.lax.axis_index(AXIS).astype(jnp.int32) * \
                    jnp.int32(shard_rows)
                return kk, ii.astype(jnp.int32) + base

            return shard_map(
                core, mesh=mesh, in_specs=(P(AXIS, None), P(AXIS, None)),
                out_specs=(P(AXIS), P(AXIS)))

        def prog(data, mask):
            keys = keys_of(data, mask)
            kk, ii = jax.lax.top_k(keys, k)
            return kk, ii.astype(jnp.int32)

        return prog

    from ..obs import device as obs_device
    jitted = obs_device.compiled("device_topn", cache_key, build,
                                 profile=getattr(ctx, "profile", None),
                                 node_key=prof_key)

    data, mask = dc.data, dc.mask
    if mesh_n > 1:
        from .device_agg import _pad_shard_axis
        data = _pad_shard_axis(data, mesh_n)
        mask = _pad_shard_axis(mask, mesh_n)
    if data.shape[0] * data.shape[1] < k * max(mesh_n, 1):
        # top_k k exceeds the (per-shard) domain — tiny table, CPU wins
        raise NotCompilable("k exceeds per-shard rows")
    kk, ii = obs_device.fetch_all(jitted(data, mask))
    ii = ii.astype(np.int64)
    if mesh_n > 1:
        # merge the per-shard candidate lists: global top-k of N*k.
        # Candidates from under-filled shards carry the padding sentinel
        # — drop them (finite/valid keys are strictly above it by the
        # gates), and widen to float64 so negating int32 min can't wrap.
        kkw = kk.astype(np.float64)
        sent = -np.inf if is_float else float(_I32_MIN)
        valid = kkw > sent
        kkw, ii = kkw[valid], ii[valid]
        order = np.argsort(-kkw, kind="stable")[: k]
        ii = ii[order]
    if zrange is not None:
        ii = ii + zrange[0]     # slice-relative → table row ids
    metrics.DEVICE_OFFLOADS.add()
    k_eff = min(k, n)
    return ii[:k_eff]
