"""Fused device-side relational execution — one dispatch per query.

PAPER.md §2's core claim is "one dispatch per query, not one kernel per
operator". The single-table half of that already exists (exec/device_agg.py
fuses Scan→Filter→Aggregate); this module extends the discipline across the
relational tier: a Scan→Filter→Join→Aggregate chain compiles into ONE jitted
JAX program over device-resident columns of BOTH tables, and a filtered
top-N (Sort+Limit over Filter→Scan) into one masked `top_k` dispatch.

Join representation (the PR-3 trick, moved on device): both sides' equi-keys
factorize host-side into ONE dense int64 code space
(exec/morsel.combined_codes — NULL keys masked to a per-side sentinel so
NULL never matches, every NaN occurrence its own code so NaN ≠ NaN, exactly
the row-tuple oracle's semantics). The codes upload as int32 tiles and the
probe happens *inside* the program as pure gathers: the build side scatters
per-code partials (count / limb sums / min / max), every probe row gathers
its code's partial and scatters it into the group accumulator — no pair list
ever materializes, on host or device. The fused-kernel shape mirrors
FLASH-MAXSIM's IO-aware late-interaction kernels and Ragged Paged
Attention's one-program-over-resident-data design (PAPERS.md).

Exactness policy (PG parity, x64 off): only integer/bool/date aggregate
arguments compile — int sums ride the 8-bit limb decomposition of
ops/agg.py, weighted by the per-row match count (or ONE direct int32
scatter column when the argument is a plain column whose value bound
times the worst-case pair count provably fits int32), and the whole
plan is admitted only while the worst-case pair count keeps every int32
limb accumulator exact (`MAX_PAIRS_EXACT`). Float arguments, DISTINCT, FILTER
clauses, residual predicates and non-inner joins fall back to the host
oracle, which stays on as the bit-identical parity reference behind
`SET serene_device_fused = off` (the serene_join_vectorized=off pattern).

Transfers: uploads go through DEVICE_CACHE, a process-wide bytes-bounded
cache keyed by the PR-5 publication tuples (provider token, data_version,
mutation_epoch) + column + surviving row range — a repeat query on an
unchanged table skips host→device transfer entirely, and any write moves
the key. Zone maps bound what uploads at all: each side's scan-level
conjuncts shrink the transfer to the surviving block envelope
(device_agg's `_zonemap_range` logic, applied per join side).
"""

from __future__ import annotations

import functools
import threading
import time
from collections import OrderedDict
from typing import Optional

import jax
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.column import Batch, Column
from ..columnar.device import (DeviceColumn, DeviceNarrowingError, LANES,
                               pad_len, to_device_column)
from ..ops import agg as ops_agg
from ..obs import device as obs_device
from ..sql.binder import _expr_key
from ..sql.expr import AggSpec, BoundColumn, BoundExpr, BoundFunc
from ..utils import log, metrics
from ..utils.config import REGISTRY as _settings_registry
from .device import DeviceExpr, NotCompilable, compile_expr
from .device_agg import MAX_GROUP_PRODUCT, MAX_INT_KEY_RANGE

#: combined join-key code-space cap (dense per-code arrays live in HBM)
MAX_CODE_SPACE = 1 << 22
#: worst-case matched-pair bound under which every int32 limb/count
#: scatter in the program is provably exact (255 * pairs < 2^31)
MAX_PAIRS_EXACT = 1 << 23

_AGG_FUNCS = {"count_star", "count", "sum", "min", "max", "avg"}

#: expressions whose host-side evaluation draws shared mutable state or
#: runs a subplan — pre-evaluating them over unfiltered rows would
#: double-draw / reorder effects (same list the morsel tier excludes)
_HOST_EVAL_UNSAFE = {
    "scalar_subquery", "array_subquery", "in_subquery", "exists",
    "currval", "lastval"}


def _trace_span(trace, name: str, t0_ns: int, **args) -> None:
    """Timeline phase attribution (serene_trace): the same boundaries
    the profiler's device_ns counters use, but with BEGIN/END stamps so
    the factorize -> upload -> dispatch sequencing is visible. No-op
    when tracing is off (trace is None); call sites bind the trace with
    functools.partial so one helper serves every program shape."""
    if trace is not None:
        trace.add(name, "device", t0_ns, time.perf_counter_ns(), **args)


def fused_enabled(settings) -> bool:
    try:
        return bool(settings.get("serene_device_fused"))
    except KeyError:  # pragma: no cover — registry always declares it
        return False


def fused_ext_enabled(settings) -> bool:
    """PR 17 extended admission (strings/DISTINCT/FILTER/residual/outer
    joins + chained stage handoff); off restores the PR 7 walls."""
    try:
        return bool(settings.get("serene_device_fused_ext"))
    except KeyError:  # pragma: no cover
        return False


def _pow2_rows(n: int) -> int:
    """pow2 row bucket (floor BLOCK_ROWS): every upload in the fused
    path pads to this, so the number of DISTINCT traced shapes per
    program family grows O(log rows) instead of O(rows / BLOCK_ROWS) —
    the admission-wall removals multiply program axes, and without the
    bucketing that product would storm the compile ledger."""
    b = 1024
    while b < n:
        b <<= 1
    return b


def _pow2_int(n: int, floor: int = 8) -> int:
    """pow2 bucket for non-row axes (DISTINCT value spaces): same
    compile-storm rationale as _pow2_rows, smaller floor."""
    b = floor
    while b < n:
        b <<= 1
    return b


# -- publication-keyed device column cache ----------------------------------


def _pub(provider, pin) -> tuple:
    """(provider token, data_version, mutation_epoch) — the PR-5
    publication tuple. The token is process-unique per provider object,
    so DROP + CREATE can never alias generations."""
    from ..cache.result import _provider_token
    if pin is not None:
        return (_provider_token(provider), pin[1], pin[2])
    return (_provider_token(provider),
            getattr(provider, "data_version", 0),
            getattr(provider, "mutation_epoch", 0))


def _charge_upload(nbytes: int) -> None:
    """Per-query attribution of a host→device transfer: the statement
    that caused the upload records the bytes in its accounted peak
    (obs/resources; no-op when `serene_mem_account` is off or the
    upload happens outside a statement)."""
    from ..obs.resources import charge_device_upload
    charge_device_upload(nbytes)


class DeviceColumnCache:
    """Process-wide cache of device-resident arrays keyed by publication
    tuples. An entry's key embeds (token, data_version, mutation_epoch)
    + column + row range, so invalidation is implicit: any write bumps
    the publication and the next query keys past the stale upload. Bytes
    are bounded by the serene_device_cache_mb global (LRU past the cap);
    superseded generations of a token are swept eagerly on store so HBM
    never holds two versions of one column."""

    def __init__(self):
        #: key -> [value, nbytes, device ids, hits, last-touch epoch s]
        self._entries: OrderedDict[tuple, list] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()

    @staticmethod
    def _trade_on() -> bool:
        try:
            return bool(_settings_registry.get_global(
                "serene_device_cache_trade"))
        except KeyError:  # pragma: no cover
            return False

    def _cap_bytes(self) -> int:
        """Byte cap of THIS side of the device budget. With the
        pressure trade on, the cap is the serene_device_cache_mb
        envelope minus the posting pool's LIVE page bytes, floored at a
        quarter of the envelope — the pool's residency squeezes the
        column cache instead of a static carve-out, and vice versa via
        shed_colder. Consults the pool's lock, so call it OUTSIDE
        self._lock (the only cross-lock order is cache-unlocked →
        pool; the pool never calls into this cache)."""
        try:
            mb = int(_settings_registry.get_global("serene_device_cache_mb"))
        except KeyError:  # pragma: no cover
            mb = 256
        env = mb << 20
        if self._trade_on():
            try:
                from ..search.posting_pool import POOL
                from ..search.vector_store import VPOOL
                return max(env // 4,
                           env - POOL.live_bytes() - VPOOL.live_bytes())
            except Exception:  # noqa: BLE001 — sizing only, never fatal
                pass
        return env

    def get(self, key: tuple):
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                metrics.DEVICE_CACHE_MISSES.add()
                return None
            self._entries.move_to_end(key)
            entry[3] += 1
            entry[4] = time.time()
            metrics.DEVICE_CACHE_HITS.add()
            return entry[0]

    def put(self, key: tuple, value, nbytes: int, sweep=None) -> None:
        """Store + LRU/byte bookkeeping. `sweep(k) -> bool` lets a
        caller mark extra keys as superseded (e.g. code tiles whose
        staleness comes from the PARTNER table's publication, which the
        owner-generation rule below cannot see)."""
        dev_ids = obs_device.value_device_ids(value) \
            if obs_device.enabled() else ()
        cap = self._cap_bytes()        # pool consult happens pre-lock
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            # sweep superseded generations: same (token, name, tag) under
            # an older publication can never be read again
            token, name = key[0][0], key[1]
            stale = [k for k in self._entries
                     if (k[0][0] == token and k[1] == name and
                         k[0] != key[0]) or
                     (sweep is not None and k != key and sweep(k))]
            for k in stale:
                self._bytes -= self._entries.pop(k)[1]
                metrics.DEVICE_CACHE_EVICTIONS.add()
            self._entries[key] = [value, nbytes, dev_ids, 0, time.time()]
            self._bytes += nbytes
            over = self._bytes - cap
            tail_idle_s = None
            if over > 0:
                for e in self._entries.values():
                    tail_idle_s = time.time() - e[4]
                    break
        if over > 0 and self._trade_on() and tail_idle_s is not None:
            # pressure trade: before shedding our own tail, offer the
            # eviction to the COLDEST pool tail (posting pages or vector
            # pages) if it is idler than ours — freed pages raise this
            # cache's cap directly
            try:
                from ..search.posting_pool import POOL
                from ..search.vector_store import VPOOL
                pools = sorted(
                    ((idle, p) for p in (POOL, VPOOL)
                     for idle in (p.tail_idle_ns(),) if idle is not None),
                    reverse=True)
                shed = False
                for pool_idle, p in pools:
                    if pool_idle > tail_idle_s * 1e9 and \
                            p.shed_colder(int(tail_idle_s * 1e9), over):
                        shed = True
                        break
                if shed:
                    cap = self._cap_bytes()
            except Exception:  # noqa: BLE001 — sizing only, never fatal
                pass
        with self._lock:
            while self._bytes > cap and len(self._entries) > 1:
                _, e = self._entries.popitem(last=False)
                self._bytes -= e[1]
                metrics.DEVICE_CACHE_EVICTIONS.add()
            metrics.DEVICE_CACHE_BYTES.set(self._bytes)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            metrics.DEVICE_CACHE_BYTES.set(0)

    # -- telemetry surfaces (obs/device.py) ---------------------------------

    def stats(self) -> dict:
        cap = self._cap_bytes()        # pool consult happens pre-lock
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "cap_bytes": cap}

    def device_bytes(self) -> dict[int, int]:
        """HBM occupancy estimate per device id: each entry's bytes
        split across the devices holding it (mesh-sharded commits land
        on several). Entries stored with telemetry off carry no
        placement and attribute to the default device 0."""
        out: dict[int, int] = {}
        with self._lock:
            for e in self._entries.values():
                ids = e[2] or (0,)
                share = len(ids)
                for i in ids:
                    out[i] = out.get(i, 0) + e[1] // share
        return out

    def snapshot(self) -> list[dict]:
        """One row per live entry — the sdb_device_cache() body: which
        publication/column occupies HBM, how big, on which devices, how
        recently touched."""
        now = time.time()
        with self._lock:
            rows = []
            for (pub, name, kind, tag), e in self._entries.items():
                rows.append({
                    "token": pub[0], "data_version": pub[1],
                    "mutation_epoch": pub[2], "column": name,
                    "kind": kind, "tag": repr(tag)[:120],
                    "bytes": e[1],
                    "devices": ",".join(str(i) for i in e[2]),
                    "hits": e[3],
                    "idle_ms": round((now - e[4]) * 1e3, 1)})
        return rows

    # -- typed helpers ------------------------------------------------------

    def column(self, provider, pub: tuple, name: str, host_col_fn,
               zrange: Optional[tuple], pad: Optional[int] = None):
        """Device tiles of one column (optionally row-sliced), cached by
        (publication, column, range). host_col_fn() materializes the host
        column only on miss. `pad` pads rows to that multiple (the fused
        tier's pow2 bucket) and keys a DISTINCT entry, so other tiers'
        cached shapes are untouched."""
        obs_device.note_provider(pub[0], getattr(provider, "name", ""))
        key = (pub, name, "col", zrange if pad is None
               else (zrange, "pad", pad))
        dc = self.get(key)
        if dc is not None:
            return dc
        col = host_col_fn()
        if zrange is not None:
            col = col.slice(zrange[0], zrange[1])
        if pad is None:
            dc = to_device_column(col)  # upload accounted at the funnel
        else:
            dc = to_device_column(col, pad_multiple=pad)
        nbytes = int(dc.data.size * dc.data.dtype.itemsize) + \
            int(dc.mask.size)
        metrics.DEVICE_BYTES.add(nbytes)
        _charge_upload(nbytes)
        self.put(key, dc, nbytes)
        return dc

    def array(self, pub: tuple, name: str, tag, build_fn, sweep=None,
              device=None):
        """Generic cached device array (code tiles, row masks). `device`
        commits the array to a specific mesh device (the sharded tier's
        data-axis placement); callers embed the shard id in `tag`, so
        placement is a pure function of the key."""
        key = (pub, name, "arr", tag)
        arr = self.get(key)
        if arr is not None:
            return arr
        t0 = time.perf_counter_ns()
        arr = build_fn()
        if device is not None:
            arr = jax.device_put(arr, device)
        nbytes = int(arr.size * arr.dtype.itemsize)
        metrics.DEVICE_BYTES.add(nbytes)
        obs_device.note_upload(nbytes, obs_device.array_device_ids(arr),
                               time.perf_counter_ns() - t0)
        _charge_upload(nbytes)
        self.put(key, arr, nbytes, sweep=sweep)
        return arr

    def tuple_arrays(self, pub: tuple, name: str, tag, build_fn,
                     sweep=None):
        """Cached tuple of device arrays under ONE key (the sharded
        tier's build-phase outputs: bacc + min/max partials) — a repeat
        query skips the build dispatch and its transfer entirely."""
        key = (pub, name, "arr", tag)
        val = self.get(key)
        if val is not None:
            return val
        t0 = time.perf_counter_ns()
        val = tuple(build_fn())
        nbytes = sum(int(a.size * a.dtype.itemsize) for a in val)
        metrics.DEVICE_BYTES.add(nbytes)
        obs_device.note_upload(nbytes, obs_device.value_device_ids(val),
                               time.perf_counter_ns() - t0)
        _charge_upload(nbytes)
        self.put(key, val, nbytes, sweep=sweep)
        return val

    def column_spans(self, provider, pub: tuple, name: str, host_col_fn,
                     spans: list, shard_tag, device=None):
        """Device tiles of one column restricted to a SHARD's row spans
        (round-robin block set — exec/shard.py's partitioning), cached
        by (publication, column, shard spans). The host concat runs only
        on miss; `device` pins the upload to the shard's mesh device."""
        obs_device.note_provider(pub[0], getattr(provider, "name", ""))
        key = (pub, name, "col", ("shard", shard_tag, tuple(spans)))
        dc = self.get(key)
        if dc is not None:
            return dc
        from .shard import _concat_spans
        dc = to_device_column(_concat_spans(host_col_fn(), spans))
        if device is not None:
            # the funnel above attributed the upload to the default
            # device; the pin to the shard's mesh device is a SECOND
            # transfer — account it against the device the tiles
            # actually land on, so sdb_device()'s per-device rows stay
            # consistent with where hbm_bytes_est places the entry
            t0 = time.perf_counter_ns()
            dc = DeviceColumn(dc.type, jax.device_put(dc.data, device),
                              jax.device_put(dc.mask, device), dc.length,
                              dc.scheme, dc.offset, dc.wide)
            obs_device.note_upload(
                int(dc.data.size * dc.data.dtype.itemsize) +
                int(dc.mask.size),
                obs_device.array_device_ids(dc.data),
                time.perf_counter_ns() - t0)
        nbytes = int(dc.data.size * dc.data.dtype.itemsize) + \
            int(dc.mask.size)
        metrics.DEVICE_BYTES.add(nbytes)
        _charge_upload(nbytes)
        self.put(key, dc, nbytes)
        return dc


DEVICE_CACHE = DeviceColumnCache()

#: host-side factorized join-code cache: (pub_l, pub_r, key exprs) →
#: (codes_l, codes_r, g, worst-case pairs). Count- AND byte-bounded
#: (int64 code arrays of large tables are real host memory); the
#: factorize pass is O(n log n) once per publication pair and the
#: pair-count admission check O(n) once — both amortize across repeat
#: queries. Superseded publication pairs are swept on store.
_CODES_CACHE: OrderedDict[tuple, tuple] = OrderedDict()
_CODES_CACHE_MAX = 16
_CODES_CACHE_MAX_BYTES = 256 << 20
_codes_bytes = 0
_codes_lock = threading.Lock()

#: column admission stats, (pub, column) → (all_valid, finite_all, lo,
#: hi) — a pure function of the publication, so cached repeats skip the
#: O(n) host scans. Shared by fused top-N admission and the direct-sum
#: range check.
_COL_STATS_CACHE: OrderedDict[tuple, tuple] = OrderedDict()
_COL_STATS_MAX = 64
_col_stats_lock = threading.Lock()


def clear_codes_cache() -> None:
    """Drop every cached factorization and reset the byte accounting —
    the two must move together or later stores evict against a phantom
    total."""
    global _codes_bytes
    with _codes_lock:
        _CODES_CACHE.clear()
        _codes_bytes = 0


def _rowmask_tiles(nrows: int, pad: Optional[int] = None) -> "jax.Array":
    import jax.numpy as jnp
    n_pad = pad_len(nrows) if pad is None else pad_len(nrows, pad)
    rm = np.zeros(n_pad, dtype=bool)
    rm[:nrows] = True
    return jnp.asarray(rm.reshape(-1, LANES))


# -- pipeline recognition ----------------------------------------------------


def _split_and(e: BoundExpr) -> list[BoundExpr]:
    """Top-level AND conjuncts (a Filter keeps only rows where the whole
    expression is TRUE, so `a AND b` splits losslessly even under
    three-valued logic)."""
    if isinstance(e, BoundFunc) and e.name == "and":
        out: list[BoundExpr] = []
        for a in e.args:
            out.extend(_split_and(a))
        return out
    return [e]


def _unwrap_side(plan):
    """Filter*(Scan) → (scan, [scan-schema-bound predicates]) or None."""
    from .plan import FilterNode, ScanNode
    preds: list[BoundExpr] = []
    node = plan
    while isinstance(node, FilterNode):
        preds.append(node.pred)
        node = node.child
    if type(node) is not ScanNode:
        return None
    if node.filter is not None:
        preds.append(node.filter)
    return node, preds


def _side_of(expr: BoundExpr, nl: int) -> int:
    """0 = probe (left), 1 = build (right); raises when the expression
    reads columns of both join sides (no per-side decomposition)."""
    sides = set()
    for sub in expr.walk():
        if isinstance(sub, BoundColumn):
            sides.add(0 if sub.index < nl else 1)
    if len(sides) > 1:
        raise NotCompilable("expression spans both join sides")
    return sides.pop() if sides else 0


def _check_host_eval_safe(exprs: list[BoundExpr]) -> None:
    from ..sql.binder import _VOLATILE_FUNCS
    unsafe = _VOLATILE_FUNCS | _HOST_EVAL_UNSAFE
    for e in exprs:
        for sub in e.walk():
            if isinstance(sub, BoundFunc) and sub.name in unsafe:
                raise NotCompilable(f"host-evaluated {sub.name}")


class _Side:
    """One join side's publication observation + host access + zone range."""

    def __init__(self, scan, preds: list[BoundExpr], ctx):
        self.scan = scan
        self.preds = preds
        self.provider = scan.provider
        self.pin = self.provider.try_pin()
        self.pub = _pub(self.provider, self.pin)
        try:
            self.nrows = self.pin[0].num_rows if self.pin is not None \
                else self.provider.row_count()
        except NotImplementedError:
            raise NotCompilable("provider without row_count")
        #: per-block scan-conjunct verdicts (the sharded tier combines
        #: them with the shard-to-shard join filter); None when zone
        #: maps could not analyze this side
        self.verdicts = None
        self.zrange = self._zone_range(ctx)

    def host_col(self, name: str) -> Column:
        if self.pin is not None:
            return self.pin[0].column(name)
        return self.provider.host_column(name)

    def _zone_range(self, ctx) -> Optional[tuple[int, int]]:
        """Surviving block envelope under this side's scan conjuncts
        (upload shrink; interior SKIP blocks still upload). (0, 0) when
        everything prunes — the caller short-circuits to the empty
        result the host path would produce from the same verdicts."""
        if not self.preds:
            return None
        from . import zonemap
        block_rows = int(ctx.settings.get("serene_morsel_rows"))
        verdicts = zonemap.block_verdicts(
            self.provider, ctx.settings, self.preds, self.scan.columns,
            block_rows, self.pin)
        if verdicts is None:
            return None
        self.verdicts = verdicts
        lo, hi = zonemap.surviving_range(verdicts, block_rows, self.nrows)
        if hi <= lo:
            return (0, 0)
        if (lo, hi) == (0, self.nrows):
            return None
        n_blocks = len(verdicts)
        lo_b, hi_b = lo // block_rows, (hi + block_rows - 1) // block_rows
        metrics.ZONEMAP_PRUNED.add(n_blocks - (hi_b - lo_b))
        metrics.ZONEMAP_SCANNED.add(hi_b - lo_b)
        if zonemap.verify_enabled(ctx.settings):
            full = self.pin[0] if self.pin is not None else \
                self.provider.full_batch(self.scan.columns)
            full = Batch(list(self.scan.columns),
                         [full.column(c) for c in self.scan.columns])
            spans = [(s, e) for s, e in ((0, lo), (hi, self.nrows))
                     if e > s]
            zonemap.verify_pruned_blocks(
                self.preds, full, spans,
                f"fused pipeline {self.provider.name}")
        return lo, hi

    @property
    def lo(self) -> int:
        return 0 if self.zrange is None else self.zrange[0]

    @property
    def n_live(self) -> int:
        if self.zrange is None:
            return self.nrows
        return self.zrange[1] - self.zrange[0]


# -- fused Scan→Filter→Join→Aggregate ---------------------------------------


#: join kinds the fused tier executes (outer kinds behind
#: serene_device_fused_ext, single-dispatch only)
_JOIN_KINDS = ("inner", "left", "right", "full")

#: DISTINCT is a no-op for these (host _DISTINCT_INVARIANT ∩ _AGG_FUNCS)
_DISTINCT_DROP = {"min", "max"}


def _note_decline(reason: str, ctx, node) -> None:
    obs_device.note_fused_decline(
        reason, profile=getattr(ctx, "profile", None), node_key=id(node))


def _admit_pipeline(node, ctx, decline):
    """Shape recognition + admission walls shared by the aggregate hook
    (try_device_pipeline) and the chained top-N hook. Returns
    (join, probe_side, build_side, post_preds) or None — every None
    taken AFTER the shape is recognizably a join pipeline went through
    `decline` first."""
    from .plan import JoinNode, FilterNode

    settings = ctx.settings
    ext = fused_ext_enabled(settings)
    post_preds: list[BoundExpr] = []
    child = node.child
    while isinstance(child, FilterNode):
        post_preds.extend(_split_and(child.pred))
        child = child.child
    if type(child) is not JoinNode:
        return None
    join = child

    if not join.left_keys:
        return decline("cross_join")
    if join.merge_pairs:
        return decline("merge_pairs")
    if join.kind not in _JOIN_KINDS:
        return decline("join_kind")
    if join.kind != "inner" and not ext:
        return decline("outer_join")
    if join.residual is not None:
        # an inner join's residual is exactly a post-join pair filter;
        # under outer kinds it changes which rows null-extend, which
        # the pre-filter decomposition cannot express
        if not ext or join.kind != "inner":
            return decline("residual")
        post_preds = post_preds + _split_and(join.residual)
    probe_side = _unwrap_side(join.left)
    build_side = _unwrap_side(join.right)
    if probe_side is None or build_side is None:
        return decline("side_shape")
    for spec in node.aggs:
        if spec.func not in _AGG_FUNCS:
            return decline("agg_func")
        if spec.order_by:
            return decline("agg_order_by")
        if spec.distinct and not ext:
            return decline("distinct")
        if spec.filter is not None and not ext:
            return decline("agg_filter")
    pscan = probe_side[0]
    if settings.get("serene_device") == "auto":
        try:
            if pscan.provider.row_count() < \
                    settings.get("serene_device_min_rows"):
                return None
        except NotImplementedError:
            return None
    return join, probe_side, build_side, post_preds


def try_device_pipeline(node, ctx) -> Optional[Batch]:
    """Attempt one-dispatch execution of AggregateNode over an
    equi-join of two scans; None → host path (the parity oracle).
    Every None taken AFTER the shape is recognizably a join pipeline
    records a per-reason decline (obs_device.note_fused_decline) so a
    fallback is diagnosable from EXPLAIN ANALYZE / metrics."""
    settings = ctx.settings
    if settings.get("serene_device") == "cpu" or not fused_enabled(settings):
        return None

    def decline(reason: str) -> None:
        _note_decline(reason, ctx, node)
        return None

    admitted = _admit_pipeline(node, ctx, decline)
    if admitted is None:
        return None
    join, probe_side, build_side, post_preds = admitted
    try:
        return _run_fused(node, join, probe_side, build_side, post_preds,
                          ctx)
    except (NotCompilable, DeviceNarrowingError) as e:
        log.debug("device", f"fused pipeline fell back to CPU: {e}")
        return decline(getattr(e, "reason", "not_compilable"))


def _run_fused(node, join, probe_side, build_side,
               post_preds: list[BoundExpr], ctx, fetch: bool = True):
    """Execute the fused pipeline. fetch=True (default) fetches program
    outputs and finalizes to a host Batch. fetch=False is the chained-
    stage entry: it returns (device_outputs, finalize_ctx) WITHOUT any
    device→host readback, so a downstream fused stage (top-N) can
    consume the accumulators in HBM — the sharded/collective branches
    are skipped in that mode (single dispatch is always bit-identical)."""
    import jax.numpy as jnp

    prof = getattr(ctx, "profile", None)
    from ..obs.trace import current_trace
    trace = current_trace()

    def clock() -> int:
        # always real: the phase stamps feed the unconditional device
        # histogram, not just the prof/trace consumers (a few ns reads
        # per ms-scale offload)
        return time.perf_counter_ns()

    tspan = functools.partial(_trace_span, trace)

    pscan, ppreds = probe_side
    bscan, bpreds = build_side
    nl = len(join.left.names)
    _check_host_eval_safe(list(join.left_keys) + list(join.right_keys))

    t0 = clock()
    probe = _Side(pscan, ppreds, ctx)
    build = _Side(bscan, bpreds, ctx)

    # split the post-join conjuncts by side: a pair filter that reads
    # only probe (build) columns is exactly a probe (build) row filter
    # under an inner join — and under an OUTER join only on the side
    # that never null-extends (a post filter on the null-extended side
    # would drop rows the pre-filter instead turns into new
    # null-extensions, so those decline)
    post_p: list[BoundExpr] = []
    post_b: list[BoundExpr] = []
    for p in post_preds:
        try:
            sd = _side_of(p, nl)
        except NotCompilable:
            raise NotCompilable("post-join predicate spans both sides",
                                "post_pred_cross_side")
        (post_p if sd == 0 else post_b).append(p)
    outer_left = join.kind in ("left", "full")    # probe rows null-extend
    outer_right = join.kind in ("right", "full")  # build rows null-extend
    if outer_left and post_b:
        raise NotCompilable("post filter on null-extended build side",
                            "outer_post_filter")
    if outer_right and post_p:
        raise NotCompilable("post filter on null-extended probe side",
                            "outer_post_filter")

    # group keys: plain probe-side columns, direct-coded (dict codes /
    # small-range ints) — build-side or computed keys fall back
    for g in node.group_exprs:
        if not isinstance(g, BoundColumn) or g.index >= nl:
            raise NotCompilable("group key is not a plain probe column")

    # referenced-column discovery + dictionaries (join-schema namespace:
    # probe scan col i == join col i, build scan col i == join col nl+i;
    # the side is derived from the index, never assumed, so a build-side
    # string column can't pick up the probe column's dictionary)
    dictionaries: dict[int, np.ndarray] = {}
    join_types = list(join.types)

    def note_dicts(exprs):
        for e in exprs:
            for sub in e.walk():
                if isinstance(sub, BoundColumn) and sub.type.is_string:
                    ji = sub.index
                    if ji in dictionaries:
                        continue
                    if ji < nl:
                        col = probe.host_col(pscan.columns[ji])
                    else:
                        col = build.host_col(bscan.columns[ji - nl])
                    if col.dictionary is not None:
                        dictionaries[ji] = col.dictionary

    note_dicts(post_p + post_b + list(node.group_exprs) +
               [s.arg for s in node.aggs if s.arg is not None] +
               [s.filter for s in node.aggs if s.filter is not None])

    # scan-level predicates compile against the scan schema; their input
    # slots translate into the join namespace (probe scan col i == join
    # col i, build scan col i == join col nl + i)
    def compile_scan_preds(side: _Side, shift: int) -> list[DeviceExpr]:
        dicts = {}
        for e in side.preds:
            for sub in e.walk():
                if isinstance(sub, BoundColumn) and sub.type.is_string \
                        and sub.index not in dicts:
                    col = side.host_col(side.scan.columns[sub.index])
                    if col.dictionary is not None:
                        dicts[sub.index] = col.dictionary
        out = []
        for e in side.preds:
            ce = compile_expr(e, side.scan.types, dicts)
            ce.inputs = [i + shift for i in ce.inputs]
            out.append(ce)
        return out

    preds_probe = compile_scan_preds(probe, 0) + \
        [compile_expr(p, join_types, dictionaries) for p in post_p]
    preds_build = compile_scan_preds(build, nl) + \
        [compile_expr(p, join_types, dictionaries) for p in post_b]

    # group-key plans (direct coding; the NULL group takes the last slot)
    key_plans, group_space = _plan_group_keys(node, join_types, probe,
                                              pscan, dictionaries)
    group_mode = bool(node.group_exprs)

    # aggregate plans: (spec, side, compiled arg | None), plus the PR 17
    # sidecars — per-agg FILTER masks (same side as the arg; an extra
    # predicate ANDed into the value-validity mask), count_star FILTER
    # as its own accumulator column on the filter's side, and DISTINCT
    # presence-grid plans over plain probe-side columns
    ext = fused_ext_enabled(ctx.settings)
    agg_plans: list[tuple] = []
    agg_filters: dict[int, DeviceExpr] = {}
    star_filter: dict[int, int] = {}       # si → side of the filter
    distinct_sis: list[int] = []
    for si, spec in enumerate(node.aggs):
        fe = None
        fside = 0
        if spec.filter is not None:
            _check_host_eval_safe([spec.filter])
            fside = _side_of(spec.filter, nl)
            fe = compile_expr(spec.filter, join_types, dictionaries)
        if spec.func == "count_star":
            if fe is not None:
                star_filter[si] = fside
                agg_filters[si] = fe
                agg_plans.append((spec, fside, None))
            else:
                agg_plans.append((spec, 0, None))
            continue
        side = _side_of(spec.arg, nl)
        if fe is not None:
            if fside != side:
                raise NotCompilable(
                    "FILTER predicate on the other join side",
                    "filter_cross_side")
            agg_filters[si] = fe
        t = spec.arg.type
        if spec.distinct and spec.func not in _DISTINCT_DROP:
            distinct_sis.append(si)
        if spec.func in ("sum", "avg"):
            if not t.is_integer:
                raise NotCompilable(f"{spec.func} over {t} (exactness)",
                                    "agg_type")
        elif spec.func in ("min", "max"):
            if not (t.is_integer or
                    t.id in (dt.TypeId.BOOL, dt.TypeId.DATE)):
                # sorted dictionaries give strings a total order on
                # int32 codes: min/max over codes, decode at finalize
                if not (ext and t.is_string and
                        isinstance(spec.arg, BoundColumn) and
                        dictionaries.get(spec.arg.index) is not None):
                    raise NotCompilable(f"{spec.func} over {t}",
                                        "agg_type")
        agg_plans.append((spec, side,
                          compile_expr(spec.arg, join_types, dictionaries)))

    # DISTINCT (count/sum/avg): a (group, value) presence grid over the
    # probe side's direct-coded values — count = nonzero presences per
    # group, sum = Σ value · present recombined host-side in int64.
    # Build-side args have no per-output-row value representation in
    # the probe-phase scatter, so they decline.
    distinct_plans: dict[int, tuple] = {}
    for si in distinct_sis:
        spec, side, ce = agg_plans[si]
        if side != 0 or not isinstance(spec.arg, BoundColumn):
            raise NotCompilable("DISTINCT arg is not a plain probe column",
                                "distinct_arg")
        ji = spec.arg.index
        t = spec.arg.type
        if t.is_string:
            d = dictionaries.get(ji)
            if d is None:
                raise NotCompilable("DISTINCT string without dictionary",
                                    "distinct_arg")
            dkind, lo_v, vspace = "dict", 0, len(d)
        elif t.is_integer or t.id in (dt.TypeId.BOOL, dt.TypeId.DATE):
            _, _, lo_v, hi_v = _col_stats(probe, pscan.columns[ji])
            if lo_v is None:
                raise NotCompilable("DISTINCT value range unknown",
                                    "distinct_space")
            rng = hi_v - lo_v + 1
            if rng > MAX_INT_KEY_RANGE:
                raise NotCompilable("DISTINCT value range too large",
                                    "distinct_space")
            dkind, vspace = "int", rng
        else:
            raise NotCompilable(f"DISTINCT over {t}", "distinct_arg")
        vspace = _pow2_int(max(vspace, 1))  # pow2-bucket the new axis
        if group_space * vspace > MAX_GROUP_PRODUCT:
            raise NotCompilable("DISTINCT presence grid too large",
                                "distinct_space")
        distinct_plans[si] = (dkind, ji, int(lo_v), vspace)
    if prof is not None:
        prof.add_device_ns(id(node), clock() - t0)
    tspan("device_compile", t0)

    # join-key factorization (host, cached per publication pair along
    # with the worst-case pair count: every int32 count/limb scatter in
    # the program is exact below the bound)
    t0 = clock()
    cl, cr, g, total_pairs = _join_codes(join, probe, build)
    if g + 2 > MAX_CODE_SPACE:
        raise NotCompilable("join code space too large")
    # outer kinds add up to one output row per null-extended input row
    # on top of the inner pairs — the scatter-exactness bound covers
    # the worst case of BOTH
    eff_pairs = total_pairs + (probe.nrows if outer_left else 0) + \
        (build.nrows if outer_right else 0)
    if eff_pairs > MAX_PAIRS_EXACT:
        raise NotCompilable(
            f"{eff_pairs} worst-case pairs exceed the exact-scatter "
            f"bound")
    if probe.zrange is not None:
        cl = cl[probe.zrange[0]:probe.zrange[1]]
    if build.zrange is not None:
        cr = cr[build.zrange[0]:build.zrange[1]]

    # direct-sum fast path: a plain-column sum whose |value| bound times
    # the worst-case pair count provably fits int32 skips the 5-column
    # limb decomposition for ONE direct scatter column (sound for every
    # slot the probe phase can read: a gathered code's build dups are
    # counted in total_pairs, so its partial is inside the bound too)
    sum_modes: dict[int, str] = {}
    for si, (spec, side_ix, ce) in enumerate(agg_plans):
        if spec.func not in ("sum", "avg") or ce is None:
            continue
        if si in distinct_plans:
            continue                 # presence-grid path, no value col
        mode = "limb"
        # outer joins weight rows by max(cnt, 1) (probe side) or add
        # the unmatched-build null-group reduction (build side) — the
        # direct bound below only covers inner pair counts, so the
        # affected side rides the always-exact limb decomposition
        outer_forced = (side_ix == 0 and outer_left) or \
            (side_ix == 1 and outer_right)
        arg = spec.arg
        if isinstance(arg, BoundColumn) and not outer_forced:
            if arg.index < nl:
                s_obj, cname = probe, pscan.columns[arg.index]
            else:
                s_obj, cname = build, bscan.columns[arg.index - nl]
            _, _, lo_v, hi_v = _col_stats(s_obj, cname)
            if lo_v is not None and max(abs(lo_v), abs(hi_v)) * \
                    max(total_pairs, 1) < (1 << 31):
                mode = "direct"
        sum_modes[si] = mode
    if prof is not None:
        prof.add_device_ns(id(join), clock() - t0)
    tspan("device_factorize", t0)

    # empty short-circuit: zero output rows only when NEITHER side can
    # null-extend past the empty one; an outer kind whose non-empty
    # side survives would need the null-extension rows, which the
    # synthesized zero accumulators cannot express — decline
    if probe.n_live == 0 or build.n_live == 0:
        empty_ok = (probe.n_live == 0 and build.n_live == 0) or \
            (probe.n_live == 0 and not outer_right) or \
            (build.n_live == 0 and not outer_left)
        if not empty_ok:
            raise NotCompilable("outer join with an empty side",
                                "outer_empty")
        results = _zero_results(agg_plans, group_space, sum_modes,
                                star_filter, distinct_plans)
        return _finalize(node, key_plans, agg_plans, results, probe,
                         pscan, dictionaries, group_space, group_mode,
                         sum_modes, star_filter=star_filter,
                         distinct_plans=distinct_plans)

    #: everything the compiled program's shape depends on besides the
    #: publications/ranges — shared by the single-dispatch and sharded
    #: program cache keys
    shape_sig = (tuple(_expr_key(p) for p in ppreds),
                 tuple(_expr_key(p) for p in bpreds),
                 tuple(_expr_key(p) for p in post_preds),
                 tuple((s.func, _expr_key(s.arg) if s.arg is not None
                        else None, bool(s.distinct),
                        _expr_key(s.filter) if s.filter is not None
                        else None) for s in node.aggs),
                 tuple(_expr_key(gx) for gx in node.group_exprs),
                 tuple(sorted(sum_modes.items())), join.kind)

    # sharded tier: run the same fused program once per probe shard
    # (round-robin block partitions) with the build phase hoisted into
    # one shared dispatch; per-shard integer accumulators combine
    # exactly on host, so results stay bit-identical to shards = 1
    from . import shard as shard_mod
    n_shards = shard_mod.shard_count(ctx.settings)
    block_rows = int(ctx.settings.get("serene_morsel_rows"))
    # outer kinds, FILTER masks and DISTINCT grids run single-dispatch
    # only: per-shard probe partitions would double-count unmatched
    # rows (LEFT's max(cnt,1) weight is not additive across shards) and
    # presence grids don't combine by addition; chained (fetch=False)
    # callers need the outputs of ONE program in HBM
    plain = (join.kind == "inner" and not agg_filters and
             not star_filter and not distinct_plans)
    if fetch and plain and n_shards > 1 and probe.n_live > block_rows:
        return _run_fused_sharded(
            node, join, probe, build, pscan, bscan, nl, preds_probe,
            preds_build, key_plans, group_space, group_mode, agg_plans,
            sum_modes, cl, cr, g, dictionaries, shape_sig, ctx, prof,
            clock, block_rows, n_shards)

    # device environment: columns via the publication-keyed cache
    needed: set[int] = set()
    for ce in preds_probe + preds_build:
        needed.update(ce.inputs)
    for kp in key_plans:
        needed.add(kp[1])
    for spec, side, ce in agg_plans:
        if ce is not None:
            needed.update(ce.inputs)
    for fe in agg_filters.values():
        needed.update(fe.inputs)
    for (_dk, d_ji, _lo, _vs) in distinct_plans.values():
        needed.add(d_ji)
    needed = sorted(needed)

    # pow2 row buckets: every upload (columns, code tiles, row masks)
    # pads to the same per-side bucket, so the traced program shape is a
    # function of the BUCKET, not the exact surviving row count — the
    # extended admission multiplies program axes and O(log rows) buckets
    # keep that product off the recompile-storm detector
    p_pad = _pow2_rows(probe.n_live)
    b_pad = _pow2_rows(build.n_live)
    t0 = clock()
    env_cols = {}
    for ji in needed:
        if ji < nl:
            side, name, zr, pad = probe, pscan.columns[ji], \
                probe.zrange, p_pad
        else:
            side, name, zr, pad = build, bscan.columns[ji - nl], \
                build.zrange, b_pad
        env_cols[ji] = DEVICE_CACHE.column(
            side.provider, side.pub, name,
            (lambda s=side, n=name: s.host_col(n)), zr, pad=pad)

    # code tiles + row masks (sentinels baked in host-side: NULL-key /
    # padding probe rows → g+1, build rows → g; neither ever matches).
    # A codes entry is stale when EITHER side's publication moved: the
    # owner-generation sweep covers this side, the sweep predicate
    # covers entries pinned to an older generation of the partner.
    keyset = (tuple(_expr_key(k) for k in join.left_keys),
              tuple(_expr_key(k) for k in join.right_keys))

    pc_dev = DEVICE_CACHE.array(
        probe.pub, "__codes__",
        (build.pub, keyset, (probe.zrange, "pad", p_pad), "p"),
        lambda: _code_tiles(cl, g + 1, pad=p_pad),
        sweep=_partner_stale_pred(probe.pub, build.pub, "p", keyset))
    bc_dev = DEVICE_CACHE.array(
        build.pub, "__codes__",
        (probe.pub, keyset, (build.zrange, "pad", b_pad), "b"),
        lambda: _code_tiles(cr, g, pad=b_pad),
        sweep=_partner_stale_pred(build.pub, probe.pub, "b", keyset))
    prow = DEVICE_CACHE.array(probe.pub, "__rowmask__",
                              (probe.zrange, "pad", p_pad),
                              lambda: _rowmask_tiles(probe.n_live, p_pad))
    brow = DEVICE_CACHE.array(build.pub, "__rowmask__",
                              (build.zrange, "pad", b_pad),
                              lambda: _rowmask_tiles(build.n_live, b_pad))
    if prof is not None:
        prof.add_device_ns(id(pscan), clock() - t0)
    tspan("device_upload", t0)

    # -- the single program -------------------------------------------------
    decode_specs = [(env_cols[i].scheme, env_cols[i].offset) for i in needed]

    def env_for(ce: DeviceExpr, arrays):
        return [arrays[i] for i in ce.inputs]

    space = g + 2

    # CPU-backend reality: every row-scatter pass costs roughly the same
    # serial walk regardless of target size or column count, so the
    # program accumulates ALL add-reductions of one phase in ONE
    # multi-column scatter — build partials land in a single
    # (code space, C) scatter, probe group accumulators in a single
    # (group space, C) scatter — instead of one scatter per aggregate.
    # Only min/max need their own (non-add) scatter combinator.
    bstart, _bmm_sis = _build_layout(
        agg_plans, sum_modes,
        star_sides={si for si, sd in star_filter.items() if sd == 1})

    def program(*flat):
        arrays = {}
        for k, ji in enumerate(needed):
            data = flat[2 * k]
            scheme, off = decode_specs[k]
            if scheme != "raw":
                data = data.astype(jnp.int32) + jnp.int32(off)
            arrays[ji] = (data, flat[2 * k + 1])
        base = 2 * len(needed)
        bcodes, pcodes = flat[base], flat[base + 1]
        bmask, pmask = flat[base + 2], flat[base + 3]

        # build phase: mask, then per-code partials (one fused scatter;
        # per-column validity gates zero the value, which scatters the
        # same result as masking the index)
        for ce in preds_build:
            v, ok = ce.fn(env_for(ce, arrays))
            b = v if v.dtype == jnp.bool_ else (v != 0)
            bmask = jnp.logical_and(bmask, jnp.logical_and(b, ok))
        bc = jnp.where(bmask, bcodes, jnp.int32(g))
        bcols = [bmask.ravel().astype(jnp.int32)]       # col 0: match count
        bmm: dict[int, "jax.Array"] = {}

        def ftrue(si, base_m):
            """AND the agg's FILTER predicate (TRUE only — SQL drops
            FALSE and NULL alike) into a validity mask."""
            fe = agg_filters.get(si)
            if fe is None:
                return base_m
            fv, fok = fe.fn(env_for(fe, arrays))
            fb = fv if fv.dtype == jnp.bool_ else (fv != 0)
            return jnp.logical_and(base_m, jnp.logical_and(fb, fok))

        for si, (spec, side, ce) in enumerate(agg_plans):
            if spec.func == "count_star":
                if si in star_filter and side == 1:
                    # count_star FILTER on the build side: its own
                    # per-code satisfied-row count
                    m = ftrue(si, bmask)
                    assert bstart[si] == len(bcols)
                    bcols.append(m.ravel().astype(jnp.int32))
                continue
            if side != 1 or ce is None or si in distinct_plans:
                continue
            v, ok = ce.fn(env_for(ce, arrays))
            m = ftrue(si, jnp.logical_and(bmask, ok))
            mi = m.ravel().astype(jnp.int32)
            assert bstart[si] == len(bcols)      # trace-time layout check
            bcols.append(mi)                             # per-agg vcnt
            if spec.func in ("sum", "avg"):
                if sum_modes[si] == "direct":
                    bcols.append(v.astype(jnp.int32).ravel() * mi)
                else:
                    bcols.extend(_limb_cols(
                        v.astype(jnp.int32).ravel(), mi))
            elif spec.func in ("min", "max"):
                bmm[si] = ops_agg.group_min_max(
                    bcodes, m, v.astype(jnp.int32), space, spec.func)
        bacc = jnp.zeros((space, len(bcols)), jnp.int32) \
            .at[bc.ravel()].add(jnp.stack(bcols, axis=1))
        bacc = bacc.at[g].set(0).at[g + 1].set(0)        # sentinel slots

        # probe phase: ONE body shared with the sharded probe programs
        # (_probe_phase) — mask, gather match counts, one fused scatter
        # into the group accumulator
        return _probe_phase(arrays, pcodes, pmask, bacc, bmm,
                            preds_probe, key_plans, group_mode,
                            group_space, agg_plans, sum_modes, bstart, g,
                            join_kind=join.kind, agg_filters=agg_filters,
                            star_filter=star_filter,
                            distinct_plans=distinct_plans,
                            right_ext=((bcodes, bmask) if outer_right
                                       else None))

    # program cache: PUBLICATION-FREE. Every data-dependent constant the
    # trace closes over is keyed explicitly — decode schemes/offsets,
    # key plans (lo offsets), code/group spaces, pow2 row buckets,
    # DISTINCT grids, and the compiled expressions' baked constants
    # (string-comparison code thresholds) via DeviceExpr.consts — so
    # repeat queries across publications/tables reuse ONE executable
    # whenever the traced shape is genuinely identical, instead of
    # recompiling per publication bump
    consts_sig = tuple(ce.consts for ce in preds_probe + preds_build) + \
        tuple(ce.consts for _s, _sd, ce in agg_plans
              if ce is not None) + \
        tuple(agg_filters[si].consts for si in sorted(agg_filters))
    cache_key = ("fused", join.kind, tuple(needed), tuple(decode_specs),
                 space, group_space, tuple(key_plans),
                 tuple(sorted(star_filter.items())),
                 tuple(sorted(distinct_plans.items())),
                 p_pad, b_pad, consts_sig) + shape_sig
    jitted = obs_device.compiled("fused", cache_key, lambda: program,
                                 profile=prof, node_key=id(node))

    flat_args = []
    for ji in needed:
        dc = env_cols[ji]
        flat_args.extend([dc.data, dc.mask])
    flat_args.extend([bc_dev, pc_dev, brow, prow])

    from .plan import check_cancel
    check_cancel()
    t0 = clock()
    metrics.DEVICE_OFFLOADS.add()
    outs = jitted(*flat_args)
    if not fetch:
        # chained handoff: accumulators STAY in HBM — the downstream
        # fused stage consumes them directly; zero device→host bytes
        # move here (the transfer ledger is the proof)
        fin = {"node": node, "key_plans": key_plans,
               "agg_plans": agg_plans, "probe": probe, "pscan": pscan,
               "dictionaries": dictionaries, "group_space": group_space,
               "group_mode": group_mode, "sum_modes": sum_modes,
               "star_filter": star_filter,
               "distinct_plans": distinct_plans,
               "stage1_key": cache_key}
        if prof is not None:
            prof.add_device_ns(id(node), clock() - t0)
        tspan("device_dispatch", t0)
        return outs, fin
    results = obs_device.fetch_all(outs)
    out = _finalize(node, key_plans, agg_plans, results, probe, pscan,
                    dictionaries, group_space, group_mode, sum_modes,
                    star_filter=star_filter,
                    distinct_plans=distinct_plans)
    if prof is not None:
        prof.add_device_ns(id(node), clock() - t0)
    metrics.DEVICE_DISPATCH_HIST.observe_ns(time.perf_counter_ns() - t0)
    tspan("device_dispatch", t0)
    return out


def _build_layout(agg_plans, sum_modes: dict,
                  star_sides=frozenset()) -> tuple[dict, list]:
    """Host-side mirror of the build accumulator's column layout, shared
    by every program shape (single-dispatch and sharded build/probe):
    col 0 = match count; per build-side agg: vcnt, then 1 direct / 5
    limb value columns for sum/avg; min/max partials ride separate
    outputs in ascending-si order. `star_sides` marks count_star aggs
    whose FILTER lives on the build side — each takes one satisfied-row
    count column."""
    bstart: dict[int, int] = {}
    bmm_sis: list[int] = []
    ncols = 1
    for si, (spec, side, ce) in enumerate(agg_plans):
        if spec.func == "count_star":
            if si in star_sides:
                bstart[si] = ncols
                ncols += 1
            continue
        if side != 1 or ce is None:
            continue
        bstart[si] = ncols
        ncols += 1
        if spec.func in ("sum", "avg"):
            ncols += 1 if sum_modes.get(si) == "direct" else 5
        elif spec.func in ("min", "max"):
            bmm_sis.append(si)
    return bstart, bmm_sis


def _probe_phase(arrays, pcodes, pmask, bacc, bmm, preds_probe,
                 key_plans, group_mode: bool, group_space: int,
                 agg_plans, sum_modes: dict, bstart: dict, g: int,
                 join_kind: str = "inner", agg_filters=None,
                 star_filter=None, distinct_plans=None, right_ext=None):
    """THE probe phase, traced into both program shapes — the single
    fused dispatch computes `bacc`/`bmm` in-program, the sharded probe
    programs take them as inputs; one body keeps the two shapes'
    bit-identity contract in one place. Masks rows through the compiled
    probe predicates, gathers per-code build partials, and lands every
    add-reduction in ONE (group space, C) scatter.

    PR 17 extensions (single-dispatch callers only): LEFT/FULL weight
    each surviving probe row by max(matches, 1) so unmatched rows emit
    their null-extended output row; RIGHT/FULL take `right_ext =
    (bcodes, bmask-after-preds)` and reduce the unmatched build rows
    into the all-NULL-key group slot; per-agg FILTER masks AND into
    value validity; DISTINCT plans scatter a (group × value) presence
    grid each."""
    import jax.numpy as jnp

    agg_filters = agg_filters or {}
    star_filter = star_filter or {}
    distinct_plans = distinct_plans or {}
    outer_left = join_kind in ("left", "full")

    cnt_code = bacc[:, 0]
    for ce in preds_probe:
        v, ok = ce.fn([arrays[i] for i in ce.inputs])
        b = v if v.dtype == jnp.bool_ else (v != 0)
        pmask = jnp.logical_and(pmask, jnp.logical_and(b, ok))
    pc = jnp.where(pmask, pcodes, jnp.int32(g + 1))
    cnt = cnt_code[pc]                       # matches per probe row
    # output rows per surviving probe row: LEFT/FULL keep unmatched
    # probe rows as one null-extended row each
    w = jnp.maximum(cnt, 1) if outer_left else cnt

    if group_mode:
        gcodes = jnp.zeros_like(pc)
        for kind, ji, lo_v, size in key_plans:
            data, ok = arrays[ji]
            if kind == "dict":
                c = data.astype(jnp.int32)
            else:
                c = data.astype(jnp.int32) - jnp.int32(lo_v)
            c = jnp.where(ok, c, jnp.int32(size - 1))
            gcodes = gcodes * jnp.int32(size) + jnp.clip(c, 0, size - 1)
    else:
        gcodes = jnp.zeros_like(pc)
    gc = jnp.where(pmask, gcodes, 0).ravel()
    pmi = pmask.ravel().astype(jnp.int32)

    def ftrue(si, base_m):
        """AND the agg's FILTER predicate (TRUE only) into a mask."""
        fe = agg_filters.get(si)
        if fe is None:
            return base_m
        fv, fok = fe.fn([arrays[i] for i in fe.inputs])
        fb = fv if fv.dtype == jnp.bool_ else (fv != 0)
        return jnp.logical_and(base_m, jnp.logical_and(fb, fok))

    pcols = [jnp.where(pmask, w, 0).ravel()]         # col 0: output rows
    pstart: dict[int, int] = {}
    pmm: dict[int, "jax.Array"] = {}
    grids: dict[int, "jax.Array"] = {}
    for si, (spec, side, ce) in enumerate(agg_plans):
        if spec.func == "count_star":
            if si not in star_filter:
                continue                     # shared output-row counts
            pstart[si] = len(pcols)
            if side == 0:
                m = ftrue(si, pmask)
                pcols.append(jnp.where(m, w, 0).ravel())
            else:
                vcnt = bacc[:, bstart[si]]
                pcols.append(jnp.where(pmask, vcnt[pc], 0).ravel())
            continue
        if si in distinct_plans:
            # presence grid: one cell per (group, value); host counts /
            # sums the present cells exactly
            dkind, ji, lo_v, vspace = distinct_plans[si]
            data, ok = arrays[ji]
            if dkind == "dict":
                c = data.astype(jnp.int32)
            else:
                c = data.astype(jnp.int32) - jnp.int32(lo_v)
            m = ftrue(si, jnp.logical_and(pmask, ok))
            m = jnp.logical_and(m, w > 0)
            cell = gcodes * jnp.int32(vspace) + jnp.clip(c, 0, vspace - 1)
            cell = jnp.where(m, cell, 0).ravel()
            grids[si] = jnp.zeros(group_space * vspace, jnp.int32) \
                .at[cell].add(m.ravel().astype(jnp.int32))
            continue
        if side == 0:
            v, ok = ce.fn([arrays[i] for i in ce.inputs])
            m = ftrue(si, jnp.logical_and(pmask, ok))
            vpairs = jnp.where(m, w, 0).ravel()
            pstart[si] = len(pcols)
            if spec.func == "count":
                pcols.append(vpairs)
            elif spec.func in ("sum", "avg"):
                if sum_modes[si] == "direct":
                    pcols.append(v.astype(jnp.int32).ravel() * vpairs)
                else:
                    pcols.extend(_limb_cols(
                        v.astype(jnp.int32).ravel(), vpairs))
                pcols.append(vpairs)
            else:   # min / max — a selection; pairs only gate entry
                pmm[si] = ops_agg.group_min_max(
                    gcodes, jnp.logical_and(m, w > 0),
                    v.astype(jnp.int32), group_space, spec.func)
                pcols.append(vpairs)
        else:
            vcnt = bacc[:, bstart[si]]
            gathered_cnt = jnp.where(pmask, vcnt[pc], 0).ravel()
            pstart[si] = len(pcols)
            if spec.func == "count":
                pcols.append(gathered_cnt)
            elif spec.func in ("sum", "avg"):
                if sum_modes[si] == "direct":
                    partial = bacc[:, bstart[si] + 1]
                    pcols.append(
                        jnp.where(pmask, partial[pc], 0).ravel())
                else:
                    lim = bacc[:, bstart[si] + 1:
                               bstart[si] + 6][pc.ravel()]
                    lim = lim * pmi[:, None]           # (n, 5)
                    pcols.extend([lim[:, j] for j in range(5)])
                pcols.append(gathered_cnt)
            else:
                mmv = bmm[si][pc]
                m2 = jnp.logical_and(pmask, vcnt[pc] > 0)
                pmm[si] = ops_agg.group_min_max(
                    gcodes, m2, mmv, group_space, spec.func)
                pcols.append(gathered_cnt)
    acc = jnp.zeros((group_space, len(pcols)), jnp.int32) \
        .at[gc].add(jnp.stack(pcols, axis=1))

    if right_ext is not None:
        # RIGHT/FULL: build rows surviving the build predicates whose
        # code matches ZERO surviving probe rows null-extend — their
        # probe side is all NULL, so every reduction lands in the
        # all-NULL composite group slot (SQL groups NULLs together, so
        # colliding with a real all-NULL-key probe group is correct)
        bcodes_r, bmask_r = right_ext
        bc_r = jnp.where(bmask_r, bcodes_r, jnp.int32(g)).ravel()
        pcc = jnp.zeros(g + 2, jnp.int32).at[pc.ravel()].add(pmi)
        # pcc[g] == 0 always (probe codes are < g or the g+1 sentinel),
        # so NULL-key build rows — host-rewritten to g — count as
        # unmatched here exactly as the oracle's NULL-never-matches rule
        ub = jnp.logical_and(bmask_r.ravel(), pcc[bc_r] == 0)
        null_gc = group_space - 1 if group_mode else 0
        acc = acc.at[null_gc, 0].add(
            jnp.sum(ub, dtype=jnp.int32))
        for si, (spec, side, ce) in enumerate(agg_plans):
            if spec.func == "count_star":
                if si in star_filter and side == 1:
                    m = ftrue(si, bmask_r)
                    mu = jnp.logical_and(m.ravel(), ub)
                    acc = acc.at[null_gc, pstart[si]].add(
                        jnp.sum(mu, dtype=jnp.int32))
                continue
            if side != 1 or si in distinct_plans:
                continue   # null-extended probe values aggregate to none
            v, ok = ce.fn([arrays[i] for i in ce.inputs])
            m = ftrue(si, jnp.logical_and(bmask_r, ok))
            mu = jnp.logical_and(m.ravel(), ub)
            mui = mu.astype(jnp.int32)
            nmu = jnp.sum(mui, dtype=jnp.int32)
            start = pstart[si]
            if spec.func == "count":
                acc = acc.at[null_gc, start].add(nmu)
            elif spec.func in ("sum", "avg"):
                # sum_modes forces limb for build-side sums under
                # RIGHT/FULL, so the layout here is always 5 limbs + cnt
                for j, lcol in enumerate(_limb_cols(
                        v.astype(jnp.int32).ravel(), mui)):
                    acc = acc.at[null_gc, start + j].add(
                        jnp.sum(lcol, dtype=jnp.int32))
                acc = acc.at[null_gc, start + 5].add(nmu)
            else:       # min / max
                ident = jnp.int32(_mm_ident(spec.func))
                red = jnp.where(mu, v.astype(jnp.int32).ravel(), ident)
                red = jnp.min(red) if spec.func == "min" else jnp.max(red)
                upd = pmm[si].at[null_gc]
                pmm[si] = upd.min(red) if spec.func == "min" \
                    else upd.max(red)
                acc = acc.at[null_gc, start].add(nmu)

    # slice the fused accumulator back into the per-agg output spec
    # (bit-identical to the one-scatter-per-aggregate layout)
    outputs = [acc[:, 0]]
    for si, (spec, side, ce) in enumerate(agg_plans):
        if spec.func == "count_star":
            if si in star_filter:
                outputs.append(acc[:, pstart[si]])
            continue
        if si in distinct_plans:
            outputs.append(grids[si])
            continue
        start = pstart[si]
        if spec.func == "count":
            outputs.append(acc[:, start])
        elif spec.func in ("sum", "avg"):
            if sum_modes[si] == "direct":
                outputs.append(acc[:, start])
                outputs.append(acc[:, start + 1])
            else:
                outputs.append(acc[:, start:start + 5])
                outputs.append(acc[:, start + 5])
        else:
            outputs.append(pmm[si])
            outputs.append(acc[:, start])
    return tuple(outputs)


def _partner_stale_pred(owner_pub, partner_pub, side_tag, keyset,
                        name="__codes__"):
    """Sweep predicate for entries pinned to an older generation of the
    PARTNER table (whose publication the owner-side generation sweep
    cannot see): code tiles and the sharded tier's cached build-phase
    outputs both embed the partner publication at tag position 0."""
    def pred(k):
        return (k[0][0] == owner_pub[0] and k[1] == name and
                isinstance(k[3], tuple) and len(k[3]) >= 4 and
                k[3][3] == side_tag and k[3][1] == keyset and
                isinstance(k[3][0], tuple) and
                k[3][0][0] == partner_pub[0] and k[3][0] != partner_pub)
    return pred


# -- sharded fused execution (serene_shards > 1) ----------------------------
#
# The same fused program over hash-partitioned probe data (PAPER.md §8):
# the probe side's surviving blocks split round-robin into shards, the
# build phase runs ONCE as its own dispatch, and each shard's probe
# phase dispatches over only its block set — pinned across
# jax.devices() via parallel/mesh.shard_devices when a multi-device
# mesh is present, fanned out as concurrent pool tasks either way. All
# accumulators are int32 adds / min-max selections over disjoint row
# sets, so the host-side combine (int64 sums, elementwise min/max) is
# exact and the result is bit-identical to the shards=1 single
# dispatch. The build side additionally publishes PER-SHARD key min/max
# (shard-to-shard join filter): probe blocks outside every build
# shard's range never upload at all.

#: per-(build publication, keyset) cache of the published shard ranges,
#: so repeat queries skip the O(n) build-key min/max scans
_SHARD_RANGES_CACHE: OrderedDict[tuple, object] = OrderedDict()
_SHARD_RANGES_MAX = 32
_shard_ranges_lock = threading.Lock()


def _shard_build_ranges(join, build: _Side, n_shards: int,
                        block_rows: int):
    """The build side's per-shard key ranges (exec/shard.ShardedRanges)
    or None when no shard publishes a rangeable key / key eval must
    fall back. Cached per build publication — pure function of it."""
    from . import shard as shard_mod
    keyset = (tuple(_expr_key(k) for k in join.left_keys),
              tuple(_expr_key(k) for k in join.right_keys))
    ck = (build.pub, keyset, n_shards, block_rows)
    with _shard_ranges_lock:
        if ck in _SHARD_RANGES_CACHE:
            _SHARD_RANGES_CACHE.move_to_end(ck)
            return _SHARD_RANGES_CACHE[ck]
    bbatch = build.pin[0] if build.pin is not None \
        else build.provider.full_batch(build.scan.columns)
    bbatch = Batch(list(build.scan.columns),
                   [bbatch.column(c) for c in build.scan.columns])
    try:
        rkeys = [k.eval(bbatch) for k in join.right_keys]
        groups = shard_mod.build_shard_ranges(
            join.left_keys, rkeys,
            build.provider.shard_view(n_shards, block_rows,
                                      bbatch.num_rows))
    except Exception:
        # key eval over unfiltered rows may legitimately raise (the
        # host path evaluates keys only over surviving rows) — then no
        # shard filter, never an error
        groups = None
    with _shard_ranges_lock:
        while len(_SHARD_RANGES_CACHE) >= _SHARD_RANGES_MAX:
            _SHARD_RANGES_CACHE.popitem(last=False)
        _SHARD_RANGES_CACHE[ck] = groups
    return groups


def _sum_i64(arrs) -> np.ndarray:
    out = np.asarray(arrs[0]).astype(np.int64)
    for a in arrs[1:]:
        out = out + np.asarray(a).astype(np.int64)
    return out


def _combine_shard_results(agg_plans, sum_modes: dict,
                           shard_outs: list[list]) -> list:
    """Exact cross-shard combine of per-shard program outputs into the
    single-dispatch output spec _finalize consumes: counts/sums add in
    int64 (limb columns stack to (C, G, 5) — combine_sum_int_limbs
    recombines chunked), min/max reduce elementwise. Integer addition
    over disjoint row sets is associative, so the combined accumulators
    equal the shards=1 dispatch bit for bit."""
    per_slot = list(zip(*shard_outs))
    out: list = [_sum_i64(per_slot[0])]
    slot = 1
    for si, (spec, _side, _ce) in enumerate(agg_plans):
        if spec.func == "count_star":
            continue
        if spec.func == "count":
            out.append(_sum_i64(per_slot[slot]))
            slot += 1
        elif spec.func in ("sum", "avg"):
            if sum_modes[si] == "direct":
                out.append(_sum_i64(per_slot[slot]))
            else:
                out.append(np.stack([np.asarray(r)
                                     for r in per_slot[slot]]))
            slot += 1
            out.append(_sum_i64(per_slot[slot]))
            slot += 1
        else:                              # min / max
            red = np.minimum.reduce if spec.func == "min" \
                else np.maximum.reduce
            out.append(red([np.asarray(m) for m in per_slot[slot]]))
            slot += 1
            out.append(_sum_i64(per_slot[slot]))
            slot += 1
    return out


def _run_fused_sharded(node, join, probe: _Side, build: _Side, pscan,
                       bscan, nl: int, preds_probe, preds_build,
                       key_plans, group_space: int, group_mode: bool,
                       agg_plans, sum_modes: dict, cl: np.ndarray,
                       cr: np.ndarray, g: int, dictionaries,
                       shape_sig: tuple, ctx, prof, clock, block_rows: int,
                       n_shards: int) -> Batch:
    import jax.numpy as jnp

    from . import shard as shard_mod
    from . import zonemap
    from ..parallel import mesh as mesh_mod
    from .plan import check_cancel

    settings = ctx.settings
    from ..obs.trace import current_trace
    trace = current_trace()
    tspan = functools.partial(_trace_span, trace)

    keyset = (tuple(_expr_key(k) for k in join.left_keys),
              tuple(_expr_key(k) for k in join.right_keys))
    space = g + 2
    plo, phi = probe.lo, probe.lo + probe.n_live

    # -- shard-to-shard join filter: per-build-shard key ranges prune
    # probe blocks (and their uploads) before any transfer
    t0 = clock()
    groups = _shard_build_ranges(join, build, n_shards, block_rows)
    v_shard = None
    if groups is not None:
        v_shard = shard_mod.sharded_verdicts(
            probe.provider, settings, groups, pscan.columns, block_rows,
            probe.pin)
    verdicts = zonemap.combine_verdicts(probe.verdicts, v_shard)

    needed_p = sorted(
        {i for ce in preds_probe for i in ce.inputs} |
        {kp[1] for kp in key_plans} |
        {i for _spec, side, ce in agg_plans
         if ce is not None and side == 0 for i in ce.inputs})
    needed_b = sorted(
        {i for ce in preds_build for i in ce.inputs} |
        {i for _spec, side, ce in agg_plans
         if ce is not None and side == 1 for i in ce.inputs})

    if v_shard is not None:
        # 4 bytes of code tile + 1 mask byte per needed column ride on
        # every uploaded probe row; count what per-shard pruning saved
        nbytes_row = 4 + sum(
            int(probe.host_col(pscan.columns[ji]).data.dtype.itemsize) + 1
            for ji in needed_p)
        shard_mod.count_shard_pruned(v_shard, nbytes_row, block_rows,
                                     probe.nrows)
        if zonemap.verify_enabled(settings) and \
                (v_shard == zonemap.SKIP).any():
            full = probe.pin[0] if probe.pin is not None else \
                probe.provider.full_batch(pscan.columns)
            full = Batch(list(pscan.columns),
                         [full.column(c) for c in pscan.columns])
            spans = [(int(b) * block_rows,
                      min((int(b) + 1) * block_rows, probe.nrows))
                     for b in np.flatnonzero(v_shard == zonemap.SKIP)]
            shard_mod.verify_sharded_pruned(
                groups, full, spans,
                f"fused shard filter {probe.provider.name}")

    n_blocks = (probe.nrows + block_rows - 1) // block_rows
    if verdicts is None:
        alive = [b for b in range(n_blocks)
                 if b * block_rows < phi and (b + 1) * block_rows > plo]
    else:
        alive = [int(b) for b in np.flatnonzero(verdicts != zonemap.SKIP)
                 if int(b) * block_rows < phi and
                 int(b) * block_rows >= plo]
    per_shard: dict[int, list[tuple[int, int]]] = {}
    for b in alive:
        s = shard_mod.shard_of_block(b, n_shards)
        per_shard.setdefault(s, []).append(
            (b * block_rows, min((b + 1) * block_rows, probe.nrows)))
    shard_ids = sorted(per_shard)
    pruned = int((v_shard == zonemap.SKIP).sum()) \
        if v_shard is not None else 0
    if not shard_ids:
        # zero pipelines actually ran — the Shards: line still renders
        # the pruning that short-circuited them
        shard_mod.stamp_profile(ctx, id(node), 0, pruned)
        results = _zero_results(agg_plans, group_space, sum_modes)
        return _finalize(node, key_plans, agg_plans, results, probe,
                         pscan, dictionaries, group_space, group_mode,
                         sum_modes)

    # -- build phase: ONE dispatch, outputs publication-cached ------------
    bstart, bmm_sis = _build_layout(agg_plans, sum_modes)

    # the build dispatch runs at most once per query (memoized closure)
    # and its outputs cache per (publication pair, device) — a repeat
    # query skips the build phase and its transfer entirely, leaving
    # only the per-shard probe dispatches
    build_state: dict = {}
    build_mu = threading.Lock()

    def _build_dispatch():
        with build_mu:
            if "v" in build_state:
                return build_state["v"]
            tb = clock()
            env_b = {}
            for ji in needed_b:
                name = bscan.columns[ji - nl]
                env_b[ji] = DEVICE_CACHE.column(
                    build.provider, build.pub, name,
                    (lambda s=build, n2=name: s.host_col(n2)),
                    build.zrange)
            bc_dev = DEVICE_CACHE.array(
                build.pub, "__codes__",
                (probe.pub, keyset, build.zrange, "b"),
                lambda: _code_tiles(cr, g),
                sweep=_partner_stale_pred(build.pub, probe.pub, "b",
                                          keyset))
            brow = DEVICE_CACHE.array(
                build.pub, "__rowmask__", (build.zrange,),
                lambda: _rowmask_tiles(build.n_live))
            jitted_b = _build_program(env_b)
            flat_b = []
            for ji in needed_b:
                dc = env_b[ji]
                flat_b.extend([dc.data, dc.mask])
            flat_b.extend([bc_dev, brow])
            check_cancel()
            metrics.DEVICE_OFFLOADS.add()
            outs = jitted_b(*flat_b)
            if prof is not None:
                prof.add_device_ns(id(join), clock() - tb)
            metrics.DEVICE_DISPATCH_HIST.observe_ns(
                time.perf_counter_ns() - tb)
            tspan("device_dispatch", tb, phase="build")
            build_state["v"] = outs
            return outs

    def _build_program(env_b):
        decode_b = [(env_b[i].scheme, env_b[i].offset) for i in needed_b]
        bkey = ("fshardb", probe.pub, build.pub, build.zrange,
                keyset) + shape_sig

        def build_program(*flat):
            arrays = {}
            for k2, ji in enumerate(needed_b):
                data = flat[2 * k2]
                scheme, off = decode_b[k2]
                if scheme != "raw":
                    data = data.astype(jnp.int32) + jnp.int32(off)
                arrays[ji] = (data, flat[2 * k2 + 1])
            base = 2 * len(needed_b)
            bcodes, bmask = flat[base], flat[base + 1]
            for ce in preds_build:
                v, ok = ce.fn([arrays[i] for i in ce.inputs])
                bb = v if v.dtype == jnp.bool_ else (v != 0)
                bmask = jnp.logical_and(bmask, jnp.logical_and(bb, ok))
            bc = jnp.where(bmask, bcodes, jnp.int32(g))
            bcols = [bmask.ravel().astype(jnp.int32)]
            bmm_out = []
            for si, (spec, side, ce) in enumerate(agg_plans):
                if side != 1 or ce is None:
                    continue
                v, ok = ce.fn([arrays[i] for i in ce.inputs])
                m = jnp.logical_and(bmask, ok)
                mi = m.ravel().astype(jnp.int32)
                bcols.append(mi)
                if spec.func in ("sum", "avg"):
                    if sum_modes[si] == "direct":
                        bcols.append(v.astype(jnp.int32).ravel() * mi)
                    else:
                        bcols.extend(_limb_cols(
                            v.astype(jnp.int32).ravel(), mi))
                elif spec.func in ("min", "max"):
                    bmm_out.append(ops_agg.group_min_max(
                        bcodes, m, v.astype(jnp.int32), space, spec.func))
            bacc = jnp.zeros((space, len(bcols)), jnp.int32) \
                .at[bc.ravel()].add(jnp.stack(bcols, axis=1))
            bacc = bacc.at[g].set(0).at[g + 1].set(0)
            return (bacc, *bmm_out)

        return obs_device.compiled("fused_build", bkey,
                                   lambda: build_program,
                                   profile=prof, node_key=id(node))

    # -- probe phase: one dispatch per shard, pinned across the mesh ------
    devs = mesh_mod.shard_devices(n_shards)

    def _build_outs_for(device, dev_tag: str):
        """The build outputs committed to one shard device, via the
        publication-keyed cache (tag position 0/1/3 match the partner
        sweep predicate)."""
        def make():
            outs = _build_dispatch()
            if device is not None:
                outs = tuple(jax.device_put(o, device) for o in outs)
            return outs
        return DEVICE_CACHE.tuple_arrays(
            build.pub, "__bacc__",
            (probe.pub, keyset, (build.zrange, shape_sig), dev_tag),
            make,
            sweep=_partner_stale_pred(build.pub, probe.pub, dev_tag,
                                      keyset, name="__bacc__"))

    # in-program cross-shard combine (serene_shard_combine=device): the
    # sharded probe executes as ONE shard_map-partitioned dispatch with
    # psum/pmin/pmax collectives reducing the integer accumulators in
    # HBM — the host sees only the final combined result. The build
    # outputs ride the SAME publication cache (mesh-replicated), so the
    # steady state is exactly one dispatch; a cold cache adds only the
    # one build dispatch, never the N per-shard probes.
    if shard_mod.combine_mode(settings) == "device":
        return _run_fused_collective(
            node, probe, build, pscan, preds_probe,
            key_plans, group_space, group_mode, agg_plans, sum_modes,
            cl, g, dictionaries, shape_sig, ctx, prof, clock,
            per_shard, shard_ids, pruned, keyset, needed_p,
            _build_outs_for, bstart, bmm_sis, tspan)

    def run_shard(s: int) -> list[np.ndarray]:
        check_cancel()
        t_up = time.perf_counter_ns() if trace is not None else 0
        device = devs[s % len(devs)] if devs else None
        spans = per_shard[s]
        spans_t = tuple(spans)
        stag = (n_shards, s)
        env_p = {}
        for ji in needed_p:
            name = pscan.columns[ji]
            env_p[ji] = DEVICE_CACHE.column_spans(
                probe.provider, probe.pub, name,
                (lambda sd=probe, n2=name: sd.host_col(n2)), spans,
                stag, device)
        side_tag = f"ps{n_shards}.{s}"
        pc_dev = DEVICE_CACHE.array(
            probe.pub, "__codes__", (build.pub, keyset, spans_t, side_tag),
            lambda: _code_tiles(
                np.concatenate([cl[a - plo:b - plo] for a, b in spans]),
                g + 1),
            sweep=_partner_stale_pred(probe.pub, build.pub, side_tag,
                                      keyset),
            device=device)
        n_live_s = sum(b - a for a, b in spans)
        prow = DEVICE_CACHE.array(
            probe.pub, "__rowmask__", (spans_t, stag),
            lambda: _rowmask_tiles(n_live_s), device=device)

        decode_p = [(env_p[i].scheme, env_p[i].offset) for i in needed_p]
        pkey = ("fshardp", probe.pub, build.pub, spans_t, stag,
                keyset) + shape_sig

        def probe_program(*flat):
            arrays = {}
            for k2, ji in enumerate(needed_p):
                data = flat[2 * k2]
                scheme, off = decode_p[k2]
                if scheme != "raw":
                    data = data.astype(jnp.int32) + jnp.int32(off)
                arrays[ji] = (data, flat[2 * k2 + 1])
            base = 2 * len(needed_p)
            pcodes, pmask = flat[base], flat[base + 1]
            bacc = flat[base + 2]
            bmm = {si: flat[base + 3 + j]
                   for j, si in enumerate(bmm_sis)}
            # ONE probe-phase body shared with the single-dispatch
            # program — the bit-identity contract lives in one place
            return _probe_phase(arrays, pcodes, pmask, bacc, bmm,
                                preds_probe, key_plans, group_mode,
                                group_space, agg_plans, sum_modes,
                                bstart, g)

        jitted_p = obs_device.compiled("fused_probe", pkey,
                                       lambda: probe_program,
                                       profile=prof, node_key=id(node))

        # cache the committed build outputs per PHYSICAL device (two
        # shards mapped onto one device share a single copy)
        dev_tag = f"bacc{device.id}" if device is not None else "bacc"
        bouts = _build_outs_for(device, dev_tag)
        flat = []
        for ji in needed_p:
            dc = env_p[ji]
            flat.extend([dc.data, dc.mask])
        flat.extend([pc_dev, prow])
        flat.extend(bouts)
        metrics.DEVICE_OFFLOADS.add()
        tspan("device_upload", t_up, shard=s)
        t_d = time.perf_counter_ns()
        outs = obs_device.fetch_all(jitted_p(*flat))
        metrics.DEVICE_DISPATCH_HIST.observe_ns(
            time.perf_counter_ns() - t_d)
        tspan("device_dispatch", t_d, shard=s)
        return outs

    shard_outs = shard_mod.run_shard_tasks(settings, run_shard, shard_ids)
    results = _combine_shard_results(agg_plans, sum_modes, shard_outs)
    shard_mod.stamp_profile(ctx, id(node), len(shard_ids), pruned)
    out = _finalize(node, key_plans, agg_plans, results, probe, pscan,
                    dictionaries, group_space, group_mode, sum_modes)
    if prof is not None:
        prof.add_device_ns(id(node), clock() - t0)
    return out


# -- in-program collective combine (serene_shard_combine=device) ------------
#
# The sharded fused join/aggregate as ONE shard_map-partitioned program
# over the parallel/mesh.py data axis: the surviving shard spans'
# tiles concatenate and split evenly across a leading mesh axis
# committed with a NamedSharding (the ragged tail pads with masked
# rows that never count — integer adds and min/max selections are
# exact over ANY row partition, so balanced re-slicing keeps
# bit-identity), the publication-cached build outputs enter
# mesh-REPLICATED, and the cross-shard reduction happens IN HBM —
# every probe-phase group accumulator reduces with a psum/pmin/pmax
# round before the (replicated) outputs return. The single dispatch is
# bit-identical to both the per-shard host combine and the shards=1
# program. Replaces PR 9's N probe dispatches + the numpy combine with
# ONE dispatch whose output is already the global answer (the build
# dispatch runs only on a publication-cache miss, exactly as in the
# host-combine path).


def _collective_out_kinds(agg_plans) -> list[str]:
    """Per-output cross-shard combine kinds mirroring _probe_phase's
    output order (the device_agg._out_combines sibling): every add
    accumulator psums (limb and direct sums alike — both are int32
    adds), min/max partials pmin/pmax."""
    kinds = ["sum"]                          # pair counts
    for si, (spec, _side, _ce) in enumerate(agg_plans):
        if spec.func == "count_star":
            continue
        if spec.func == "count":
            kinds.append("sum")
        elif spec.func in ("sum", "avg"):
            kinds.extend(["sum", "sum"])     # value (limb/direct) + vcnt
        else:
            kinds.extend([spec.func, "sum"])  # mm partial + vcnt
    return kinds


def _run_fused_collective(node, probe: _Side, build: _Side, pscan,
                          preds_probe,
                          key_plans, group_space: int, group_mode: bool,
                          agg_plans, sum_modes: dict, cl: np.ndarray,
                          g: int, dictionaries,
                          shape_sig: tuple, ctx, prof, clock,
                          per_shard: dict, shard_ids: list,
                          pruned: int, keyset, needed_p,
                          build_outs_for, bstart: dict, bmm_sis: list,
                          tspan) -> Batch:
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..columnar.device import host_tile_arrays
    from ..parallel import mesh as mesh_mod
    from . import shard as shard_mod
    from .plan import check_cancel
    from .shard import _concat_spans

    plo = probe.lo
    S = len(shard_ids)
    mesh = mesh_mod.data_mesh(S)
    M = mesh.shape[mesh_mod.AXIS]
    # the surviving shard spans concatenate (ascending shard, ascending
    # span — deterministic) and split EVENLY across the mesh axis:
    # integer adds and min/max selections are exact over ANY row
    # partition, so re-slicing for balance keeps bit-identity while
    # ragged shards cost < M·BLOCK_ROWS padding rows instead of padding
    # every shard to the widest one
    all_spans = [sp for s in shard_ids for sp in per_shard[s]]
    n_rows = sum(e - a for a, e in all_spans)
    t_slice = pad_len(-(-n_rows // M)) // LANES   # tiles per mesh slice
    rows_pad = M * t_slice * LANES
    spans_sig = tuple((s, tuple(per_shard[s])) for s in shard_ids)
    stack_tag = ("collstack", spans_sig, M, t_slice)
    sh3 = mesh_mod.data_sharding(mesh, 3)

    # -- shard-sharded inputs, publication-cached -------------------------
    t0 = clock()

    def _for_spec(ji: int) -> tuple[str, int]:
        """Frame-of-reference scheme for one stacked column, decided
        ONCE from whole-column stats (cached per publication) so every
        mesh slice encodes with the same offset — range-fitting int
        tiles ship as uint8/uint16 deltas and decode in-kernel, the
        to_device_column compression restated for the stacked layout.
        Eligibility comes from the SCAN SCHEMA (dictionary strings ride
        int32 codes; never materializes a host column — _col_stats is
        publication-cached, so the warm path stays zero-host-work)."""
        t = pscan.types[ji]
        if t.is_string:
            kind, size = "i", 4              # dictionary codes
        else:
            try:
                nd = np.dtype(t.np_dtype)
            except Exception:  # pragma: no cover — exotic type ⇒ raw
                return "raw", 0
            kind, size = nd.kind, nd.itemsize
        if kind != "i" or size <= 1:
            return "raw", 0
        try:
            _av, _fin, lo_v, hi_v = _col_stats(probe, pscan.columns[ji])
        except Exception:  # noqa: BLE001 — unstatable column ⇒ raw
            return "raw", 0
        if lo_v is None or not (-2**31 <= lo_v and hi_v < 2**31):
            return "raw", 0
        rng = hi_v - lo_v
        if rng < (1 << 8):
            return "for8", lo_v
        if rng < (1 << 16):
            return "for16", lo_v
        return "raw", 0

    decode_p = {ji: _for_spec(ji) for ji in needed_p}

    def _stack_probe_col(name: str, scheme: str, offset: int):
        def mk():
            d2, m2 = host_tile_arrays(
                _concat_spans(probe.host_col(name), all_spans), rows_pad,
                scheme, offset)
            return (jax.device_put(
                        d2.reshape(M, t_slice, LANES), sh3),
                    jax.device_put(
                        m2.reshape(M, t_slice, LANES), sh3))
        return DEVICE_CACHE.tuple_arrays(probe.pub, name, stack_tag, mk)

    env_p = {ji: _stack_probe_col(pscan.columns[ji], *decode_p[ji])
             for ji in needed_p}

    def _stack_codes():
        padded = np.full(rows_pad, g + 1, dtype=np.int32)
        rows = np.concatenate(
            [cl[a - plo:b - plo] for a, b in all_spans])
        padded[:len(rows)] = rows
        return jax.device_put(padded.reshape(M, t_slice, LANES), sh3)

    pc_dev = DEVICE_CACHE.array(
        probe.pub, "__codes__", (build.pub, keyset, stack_tag, "pcoll"),
        _stack_codes,
        sweep=_partner_stale_pred(probe.pub, build.pub, "pcoll", keyset))

    def _stack_rowmask():
        m = np.zeros(rows_pad, dtype=bool)
        m[:n_rows] = True
        return jax.device_put(m.reshape(M, t_slice, LANES), sh3)

    prow = DEVICE_CACHE.array(probe.pub, "__rowmask__", stack_tag,
                              _stack_rowmask)

    # build outputs: the SAME publication-cached dispatch products the
    # host-combine path consumes, committed mesh-REPLICATED (every
    # device reads the full per-code partials) — a repeat query enters
    # the collective dispatch with zero build work and zero transfer
    rep_sh = NamedSharding(mesh, P())
    bouts = build_outs_for(rep_sh, f"coll{M}")
    if prof is not None:
        prof.add_device_ns(id(pscan), clock() - t0)
    tspan("device_upload", t0, shards=S)

    # -- the single collective program ------------------------------------
    out_kinds = _collective_out_kinds(agg_plans)
    np_cols = len(needed_p)

    # the traced program depends only on (slice shape, mesh width,
    # publications [which pin decode schemes/code space/layout], key
    # set, expression shapes) — NOT on which spans survived pruning:
    # span-dependent values all enter as runtime inputs, so two
    # queries with different pruning patterns but equal t_slice reuse
    # one compiled executable (spans_sig keys only the DATA caches)
    cache_key = ("fcollective", probe.pub, build.pub,
                 t_slice, M, keyset) + shape_sig

    def build_collective():
        def collective(*flat):
            # local probe slice: (1, t_slice, L) tiles → one row block
            # (the mesh slice is just a row subset; the group scatter
            # is the same int add in any order)
            arrays = {}
            for k2, ji in enumerate(needed_p):
                d, m = flat[2 * k2], flat[2 * k2 + 1]
                d = d.reshape(-1, d.shape[-1])
                scheme, off = decode_p[ji]
                if scheme != "raw":
                    d = d.astype(jnp.int32) + jnp.int32(off)
                arrays[ji] = (d, m.reshape(-1, m.shape[-1]))
            base = 2 * np_cols
            pcodes = flat[base].reshape(-1, flat[base].shape[-1])
            pmask = flat[base + 1].reshape(-1, flat[base + 1].shape[-1])
            bacc = flat[base + 2]
            bmm = {si: flat[base + 3 + j] for j, si in enumerate(bmm_sis)}

            # probe phase: THE shared body (bit-identity contract in
            # one place), then the cross-shard psum/pmin/pmax combine
            outs = _probe_phase(arrays, pcodes, pmask, bacc, bmm,
                                preds_probe, key_plans, group_mode,
                                group_space, agg_plans, sum_modes,
                                bstart, g)
            return mesh_mod.apply_axis_combines(outs, out_kinds,
                                                fuse_sums=True)

        in_specs = tuple([P(mesh_mod.AXIS, None, None)] * (2 * np_cols)
                         + [P(mesh_mod.AXIS, None, None)] * 2
                         + [P()] * (1 + len(bmm_sis)))
        out_specs = tuple(P() for _ in out_kinds)
        # check_rep off: replication of the post-psum outputs holds by
        # construction but the checker can't infer it through the
        # scatter/gather bodies
        return shard_map(
            collective, mesh=mesh, in_specs=in_specs,
            out_specs=out_specs, check_rep=False)

    jitted = obs_device.compiled("fused_collective", cache_key,
                                 build_collective, profile=prof,
                                 node_key=id(node))

    flat_args: list = []
    for ji in needed_p:
        flat_args.extend(env_p[ji])
    flat_args.extend([pc_dev, prow])
    flat_args.extend(bouts)

    check_cancel()
    t_d = time.perf_counter_ns()
    metrics.DEVICE_OFFLOADS.add()
    metrics.COLLECTIVE_DISPATCHES.add()
    # the shard workloads still execute — as lanes of one program
    metrics.SHARD_PIPELINES.add(S)
    from ..obs.resources import wait_scope
    with wait_scope("Device", "CollectiveCombine"):
        results = obs_device.fetch_all(jitted(*flat_args))
    dt = time.perf_counter_ns() - t_d
    metrics.COLLECTIVE_COMBINE_NS.add(dt)
    metrics.DEVICE_DISPATCH_HIST.observe_ns(dt)
    tspan("collective_dispatch", t_d, shards=S, mesh=M)
    shard_mod.stamp_profile(ctx, id(node), S, pruned, collective=True)
    out = _finalize(node, key_plans, agg_plans, results, probe, pscan,
                    dictionaries, group_space, group_mode, sum_modes)
    if prof is not None:
        prof.add_device_ns(id(node), time.perf_counter_ns() - t_d)
    return out


def _mm_ident(func: str) -> int:
    info = np.iinfo(np.int32)
    return info.max if func == "min" else info.min


def _limb_cols(vals, weights) -> list:
    """Exact weighted int-sum columns: the 8-bit limb decomposition of
    ops_agg.group_sum_int_limbs, multiplicity-weighted and returned as
    5 per-row int32 columns [4 byte-limbs · w, (v < 0) · w] for the
    caller's fused scatter; host recombines in int64
    (ops_agg.combine_sum_int_limbs). Exact while 255 · Σw < 2^31 per
    group (the MAX_PAIRS_EXACT admission bound)."""
    import jax.numpy as jnp
    vu = jax.lax.bitcast_convert_type(vals, jnp.uint32)
    cols = [(jnp.right_shift(vu, 8 * limb) &
             jnp.uint32(0xFF)).astype(jnp.int32) * weights
            for limb in range(4)]
    cols.append((vals < 0).astype(jnp.int32) * weights)
    return cols


def _code_tiles(codes: np.ndarray, sentinel: int,
                pad: Optional[int] = None) -> "jax.Array":
    """Factorized join codes → int32 device tiles; padding rows take the
    side's never-matches sentinel. `pad` rounds rows up to that multiple
    (the fused tier's pow2 bucket)."""
    import jax.numpy as jnp
    n = len(codes)
    n_pad = pad_len(n) if pad is None else pad_len(n, pad)
    padded = np.full(n_pad, sentinel, dtype=np.int32)
    padded[:n] = codes
    return jnp.asarray(padded.reshape(-1, LANES))


def _join_codes(join, probe: _Side, build: _Side
                ) -> tuple[np.ndarray, np.ndarray, int, int]:
    """PR-3 key-code factorization over BOTH sides (one shared dense
    int64 code space), with NULL-key rows already rewritten to the
    per-side sentinel (g for build, g+1 for probe) so NULL never
    matches, plus the worst-case matched-pair count for the exactness
    admission (computed over the UNSLICED sides — an upper bound of any
    zone-sliced run, so admission stays sound). Cached per publication
    pair — repeat queries skip both the O(n log n) factorize and the
    O(n) pair count."""
    from .morsel import combined_codes, rows_valid
    keyset = (tuple(_expr_key(k) for k in join.left_keys),
              tuple(_expr_key(k) for k in join.right_keys))
    ck = (probe.pub, build.pub, keyset)
    with _codes_lock:
        hit = _CODES_CACHE.get(ck)
        if hit is not None:
            _CODES_CACHE.move_to_end(ck)
            return hit
    pbatch = probe.pin[0] if probe.pin is not None \
        else probe.provider.full_batch(probe.scan.columns)
    bbatch = build.pin[0] if build.pin is not None \
        else build.provider.full_batch(build.scan.columns)
    pbatch = Batch(list(probe.scan.columns),
                   [pbatch.column(c) for c in probe.scan.columns])
    bbatch = Batch(list(build.scan.columns),
                   [bbatch.column(c) for c in build.scan.columns])
    try:
        lkeys = [k.eval(pbatch) for k in join.left_keys]
        rkeys = [k.eval(bbatch) for k in join.right_keys]
    except Exception as e:
        # the host path evaluates keys only over filter-surviving rows;
        # an eval error on a filtered-out row must fall back, not surface
        raise NotCompilable(f"key eval over unfiltered rows: {e}")
    pair = combined_codes(lkeys, rkeys)
    if pair is None:
        raise NotCompilable("join keys have no shared code representation")
    cl, cr, g = pair
    lvalid = rows_valid(lkeys)
    rvalid = rows_valid(rkeys)
    cl = cl.astype(np.int64)
    cr = cr.astype(np.int64)
    if lvalid is not None:
        cl = np.where(lvalid, cl, g + 1)
    if rvalid is not None:
        cr = np.where(rvalid, cr, g)
    total_pairs = 0
    if len(cl) and len(cr) and g:
        bc_counts = np.bincount(cr[cr < g], minlength=g)
        pl = cl[cl < g]
        total_pairs = int(bc_counts[pl].sum()) if len(pl) else 0
    value = (cl, cr, g, total_pairs)
    nbytes = int(cl.nbytes) + int(cr.nbytes)
    global _codes_bytes
    with _codes_lock:
        # superseded generations of the same (table pair, keyset) are
        # unreachable — publications are monotone — sweep them first
        stale = [k for k in _CODES_CACHE
                 if k[2] == keyset and k[0][0] == ck[0][0] and
                 k[1][0] == ck[1][0] and k != ck]
        for k in stale:
            old = _CODES_CACHE.pop(k)
            _codes_bytes -= int(old[0].nbytes) + int(old[1].nbytes)
        while _CODES_CACHE and (
                len(_CODES_CACHE) >= _CODES_CACHE_MAX or
                _codes_bytes + nbytes > _CODES_CACHE_MAX_BYTES):
            _, old = _CODES_CACHE.popitem(last=False)
            _codes_bytes -= int(old[0].nbytes) + int(old[1].nbytes)
        _CODES_CACHE[ck] = value
        _codes_bytes += nbytes
    return value


def _plan_group_keys(node, join_types, probe: _Side, pscan, dictionaries
                     ) -> tuple[list, int]:
    """Direct coding of the probe-side group keys (device_agg's
    _plan_direct_keys, join-namespace variant): dictionary codes for
    strings, offset small-range coding for ints; the NULL group takes
    slot size-1, matching factorize_keys' (values asc, NULL last)
    composite order so the host oracle's group order is reproduced."""
    key_plans = []
    group_space = 1
    for gx in node.group_exprs:
        t = join_types[gx.index]
        if t.is_string:
            d = dictionaries.get(gx.index)
            if d is None:
                raise NotCompilable("string group key without dictionary")
            size = len(d) + 1
            key_plans.append(("dict", gx.index, 0, size))
        elif t.is_integer or t.id in (dt.TypeId.BOOL, dt.TypeId.DATE):
            col = probe.host_col(pscan.columns[gx.index])
            if col.data.size == 0:
                lo, hi = 0, 0
            else:
                lo, hi = int(col.data.min()), int(col.data.max())
            rng = hi - lo + 1
            if rng > MAX_INT_KEY_RANGE:
                raise NotCompilable("group key range too large")
            if not (-2**31 <= lo and hi < 2**31):
                raise NotCompilable("group key offset beyond int32")
            size = rng + 1
            key_plans.append(("int", gx.index, lo, size))
        else:
            raise NotCompilable(f"group key type {t}")
        group_space *= size
        if group_space > MAX_GROUP_PRODUCT:
            raise NotCompilable("group code space too large")
    return key_plans, group_space


def _zero_results(agg_plans, group_space: int, sum_modes: dict,
                  star_filter=None, distinct_plans=None) -> list:
    """Host-side zero accumulators matching the program's output spec —
    the no-surviving-rows short-circuit (empty table or every block
    zone-pruned) never dispatches."""
    star_filter = star_filter or {}
    distinct_plans = distinct_plans or {}
    out = [np.zeros(group_space, dtype=np.int32)]
    for si, (spec, side, ce) in enumerate(agg_plans):
        if spec.func == "count_star":
            if si in star_filter:
                out.append(np.zeros(group_space, dtype=np.int32))
            continue
        if si in distinct_plans:
            vspace = distinct_plans[si][3]
            out.append(np.zeros(group_space * vspace, dtype=np.int32))
            continue
        if spec.func == "count":
            out.append(np.zeros(group_space, dtype=np.int32))
        elif spec.func in ("sum", "avg"):
            if sum_modes[si] == "direct":
                out.append(np.zeros(group_space, dtype=np.int32))
            else:
                out.append(np.zeros((group_space, 5), dtype=np.int32))
            out.append(np.zeros(group_space, dtype=np.int32))
        else:
            out.append(np.full(group_space, _mm_ident(spec.func),
                               dtype=np.int32))
            out.append(np.zeros(group_space, dtype=np.int32))
    return out


def _finalize(node, key_plans, agg_plans, results, probe: _Side, pscan,
              dictionaries, group_space: int, group_mode: bool,
              sum_modes: dict, star_filter=None, distinct_plans=None,
              slots=None) -> Batch:
    """Device accumulators → result batch, bit-matching the host oracle:
    groups emit in ascending composite-code order (= factorize_keys
    order), int sums recombine from limbs in int64, empty groups /
    scalar aggregates go NULL exactly where the oracle's do.

    `slots=(codes, row_lo, row_hi)` is the chained-top-N entry: results
    arrive pre-gathered to the selected group rows (stage 2's top_k
    indices), `codes` holds those rows' composite group codes, and only
    rows [row_lo, row_hi) emit (host-side OFFSET/LIMIT slice)."""
    star_filter = star_filter or {}
    distinct_plans = distinct_plans or {}
    ri = iter(results)
    pair_counts = np.asarray(next(ri)).astype(np.int64)
    if slots is not None:
        slot_codes, row_lo, row_hi = slots
        present = np.arange(row_lo, row_hi)
    elif group_mode:
        present = np.flatnonzero(pair_counts > 0)
    else:
        present = np.asarray([0])
    cols: list[Column] = []
    if group_mode:
        sizes = [kp[3] for kp in key_plans]
        rem = slot_codes[row_lo:row_hi].copy() if slots is not None \
            else present.copy()
        key_codes = []
        for size in reversed(sizes):
            key_codes.append(rem % size)
            rem //= size
        key_codes.reverse()
        for pos, ((kind, ji, lo, size), kc) in \
                enumerate(zip(key_plans, key_codes)):
            null_mask = kc == (size - 1)
            t = node.group_exprs[pos].type
            if kind == "dict":
                d = dictionaries[ji]
                data = np.where(null_mask, 0, kc).astype(np.int32)
                cols.append(Column(
                    t, data, ~null_mask if null_mask.any() else None, d))
            else:
                data = (kc + lo).astype(t.np_dtype)
                data = np.where(null_mask, 0, data).astype(t.np_dtype)
                cols.append(Column(
                    t, data, ~null_mask if null_mask.any() else None))
    for si, (spec, side, ce) in enumerate(agg_plans):
        if si in distinct_plans:
            cols.append(_distinct_result_col(
                spec, np.asarray(next(ri)), distinct_plans[si],
                group_space, group_mode, present))
            continue
        if spec.func == "count_star" and si in star_filter:
            c = np.asarray(next(ri)).astype(np.int64)
            if group_mode:
                cols.append(Column(dt.BIGINT, c[present]))
            else:
                cols.append(Column.from_pylist([int(c[0])], spec.type))
            continue
        cols.append(_agg_result_col(spec, ri, pair_counts, present,
                                    group_mode,
                                    sum_modes.get(si, "limb"),
                                    dictionaries))
    return Batch(list(node.names), cols)


def _distinct_result_col(spec: AggSpec, grid: np.ndarray, dplan,
                         group_space: int, group_mode: bool,
                         present) -> Column:
    """Presence grid → count/sum/avg DISTINCT, exactly: a cell is
    present iff ≥ 1 surviving (group, value) occurrence scattered into
    it; counts are presences per group, sums recombine value · present
    in int64 (values are the direct-coded axis, so the grid IS the
    distinct value set)."""
    _dkind, _ji, lo_v, vspace = dplan
    if grid.ndim == 1:
        grid = grid.reshape(group_space, vspace)
    pres = grid[present] > 0                   # (rows, vspace)
    cnt = pres.sum(axis=1).astype(np.int64)
    if spec.func == "count":
        if group_mode:
            return Column(dt.BIGINT, cnt)
        return Column.from_pylist([int(cnt[0])], spec.type)
    vals = (np.int64(lo_v) + np.arange(vspace, dtype=np.int64))
    sums = (pres * vals).sum(axis=1)
    t = spec.type
    if group_mode:
        empty = cnt == 0
        if spec.func == "avg":
            with np.errstate(invalid="ignore", divide="ignore"):
                data = np.where(empty, 0.0, sums / np.maximum(cnt, 1))
            return Column(dt.DOUBLE, data, ~empty if empty.any() else None)
        if t.is_integer:
            return Column(dt.BIGINT, sums,
                          ~empty if empty.any() else None)
        return Column(dt.DOUBLE, sums.astype(np.float64),
                      ~empty if empty.any() else None)
    s, n = int(sums[0]), int(cnt[0])
    if n == 0:
        return Column.from_pylist([None], t)
    if spec.func == "avg":
        return Column.from_pylist([s / n], t)
    return Column.from_pylist([s if t.is_integer else float(s)], t)


def _agg_result_col(spec: AggSpec, ri, pair_counts, present,
                    group_mode: bool, sum_mode: str = "limb",
                    dictionaries=None) -> Column:
    t = spec.type
    if spec.func == "count_star":
        if group_mode:
            return Column(dt.BIGINT, pair_counts[present])
        return Column.from_pylist([int(pair_counts[0])], t)
    if spec.func == "count":
        c = np.asarray(next(ri)).astype(np.int64)
        if group_mode:
            return Column(dt.BIGINT, c[present])
        return Column.from_pylist([int(c[0])], t)
    if spec.func in ("sum", "avg"):
        raw = np.asarray(next(ri))
        cnt = np.asarray(next(ri)).astype(np.int64)
        sums = raw.astype(np.int64) if sum_mode == "direct" \
            else ops_agg.combine_sum_int_limbs(raw)
        if group_mode:
            sums, cnt = sums[present], cnt[present]
            empty = cnt == 0
            if spec.func == "avg":
                with np.errstate(invalid="ignore", divide="ignore"):
                    data = np.where(empty, 0.0, sums / np.maximum(cnt, 1))
                return Column(dt.DOUBLE, data,
                              ~empty if empty.any() else None)
            if t.is_integer:
                return Column(dt.BIGINT, sums,
                              ~empty if empty.any() else None)
            return Column(dt.DOUBLE, sums.astype(np.float64),
                          ~empty if empty.any() else None)
        s, n = int(sums[0]), int(cnt[0])
        if n == 0:
            return Column.from_pylist([None], t)
        if spec.func == "avg":
            return Column.from_pylist([s / n], t)
        return Column.from_pylist([s if t.is_integer else float(s)], t)
    if spec.func in ("min", "max"):
        v = np.asarray(next(ri)).astype(np.int64)
        cnt = np.asarray(next(ri)).astype(np.int64)
        at = spec.arg.type
        if at.is_string:
            # min/max ran over sorted-dictionary codes (code order ==
            # string order); decode back through the dictionary
            d = (dictionaries or {}).get(spec.arg.index)
            if group_mode:
                v, cnt = v[present], cnt[present]
                empty = cnt == 0
                codes = np.where(empty, 0, v).astype(np.int32)
                return Column(at, codes,
                              ~empty if empty.any() else None, d)
            if int(cnt[0]) == 0:
                return Column.from_pylist([None], t)
            return Column.from_pylist([str(d[int(v[0])])], t)
        if group_mode:
            v, cnt = v[present], cnt[present]
            empty = cnt == 0
            data = np.where(empty, 0, v).astype(at.np_dtype)
            return Column(at, data, ~empty if empty.any() else None)
        if int(cnt[0]) == 0:
            return Column.from_pylist([None], t)
        out = int(v[0])
        if at.id is dt.TypeId.BOOL:
            out = bool(out)
        return Column.from_pylist([out], t)
    raise NotCompilable(spec.func)


# -- fused filtered top-N ----------------------------------------------------


def _col_stats(side: _Side, name: str) -> tuple:
    """(all_valid, finite_all, lo, hi) of one column — a pure function
    of the publication, so cached repeats skip the O(n) host scans.
    lo/hi are None for float columns (only finiteness gates those) and
    span EVERY slot including NULL ones (garbage under an invalid slot
    widens the range, which can only make callers more conservative)."""
    ck = (side.pub, name)
    with _col_stats_lock:
        hit = _COL_STATS_CACHE.get(ck)
        if hit is not None:
            _COL_STATS_CACHE.move_to_end(ck)
            return hit
    host = side.host_col(name)
    all_valid = bool(host.valid_mask().all())
    if host.data.dtype.kind == "f":
        stats = (all_valid, bool(np.isfinite(host.data).all()), None, None)
    elif host.data.size == 0:
        stats = (all_valid, True, 0, 0)
    else:
        stats = (all_valid, True,
                 int(host.data.min()), int(host.data.max()))
    with _col_stats_lock:
        while len(_COL_STATS_CACHE) >= _COL_STATS_MAX:
            _COL_STATS_CACHE.popitem(last=False)
        _COL_STATS_CACHE[ck] = stats
    return stats


def try_device_fused_topn(limit_node, ctx) -> Optional[Batch]:
    """One-dispatch ORDER BY col LIMIT k over a FILTERED scan: the
    compiled predicate masks filtered-out rows to the sort sentinel
    inside the same program as `top_k`, so Filter→Sort→Limit is one
    dispatch (device_topn covers only the unfiltered shape). None → CPU
    lexsort oracle."""
    from .plan import FilterNode, ProjectNode, ScanNode, SortNode
    from .device_topn import MAX_TOPN_K

    settings = ctx.settings
    if settings.get("serene_device") == "cpu" or not fused_enabled(settings):
        return None
    if limit_node.limit is None:
        return None

    def decline(reason: str) -> None:
        _note_decline(reason, ctx, limit_node)
        return None

    k = limit_node.limit + limit_node.offset
    if k == 0:
        return None
    if k > MAX_TOPN_K:
        return decline("topn_k")
    sort = limit_node.child
    if not isinstance(sort, SortNode) or len(sort.key_indices) != 1 or \
            sort.nulls_first[0] is not None:
        return None
    proj = None
    inner = sort.child
    if isinstance(inner, ProjectNode):
        proj = inner
        inner = inner.child
    side = _unwrap_side(inner)
    if side is None or not side[1]:
        return None       # unfiltered shape: device_topn's territory
    scan, preds = side
    ki = sort.key_indices[0]
    if proj is not None:
        # plain column projections only: the host oracle evaluates the
        # Project over EVERY filter-surviving row, the fused path over
        # only the k selected ones — a computed expression that raises
        # (100/b with a zero outside the top k) or draws state would
        # diverge, so anything beyond column selection/reorder falls back
        if not all(isinstance(e, BoundColumn) for e in proj.exprs):
            return decline("topn_project")
        ki = proj.exprs[ki].index
    t = scan.types[ki]
    if not (t.is_integer or t.id in (dt.TypeId.DATE, dt.TypeId.FLOAT)):
        return decline("topn_key_type")
    provider = scan.provider
    if settings.get("serene_device") == "auto":
        try:
            if provider.row_count() < settings.get("serene_device_min_rows"):
                return None
        except NotImplementedError:
            return None
    desc = bool(sort.descs[0])
    try:
        prof = getattr(ctx, "profile", None)
        from ..obs.trace import current_trace
        trace = current_trace()
        t0 = time.perf_counter_ns()
        out = _run_fused_topn(limit_node, scan, preds, ki, desc, k, ctx,
                              proj)
        if prof is not None:
            prof.add_device_ns(id(limit_node),
                               time.perf_counter_ns() - t0)
        if out is not None:
            metrics.DEVICE_DISPATCH_HIST.observe_ns(
                time.perf_counter_ns() - t0)
            if trace is not None:
                trace.add("device_dispatch", "device", t0,
                          time.perf_counter_ns(), op="topn")
        return out
    except (NotCompilable, DeviceNarrowingError) as e:
        log.debug("device", f"fused top-N fell back to CPU: {e}")
        return decline(getattr(e, "reason", "not_compilable"))


def _run_fused_topn(limit_node, scan, preds, ki: int, desc: bool, k: int,
                    ctx, proj=None) -> Optional[Batch]:
    import jax.numpy as jnp
    from .device_topn import _I32_MIN, _I32_MAX
    from .plan import check_cancel

    side = _Side(scan, preds, ctx)
    if side.nrows == 0 or side.n_live == 0:
        from .plan import empty_batch
        if proj is not None:
            return empty_batch(list(proj.names),
                               [e.type for e in proj.exprs])
        return empty_batch(list(scan.names), list(scan.types))
    name = scan.columns[ki]
    all_valid, finite_all, lo_v, hi_v = _col_stats(side, name)
    if not all_valid:
        raise NotCompilable("top-N key column has NULLs")
    if lo_v is None:                         # float key
        if not finite_all:
            raise NotCompilable("top-N float key has NaN/inf")
    else:
        if desc and lo_v <= _I32_MIN:
            raise NotCompilable("key touches int32 min")
        if not desc and hi_v >= _I32_MAX:
            raise NotCompilable("key touches int32 max")

    dicts = {}
    for e in preds:
        for sub in e.walk():
            if isinstance(sub, BoundColumn) and sub.type.is_string and \
                    sub.index not in dicts:
                col = side.host_col(scan.columns[sub.index])
                if col.dictionary is not None:
                    dicts[sub.index] = col.dictionary
    compiled = [compile_expr(p, scan.types, dicts) for p in preds]

    needed = sorted({ki} | {i for ce in compiled for i in ce.inputs})
    env_cols = {
        i: DEVICE_CACHE.column(side.provider, side.pub, scan.columns[i],
                               (lambda s=side, n=scan.columns[i]:
                                s.host_col(n)), side.zrange)
        for i in needed}
    rowmask = DEVICE_CACHE.array(side.pub, "__rowmask__", (side.zrange,),
                                 lambda: _rowmask_tiles(side.n_live))
    kc = env_cols[ki]
    is_float = kc.data.dtype.kind == "f"
    if int(kc.data.shape[0]) * LANES < k:
        raise NotCompilable("k exceeds padded rows")

    decode_specs = [(env_cols[i].scheme, env_cols[i].offset) for i in needed]
    kpos = needed.index(ki)

    cache_key = ("fusedtopn", side.pub, side.zrange, name, desc, k,
                 tuple(_expr_key(p) for p in preds))

    def program(*flat):
        arrays = {}
        for j, i in enumerate(needed):
            data = flat[2 * j]
            scheme, off = decode_specs[j]
            if scheme != "raw":
                data = data.astype(jnp.int32) + jnp.int32(off)
            arrays[i] = (data, flat[2 * j + 1])
        mask = flat[-1]
        for ce in compiled:
            v, ok = ce.fn([arrays[i] for i in ce.inputs])
            b = v if v.dtype == jnp.bool_ else (v != 0)
            mask = jnp.logical_and(mask, jnp.logical_and(b, ok))
        v = arrays[needed[kpos]][0]
        if is_float:
            keys = v if desc else -v
            sent = jnp.float32(-jnp.inf)
        else:
            v = v.astype(jnp.int32)
            keys = v if desc else ~v
            sent = jnp.int32(_I32_MIN)
        keys = jnp.where(mask.ravel(), keys.ravel(), sent)
        kk, ii = jax.lax.top_k(keys, k)
        return kk, ii.astype(jnp.int32), \
            jnp.sum(mask, dtype=jnp.int32)

    jitted = obs_device.compiled("fused_topn", cache_key,
                                 lambda: program,
                                 profile=getattr(ctx, "profile", None),
                                 node_key=id(limit_node))

    flat_args = []
    for i in needed:
        dc = env_cols[i]
        flat_args.extend([dc.data, dc.mask])
    flat_args.append(rowmask)
    check_cancel()
    metrics.DEVICE_OFFLOADS.add()
    kk, ii, nsurv = obs_device.fetch_all(jitted(*flat_args))
    idx = ii.astype(np.int64)
    k_eff = min(k, int(nsurv))
    idx = idx[:k_eff]
    if side.zrange is not None:
        idx = idx + side.zrange[0]
    idx = idx[limit_node.offset:]
    if side.pin is not None and all(c in side.pin[0] for c in scan.columns):
        base = Batch(list(scan.columns),
                     [side.pin[0].column(c) for c in scan.columns])
    else:
        base = side.provider.full_batch(scan.columns)
    base = base.take(idx)
    if proj is None:
        return base
    return Batch(list(proj.names), [e.eval(base) for e in proj.exprs])


# -- chained device-resident stages: fused agg → fused top-N -----------------


def _stage1_out_slots(agg_plans, star_filter, distinct_plans
                      ) -> dict[int, int]:
    """agg index → its FIRST slot in the stage-1 output tuple (mirrors
    _probe_phase's output ordering exactly)."""
    slots: dict[int, int] = {}
    pos = 1
    for si, (spec, _side, _ce) in enumerate(agg_plans):
        if spec.func == "count_star":
            if si in star_filter:
                slots[si] = pos
                pos += 1
            continue
        slots[si] = pos
        if si in distinct_plans or spec.func == "count":
            pos += 1
        else:
            pos += 2                      # sum/avg and min/max: 2 slots
    return slots


def try_device_chained_topn(limit_node, ctx) -> Optional[Batch]:
    """Whole-query device residency: Limit(Sort(Project?(Aggregate)))
    over a fused-admissible join runs as TWO chained dispatches — the
    stage-1 group accumulators NEVER leave HBM. Stage 2 (jitted with
    donate_argnums over the stage-1 outputs, so XLA reuses their
    buffers) masks absent groups to the sort sentinel, top_k-selects
    the k requested group slots, and gathers every accumulator down to
    those k rows; the host fetches only the k-row tail. Sort keys are
    group-key columns (composite-code order == value order: sorted
    dictionaries / offset ints, NULL slot last ⇒ PG's default asc
    NULLS LAST / desc NULLS FIRST exactly) or count-family aggregates;
    min/max/sum keys decline (their device identities have no
    NULL-consistent total order to hand top_k). None → host path."""
    import jax.numpy as jnp
    from .device_topn import _I32_MIN
    from .plan import AggregateNode, ProjectNode, SortNode, check_cancel

    settings = ctx.settings
    if settings.get("serene_device") == "cpu" or \
            not fused_enabled(settings) or \
            not fused_ext_enabled(settings):
        return None
    if limit_node.limit is None or limit_node.limit == 0:
        return None
    k = limit_node.limit + limit_node.offset
    sort = limit_node.child
    if not isinstance(sort, SortNode) or len(sort.key_indices) != 1 or \
            sort.nulls_first[0] is not None:
        return None
    proj = None
    agg = sort.child
    if isinstance(proj_c := agg, ProjectNode):
        proj = proj_c
        agg = proj_c.child
    if not isinstance(agg, AggregateNode):
        return None
    if proj is not None and not all(isinstance(e, BoundColumn)
                                    for e in proj.exprs):
        return None
    if not agg.group_exprs:
        return None               # scalar aggregate: one row, host-trivial

    def decline(reason: str) -> None:
        _note_decline(reason, ctx, limit_node)
        return None

    sel = sort.key_indices[0]
    if proj is not None:
        sel = proj.exprs[sel].index
    ng = len(agg.group_exprs)
    if sel >= ng:
        spec = agg.aggs[sel - ng]
        if spec.func not in ("count_star", "count") or spec.distinct:
            return decline("chain_sort_key")
    admitted = _admit_pipeline(agg, ctx, decline)
    if admitted is None:
        return None
    join, probe_side, build_side, post_preds = admitted
    try:
        res = _run_fused(agg, join, probe_side, build_side, post_preds,
                         ctx, fetch=False)
    except (NotCompilable, DeviceNarrowingError) as e:
        log.debug("device", f"chained fused top-N fell back to CPU: {e}")
        return decline(getattr(e, "reason", "not_compilable"))
    if isinstance(res, Batch):
        return None               # empty short-circuit: host path, cheap
    outs, fin = res
    desc = bool(sort.descs[0])
    group_space = fin["group_space"]
    key_plans = fin["key_plans"]
    agg_plans = fin["agg_plans"]
    sum_modes = fin["sum_modes"]
    star_filter = fin["star_filter"]
    distinct_plans = fin["distinct_plans"]
    k_pad = min(_pow2_int(k, floor=8), group_space)

    if sel >= ng:
        si = sel - ng
        if agg_plans[si][0].func == "count_star" and \
                si not in star_filter:
            sort_mode = ("agg", 0)        # shared output-row counts
        else:
            sort_mode = ("agg", _stage1_out_slots(
                agg_plans, star_filter, distinct_plans)[si])
    else:
        sizes = [kp[3] for kp in key_plans]
        stride = 1
        for s2 in sizes[sel + 1:]:
            stride *= s2
        sort_mode = ("gkey", stride, sizes[sel])

    ckey = ("fused_chain", fin["stage1_key"], sort_mode, desc, k_pad,
            group_space)

    def build_stage2():
        def stage2(*souts):
            present = souts[0] > 0
            if sort_mode[0] == "agg":
                v = souts[sort_mode[1]].astype(jnp.int32)
            else:
                idx = jnp.arange(group_space, dtype=jnp.int32)
                v = (idx // jnp.int32(sort_mode[1])) % \
                    jnp.int32(sort_mode[2])
            # asc rides ~v: monotone-decreasing, exact on int32 (codes
            # < 2^21 and counts ≤ 2^23 keep ~v clear of the sentinel);
            # ties take the lowest slot = ascending composite code =
            # the host oracle's stable sort order
            sv = v if desc else ~v
            sv = jnp.where(present, sv, jnp.int32(_I32_MIN))
            _kk, ii = jax.lax.top_k(sv, k_pad)
            picked = []
            for o in souts:
                if o.ndim == 1 and o.shape[0] != group_space:
                    o = o.reshape(group_space, -1)  # DISTINCT grid
                picked.append(o[ii])
            return (ii.astype(jnp.int32),
                    jnp.sum(present, dtype=jnp.int32), *picked)
        return stage2

    prof = getattr(ctx, "profile", None)
    # donate the stage-1 accumulators: XLA reuses their HBM for the
    # gathered outputs (donation is a no-op warning on the CPU backend)
    donate = tuple(range(len(outs))) \
        if jax.default_backend() != "cpu" else None
    jitted2 = obs_device.compiled("fused_chain", ckey, build_stage2,
                                  profile=prof,
                                  node_key=id(limit_node),
                                  donate_argnums=donate)
    check_cancel()
    t0 = time.perf_counter_ns()
    metrics.DEVICE_OFFLOADS.add()
    metrics.REGISTRY.gauge(
        "DeviceChainedStages",
        "Fused agg→top-N chains executed with the intermediate "
        "accumulators handed off in HBM").add()
    fetched = obs_device.fetch_all(jitted2(*outs))
    ii_np = np.asarray(fetched[0]).astype(np.int64)
    npres = int(fetched[1])
    k_eff = min(k, npres)
    row_lo = min(limit_node.offset, k_eff)
    out = _finalize(agg, key_plans, agg_plans, list(fetched[2:]),
                    fin["probe"], fin["pscan"], fin["dictionaries"],
                    group_space, True, sum_modes,
                    star_filter=star_filter,
                    distinct_plans=distinct_plans,
                    slots=(ii_np, row_lo, k_eff))
    if proj is not None:
        out = Batch(list(proj.names),
                    [out.columns[e.index] for e in proj.exprs])
    if prof is not None:
        prof.add_device_ns(id(limit_node), time.perf_counter_ns() - t0)
    metrics.DEVICE_DISPATCH_HIST.observe_ns(time.perf_counter_ns() - t0)
    return out
