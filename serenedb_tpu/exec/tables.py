"""Table providers: the scan sources the executor reads from.

Reference analog: DuckDB table entries + the iresearch scan table function +
remote-file index sources (SURVEY.md §2.5). Providers expose columnar
batches, and cache *device-resident* columns — the HBM working set that the
north-star design keeps hot between queries (BASELINE.json north_star:
"column batches ship to HBM and run as Pallas kernels").
"""

from __future__ import annotations

import threading
from typing import Iterator, Optional

import numpy as np

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Batch, Column
from ..columnar.device import DeviceColumn, to_device_column
from ..utils import metrics

DEFAULT_BATCH_ROWS = 1 << 17


class TableProvider:
    name: str
    column_names: list[str]
    column_types: list[dt.SqlType]

    def row_count(self) -> int:
        raise NotImplementedError

    def full_batch(self, columns: Optional[list[str]] = None) -> Batch:
        raise NotImplementedError

    def batches(self, columns: Optional[list[str]] = None,
                batch_rows: int = DEFAULT_BATCH_ROWS) -> Iterator[Batch]:
        full = self.full_batch(columns)
        n = full.num_rows
        if n == 0:
            yield full
            return
        for start in range(0, n, batch_rows):
            yield full.slice(start, min(start + batch_rows, n))

    # -- device cache ------------------------------------------------------

    #: bumped on every data mutation; device program/column caches key on it
    data_version: int = 0

    def pinned(self):
        """(batch, data_version, mutation_epoch) observation. MemTable
        overrides this with a genuinely atomic single-reference read so
        readers never need a lock against concurrent DML; other providers
        are immutable and the default composition is safe."""
        return (self.full_batch(), self.data_version,
                getattr(self, "mutation_epoch", 0))

    def try_pin(self):
        """Atomic (batch, data_version, mutation_epoch) observation for
        MUTABLE providers (MemTable overrides); None for immutable ones,
        whose per-column reads are torn-free by construction — and which
        must not pay a whole-file materialization just to pin (a
        ParquetTable decodes columns lazily)."""
        return None

    def __init_device_cache(self):
        if not hasattr(self, "_device_cache"):
            self._device_cache: dict[str, tuple[int, DeviceColumn]] = {}
            self._device_lock = threading.Lock()

    def device_columns(self, names, pin=None) -> dict:
        """{name: DeviceColumn} with EVERY entry built from one
        publication — the given pin (from try_pin()) or per-column reads
        on immutable providers. A multi-column device program must get
        its whole environment here: fetching columns one at a time could
        mix two publications (mismatched lengths / row order) when DML
        lands between the fetches. Entries are version-stamped so a
        racing publish can never leave a stale column cached under the
        new version."""
        self.__init_device_cache()
        with self._device_lock:
            if pin is not None:
                batch, ver = pin[0], pin[1]
            else:
                batch, ver = None, self.data_version
            out = {}
            for name in names:
                entry = self._device_cache.get(name)
                if entry is None or entry[0] != ver:
                    col = (batch.column(name) if batch is not None
                           else self.full_batch([name]).column(name))
                    dc = to_device_column(col)
                    metrics.DEVICE_BYTES.add(
                        int(dc.data.size * dc.data.dtype.itemsize))
                    self._device_cache[name] = (ver, dc)
                    out[name] = dc
                else:
                    out[name] = entry[1]
            return out

    def shard_view(self, n_shards: int, block_rows: int,
                   nrows: Optional[int] = None
                   ) -> list[list[tuple[int, int]]]:
        """Deterministic hash-partitioned shard view: per-shard row
        spans under round-robin morsel-block assignment (exec/shard.py
        owns the partitioning function). Blocks never migrate between
        shards, so a pure append only creates/extends TAIL blocks and
        every other shard's zone maps / device uploads stay valid.
        Callers pass `nrows` from their own pinned publication so the
        view can never straddle a concurrent publish."""
        from .shard import shard_spans
        if nrows is None:
            nrows = self.row_count()
        return shard_spans(nrows, block_rows, n_shards)

    def device_column(self, name: str) -> DeviceColumn:
        return self.device_columns([name], self.try_pin())[name]

    def host_column(self, name: str) -> Column:
        return self.full_batch([name]).column(name)

    def clear_device_cache(self):
        self.__init_device_cache()
        with self._device_lock:
            self._device_cache.clear()
            if hasattr(self, "_device_rowmask"):
                del self._device_rowmask
        # range-sliced uploads (zone-map prefix/suffix pruning) are
        # version-stamped like the main cache, but drop them with it so
        # stale HBM is released on mutation
        if hasattr(self, "_zonemap_devcache"):
            self._zonemap_devcache.clear()

    def type_of(self, name: str) -> dt.SqlType:
        return self.column_types[self.column_names.index(name)]


class MemTable(TableProvider):
    """In-memory columnar table (also the transactional-store table engine's
    in-memory representation until the storage layer lands).

    Two change counters steer index maintenance:
    - data_version: bumps on ANY change (freshness checks)
    - mutation_epoch: bumps when existing row identity/order changes
      (delete/update/truncate) or when COLUMN identity changes
      (drop/rename — per-column-name caches like zone maps must not
      survive values moving under an old name). Pure appends and
      column ADDs keep the epoch, which lets search indexes refresh
      incrementally with a new segment instead of a full rebuild (the
      reference's segment model, SURVEY.md §2.7)."""

    def __init__(self, name: str, batch: Batch):
        self.name = name
        #: the table's entire mutable state, published as ONE reference:
        #: (batch, data_version, mutation_epoch, column_names,
        #: column_types). Readers observe it with a single attribute read
        #: — no lock — so SELECTs never wait on DML and can never pair a
        #: torn batch with the wrong version or schema (reference analog:
        #: publish-by-swap DirectoryReader snapshots, SURVEY.md §2.7; and
        #: the morsel-parallel reads of server_engine.cpp:225-244).
        self._pub = (batch, 0, 0, list(batch.names),
                     [c.type for c in batch.columns])
        #: serializes WRITERS of this table only (DML, checkpoint capture,
        #: ALTER); readers never take it
        self.write_lock = threading.RLock()
        #: wakes fast-path-publish waiters / quiescers of THIS table
        self.pub_cond = threading.Condition(self.write_lock)

    # single-reference publication: all views of the state are slices of
    # one tuple read
    @property
    def _batch(self) -> Batch:
        return self._pub[0]

    @property
    def data_version(self) -> int:
        return self._pub[1]

    @data_version.setter
    def data_version(self, v: int):
        b, _, e, n, t = self._pub
        self._pub = (b, v, e, n, t)

    @property
    def mutation_epoch(self) -> int:
        return self._pub[2]

    @mutation_epoch.setter
    def mutation_epoch(self, e: int):
        b, v, _, n, t = self._pub
        self._pub = (b, v, e, n, t)

    @property
    def column_names(self) -> list:
        return self._pub[3]

    @property
    def column_types(self) -> list:
        return self._pub[4]

    def pinned(self):
        return self._pub[:3]

    def try_pin(self):
        return self._pub[:3]

    def type_of(self, name: str) -> dt.SqlType:
        # one tuple read: two separate property reads could straddle a
        # publish and pair shifted indices during ALTER
        _, _, _, names, types = self._pub
        return types[names.index(name)]

    def row_count(self) -> int:
        return self._batch.num_rows

    def full_batch(self, columns: Optional[list[str]] = None) -> Batch:
        batch = self._batch
        if columns is None:
            return batch
        missing = [c for c in columns if c not in batch]
        if missing:
            raise errors.SqlError(errors.UNDEFINED_COLUMN,
                                  f"column {missing[0]} does not exist")
        return Batch(list(columns), [batch.column(c) for c in columns])

    def replace(self, batch: Batch, *, rows_preserved: bool = False):
        _, v, e, _, _ = self._pub
        self._pub = (batch, v + 1, e if rows_preserved else e + 1,
                     list(batch.names), [c.type for c in batch.columns])
        self.clear_device_cache()

    def append_batch(self, aligned: Batch):
        """Append rows (schema-aligned) without changing existing row
        identity — search indexes stay valid for the old rows."""
        self.append_batches([aligned])

    def append_batches(self, aligned_list: list):
        """Append several schema-aligned batches in ONE publication — the
        group-commit window's in-memory half: one column concat, one
        data_version bump, one device-cache clear, so per-table
        invalidation (result cache keys, device uploads) is paid per
        WINDOW, not per statement. Callers order the batches by WAL tick;
        the concat preserves that order, so replayed state matches."""
        from ..columnar.column import concat_batches
        batch = self._batch
        cols = []
        for i, name in enumerate(self.column_names):
            merged = concat_batches(
                [Batch([name], [batch.columns[i]])] +
                [Batch([name], [a.columns[i]])
                 for a in aligned_list]).columns[0]
            cols.append(merged)
        self.replace(Batch(list(self.column_names), cols),
                     rows_preserved=True)


_PA_TYPE_MAP = None


def _arrow_to_column(arr) -> Column:
    """pyarrow ChunkedArray/Array → Column (sorted-dictionary for strings)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    t = arr.type
    if pa.types.is_dictionary(t):
        arr = arr.cast(t.value_type)
        t = arr.type
    null_mask = None
    if arr.null_count:
        null_mask = np.asarray(arr.is_valid())
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        if arr.null_count:
            arr = arr.fill_null("")
        enc = pc.dictionary_encode(arr)
        if isinstance(enc, pa.ChunkedArray):
            enc = enc.combine_chunks()
        codes = np.asarray(enc.indices, dtype=np.int64)
        dictionary = np.asarray(enc.dictionary.to_pylist(), dtype=object)
        order = np.argsort(dictionary.astype(str), kind="stable")
        remap = np.empty(len(order), dtype=np.int32)
        remap[order] = np.arange(len(order), dtype=np.int32)
        sorted_dict = dictionary[order]
        return Column(dt.VARCHAR, remap[codes], null_mask, sorted_dict)
    if pa.types.is_timestamp(t):
        us = arr.cast(pa.timestamp("us"))
        data = np.asarray(us.cast(pa.int64()).fill_null(0))
        return Column(dt.TIMESTAMP, data.astype(np.int64), null_mask)
    if pa.types.is_date32(t):
        data = np.asarray(arr.cast(pa.int32()).fill_null(0))
        return Column(dt.DATE, data.astype(np.int32), null_mask)
    if pa.types.is_boolean(t):
        data = np.asarray(arr.fill_null(False))
        return Column(dt.BOOL, data.astype(np.bool_), null_mask)
    if arr.null_count:
        arr = arr.fill_null(0)
    data = np.asarray(arr)
    return Column(dt.type_of_numpy(data.dtype), data, null_mask)


def columns_parallel(tbl, names: list) -> dict:
    """{name: Column} conversions of a pyarrow Table's columns, fanned
    out over the shared worker pool when `serene_parallel_ingest` is on.

    History: PR 1 serialized ALL parquet column work because pyarrow's
    INTERNAL thread pool segfaulted after a write on another daemon
    thread. The crash lived in pyarrow's own pool (use_threads=True),
    which the file READ still avoids; each conversion here runs
    single-threaded pyarrow compute (combine_chunks / cast /
    dictionary_encode) on one of OUR workers, which the regression test
    in tests/test_ingest_stream.py drives through the original
    write-on-daemon-thread-then-read scenario. Off (or a single column)
    falls back to the serial loop — the parity oracle."""
    names = list(names)
    from ..search.segment import _ingest_setting
    if len(names) > 1 and _ingest_setting(None, "serene_parallel_ingest"):
        from ..parallel.pool import parallel_map
        cols = parallel_map(
            None, lambda n: _arrow_to_column(tbl.column(n)), names)
        return dict(zip(names, cols))
    return {n: _arrow_to_column(tbl.column(n)) for n in names}


class ParquetTable(TableProvider):
    """Zero-ETL parquet scan (reference analog: view-over-parquet fast path,
    index_source_view_file.*, examples/demo0/demo.sql)."""

    def __init__(self, path: str, name: Optional[str] = None):
        import pyarrow.parquet as pq
        self.path = path
        self.name = name or path
        self._pf = pq.ParquetFile(path)
        schema = self._pf.schema_arrow
        self.column_names = list(schema.names)
        self.column_types = []
        self._columns: dict[str, Column] = {}
        self._lock = threading.Lock()
        for f in schema:
            self.column_types.append(_arrow_field_type(f.type))

    def row_count(self) -> int:
        return self._pf.metadata.num_rows

    def full_batch(self, columns: Optional[list[str]] = None) -> Batch:
        cols = columns if columns is not None else self.column_names
        missing = [c for c in cols if c not in self.column_names]
        if missing:
            raise errors.SqlError(errors.UNDEFINED_COLUMN,
                                  f"column {missing[0]} does not exist")
        with self._lock:
            to_read = [c for c in cols if c not in self._columns]
            if to_read:
                # use_threads=False: pyarrow's INTERNAL CPU pool segfaults
                # when a write happened on another (daemon) server thread
                # earlier in this process; single-threaded file decode is
                # safe and the column cache amortizes it (see
                # test_filesource server drive). Column BUILDING fans out
                # over OUR worker pool instead (columns_parallel) — each
                # worker runs single-threaded pyarrow compute, which does
                # not wake pyarrow's pool; serene_parallel_ingest=off
                # restores the fully serial loop.
                tbl = self._pf.read(columns=to_read, use_threads=False)
                self._columns.update(columns_parallel(tbl, to_read))
            return Batch(list(cols), [self._columns[c] for c in cols])


def _arrow_field_type(t) -> dt.SqlType:
    import pyarrow as pa
    if pa.types.is_dictionary(t):
        t = t.value_type
    if pa.types.is_boolean(t):
        return dt.BOOL
    if pa.types.is_int8(t):
        return dt.TINYINT
    if pa.types.is_int16(t) or pa.types.is_uint8(t):
        return dt.SMALLINT
    if pa.types.is_int32(t) or pa.types.is_uint16(t):
        return dt.INT
    if pa.types.is_integer(t):
        return dt.BIGINT
    if pa.types.is_float32(t):
        return dt.FLOAT
    if pa.types.is_floating(t):
        return dt.DOUBLE
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return dt.VARCHAR
    if pa.types.is_timestamp(t):
        return dt.TIMESTAMP
    if pa.types.is_date(t):
        return dt.DATE
    return dt.VARCHAR
