"""Index-driven scan: the TPU analog of the reference's IResearch scan modes.

Reference analog: IResearchScanInitGlobal / DecideScanMode — Stream (filter
→ doc ids → materialize) and TopK (scored collectors)
(reference: server/connector/duckdb_search_full_scan.hpp:54-76).

Two modes:
- filter: evaluate the ts-predicate on the index (CPU doc-set algebra with
  device disjunction bitmaps), materialize matching rows, apply residual
  predicates.
- topk: BM25 block scoring + top-k on device (ops/bm25.py); emits rows in
  score order plus a `#score` float column the planner wires into bm25()
  calls and ORDER BY.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..columnar import dtypes as dt
from ..columnar.column import Batch, Column
from ..search.query import QNode
from ..sql.expr import BoundExpr
from .plan import PlanNode
from .tables import TableProvider

SCORE_COL = "#score"


class SearchScanNode(PlanNode):
    def __init__(self, provider: TableProvider, columns: list[str],
                 alias: str, search_column: str, qnode: QNode,
                 residual: Optional[BoundExpr], topk: Optional[int],
                 with_score: bool, scorer: str = "bm25"):
        self.provider = provider
        self.columns = columns
        self.alias = alias
        self.search_column = search_column
        self.qnode = qnode
        self.residual = residual
        self.topk = topk
        self.with_score = with_score
        self.scorer = scorer
        self.names = list(columns) + ([SCORE_COL] if with_score else [])
        self.types = [provider.type_of(c) for c in columns] + \
            ([dt.FLOAT] if with_score else [])

    def children(self):
        return []

    def label(self):
        mode = f"TopK k={self.topk}" if self.topk is not None else "Stream"
        return (f"SearchScan {self.provider.name}.{self.search_column} "
                f"mode={mode}")

    def _searcher(self):
        from ..search.index import find_index
        idx = find_index(self.provider, self.search_column)
        if idx is None:
            return None
        return idx.searcher(self.search_column)

    def _matching_docs(self, searcher) -> np.ndarray:
        """Doc selection with PG NULL semantics: a predicate over a NULL
        text value is NULL, never true — negation queries must not surface
        NULL rows. The count fast path shares this exact logic."""
        docs = searcher.eval_filter(self.qnode)
        col = self.provider.host_column(self.search_column)
        if col.validity is not None:
            docs = docs[col.validity[docs]]
        return docs

    def count_matching(self):
        """Row count without materialization (reference: ScanMode::Count);
        None when not applicable (top-k or residual present)."""
        if self.residual is not None or self.topk is not None:
            return None
        searcher = self._searcher()
        if searcher is None:
            return None
        return len(self._matching_docs(searcher))

    def batches(self, ctx):
        from .plan import check_cancel
        check_cancel()
        searcher = self._searcher()
        if searcher is None:
            raise RuntimeError("search index disappeared under the plan "
                               "(stale rewrite)")
        # ONE publication observation: the batch being materialized and
        # the zone-map verdicts pruning its candidate docs must come
        # from the same pin, or a racing publish could prune docs whose
        # values in the batch actually being scanned still match
        pin = self.provider.try_pin()
        if pin is not None and all(c in pin[0] for c in self.columns):
            full = Batch(list(self.columns),
                         [pin[0].column(c) for c in self.columns])
        else:
            full = self.provider.full_batch(self.columns)
        mesh_n = int(ctx.settings.get("serene_mesh") or 0)
        # stamp the scan's publication identity onto the searcher's
        # segments so posting-pool pages written for them report which
        # table/version/epoch they serve (sdb_posting_pool rows)
        from ..search import posting_pool
        posting_pool.note_publication(searcher, self.provider, pin)
        if self.topk is not None:
            # all serving paths (SQL @@@/bm25 scans, ES _search/_msearch)
            # funnel through this scan — the batcher coalesces concurrent
            # sessions' top-k dispatches here (serene_search_batch=off
            # dispatches serially, the parity oracle)
            from ..search.batcher import batched_topk
            (scores, docs), bstats = batched_topk(
                searcher, self.qnode, self.topk, self.scorer, mesh_n,
                ctx.settings)
            self._stamp_batch(ctx, bstats)
            self._stamp_shards(ctx, searcher)
            out = full.take(docs.astype(np.int64))
            if self.with_score:
                out = Batch(list(self.names),
                            out.columns + [Column(dt.FLOAT,
                                                  scores.astype(np.float32))])
            if self.residual is not None:
                c = self.residual.eval(out)
                out = out.filter(c.data.astype(bool) & c.valid_mask())
            yield out
            return
        docs = self._matching_docs(searcher)
        # the score pass ranks ALL index matches (it knows nothing of
        # the residual), so k must cover the PRE-prune candidate count —
        # otherwise pruned high-score docs would occupy the k slots and
        # surviving docs would read 0.0 off the score map
        n_candidates = len(docs)
        # zone maps on the column-filter side: candidate docs landing in
        # blocks the residual provably can't match are dropped BEFORE
        # materialization, and residual evaluation is skipped entirely
        # when every surviving doc sits in an all-match block (stream
        # mode only — top-k applies its residual after ranking)
        docs, residual_decided = self._prune_docs_by_zones(ctx, full, docs,
                                                           pin)
        out = full.take(docs.astype(np.int64))
        if self.with_score:
            from ..search.batcher import batched_topk
            (scores, sdocs), bstats = batched_topk(
                searcher, self.qnode, max(n_candidates, 1), self.scorer,
                mesh_n, ctx.settings)
            self._stamp_batch(ctx, bstats)
            self._stamp_shards(ctx, searcher)
            smap = np.zeros(max(searcher.num_docs, 1), dtype=np.float32)
            smap[sdocs] = scores
            out = Batch(list(self.names),
                        out.columns + [Column(dt.FLOAT, smap[docs])])
        if self.residual is not None and not residual_decided:
            c = self.residual.eval(out)
            out = out.filter(c.data.astype(bool) & c.valid_mask())
        yield out

    def _stamp_batch(self, ctx, bstats) -> None:
        """Profiler attribution of one batcher round trip (None when the
        query was served from the fragment cache or dispatched serially)."""
        prof = getattr(ctx, "profile", None)
        if prof is not None and bstats is not None:
            prof.add_search_batch(id(self), queries=bstats["queries"],
                                  window_ns=bstats["window_ns"],
                                  scoring_ns=bstats["scoring_ns"])

    def _stamp_shards(self, ctx, searcher) -> None:
        """`Shards:` attribution for a sharded multi-segment search:
        the segment set partitioned into min(serene_shards, segments)
        per-shard collector groups (searcher._run_segment_shards)."""
        from . import shard as shard_mod
        n = shard_mod.shard_count(ctx.settings)
        nseg = len(getattr(searcher, "segments", ()) or ())
        if n > 1 and nseg > 1:
            shard_mod.stamp_profile(ctx, id(self), min(n, nseg))

    def _prune_docs_by_zones(self, ctx, full: Batch, docs: np.ndarray,
                             pin) -> tuple[np.ndarray, bool]:
        """(surviving docs, residual_decided). residual_decided is True
        when zone maps proved the residual holds for every survivor.
        `pin` is the SAME publication observation `full` was built from."""
        if self.residual is None or not len(docs):
            return docs, False
        from . import zonemap
        block_rows = int(ctx.settings.get("serene_morsel_rows"))
        verdicts = zonemap.block_verdicts(
            self.provider, ctx.settings, [self.residual], self.columns,
            block_rows, pin)
        if verdicts is None:
            return docs, False
        bidx = docs // block_rows
        # an index refreshed past the pinned publication can hold docs
        # beyond the stats tail: treat those as must-scan
        v = np.where(bidx < len(verdicts),
                     verdicts[np.minimum(bidx, len(verdicts) - 1)],
                     np.int8(zonemap.SCAN))
        keep = v != zonemap.SKIP
        if not keep.all():
            from ..utils import metrics
            scanned_blocks = np.unique(bidx[keep])
            pruned_blocks = np.setdiff1d(np.unique(bidx[~keep]),
                                         scanned_blocks)
            metrics.ZONEMAP_PRUNED.add(len(pruned_blocks))
            metrics.ZONEMAP_SCANNED.add(len(scanned_blocks))
            prof = getattr(ctx, "profile", None)
            if prof is not None:
                prof.add_scan_morsels(id(self),
                                      scheduled=len(scanned_blocks),
                                      pruned=len(pruned_blocks))
            if zonemap.verify_enabled(ctx.settings):
                dropped = full.take(docs[~keep].astype(np.int64))
                c = self.residual.eval(dropped)
                if (c.data.astype(bool) & c.valid_mask()).any():
                    raise AssertionError(
                        "serene_zonemap_verify: zone map dropped a "
                        f"matching candidate doc in search scan of "
                        f"{self.provider.name}")
            docs = docs[keep]
            v = v[keep]
        return docs, bool(len(v)) and bool((v == zonemap.ALL).all())


class IvfScanNode(PlanNode):
    """ANN top-k scan: rows in ascending distance order + a `#dist` column.

    Reference analog: the ANN claim path (TryClaimAnnRange,
    optimizer/iresearch_plan.cpp:927-1015) feeding the IVF index."""

    DIST_COL = "#dist"

    def __init__(self, provider: TableProvider, columns: list[str],
                 alias: str, vector_column: str, query_vec, topk: int):
        self.provider = provider
        self.columns = columns
        self.alias = alias
        self.vector_column = vector_column
        self.query_vec = query_vec
        self.topk = topk
        self.names = list(columns) + [self.DIST_COL]
        self.types = [provider.type_of(c) for c in columns] + [dt.DOUBLE]

    def children(self):
        return []

    def label(self):
        return (f"IvfScan {self.provider.name}.{self.vector_column} "
                f"k={self.topk}")

    def batches(self, ctx):
        from .plan import check_cancel
        check_cancel()
        from ..search import vector_store
        from ..search.ivf import find_ivf_index
        idx = find_ivf_index(self.provider, self.vector_column)
        if idx is None:
            raise RuntimeError("ivf index disappeared under the plan")
        pin = self.provider.try_pin()
        # stamp the publication identity onto the index so vector-pool
        # pages written for its segments report which table/version they
        # serve (sdb_vector_pool rows)
        vector_store.note_publication(idx, self.provider, pin)
        nprobe = vector_store.effective_nprobe(ctx.settings)
        rerank = int(ctx.settings.get("sdb_rerank_factor"))
        mesh_n = int(ctx.settings.get("serene_mesh") or 0)
        # knn dispatches coalesce through the same batcher as BM25 —
        # the probe knobs ride in the scorer string, so queries with
        # different (k, nprobe, rerank) never share a stacked dispatch
        from ..search.batcher import batched_topk
        (dists, rows), bstats = batched_topk(
            idx, np.ascontiguousarray(self.query_vec, np.float32),
            self.topk, f"knn:{nprobe}:{rerank}", mesh_n, ctx.settings)
        prof = getattr(ctx, "profile", None)
        if prof is not None and bstats is not None:
            prof.add_search_batch(id(self), queries=bstats["queries"],
                                  window_ns=bstats["window_ns"],
                                  scoring_ns=bstats["scoring_ns"])
        keep = np.isfinite(dists)
        d, r = dists[keep], rows[keep]
        full = self.provider.full_batch(self.columns)
        out = full.take(r.astype(np.int64))
        yield Batch(list(self.names),
                    out.columns + [Column(dt.DOUBLE, d.astype(np.float64))])


class MaxSimScanNode(PlanNode):
    """Late-interaction top-k scan: rows in DESCENDING MaxSim-score
    order + a `#msim` column. Docs without tokens (NULL / empty) never
    match. `serene_maxsim = off` serves the exact float64 host oracle
    instead of the device program (FLASH-MAXSIM's reference check)."""

    SCORE_COL = "#msim"

    def __init__(self, provider: TableProvider, columns: list[str],
                 alias: str, vector_column: str, query_toks, topk: int):
        self.provider = provider
        self.columns = columns
        self.alias = alias
        self.vector_column = vector_column
        self.query_toks = query_toks
        self.topk = topk
        self.names = list(columns) + [self.SCORE_COL]
        self.types = [provider.type_of(c) for c in columns] + [dt.DOUBLE]

    def children(self):
        return []

    def label(self):
        return (f"MaxSimScan {self.provider.name}.{self.vector_column} "
                f"k={self.topk}")

    def batches(self, ctx):
        from .plan import check_cancel
        check_cancel()
        from ..search import vector_store
        from ..search.ivf import find_maxsim_index
        idx = find_maxsim_index(self.provider, self.vector_column)
        if idx is None:
            raise RuntimeError("maxsim index disappeared under the plan")
        pin = self.provider.try_pin()
        vector_store.note_publication(idx, self.provider, pin)
        q = np.ascontiguousarray(self.query_toks, np.float32)
        if vector_store.maxsim_device(ctx.settings):
            mesh_n = int(ctx.settings.get("serene_mesh") or 0)
            from ..search.batcher import batched_topk
            (keys, rows), bstats = batched_topk(
                idx, q, self.topk, "maxsim", mesh_n, ctx.settings)
            prof = getattr(ctx, "profile", None)
            if prof is not None and bstats is not None:
                prof.add_search_batch(id(self), queries=bstats["queries"],
                                      window_ns=bstats["window_ns"],
                                      scoring_ns=bstats["scoring_ns"])
            keep = np.isfinite(keys)
            scores = -keys[keep].astype(np.float64)
            r = rows[keep]
        else:
            hs = idx.host_scores(q)
            order = np.lexsort((idx.doc_rows, -hs))[:self.topk]
            scores = hs[order]
            r = idx.doc_rows[order]
        full = self.provider.full_batch(self.columns)
        out = full.take(r.astype(np.int64))
        yield Batch(list(self.names),
                    out.columns + [Column(dt.DOUBLE, scores)])


class BtreeScanNode(PlanNode):
    """Point/range lookup through a btree index (reference: PK lookup
    fast path, scripts/perf/bench_pk_lookup.sh)."""

    def __init__(self, provider: TableProvider, columns: list[str],
                 alias: str, index_column: str, eq_value, residual):
        self.provider = provider
        self.columns = columns
        self.alias = alias
        self.index_column = index_column
        self.eq_value = eq_value
        self.residual = residual
        self.names = list(columns)
        self.types = [provider.type_of(c) for c in columns]

    def children(self):
        return []

    def label(self):
        return f"BtreeScan {self.provider.name}.{self.index_column} eq"

    def count_matching(self):
        if self.residual is not None:
            return None
        from ..search.index import find_btree_index
        idx = find_btree_index(self.provider, self.index_column)
        if idx is None:
            return None
        return len(idx.lookup_eq(self.eq_value))

    def batches(self, ctx):
        from .plan import check_cancel
        check_cancel()
        from ..search.index import find_btree_index
        idx = find_btree_index(self.provider, self.index_column)
        if idx is None:
            raise RuntimeError("btree index disappeared under the plan")
        rows = idx.lookup_eq(self.eq_value)
        out = self.provider.full_batch(self.columns).take(rows)
        if self.residual is not None:
            c = self.residual.eval(out)
            out = out.filter(c.data.astype(bool) & c.valid_mask())
        yield out


class PkScanNode(PlanNode):
    """Primary-key scan through the sorted memcomparable key index
    (reference: PK point lookups + PK RANGE scans enabled by
    key_encoding.cpp order-preserving terms). Two modes:

    - "point": equality on EVERY PK column → at most one row
    - "range": bounds on the LEADING PK column → contiguous key slice
    """

    def __init__(self, provider: TableProvider, columns: list[str],
                 alias: str, mode: str, lo, hi, residual):
        self.provider = provider
        self.columns = columns
        self.alias = alias
        self.mode = mode
        self.lo = lo            # encoded key bytes (point: exact key)
        self.hi = hi            # range: exclusive upper bound or None
        self.residual = residual
        self.names = list(columns)
        self.types = [provider.type_of(c) for c in columns]

    def children(self):
        return []

    def label(self):
        return f"PkScan {self.provider.name} {self.mode}"

    def count_matching(self):
        if self.residual is not None:
            return None
        rows = self._rows()
        return None if rows is None else len(rows)

    def _rows(self):
        from ..search.pkindex import pk_index
        idx = pk_index(self.provider)
        if idx is None:
            return None
        if self.mode == "point":
            r = idx.get(self.lo)
            return np.asarray([r] if r >= 0 else [], dtype=np.int64)
        return idx.range_rows(self.lo, self.hi)

    def batches(self, ctx):
        from .plan import check_cancel
        check_cancel()
        rows = self._rows()
        if rows is None:
            raise RuntimeError("PK index disappeared under the plan")
        out = self.provider.full_batch(self.columns).take(rows)
        if self.residual is not None:
            c = self.residual.eval(out)
            out = out.filter(c.data.astype(bool) & c.valid_mask())
        yield out


class GeoScanNode(PlanNode):
    """Geo-predicate scan through the cell-term index: candidate rows
    from the posting lists of the query's probe terms, exact-verified by
    re-evaluating the ORIGINAL predicates over just the candidates
    (reference: GeoFilter candidate iteration + exact S2 verification,
    geo_filter_builder.cpp). Rows whose geometry text failed to parse at
    index build are not candidates."""

    def __init__(self, provider: TableProvider, columns: list[str],
                 alias: str, index_column: str, probe_terms: list,
                 residual):
        self.provider = provider
        self.columns = columns
        self.alias = alias
        self.index_column = index_column
        self.probe_terms = list(probe_terms)
        self.residual = residual
        self.names = list(columns)
        self.types = [provider.type_of(c) for c in columns]

    def children(self):
        return []

    def label(self):
        return (f"GeoScan {self.provider.name}.{self.index_column} "
                f"probes={len(self.probe_terms)}")

    def batches(self, ctx):
        from .plan import check_cancel
        check_cancel()
        from ..search.index import find_geo_index
        idx = find_geo_index(self.provider, self.index_column)
        if idx is None:
            raise RuntimeError("geo index disappeared under the plan")
        rows = idx.candidates(self.probe_terms)
        out = self.provider.full_batch(self.columns).take(rows)
        if self.residual is not None:
            c = self.residual.eval(out)
            out = out.filter(c.data.astype(bool) & c.valid_mask())
        yield out
