"""Morsel-driven parallel host pipelines: Scan→Filter→Project→partial-Agg.

Reference analog: DuckDB's morsel-driven parallelism (SURVEY.md §3.2) — a
table scan splits into fixed-size row morsels, each worker runs the WHOLE
operator chain over its morsel and feeds a partial-aggregate sink, and a
single combine step merges the partials. This is the host-CPU half of the
engine's headline ratios; the device offload (exec/device_agg.py) claims
the pipeline first and this path takes over whenever the device declines.

Determinism contract (the bench ledger asserts device-vs-CPU parity, so
the CPU result must not wobble):

- the morsel split is a pure function of (row count, serene_morsel_rows)
  — never of worker count or scheduling;
- partial batches merge in MORSEL ORDER via one vectorized second-level
  aggregation whose group order comes from the same composite-key
  factorization the serial path uses (ops/agg.py factorize_keys), so
  `serene_workers = 1` and `= N` produce bit-identical batches;
- exact combiners: integer SUM/COUNT merge in int64, MIN/MAX are
  selections, float partials accumulate in float64 with a fixed
  association.

Anything outside the supported shape (DISTINCT, ordered string_agg, record
keys, custom providers) falls back to the serial CPU oracle in plan.py.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..columnar import dtypes as dt
from ..columnar.column import (Batch, Column, concat_batches,
                               merge_dictionaries)
from ..obs.trace import batch_nbytes
from ..ops.agg import factorize_codes, factorize_keys
from ..parallel.pool import parallel_map
from ..sql.expr import AggSpec, BoundColumn

#: aggregate functions with an exact partial/combine decomposition
_PARALLEL_FUNCS = {
    "count_star", "count", "sum", "min", "max", "avg",
    "bool_and", "bool_or",
    "stddev", "stddev_samp", "var_samp", "variance", "stddev_pop",
    "var_pop",
}

_STDDEV = {"stddev", "stddev_samp", "var_samp", "variance", "stddev_pop",
           "var_pop"}


class _Fallback(Exception):
    """Shape turned out unsupported mid-flight — use the serial path."""


def _stage_clocks() -> tuple[int, int]:
    return time.perf_counter_ns(), time.thread_time_ns()


def _stage_stamp(prof, key: int, b: Batch,
                 clocks: tuple[int, int]) -> tuple[int, int]:
    """One morsel × one fused stage → one add_stage() span; returns fresh
    clocks so consecutive stages chain without double counting."""
    t1, c1 = time.perf_counter_ns(), time.thread_time_ns()
    prof.add_stage(key, b.num_rows, t1 - clocks[0], c1 - clocks[1],
                   batch_nbytes(b))
    return t1, c1


def try_parallel_aggregate(node, ctx) -> Optional[Batch]:
    """Morsel-parallel execution of an AggregateNode; None → serial CPU."""
    from .plan import FilterNode, ProjectNode, ScanNode, check_cancel

    settings = ctx.settings
    stages = []
    child = node.child
    while isinstance(child, (FilterNode, ProjectNode)):
        stages.append(child)
        child = child.child
    if type(child) is not ScanNode:
        return None
    scan = child
    stages.reverse()
    for spec in node.aggs:
        if spec.func not in _PARALLEL_FUNCS or spec.distinct \
                or spec.order_by:
            return None
    for g in node.group_exprs:
        if g.type.id is dt.TypeId.RECORD:
            return None
    # two classes of expression pin a pipeline to serial execution:
    # subquery impls carry lazily-computed one-shot caches that are not
    # synchronized (every worker would run the inner plan), and volatile
    # / sequence functions (nextval & co.) draw from shared mutable
    # state whose interleaving would break the workers=1 == workers=N
    # bit-identity contract.
    from ..sql.binder import _VOLATILE_FUNCS
    serial_only = _VOLATILE_FUNCS | {
        "scalar_subquery", "array_subquery", "in_subquery", "exists",
        "currval", "lastval"}
    exprs = ([scan.filter] if scan.filter is not None else []) + \
        [st.pred for st in stages if isinstance(st, FilterNode)] + \
        [e for st in stages if isinstance(st, ProjectNode)
         for e in st.exprs] + \
        list(node.group_exprs) + \
        [e for s in node.aggs for e in (s.arg, s.filter) if e is not None]
    for e in exprs:
        for sub in e.walk():
            if getattr(sub, "name", None) in serial_only:
                return None
    provider = scan.provider
    try:
        nrows = provider.row_count()
    except NotImplementedError:
        return None
    morsel_rows = int(settings.get("serene_morsel_rows"))
    if nrows < int(settings.get("serene_parallel_min_rows")) or \
            nrows <= morsel_rows:
        return None
    # ONE publication observation for the whole pipeline (same rule as the
    # device path): every morsel slices the same batch reference, and the
    # zone-map verdicts are built from the same pin so a racing publish
    # can never pair fresh data with stale block stats.
    from . import zonemap
    pin = provider.try_pin()
    if pin is not None:
        nrows = pin[0].num_rows

    # scan-schema-bound predicates: the pushed-down scan filter plus every
    # FilterNode ahead of the first projection (after a Project, column
    # indices refer to the projected batch, not the scan)
    first_proj = next((i for i, st in enumerate(stages)
                       if isinstance(st, ProjectNode)), len(stages))
    scan_preds = ([scan.filter] if scan.filter is not None else []) + \
        [st.pred for st in stages[:first_proj]]
    leading = frozenset(id(st) for st in stages[:first_proj])

    verdicts = zonemap.block_verdicts(provider, settings, scan_preds,
                                      scan.columns, morsel_rows, pin)
    spans = [(s, min(s + morsel_rows, nrows))
             for s in range(0, nrows, morsel_rows)]
    verify = verdicts is not None and zonemap.verify_enabled(settings)
    if verdicts is not None:
        zonemap.count_pruned(verdicts)
        keep = [(sp, int(v)) for sp, v in zip(spans, verdicts)
                if v != zonemap.SKIP]
    else:
        keep = [(sp, zonemap.SCAN) for sp in spans]
    prof = getattr(ctx, "profile", None)
    if prof is not None:
        prof.add_scan_morsels(id(scan), scheduled=len(keep),
                              pruned=len(spans) - len(keep))
    mem = getattr(ctx, "mem", None)
    if mem is not None:
        mem.add_morsels_scheduled(len(keep))
        mem.set_op(scan.label())

    # late materialization: only columns the scan-bound expressions
    # actually read are fetched before morsels run; the rest never
    # materialize (pinned providers hand out column references for free,
    # so the pin batch is used whole there)
    full = None
    if keep or verify:
        full = _scan_batch(provider, scan, stages, node, first_proj,
                           scan_preds, pin)
    if verify:
        pruned = [sp for sp, v in zip(spans, verdicts)
                  if v == zonemap.SKIP]
        zonemap.verify_pruned_blocks(scan_preds, full, pruned,
                                     "morsel aggregate")
    if not keep:
        # every block pruned: one empty morsel keeps the merge shape
        # (zero groups / NULL scalar aggregates) without touching data
        from .plan import empty_batch
        empty = empty_batch(list(scan.columns), list(scan.types))
        keep = [((0, 0), zonemap.SCAN)]
        full = empty

    def run_morsel(item):
        # per-stage span stamps (profile on): the fused pipeline is the
        # only execution these operators get, so each stage's rows/time
        # accumulate under the PLAN NODE's id from every worker thread —
        # the sink merge sums them, giving exact per-operator actual
        # rows at any worker count
        span, verdict = item
        check_cancel()
        b = full.slice(span[0], span[1])
        in_rows = b.num_rows
        in_bytes = batch_nbytes(b) if mem is not None else 0
        if in_bytes:
            # the morsel's working slice is this worker's live set for
            # the duration of the task (the slice views the pinned
            # batch, but filter/project stages materialize copies of
            # the same order of bytes — the slice size is the charge)
            mem.charge(id(scan), in_bytes)
        all_match = verdict == zonemap.ALL
        clocks = _stage_clocks() if prof is not None else None
        if scan.filter is not None and not all_match:
            c = scan.filter.eval(b)
            b = b.filter(c.data.astype(bool) & c.valid_mask())
        if clocks is not None:
            clocks = _stage_stamp(prof, id(scan), b, clocks)
        for st in stages:
            if isinstance(st, FilterNode):
                if all_match and id(st) in leading:
                    if clocks is not None:
                        clocks = _stage_stamp(prof, id(st), b, clocks)
                    continue     # zone maps proved every row matches
                c = st.pred.eval(b)
                b = b.filter(c.data.astype(bool) & c.valid_mask())
            else:
                b = Batch(list(st.names), [e.eval(b) for e in st.exprs])
            if clocks is not None:
                clocks = _stage_stamp(prof, id(st), b, clocks)
        p = _morsel_partials(node, b)
        if mem is not None:
            # the partial outlives the task (released by the merge
            # sink); the input slice retires with it
            mem.charge(id(node), batch_nbytes(p))
            mem.release(id(scan), in_bytes)
            mem.add_progress(rows=in_rows, nbytes=in_bytes, morsels=1)
        return p

    from ..obs.trace import current_trace
    from . import shard as shard_mod
    n_shards = shard_mod.shard_count(settings)
    trace = current_trace()
    t_pipe = time.perf_counter_ns() if trace is not None else 0
    try:
        if n_shards > 1 and len(keep) > 1:
            # sharded tier (exec/shard.py): ONE pipeline per shard — the
            # same morsel plan over the shard's round-robin block set,
            # fanned out as concurrent pool tasks. Partials re-enter the
            # merge in GLOBAL morsel order, so the sink consumes exactly
            # the shards=1 partial list and the combine stays the
            # bit-identical deterministic merge.
            groups: dict[int, list] = {}
            for pos, item in enumerate(keep):
                s = shard_mod.shard_of_block(item[0][0] // morsel_rows,
                                             n_shards)
                groups.setdefault(s, []).append((pos, item))
            shard_lists = [groups[s] for s in sorted(groups)]

            def run_shard(entries):
                return [(pos, run_morsel(item)) for pos, item in entries]

            parts = shard_mod.run_shard_tasks(settings, run_shard,
                                              shard_lists)
            ordered: list = [None] * len(keep)
            for chunk in parts:
                for pos, p in chunk:
                    ordered[pos] = p
            shard_mod.stamp_profile(ctx, id(node), len(shard_lists))
            out = _merge_partials(node, ordered)
            if mem is not None:
                mem.release(id(node),
                            sum(batch_nbytes(p) for p in ordered))
            if trace is not None:
                trace.add("morsel_pipeline", "morsel", t_pipe,
                          time.perf_counter_ns(), morsels=len(keep),
                          shards=len(shard_lists))
            return out
        partials = parallel_map(settings, run_morsel, keep)
        out = _merge_partials(node, partials)
        if mem is not None:
            mem.release(id(node),
                        sum(batch_nbytes(p) for p in partials))
        if trace is not None:
            trace.add("morsel_pipeline", "morsel", t_pipe,
                      time.perf_counter_ns(), morsels=len(keep))
        return out
    except _Fallback:
        return None


def _scan_batch(provider, scan, stages, node, first_proj: int,
                scan_preds: list, pin) -> Batch:
    """The pipeline's input batch under one publication observation.
    Pinned (mutable) providers hand back their published batch — column
    references, zero cost. Pin-less providers (parquet) decode columns
    lazily, so only the columns the scan-bound expressions actually
    reference are fetched; unreferenced positions get zero-byte
    broadcast placeholders that keep Batch geometry without
    materializing (they are provably never evaluated)."""
    names = scan.columns
    if pin is not None:
        batch = pin[0]
        if all(c in batch for c in names):
            return Batch(list(names), [batch.column(c) for c in names])
        return provider.full_batch(names)     # surface the proper error
    scan_bound = list(scan_preds)
    if first_proj < len(stages):
        scan_bound += list(stages[first_proj].exprs)
    else:
        scan_bound += list(node.group_exprs)
        scan_bound += [e for s in node.aggs
                       for e in (s.arg, s.filter) if e is not None]
    referenced: set[int] = set()
    for e in scan_bound:
        for sub in e.walk():
            if isinstance(sub, BoundColumn):
                referenced.add(sub.index)
    if len(referenced) >= len(names):
        return provider.full_batch(names)
    need = [names[i] for i in sorted(referenced)]
    fetched = provider.full_batch(need) if need else None
    n = fetched.num_rows if fetched is not None else provider.row_count()
    cols = []
    for i, c in enumerate(names):
        if i in referenced:
            cols.append(fetched.column(c))
        else:
            t = scan.types[i]
            cols.append(Column(
                t, np.broadcast_to(np.zeros(1, dtype=t.np_dtype), (n,)),
                None,
                np.asarray([""], dtype=object) if t.is_string else None))
    return Batch(list(names), cols)


# -- per-morsel partial states ----------------------------------------------
#
# Each morsel reduces to a tiny Batch: one row per (group seen in the
# morsel), key columns first (real Columns, so dictionary-encoded string
# keys merge through the normal concat machinery), then fixed-width state
# columns per aggregate.


#: combined slot-space cap for the direct (perfect-hash) key coding
_DIRECT_SPACE_CAP = 1 << 16


def _direct_key_plan(key_cols: list[Column]) -> Optional[list[tuple]]:
    """[(lo, range)] per key when every key direct-codes into a small
    slot space (dict codes / small-range ints), else None. Mirrors the
    device path's perfect-hash key coding (device_agg._plan_direct_keys)
    so the host morsel sink skips the composite lexsort entirely."""
    plan: list[tuple] = []
    space = 1
    for kc in key_cols:
        d = kc.data
        if kc.type.is_string and kc.dictionary is not None:
            lo, r = 0, len(kc.dictionary)
        elif d.dtype.kind in "iu":
            vd = d if kc.validity is None else d[kc.validity]
            if not len(vd):
                lo, r = 0, 0
            else:
                lo = int(vd.min())
                r = int(vd.max()) - lo + 1
        else:
            return None
        plan.append((lo, r))
        space *= r + 1          # one extra slot per key: NULL sorts last
        if space > _DIRECT_SPACE_CAP:
            return None
    return plan


def _direct_codes(key_cols: list[Column], plan: list[tuple],
                  ) -> tuple[np.ndarray, list[np.ndarray], np.ndarray, int]:
    """Dense group codes via direct slot coding — no sort. Slot order per
    key is (valid values ascending, NULL last), the exact composite order
    factorize_keys produces, so group order is identical either way."""
    n = len(key_cols[0].data)
    codes = np.zeros(n, dtype=np.int64)
    for kc, (lo, r) in zip(key_cols, plan):
        slot = kc.data.astype(np.int64) - lo
        if kc.validity is not None:
            slot = np.where(kc.validity, slot, r)
        codes = codes * (r + 1) + slot
    space = 1
    for _, r in plan:
        space *= r + 1
    occ = np.bincount(codes, minlength=space)
    present = np.flatnonzero(occ)
    remap = np.zeros(space, dtype=np.int64)
    remap[present] = np.arange(len(present))
    dense = remap[codes].astype(np.int32)
    uniq_vals: list[np.ndarray] = []
    valids: list[np.ndarray] = []
    rem = present.copy()
    for kc, (lo, r) in zip(reversed(key_cols), reversed(plan)):
        slot = rem % (r + 1)
        rem = rem // (r + 1)
        valid = slot != r
        vals = np.where(valid, slot + lo, 0).astype(kc.data.dtype)
        uniq_vals.append(vals)
        valids.append(valid)
    uniq_vals.reverse()
    valids.reverse()
    uniq_valid = np.stack(valids) if valids \
        else np.ones((0, len(present)), dtype=bool)
    return dense, uniq_vals, uniq_valid, len(present)


def _group_codes(key_cols: list[Column],
                 ) -> tuple[np.ndarray, list[np.ndarray], np.ndarray, int]:
    n = len(key_cols[0].data)
    if n:
        plan = _direct_key_plan(key_cols)
        if plan is not None:
            return _direct_codes(key_cols, plan)
    codes, uniq_vals, uniq_valid = factorize_keys(
        [c.data for c in key_cols],
        [c.validity for c in key_cols])
    g = len(uniq_vals[0]) if uniq_vals else 0
    return codes, uniq_vals, uniq_valid, g


def _morsel_partials(node, b: Batch) -> Batch:
    key_cols = [g.eval(b) for g in node.group_exprs]
    if key_cols:
        codes, uniq_vals, uniq_valid, g = _group_codes(key_cols)
    else:
        codes = np.zeros(b.num_rows, dtype=np.int32)
        uniq_vals, uniq_valid = [], np.ones((0, 1), dtype=bool)
        g = 1
    names: list[str] = []
    cols: list[Column] = []
    for k, kc in enumerate(key_cols):
        validity = uniq_valid[k] if uniq_valid.size else None
        if validity is not None and validity.all():
            validity = None
        names.append(f"#k{k}")
        cols.append(Column(kc.type, uniq_vals[k], validity, kc.dictionary))
    for j, spec in enumerate(node.aggs):
        for m, c in enumerate(_partial_state(spec, b, codes, g)):
            names.append(f"#s{j}_{m}")
            cols.append(c)
    return Batch(names, cols)


def _partial_state(spec: AggSpec, b: Batch, codes: np.ndarray,
                   g: int) -> list[Column]:
    if spec.filter is not None:
        c = spec.filter.eval(b)
        fm = c.data.astype(bool) & c.valid_mask()
        b = b.filter(fm)
        codes = codes[fm]
    if spec.func == "count_star":
        return [_i64(np.bincount(codes, minlength=g))]
    arg = spec.arg.eval(b)
    valid = arg.valid_mask()
    vc = codes[valid]
    cnt = np.bincount(vc, minlength=g).astype(np.int64)
    if spec.func == "count":
        return [_i64(cnt)]
    vals = arg.data[valid]
    empty = cnt == 0
    if spec.func in ("sum", "avg") or spec.func in _STDDEV:
        # keyed off the DECLARED result type: sum(bool) binds as DOUBLE
        # (BOOL is not is_integer), so its partials must be float or the
        # result batch would contradict the RowDescription type
        int_sum = spec.func == "sum" and spec.type.is_integer
        if int_sum:
            acc = np.zeros(g, dtype=np.int64)
            np.add.at(acc, vc, vals.astype(np.int64))
            return [_i64(acc), _i64(cnt)]
        s1 = np.zeros(g, dtype=np.float64)
        fv = vals.astype(np.float64)
        np.add.at(s1, vc, fv)
        if spec.func in _STDDEV:
            s2 = np.zeros(g, dtype=np.float64)
            np.add.at(s2, vc, fv * fv)
            return [_f64(s1), _f64(s2), _i64(cnt)]
        return [_f64(s1), _i64(cnt)]
    if spec.func in ("min", "max"):
        if arg.type.is_string:
            if arg.dictionary is None:
                raise _Fallback("string min/max without dictionary")
            # sorted dictionary ⇒ code order == string order; ship the
            # per-group champion as a real VARCHAR column so concat
            # re-encodes codes onto the merged dictionary
            ident = np.iinfo(np.int64).max if spec.func == "min" else -1
            acc = np.full(g, ident, dtype=np.int64)
            ufunc = np.minimum if spec.func == "min" else np.maximum
            ufunc.at(acc, vc, vals.astype(np.int64))
            acc = np.where(empty, 0, acc).astype(np.int32)
            return [Column(dt.VARCHAR, acc,
                           ~empty if empty.any() else None, arg.dictionary),
                    _i64(cnt)]
        if arg.type.is_float:
            if spec.func == "min":
                # PG float order: min skips NaN unless the group is
                # all-NaN — track has-non-NaN alongside (serial path's
                # np.fmin + has_non_nan stamp, decomposed)
                acc = np.full(g, np.inf, dtype=np.float64)
                with np.errstate(invalid="ignore"):
                    np.fmin.at(acc, vc, vals.astype(np.float64))
                nonnan = np.zeros(g, dtype=bool)
                np.logical_or.at(nonnan, vc, ~np.isnan(vals))
                return [_f64(acc), _i64(nonnan.astype(np.int64)),
                        _i64(cnt)]
            acc = np.full(g, -np.inf, dtype=np.float64)
            with np.errstate(invalid="ignore"):   # NaN propagation wanted
                np.maximum.at(acc, vc, vals.astype(np.float64))
            return [_f64(acc), _i64(cnt)]
        ident = np.iinfo(np.int64).max if spec.func == "min" else \
            np.iinfo(np.int64).min
        acc = np.full(g, ident, dtype=np.int64)
        ufunc = np.minimum if spec.func == "min" else np.maximum
        ufunc.at(acc, vc, vals.astype(np.int64))
        return [_i64(acc), _i64(cnt)]
    if spec.func in ("bool_and", "bool_or"):
        vb = vals.astype(bool)
        if spec.func == "bool_and":
            acc = np.ones(g, dtype=bool)
            np.logical_and.at(acc, vc, vb)
        else:
            acc = np.zeros(g, dtype=bool)
            np.logical_or.at(acc, vc, vb)
        return [Column(dt.BOOL, acc), _i64(cnt)]
    raise _Fallback(f"aggregate {spec.func}")


# -- vectorized relational tier (hash join / set ops / DISTINCT ON) ----------
#
# Shared key machinery for the operators above the scan (ISSUE 3): factorize
# composite keys from BOTH inputs into ONE dense int64 code space, then do
# all matching with array kernels — the batched-codes trick GPUSparse uses
# for accelerator-side postings intersection, applied host-side. The legacy
# row-tuple interpreters in plan.py stay as the parity oracle behind
# `SET serene_join_vectorized = off`.


def vectorized_enabled(settings) -> bool:
    try:
        return bool(settings.get("serene_join_vectorized"))
    except KeyError:  # pragma: no cover — registry always declares it
        return False


def combined_codes(cols_a: list[Column], cols_b: list[Column]
                   ) -> Optional[tuple[np.ndarray, np.ndarray, int]]:
    """Dense int64 codes over the CONCATENATION of two equal-arity column
    lists (a-rows first), in one shared code space: equal code ⟺ the
    legacy python row tuples would compare equal. Dictionary-encoded
    string pairs re-encode onto one merged dictionary first (code order
    is irrelevant here, only equality); numeric pairs concatenate under
    numpy promotion (int vs float keys compare by value, like python).
    Returns (codes_a, codes_b, num_codes), or None when a column pair
    has no sound array representation (mixed string/non-string keys,
    dictionary-less strings) — callers fall back to the row-tuple path.
    """
    if not cols_a or len(cols_a) != len(cols_b):
        return None
    arrays: list[np.ndarray] = []
    valids: list[Optional[np.ndarray]] = []
    for ca, cb in zip(cols_a, cols_b):
        if ca.type.is_string or cb.type.is_string:
            if not (ca.type.is_string and cb.type.is_string) or \
                    ca.dictionary is None or cb.dictionary is None:
                return None
            ma, mb = merge_dictionaries([ca, cb])
            data = np.concatenate([ma.data, mb.data])
        else:
            if ca.data.dtype.kind not in "biuf" or \
                    cb.data.dtype.kind not in "biuf":
                return None
            data = np.concatenate([ca.data, cb.data])
            if data.dtype.kind == "f":
                # an integer side promoted to float64 meets its partner
                # exactly only below 2**53 — python row tuples compare
                # int == float losslessly, so beyond that bound the
                # array path must defer to the oracle
                for side in (ca.data, cb.data):
                    if side.dtype.kind in "iu" and len(side) and \
                            (int(side.max()) > 2 ** 53 or
                             int(side.min()) < -(2 ** 53)):
                        return None
        if ca.validity is None and cb.validity is None:
            valid = None
        else:
            valid = np.concatenate([ca.valid_mask(), cb.valid_mask()])
        arrays.append(data)
        valids.append(valid)
    codes, g = factorize_codes(arrays, valids)
    na = len(cols_a[0])
    return codes[:na], codes[na:], g


def rows_valid(cols: list[Column]) -> Optional[np.ndarray]:
    """AND of the columns' validities (None ⇒ every row fully valid)."""
    valid: Optional[np.ndarray] = None
    for c in cols:
        if c.validity is not None:
            valid = c.validity if valid is None else (valid & c.validity)
    return valid


def first_occurrence_mask(codes: np.ndarray, g: int) -> np.ndarray:
    """True at the FIRST row of each code, in row order."""
    n = len(codes)
    first = np.full(g, n, dtype=np.int64)
    np.minimum.at(first, codes, np.arange(n, dtype=np.int64))
    return first[codes] == np.arange(n, dtype=np.int64)


def occurrence_ranks(codes: np.ndarray, g: int) -> np.ndarray:
    """0-based occurrence number of each row within its code, in row
    order (row i holding code c ranks k when it is the (k+1)-th row with
    c) — the vectorized form of the bag-semantics counters the legacy
    INTERSECT/EXCEPT ALL paths kept per row."""
    n = len(codes)
    order = np.argsort(codes, kind="stable")
    sorted_codes = codes[order]
    counts = np.bincount(codes, minlength=g)
    group_start = np.concatenate([[0], np.cumsum(counts)[:-1]]) \
        if g else np.zeros(0, dtype=np.int64)
    pos_sorted = np.arange(n, dtype=np.int64) - \
        np.repeat(group_start, counts) if n else \
        np.zeros(0, dtype=np.int64)
    ranks = np.empty(n, dtype=np.int64)
    ranks[order] = pos_sorted
    return ranks


_EMPTY_I64 = np.empty(0, dtype=np.int64)


def join_pairs(lkeys: list[Column], rkeys: list[Column], settings,
               nl: int, nr: int
               ) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """Candidate (left, right) index pairs of the equi-join, vectorized.

    Build side (right): rows grouped by key code via one stable argsort +
    bincount prefix sums — a dense offset/payload index, no python dicts.
    Probe side (left): morsel tasks over the shared worker pool expand
    matches with repeat/cumsum arithmetic; partial pair vectors merge in
    MORSEL ORDER, so the pair stream is bit-identical to the serial scan
    at any worker count and exactly matches the legacy interpreter's
    (left row, right insertion order) emission. NULL keys never match
    (masked out per side, NOT grouped). None → caller uses the legacy
    row-tuple path.
    """
    if nl == 0 or nr == 0:
        return _EMPTY_I64, _EMPTY_I64
    pair = combined_codes(lkeys, rkeys)
    if pair is None:
        return None
    cl, cr, g = pair
    lvalid = rows_valid(lkeys)
    rvalid = rows_valid(rkeys)

    # build: right row ids grouped by code, plus per-code [offset, count)
    if rvalid is None:
        bidx = np.arange(nr, dtype=np.int64)
        crv = cr
    else:
        bidx = np.flatnonzero(rvalid).astype(np.int64)
        crv = cr[bidx]
    order = np.argsort(crv, kind="stable")
    sorted_right = bidx[order]
    counts = np.bincount(crv, minlength=g)
    offsets = np.concatenate([[0], np.cumsum(counts)[:-1]]) \
        if g else np.zeros(0, dtype=np.int64)

    def probe(span: tuple[int, int]) -> tuple[np.ndarray, np.ndarray]:
        from .plan import check_cancel
        check_cancel()
        s, e = span
        if lvalid is None:
            pidx = np.arange(s, e, dtype=np.int64)
        else:
            pidx = np.flatnonzero(lvalid[s:e]).astype(np.int64) + s
        pc = cl[pidx]
        cnt = counts[pc]
        li = np.repeat(pidx, cnt)
        total = int(cnt.sum())
        if total == 0:
            return li, _EMPTY_I64
        cum = np.cumsum(cnt)
        within = np.arange(total, dtype=np.int64) - \
            np.repeat(cum - cnt, cnt)
        ri = sorted_right[np.repeat(offsets[pc], cnt) + within]
        return li, ri

    morsel_rows = int(settings.get("serene_morsel_rows"))
    spans = [(s, min(s + morsel_rows, nl))
             for s in range(0, nl, morsel_rows)]
    if nl > morsel_rows and \
            nl >= int(settings.get("serene_parallel_min_rows")):
        parts = parallel_map(settings, probe, spans)
    else:
        parts = [probe(sp) for sp in spans]
    li = np.concatenate([p[0] for p in parts])
    ri = np.concatenate([p[1] for p in parts])
    return li, ri


def _i64(a: np.ndarray) -> Column:
    return Column(dt.BIGINT, a.astype(np.int64))


def _f64(a: np.ndarray) -> Column:
    return Column(dt.DOUBLE, a.astype(np.float64))


_STATE_WIDTH = {"count_star": 1, "count": 1, "sum": 2, "avg": 2,
                "min": 2, "max": 2, "bool_and": 2, "bool_or": 2}


def _state_width(spec: AggSpec) -> int:
    if spec.func in _STDDEV:
        return 3
    if spec.func == "min" and spec.arg is not None and \
            spec.arg.type.is_float:
        return 3
    return _STATE_WIDTH[spec.func]


# -- merge sink --------------------------------------------------------------


def _merge_partials(node, partials: list[Batch]) -> Batch:
    nk = len(node.group_exprs)
    merged = concat_batches(partials)
    if nk:
        key_cols = merged.columns[:nk]
        codes, uniq_vals, uniq_valid = factorize_keys(
            [c.data for c in key_cols],
            [c.validity for c in key_cols])
        g = len(uniq_vals[0]) if uniq_vals else 0
    else:
        codes = np.zeros(merged.num_rows, dtype=np.int32)
        uniq_vals, uniq_valid = [], np.ones((0, 1), dtype=bool)
        g = 1
    out_cols: list[Column] = []
    for k in range(nk):
        kc = key_cols[k]
        validity = uniq_valid[k] if uniq_valid.size else None
        if validity is not None and validity.all():
            validity = None
        out_cols.append(Column(kc.type, uniq_vals[k], validity,
                               kc.dictionary))
    ci = nk
    for spec in node.aggs:
        w = _state_width(spec)
        out_cols.append(_combine(spec, merged.columns[ci:ci + w], codes, g))
        ci += w
    return Batch(list(node.names), out_cols)


def _combine(spec: AggSpec, states: list[Column], codes: np.ndarray,
             g: int) -> Column:
    if spec.func in ("count_star", "count"):
        acc = np.zeros(g, dtype=np.int64)
        np.add.at(acc, codes, states[0].data)
        return Column(dt.BIGINT, acc)
    cnt = np.zeros(g, dtype=np.int64)
    np.add.at(cnt, codes, states[-1].data)
    empty = cnt == 0
    validity = ~empty if empty.any() else None
    # value scatters only take partial rows that actually saw valid input
    live = states[-1].data > 0
    lc = codes[live]
    if spec.func == "sum":
        v = states[0]
        if v.data.dtype.kind == "i":
            acc = np.zeros(g, dtype=np.int64)
            np.add.at(acc, lc, v.data[live])
            return Column(dt.BIGINT, acc, validity)
        acc = np.zeros(g, dtype=np.float64)
        np.add.at(acc, lc, v.data[live])
        return Column(dt.DOUBLE, acc, validity)
    if spec.func == "avg":
        acc = np.zeros(g, dtype=np.float64)
        np.add.at(acc, lc, states[0].data[live])
        with np.errstate(invalid="ignore", divide="ignore"):
            data = acc / cnt
        return Column(dt.DOUBLE, np.where(empty, 0.0, data), validity)
    if spec.func in _STDDEV:
        pop = spec.func.endswith("_pop")
        s1 = np.zeros(g, dtype=np.float64)
        s2 = np.zeros(g, dtype=np.float64)
        np.add.at(s1, lc, states[0].data[live])
        np.add.at(s2, lc, states[1].data[live])
        fc = cnt.astype(np.float64)
        with np.errstate(invalid="ignore", divide="ignore"):
            var = (s2 - s1 * s1 / fc) / (fc if pop else fc - 1)
        var = np.maximum(var, 0.0)     # float cancellation clamp (PG)
        bad = cnt < (1 if pop else 2)
        data = np.sqrt(var) if spec.func.startswith("stddev") else var
        return Column(dt.DOUBLE, np.where(bad, 0.0, data),
                      ~bad if bad.any() else None)
    if spec.func in ("min", "max"):
        t = spec.arg.type
        if t.is_string:
            v = states[0]
            ident = np.iinfo(np.int64).max if spec.func == "min" else -1
            acc = np.full(g, ident, dtype=np.int64)
            ufunc = np.minimum if spec.func == "min" else np.maximum
            ufunc.at(acc, lc, v.data[live].astype(np.int64))
            acc = np.where(empty, 0, acc).astype(np.int32)
            return Column(dt.VARCHAR, acc, validity, v.dictionary)
        if t.is_float:
            if spec.func == "min":
                acc = np.full(g, np.inf, dtype=np.float64)
                # partial mins never hold NaN (fmin skips; all-NaN groups
                # hold the +inf identity) so plain minimum is exact here
                np.minimum.at(acc, lc, states[0].data[live])
                nonnan = np.zeros(g, dtype=bool)
                np.logical_or.at(nonnan, lc, states[1].data[live] > 0)
                acc = np.where(~empty & ~nonnan, np.nan, acc)
            else:
                acc = np.full(g, -np.inf, dtype=np.float64)
                with np.errstate(invalid="ignore"):
                    np.maximum.at(acc, lc, states[0].data[live])
            acc = np.where(empty, 0, acc).astype(t.np_dtype)
            return Column(t, acc, validity)
        ident = np.iinfo(np.int64).max if spec.func == "min" else \
            np.iinfo(np.int64).min
        acc = np.full(g, ident, dtype=np.int64)
        ufunc = np.minimum if spec.func == "min" else np.maximum
        ufunc.at(acc, lc, states[0].data[live])
        acc = np.where(empty, 0, acc).astype(t.np_dtype)
        return Column(t, acc, validity)
    if spec.func in ("bool_and", "bool_or"):
        v = states[0].data.astype(bool)
        if spec.func == "bool_and":
            acc = np.ones(g, dtype=bool)
            np.logical_and.at(acc, lc, v[live])
        else:
            acc = np.zeros(g, dtype=bool)
            np.logical_or.at(acc, lc, v[live])
        return Column(dt.BOOL, acc, validity)
    raise _Fallback(f"aggregate {spec.func}")
