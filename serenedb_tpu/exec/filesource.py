"""Zero-ETL file sources: read_parquet / read_csv table functions with
glob expansion and remote-URL fetch.

Reference analog: server/connector/index_source_view_file.cpp (file-backed
views dispatching read_parquet over member files) + its http/S3 readers.
Remote fetch is a straight HTTP GET with an on-disk content cache; in a
no-egress environment it surfaces SQLSTATE 58030 rather than hanging.
"""

from __future__ import annotations

import glob as _glob
import hashlib
import os
import tempfile

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Batch, Column, concat_batches
from .tables import MemTable, ParquetTable, TableProvider

_FETCH_CACHE_DIR = os.path.join(tempfile.gettempdir(),
                                "serenedb_fetch_cache")


def is_remote(path: str) -> bool:
    return path.startswith(("http://", "https://", "s3://"))


def resolve_path(path: str) -> str:
    """Local path for a possibly-remote file (download-through cache)."""
    if not is_remote(path):
        return path
    if path.startswith("s3://"):
        # anonymous S3 over the HTTP endpoint (the reference's S3 reader
        # with credentials is config surface we don't have secrets for yet)
        bucket, _, key = path[5:].partition("/")
        path = f"https://{bucket}.s3.amazonaws.com/{key}"
    os.makedirs(_FETCH_CACHE_DIR, exist_ok=True)
    name = hashlib.sha256(path.encode()).hexdigest()[:32] + \
        os.path.splitext(path)[1]
    local = os.path.join(_FETCH_CACHE_DIR, name)
    if os.path.exists(local):
        return local
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(path, timeout=60) as resp:
            data = resp.read()
    except (urllib.error.URLError, OSError) as e:
        raise errors.SqlError(
            "58030", f"remote file fetch failed for {path}: {e}")
    tmp = local + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, local)
    return local


def expand_glob(path: str) -> list[str]:
    if is_remote(path):
        return [path]
    if any(ch in path for ch in "*?["):
        matches = sorted(_glob.glob(path))
        if not matches:
            raise errors.SqlError("58P01",
                                  f"no files match {path!r}")
        return matches
    if not os.path.exists(path):
        raise errors.SqlError(
            "58P01", f'could not open file "{path}": '
                     "No such file or directory")
    return [path]


def parquet_source(db, path: str, pinned: bool = False) -> TableProvider:
    """read_parquet over a path, glob, or URL. Single local files reuse
    the provider cache (HBM column cache + compiled programs); multi-file
    globs materialize a unioned table cached by (paths, mtimes).

    pinned=True: iceberg-style snapshot pinning (reference:
    index_source_view_file.cpp pinned snapshots) — the FIRST resolution
    freezes the file list and contents; files added, changed or removed
    later never alter results. Pins live for the Database's lifetime
    (a fresh Database re-resolves)."""
    if pinned:
        with db.lock:
            pins = getattr(db, "_pinned_snapshots", None)
            if pins is None:
                pins = db._pinned_snapshots = {}
            hit = pins.get(("parquet", path))
        if hit is not None:
            return hit
        provider = parquet_source(db, path, pinned=False)
        # materialize NOW: later mtime/file changes must not show through
        frozen = MemTable(os.path.basename(path),
                          provider.full_batch())
        with db.lock:
            # two concurrent first resolutions: FIRST pin wins, both
            # serve the same snapshot thereafter
            return pins.setdefault(("parquet", path), frozen)
    paths = [resolve_path(p) for p in expand_glob(path)]
    if len(paths) == 1:
        with db.lock:
            p = db._parquet_cache.get(paths[0])
            if p is None:
                p = db._parquet_cache[paths[0]] = ParquetTable(paths[0])
        return p
    key = tuple((p, os.path.getmtime(p)) for p in paths)
    cache = getattr(db, "_fileview_cache", None)
    if cache is None:
        cache = db._fileview_cache = {}
    hit = cache.get(("parquet", key))
    if hit is not None:
        return hit
    batches = [ParquetTable(p).full_batch() for p in paths]
    names = batches[0].names
    types0 = [c.type for c in batches[0].columns]
    for i, b in enumerate(batches[1:], 1):
        if list(b.names) != list(names) or \
                [c.type for c in b.columns] != types0:
            raise errors.SqlError(
                "42P16", f"parquet files disagree on schema: "
                         f"{paths[0]} vs {paths[i]}")
    t = MemTable(os.path.basename(path), concat_batches(batches))
    if len(cache) > 32:
        cache.clear()
    cache[("parquet", key)] = t
    return t


def _infer_column(vals: list) -> Column:
    """int64 → float64 → text inference over csv strings ('' = NULL)."""
    live = [v for v in vals if v != ""]

    def try_cast(cast, typ):
        out = []
        for v in vals:
            if v == "":
                out.append(None)
            else:
                out.append(cast(v))
        return Column.from_pylist(out, typ)
    try:
        return try_cast(int, dt.BIGINT)
    except ValueError:
        pass
    try:
        return try_cast(float, dt.DOUBLE)
    except ValueError:
        pass
    if live and all(v.lower() in ("true", "false", "t", "f") for v in live):
        return Column.from_pylist(
            [None if v == "" else v.lower() in ("true", "t")
             for v in vals], dt.BOOL)
    return Column.from_pylist([None if v == "" else v for v in vals],
                              dt.VARCHAR)


def csv_source(db, path: str, header=None, delimiter=",") -> TableProvider:
    """read_csv with type inference; header auto-detected unless given
    (a first row whose cells don't parse under the inferred body types)."""
    import csv as _csv
    paths = [resolve_path(p) for p in expand_glob(path)]
    key = tuple((p, os.path.getmtime(p)) for p in paths) + \
        (header, delimiter)
    cache = getattr(db, "_fileview_cache", None)
    if cache is None:
        cache = db._fileview_cache = {}
    hit = cache.get(("csv", key))
    if hit is not None:
        return hit
    all_rows: list[list[str]] = []
    first_header: list[str] | None = None
    for pi, p in enumerate(paths):
        try:
            with open(p, newline="") as f:
                rows = list(_csv.reader(f, delimiter=delimiter))
        except OSError as e:
            raise errors.SqlError("58030", f"cannot read {p}: {e}")
        if not rows:
            continue
        use_header = header
        if use_header is None:
            # auto-detect: a first row that is all-text while any body
            # cell in the same column parses numeric ⇒ header
            use_header = _looks_like_header(rows)
        if use_header:
            if first_header is None:
                first_header = [c.strip() for c in rows[0]]
            rows = rows[1:]
        all_rows.extend(rows)
    ncols = max((len(r) for r in all_rows), default=0)
    if first_header is not None:
        # header-only files still expose their declared columns
        ncols = max(ncols, len(first_header))
    if first_header is None:
        first_header = [f"column{i}" for i in range(ncols)]
    if len(first_header) < ncols:
        first_header += [f"column{i}"
                         for i in range(len(first_header), ncols)]
    cols = []
    for ci in range(ncols):
        vals = [(r[ci] if ci < len(r) else "") for r in all_rows]
        cols.append(_infer_column(vals))
    t = MemTable(os.path.basename(path),
                 Batch(first_header[:ncols], cols))
    if len(cache) > 32:
        cache.clear()
    cache[("csv", key)] = t
    return t


def _looks_like_header(rows: list[list[str]]) -> bool:
    if len(rows) < 2:
        return False
    head, body = rows[0], rows[1:]

    def numericish(v: str) -> bool:
        try:
            float(v)
            return True
        except ValueError:
            return False
    for ci in range(len(head)):
        if numericish(head[ci]):
            return False        # numeric header cell ⇒ data row
        if any(ci < len(r) and r[ci] != "" and numericish(r[ci])
               for r in body):
            return True         # text over a numeric column ⇒ header
    return False
