"""Zone maps: per-morsel block statistics + predicate skip-scan.

Reference analog: ClickHouse-style granule pruning on the analytics side
and block-max WAND on the search side (ops/bm25.py) share one discipline —
consult per-block bounds before touching data, and never materialize a
block whose bounds prove it can't contribute. This module gives the
columnar scan paths that capability:

- **Block stats** (`column_zones`): per `serene_morsel_rows`-aligned block,
  min / max / null count (+ a has-NaN flag for floats) for numeric, date/
  timestamp, interval, bool, and dictionary-encoded string columns. Stats
  are built lazily per column, cached on the TableProvider, and
  version-stamped exactly like the device-column cache: any `data_version`
  bump invalidates, but a pure append (same `mutation_epoch`) only
  rebuilds the tail blocks — complete prefix blocks are reused because
  epoch-preserving operations never change existing row values. String
  min/max are stored DECODED (python str, the sorted-dictionary order) so
  append-time dictionary re-encodes can't stale them.

- **Interval analyzer** (`block_verdicts`): evaluates a conjunction of
  bound filter expressions against each block's stats to a three-state
  verdict — SKIP (no row can match), ALL (every row must match), SCAN
  (unknown). Internally each subexpression maps to the SET of row
  outcomes it can take on the block ({true, false, null} bitmask), so
  AND/OR/NOT compose with exact Kleene algebra and anything unsupported
  (expressions over columns, casts of columns, functions, subqueries)
  degrades to the safe "all outcomes possible" set. Comparisons follow
  the engine's PG float total order: NaN = NaN and NaN > everything.

- **Consumers**: exec/morsel.py never enqueues SKIP morsels and skips
  filter evaluation on ALL morsels; plan.ScanNode skip-scans filtered
  serial scans; exec/device_agg.py / device_topn.py shrink the padded
  device upload to the contiguous surviving block range; search_scan's
  stream mode drops candidate docs that fall in SKIP blocks.

`SET serene_zonemap = off` disables everything; `serene_zonemap_verify`
re-scans every pruned block and fails loudly if any row matched (the
structural guard the verify script arms over the parity suite).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from .. import errors
from ..columnar import dtypes as dt
from ..columnar.column import Batch, Column
from ..sql.binder import _CMP_CANON, comparison_parts
from ..sql.expr import BoundColumn, BoundExpr, BoundFunc, BoundLiteral
from ..utils import metrics

#: three-state block verdicts (ints so verdict vectors are numpy arrays)
SKIP, SCAN, ALL = 0, 1, 2

#: possible row outcomes of a predicate over a block, as a bitmask set
_T, _F, _N = 1, 2, 4
_TFN = _T | _F | _N

#: column type ids whose values zone-compare exactly: fixed-width scalars
#: ordered by their physical representation, plus sorted-dictionary
#: VARCHAR (decoded min/max compare in python-str order == code order).
#: ARRAY/RECORD share the dictionary representation but compare
#: field-wise, not text-wise — excluded.
_SUPPORTED = {dt.TypeId.BOOL, dt.TypeId.TINYINT, dt.TypeId.SMALLINT,
              dt.TypeId.INT, dt.TypeId.BIGINT, dt.TypeId.FLOAT,
              dt.TypeId.DOUBLE, dt.TypeId.DATE, dt.TypeId.TIMESTAMP,
              dt.TypeId.INTERVAL, dt.TypeId.VARCHAR}


def enabled(settings) -> bool:
    try:
        return bool(settings.get("serene_zonemap"))
    except KeyError:  # pragma: no cover — registry always declares it
        return False


def verify_enabled(settings) -> bool:
    try:
        return bool(settings.get("serene_zonemap_verify"))
    except KeyError:  # pragma: no cover
        return False


def join_filter_enabled(settings) -> bool:
    try:
        return bool(settings.get("serene_join_filter"))
    except KeyError:  # pragma: no cover
        return False


# -- per-column block statistics --------------------------------------------


@dataclass
class ColumnZones:
    """Block stats for one column at one block size. mins/maxs hold
    DECODED python values (str for VARCHAR, int/float/bool otherwise);
    None marks a block with no valid non-NaN value."""

    type: dt.SqlType
    block_rows: int
    nrows: int
    mins: list
    maxs: list
    nulls: np.ndarray     # int64 per block: invalid rows
    counts: np.ndarray    # int64 per block: total rows
    nans: np.ndarray      # bool per block: any NaN among valid (floats)

    @property
    def n_blocks(self) -> int:
        return len(self.mins)


def _build_blocks(col: Column, block_rows: int, start_row: int,
                  nrows: int) -> tuple[list, list, list, list, list]:
    """Stats for blocks covering [start_row, nrows) (start block-aligned)."""
    mins, maxs, nulls, counts, nans = [], [], [], [], []
    is_str = col.type.id is dt.TypeId.VARCHAR
    is_float = col.data.dtype.kind == "f"
    for s in range(start_row, nrows, block_rows):
        e = min(s + block_rows, nrows)
        data = col.data[s:e]
        if col.validity is None:
            valid_n = e - s
            vals = data
        else:
            v = col.validity[s:e]
            valid_n = int(v.sum())
            vals = data[v]
        counts.append(e - s)
        nulls.append((e - s) - valid_n)
        has_nan = False
        mn = mx = None
        if valid_n:
            if is_float:
                nan = np.isnan(vals)
                has_nan = bool(nan.any())
                vv = vals[~nan] if has_nan else vals
                if len(vv):
                    mn, mx = vv.min().item(), vv.max().item()
            elif is_str:
                d = col.dictionary
                mn = str(d[int(vals.min())])
                mx = str(d[int(vals.max())])
            else:
                mn, mx = vals.min().item(), vals.max().item()
        mins.append(mn)
        maxs.append(mx)
        nans.append(has_nan)
    return mins, maxs, nulls, counts, nans


_cache_guard = threading.Lock()

#: bound on cached (column, block_rows) entries per provider
_CACHE_CAP = 64


def _zone_lock(provider) -> threading.Lock:
    lk = getattr(provider, "_zonemap_lock", None)
    if lk is None:
        with _cache_guard:
            lk = getattr(provider, "_zonemap_lock", None)
            if lk is None:
                lk = threading.Lock()
                provider._zonemap_lock = lk
    return lk


def column_zones(provider, name: str, block_rows: int,
                 pin=None) -> Optional[ColumnZones]:
    """Version-stamped block stats for one column, cached on the
    provider. `pin` is the caller's (batch, data_version, mutation_epoch)
    publication observation (tables.TableProvider.try_pin); stats are
    built from — and stamped with — that same observation so a racing
    publish can never pair stale stats with fresh data. Returns None for
    unsupported column types (dictionary-less strings included)."""
    if pin is not None:
        batch, ver, epoch = pin[0], pin[1], pin[2]
    else:
        batch = None
        ver = provider.data_version
        epoch = getattr(provider, "mutation_epoch", 0)
    lock = _zone_lock(provider)
    key = (name, block_rows)
    # the column's schema POSITION is part of the cache identity.
    # Column-identity ALTERs (drop/rename) bump mutation_epoch today, so
    # the epoch check alone already rejects them — the position check is
    # defense in depth: if a future change makes some schema ALTER
    # epoch-preserving again, a name moving to a different position
    # still forces a rebuild instead of silently reusing stale stats
    try:
        names = list(batch.names) if batch is not None \
            else list(provider.column_names)
        col_pos = names.index(name)
    except ValueError:
        return None
    with lock:
        cache = getattr(provider, "_zonemap_cache", None)
        if cache is None:
            cache = provider._zonemap_cache = {}
        entry = cache.get(key)
        if entry is not None and entry[0] == ver and entry[2] == col_pos:
            return entry[3]
    try:
        col = (batch.column(name) if batch is not None
               else provider.full_batch([name]).column(name))
    except Exception:   # column dropped/renamed under the plan
        return None
    if col.type.id not in _SUPPORTED or \
            (col.type.id is dt.TypeId.VARCHAR and col.dictionary is None):
        return None
    nrows = len(col)
    old: Optional[ColumnZones] = None
    if entry is not None:
        old = entry[3]
        if old is not None and entry[1] == epoch and entry[2] == col_pos \
                and old.type == col.type \
                and old.block_rows == block_rows and nrows >= old.nrows:
            # pure append: existing row values are unchanged (epoch
            # semantics), so complete prefix blocks carry over verbatim
            # and only the tail rebuilds
            keep = old.nrows // block_rows
            m, x, nu, cn, na = _build_blocks(col, block_rows,
                                             keep * block_rows, nrows)
            zones = ColumnZones(
                col.type, block_rows, nrows,
                old.mins[:keep] + m, old.maxs[:keep] + x,
                np.concatenate([old.nulls[:keep],
                                np.asarray(nu, dtype=np.int64)]),
                np.concatenate([old.counts[:keep],
                                np.asarray(cn, dtype=np.int64)]),
                np.concatenate([old.nans[:keep],
                                np.asarray(na, dtype=bool)]))
            with lock:
                cache[key] = (ver, epoch, col_pos, zones)
            return zones
        metrics.ZONEMAP_STALE_REBUILDS.add()
    m, x, nu, cn, na = _build_blocks(col, block_rows, 0, nrows)
    zones = ColumnZones(col.type, block_rows, nrows, m, x,
                        np.asarray(nu, dtype=np.int64),
                        np.asarray(cn, dtype=np.int64),
                        np.asarray(na, dtype=bool))
    with lock:
        if len(cache) >= _CACHE_CAP:
            cache.pop(next(iter(cache)))
        cache[key] = (ver, epoch, col_pos, zones)
    return zones


# -- interval analyzer -------------------------------------------------------
#
# A predicate over one block maps to the SET of outcomes its rows can take
# ({true, false, null} bitmask). Leaves derive their set from block stats;
# AND/OR/NOT combine sets with exact Kleene algebra over the cross product
# (sound over-approximation: children share rows, so the true outcome set
# is a subset of the combination set). Unknown shapes yield {T,F,N}.

def _and3(x: int, y: int) -> int:
    if x == _F or y == _F:
        return _F
    if x == _N or y == _N:
        return _N
    return _T


def _or3(x: int, y: int) -> int:
    if x == _T or y == _T:
        return _T
    if x == _N or y == _N:
        return _N
    return _F


def _combine(a: int, b: int, op3) -> int:
    out = 0
    for x in (_T, _F, _N):
        if not a & x:
            continue
        for y in (_T, _F, _N):
            if b & y:
                out |= op3(x, y)
    return out


def _not_set(a: int) -> int:
    out = a & _N
    if a & _T:
        out |= _F
    if a & _F:
        out |= _T
    return out


def _cmp_set(op: str, zones: ColumnZones, b: int, const) -> int:
    """Outcome set of `column <op> const` over block b."""
    nulls = int(zones.nulls[b])
    nvalid = int(zones.counts[b]) - nulls
    s = _N if nulls else 0
    if nvalid == 0:
        return s or _N      # empty block degenerates to "no rows": N only
    if const is None:
        return s | _N       # strict comparison with NULL is NULL per row
    mn, mx = zones.mins[b], zones.maxs[b]
    has_nan = bool(zones.nans[b])
    has_range = mn is not None
    if zones.type.id is dt.TypeId.VARCHAR:
        if not isinstance(const, str):
            return _TFN
        c = const
    else:
        if isinstance(const, str):
            return _TFN
        c = const
    c_nan = isinstance(c, float) and c != c
    t = f = False
    if c_nan:
        # PG float total order: NaN = NaN and NaN is the greatest value
        if op == "=":
            t, f = has_nan, has_range
        elif op == "<>":
            t, f = has_range, has_nan
        elif op == "<":
            t, f = has_range, has_nan
        elif op == "<=":
            t, f = True, False
        elif op == ">":
            t, f = False, True
        else:                # >=
            t, f = has_nan, has_range
    else:
        if op == "=":
            t = has_range and mn <= c <= mx
            f = has_nan or (has_range and not (mn == c == mx))
        elif op == "<>":
            t = has_nan or (has_range and not (mn == c == mx))
            f = has_range and mn <= c <= mx
        elif op == "<":
            t = has_range and mn < c
            f = has_nan or (has_range and mx >= c)
        elif op == "<=":
            t = has_range and mn <= c
            f = has_nan or (has_range and mx > c)
        elif op == ">":
            t = has_nan or (has_range and mx > c)
            f = has_range and mn <= c
        else:                # >=
            t = has_nan or (has_range and mx >= c)
            f = has_range and mn < c
    if t:
        s |= _T
    if f:
        s |= _F
    return s


class _Analyzer:
    """Compiled once per predicate list; evaluated per block. `zones_of`
    maps a scan column index to its ColumnZones (or None)."""

    def __init__(self, exprs: list[BoundExpr],
                 zones_of: Callable[[int], Optional[ColumnZones]]):
        self.exprs = exprs
        self.zones_of = zones_of
        #: comparison leaves fold their constant side ONCE per query —
        #: re-folding per block would rebuild a dummy batch and re-eval
        #: the constant expression once per block for nothing
        self._parts: dict[int, Optional[tuple]] = {}
        self.prunable = any(self._has_prunable_leaf(e) for e in exprs)

    def _parts_of(self, e: BoundFunc) -> Optional[tuple]:
        k = id(e)
        if k not in self._parts:
            self._parts[k] = comparison_parts(e)
        return self._parts[k]

    def _has_prunable_leaf(self, e: BoundExpr) -> bool:
        for sub in e.walk():
            if isinstance(sub, BoundFunc):
                if sub.name in _CMP_CANON:
                    parts = self._parts_of(sub)
                    if parts is not None and \
                            self.zones_of(parts[0]) is not None:
                        return True
                if sub.name in ("is_null", "is_not_null") and \
                        isinstance(sub.args[0], BoundColumn) and \
                        self.zones_of(sub.args[0].index) is not None:
                    return True
        return False

    def outcome_set(self, e: BoundExpr, b: int) -> int:
        if isinstance(e, BoundLiteral):
            if e.value is None:
                return _N
            if isinstance(e.value, bool):
                return _T if e.value else _F
            return _TFN
        if not isinstance(e, BoundFunc):
            return _TFN
        name = e.name
        if name == "and":
            s = _T
            for a in e.args:
                s = _combine(s, self.outcome_set(a, b), _and3)
            return s
        if name == "or":
            s = _F
            for a in e.args:
                s = _combine(s, self.outcome_set(a, b), _or3)
            return s
        if name == "opnot" or name == "not":
            if len(e.args) == 1:
                return _not_set(self.outcome_set(e.args[0], b))
            return _TFN
        if name in ("is_null", "is_not_null") and len(e.args) == 1 and \
                isinstance(e.args[0], BoundColumn):
            zones = self.zones_of(e.args[0].index)
            if zones is None:
                return _TFN
            nulls = int(zones.nulls[b])
            total = int(zones.counts[b])
            has_null, has_val = nulls > 0, nulls < total
            if name == "is_not_null":
                has_null, has_val = has_val, has_null
            return (_T if has_null else 0) | (_F if has_val else 0) or _N
        if name in _CMP_CANON:
            parts = self._parts_of(e)
            if parts is None:
                return _TFN
            ci, op, const = parts
            zones = self.zones_of(ci)
            if zones is None:
                return _TFN
            return _cmp_set(op, zones, b, const)
        return _TFN

    def verdict(self, b: int) -> int:
        s = _T
        for e in self.exprs:
            s = _combine(s, self.outcome_set(e, b), _and3)
            if not s & _T:
                return SKIP
        return ALL if s == _T else SCAN


def block_verdicts(provider, settings, exprs: list[BoundExpr],
                   columns: list[str], block_rows: int,
                   pin=None) -> Optional[np.ndarray]:
    """Per-block verdict vector (SKIP/SCAN/ALL) for the conjunction of
    `exprs` over a scan of `columns`, or None when zone maps can't help
    (disabled, single block, no prunable conjunct, provider without
    row_count). BoundColumn indices in `exprs` index into `columns`."""
    if not exprs or not enabled(settings):
        return None
    try:
        nrows = pin[0].num_rows if pin is not None else provider.row_count()
    except NotImplementedError:
        return None
    if nrows <= block_rows:
        return None
    zcache: dict[int, Optional[ColumnZones]] = {}

    def zones_of(ci: int) -> Optional[ColumnZones]:
        if ci not in zcache:
            if 0 <= ci < len(columns):
                zcache[ci] = column_zones(provider, columns[ci],
                                          block_rows, pin)
            else:
                zcache[ci] = None
        return zcache[ci]

    az = _Analyzer(exprs, zones_of)
    if not az.prunable:
        return None
    n_blocks = (nrows + block_rows - 1) // block_rows
    # a concurrent append can leave cached zones one (rebuilt) call away
    # from the pinned row count; zones_of built from the same pin, so the
    # block counts always agree with nrows here
    out = np.empty(n_blocks, dtype=np.int8)
    for b in range(n_blocks):
        out[b] = az.verdict(b)
    return out


def count_pruned(verdicts: np.ndarray) -> None:
    """Bump the sdb_metrics counters for one pruned scan."""
    pruned = int((verdicts == SKIP).sum())
    if pruned:
        metrics.ZONEMAP_PRUNED.add(pruned)
    scanned = len(verdicts) - pruned
    if scanned:
        metrics.ZONEMAP_SCANNED.add(scanned)


def count_join_filter(verdicts: np.ndarray) -> None:
    """Bump the join-filter sideways-pushdown counters (verdicts from the
    published build-key range alone, so pruning is attributed exactly)."""
    pruned = int((verdicts == SKIP).sum())
    if pruned:
        metrics.JOIN_FILTER_PRUNED.add(pruned)
    scanned = len(verdicts) - pruned
    if scanned:
        metrics.JOIN_FILTER_SCANNED.add(scanned)


def combine_verdicts(a: Optional[np.ndarray],
                     b: Optional[np.ndarray]) -> Optional[np.ndarray]:
    """Conjunction of two per-block verdict vectors. SKIP < SCAN < ALL by
    value, and conjunction is exactly the minimum: SKIP if either side
    skips, ALL iff both prove every row matches."""
    if a is None:
        return b
    if b is None:
        return a
    return np.minimum(a, b)


#: key column families the join filter can publish a range for: the
#: range literal must both zone-compare (exec/zonemap._cmp_set) and
#: evaluate through the engine's comparison kernels in verify mode
_JF_RANGEABLE = {dt.TypeId.BOOL, dt.TypeId.TINYINT, dt.TypeId.SMALLINT,
                 dt.TypeId.INT, dt.TypeId.BIGINT, dt.TypeId.FLOAT,
                 dt.TypeId.DOUBLE, dt.TypeId.DATE, dt.TypeId.TIMESTAMP,
                 dt.TypeId.VARCHAR}


def build_key_range_exprs(probe_keys, build_key_cols) -> list[BoundExpr]:
    """Min/max sideways information passing (the SereneDB/DuckDB join
    filter): for every equi-key whose probe side is a bare scan column,
    fold the build side's observed key range into two synthetic
    comparison conjuncts `col >= lo AND col <= hi`, bound with the same
    scalar kernels the binder would use. The exprs feed `block_verdicts`
    on the probe scan, so morsels whose block stats can't overlap the
    build keys are never enqueued — and `serene_zonemap_verify` re-scans
    them structurally like any other pruned block.

    NULL and NaN build keys never find a partner (row-tuple semantics),
    so they are excluded from the published range; probe blocks that are
    all-NULL or all-NaN on the key prune as a consequence. Returns []
    when no key is rangeable (caller scans normally)."""
    from ..functions import scalar as fnlib

    exprs: list[BoundExpr] = []
    for pk, kc in zip(probe_keys, build_key_cols):
        if not isinstance(pk, BoundColumn) or \
                pk.type.id not in _JF_RANGEABLE or \
                kc.type.id not in _JF_RANGEABLE:
            continue
        valid = kc.valid_mask()
        if kc.type.is_string:
            if kc.dictionary is None:
                continue
            vals = kc.data[valid]
            if not len(vals):
                continue
            lo = str(kc.dictionary[int(vals.min())])
            hi = str(kc.dictionary[int(vals.max())])
            lit_t = dt.VARCHAR
        else:
            vals = kc.data[valid]
            if vals.dtype.kind == "f":
                vals = vals[~np.isnan(vals)]
            if not len(vals):
                continue
            lo, hi = vals.min().item(), vals.max().item()
            if vals.dtype.kind == "f":
                lit_t = dt.DOUBLE
            elif kc.type.id in (dt.TypeId.DATE, dt.TypeId.TIMESTAMP):
                lit_t = kc.type
            elif kc.type.id is dt.TypeId.BOOL:
                lit_t = dt.BOOL
            else:
                lit_t = dt.BIGINT
        try:
            pair = []
            for op, v in (("op>=", lo), ("op<=", hi)):
                res = fnlib.resolve(op, [pk.type, lit_t])

                def impl(cols, batch, _impl=res.impl):
                    return _impl(cols, batch.num_rows)

                pair.append(BoundFunc(
                    op, [BoundColumn(pk.index, pk.type, pk.name),
                         BoundLiteral(v, lit_t)], dt.BOOL, impl))
        except errors.SqlError:
            continue          # no comparison kernel for this type pair
        exprs.extend(pair)
    return exprs


def surviving_range(verdicts: np.ndarray, block_rows: int,
                    nrows: int) -> tuple[int, int]:
    """Row range [lo, hi) covering every non-SKIP block (contiguous
    envelope — interior SKIP blocks stay, prefix/suffix prune). lo == hi
    when everything is pruned. lo is always block-aligned (and therefore
    a multiple of 128: serene_morsel_rows is floored at 1024)."""
    alive = np.flatnonzero(verdicts != SKIP)
    if not len(alive):
        return 0, 0
    lo = int(alive[0]) * block_rows
    hi = min((int(alive[-1]) + 1) * block_rows, nrows)
    return lo, hi


# -- verification (debug assert mode) ---------------------------------------


def verify_pruned_blocks(exprs: list[BoundExpr], full: Batch,
                         spans: list[tuple[int, int]], what: str) -> None:
    """serene_zonemap_verify: re-scan pruned blocks with the REAL filter
    and fail loudly if any row matched — stats/data divergence must
    surface structurally, never as silently wrong results."""
    for s, e in spans:
        b = full.slice(s, e)
        mask = np.ones(b.num_rows, dtype=bool)
        for ex in exprs:
            c = ex.eval(b)
            mask &= c.data.astype(bool) & c.valid_mask()
            if not mask.any():
                break
        if mask.any():
            raise AssertionError(
                f"serene_zonemap_verify: zone map pruned a matching "
                f"morsel in {what} (rows {s}..{e}: "
                f"{int(mask.sum())} matching rows) — block statistics "
                f"diverged from table data")


# -- top-N candidate range ---------------------------------------------------


def topn_block_range(provider, settings, name: str, block_rows: int,
                     desc: bool, k: int, pin=None
                     ) -> Optional[tuple[int, int]]:
    """Row range [lo, hi) that provably contains every top-k candidate
    for ORDER BY name [DESC] LIMIT k, from block bounds alone: take
    blocks in best-block-WORST-value order until they cover k rows — the
    k-th best value is then at least that threshold, so any block whose
    best value is strictly beyond it cannot contribute. Assumes the
    caller already rejected NULLs and NaNs (device_topn's gates). None
    when nothing prunes."""
    if not enabled(settings):
        return None
    zones = column_zones(provider, name, block_rows, pin)
    if zones is None or zones.n_blocks <= 1 or zones.nans.any() or \
            int(zones.nulls.sum()):
        return None
    mins = zones.mins
    maxs = zones.maxs
    nb = zones.n_blocks
    if any(m is None for m in mins):
        return None
    # worst value still inside block b for the sort direction
    worst = mins if desc else maxs
    best = maxs if desc else mins
    order = sorted(range(nb), key=lambda b: worst[b], reverse=desc)
    covered = 0
    thresh = None
    for b in order:
        thresh = worst[b]
        covered += int(zones.counts[b])
        if covered >= k:
            break
    if covered < k:
        return None          # fewer rows than k: nothing to prune
    if desc:
        alive = [b for b in range(nb) if best[b] >= thresh]
    else:
        alive = [b for b in range(nb) if best[b] <= thresh]
    if len(alive) == nb:
        return None
    lo = alive[0] * block_rows
    hi = min((alive[-1] + 1) * block_rows, zones.nrows)
    if hi - lo >= zones.nrows:
        return None
    metrics.ZONEMAP_PRUNED.add(nb - (alive[-1] - alive[0] + 1))
    metrics.ZONEMAP_SCANNED.add(alive[-1] - alive[0] + 1)
    return lo, hi
