"""Sharded execution tier: the same pipeline over hash-partitioned data.

PAPER.md §8's scale-out claim is "the same program over a mesh" — no
shard-aware operators, no exchange plans: storage partitions
deterministically, every shard runs the UNCHANGED morsel / fused-device
pipeline over its partition, and the engine's existing deterministic
merge sinks (ordered partial merge from PR 1, single-heap top-k,
partial-aggregate combine) become the cross-shard combiners.

Partitioning is a pure function of (row count, `serene_morsel_rows`,
`serene_shards`): morsel block b belongs to shard b % N (round-robin).
Round-robin keeps existing blocks pinned to their shard forever, so a
pure append only creates/extends TAIL blocks — every other shard's zone
maps, device uploads and cached fragments stay valid, the same
append-friendliness the zone maps rely on. `serene_shards = 1` is
today's unsharded execution and the bit-identity parity oracle: the
shard split only GROUPS work, the combine consumes partials in the same
global morsel order the unsharded path produces, so results are
bit-identical at any shard count, worker count, or device count.

Placement: shard pipelines run as concurrent PR-1 worker-pool tasks;
when a multi-device jax mesh is present (parallel/mesh.py), per-shard
fused device programs additionally pin their inputs to
`mesh.shard_devices()` so shard s dispatches on device s % n_devices —
the data axis of the mesh, with the host-side exact integer combine
playing the psum role.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..utils import metrics


def shard_count(settings=None) -> int:
    """The session's `serene_shards` (>= 1). settings=None → the
    executing connection's settings when inside a statement, else the
    global default (library callers outside any session) — the
    session_workers(None) pattern."""
    if settings is None:
        from ..engine import CURRENT_CONNECTION
        conn = CURRENT_CONNECTION.get()
        if conn is not None:
            settings = conn.settings
    try:
        if settings is not None:
            n = int(settings.get("serene_shards"))
        else:
            from ..utils.config import REGISTRY
            n = int(REGISTRY.get_global("serene_shards"))
    except KeyError:  # pragma: no cover — registry always declares it
        n = 1
    return max(1, n)


def combine_mode(settings=None) -> str:
    """Resolved `serene_shard_combine`: 'device' or 'host'. 'auto'
    resolves to device when the process sees more than one jax device
    (the mesh data axis has real width), else host — so a single-chip
    box defaults to the PR 9 per-shard-dispatch path and a multi-device
    mesh gets the one-dispatch psum combine. The auto probe is PASSIVE:
    it never initializes the jax backend (a pure-host sharded search
    must stay jax-free, and initializing a tunneled device backend
    during a tunnel outage is a hard hang), so before the first real
    device dispatch of the process auto conservatively reads host.
    Same settings-resolution pattern as shard_count(None)."""
    if settings is None:
        from ..engine import CURRENT_CONNECTION
        conn = CURRENT_CONNECTION.get()
        if conn is not None:
            settings = conn.settings
    try:
        if settings is not None:
            mode = str(settings.get("serene_shard_combine"))
        else:
            from ..utils.config import REGISTRY
            mode = str(REGISTRY.get_global("serene_shard_combine"))
    except KeyError:  # pragma: no cover — registry always declares it
        mode = "auto"
    if mode == "auto":
        from ..parallel.mesh import device_count_if_initialized
        return "device" if device_count_if_initialized() > 1 else "host"
    return mode


def shard_of_block(block: int, n_shards: int) -> int:
    """Round-robin block→shard assignment (THE partitioning function)."""
    return block % n_shards


def shard_spans(nrows: int, block_rows: int, n_shards: int
                ) -> list[list[tuple[int, int]]]:
    """Per-shard row spans of a table: shard s owns every morsel block b
    with b % n_shards == s, as [(start, end)] in ascending block order.
    Empty tables yield n_shards empty lists."""
    out: list[list[tuple[int, int]]] = [[] for _ in range(n_shards)]
    for b, start in enumerate(range(0, nrows, block_rows)):
        out[shard_of_block(b, n_shards)].append(
            (start, min(start + block_rows, nrows)))
    return out


def group_round_robin(items: list, n_shards: int) -> list[list]:
    """Round-robin grouping of an ordered work list (segments, morsels)
    into at most n_shards non-empty shard groups, preserving intra-group
    order. Pure function of (len(items), n_shards) — never of worker
    count or scheduling."""
    n = min(n_shards, len(items))
    if n <= 1:
        return [list(items)] if items else []
    groups: list[list] = [[] for _ in range(n)]
    for i, it in enumerate(items):
        groups[i % n].append(it)
    return groups


def run_shard_tasks(settings, fn: Callable, shard_items: list) -> list:
    """One pipeline execution per shard on the shared worker pool,
    results in shard order (deterministic). Counts each launched shard
    pipeline in the ShardPipelines gauge; under `serene_trace` each
    shard's execution is stamped as a `shard_pipeline` span (with its
    shard index) into the query's timeline — the shard fan-out becomes
    visible as parallel lanes in the Chrome trace."""
    import time

    from ..obs.resources import current_accountant
    from ..obs.trace import current_trace
    from ..parallel.pool import parallel_map
    metrics.SHARD_PIPELINES.add(len(shard_items))
    acct = current_accountant()
    if acct is not None:
        # live progress: the statement is now fanning out per-shard
        # pipelines (sdb_query_progress current-operator label)
        acct.set_op(f"ShardFanout n={len(shard_items)}")
    trace = current_trace()
    if trace is None:
        return parallel_map(settings, fn, shard_items)

    def traced(pair):
        s, item = pair
        # the fused device path passes REAL shard ids (possibly
        # non-contiguous after pruning, e.g. [0, 2, 3]) — label with
        # them so the lane agrees with the device spans stamped inside;
        # other callers pass per-shard work lists, labeled by position
        label = item if isinstance(item, int) else s
        t0 = time.perf_counter_ns()
        try:
            return fn(item)
        finally:
            trace.add("shard_pipeline", "shard", t0,
                      time.perf_counter_ns(), shard=label)

    return parallel_map(settings, traced, list(enumerate(shard_items)))


class ShardedRanges(list):
    """Per-shard build-key min/max conjunct groups published through
    `ExecContext.join_filters` (shard-to-shard sideways information
    passing). Each element is one build shard's conjunct list
    (`col >= lo AND col <= hi` per rangeable key); a probe block may
    match a build row only if SOME shard's conjunction can hold, so the
    block verdict is the OR (elementwise max) across groups — strictly
    more pruning than the single global range whenever the shard ranges
    leave gaps."""


def build_shard_ranges(probe_keys, build_key_cols,
                       shard_view: list[list[tuple[int, int]]]
                       ) -> Optional[ShardedRanges]:
    """Per-build-shard key ranges: slice the build keys by the given
    shard view (TableProvider.shard_view for provider-backed sides,
    shard_spans for materialized batches) and fold each shard's
    observed min/max into synthetic range conjuncts
    (zonemap.build_key_range_exprs per shard). None when no shard
    publishes a rangeable key (caller falls back to the global range /
    plain scan)."""
    from .zonemap import build_key_range_exprs
    groups = ShardedRanges()
    for spans in shard_view:
        if not spans:
            continue
        sliced = [_concat_spans(c, spans) for c in build_key_cols]
        exprs = build_key_range_exprs(probe_keys, sliced)
        if not exprs:
            return None     # an unrangeable shard can match anywhere
        groups.append(exprs)
    return groups if groups else None


def _concat_spans(col, spans: list[tuple[int, int]]):
    """One column restricted to a shard's row spans (a host-side view
    concat; spans are block-aligned and ascending)."""
    if len(spans) == 1:
        return col.slice(spans[0][0], spans[0][1])
    from ..columnar.column import Batch, concat_batches
    parts = [Batch(["c"], [col.slice(s, e)]) for s, e in spans]
    return concat_batches(parts).columns[0]


def sharded_verdicts(provider, settings, groups: ShardedRanges,
                     columns: list[str], block_rows: int, pin=None):
    """Per-block verdicts for the OR of per-shard range groups: a block
    prunes only when EVERY shard's range conjunction proves no row can
    match (elementwise max over the per-group verdict vectors — SKIP <
    SCAN < ALL, so max is exactly disjunction). None when any group's
    range cannot be analyzed (unknown ⇒ no pruning)."""
    import numpy as np

    from . import zonemap
    combined = None
    for exprs in groups:
        v = zonemap.block_verdicts(provider, settings, exprs, columns,
                                   block_rows, pin)
        if v is None:
            return None
        combined = v if combined is None else np.maximum(combined, v)
    return combined


def verify_sharded_pruned(groups: ShardedRanges, full, spans,
                          what: str) -> None:
    """serene_zonemap_verify for shard-pruned blocks: a block was pruned
    because NO shard's range conjunction can hold, so re-scan it against
    every group and fail loudly if any group's conjunction matches a
    row."""
    from . import zonemap
    for exprs in groups:
        zonemap.verify_pruned_blocks(exprs, full, spans, what)


def count_shard_pruned(verdicts, nbytes_per_row: int = 0,
                       block_rows: int = 0, nrows: int = 0) -> None:
    """Gauge attribution of one shard-filter pruning pass; when the
    caller is about to upload (device path) it passes the per-row byte
    width so the skipped transfer volume lands in ShardBytesSkipped."""
    import numpy as np

    from . import zonemap
    pruned_blocks = np.flatnonzero(verdicts == zonemap.SKIP)
    if not len(pruned_blocks):
        return
    metrics.SHARD_MORSELS_PRUNED.add(len(pruned_blocks))
    if nbytes_per_row and block_rows:
        rows = 0
        for b in pruned_blocks:
            rows += min((int(b) + 1) * block_rows, nrows) - \
                int(b) * block_rows
        metrics.SHARD_BYTES_SKIPPED.add(rows * nbytes_per_row)


def stamp_profile(ctx, key: int, pipelines: int, pruned: int = 0,
                  collective: bool = False) -> None:
    """Per-shard span stamp for EXPLAIN ANALYZE's `Shards:` line.
    `collective=True` marks the shards as combined in-program (one
    shard_map dispatch, psum/pmin/pmax) — rendered as combine=device."""
    prof = getattr(ctx, "profile", None)
    if prof is not None:
        prof.add_shards(key, pipelines, pruned,
                        pipelines if collective else 0)


__all__ = [
    "shard_count", "combine_mode", "shard_of_block", "shard_spans",
    "group_round_robin", "run_shard_tasks", "ShardedRanges",
    "build_shard_ranges", "sharded_verdicts", "verify_sharded_pruned",
    "count_shard_pruned", "stamp_profile",
]
