from . import device, plan, tables

__all__ = ["device", "plan", "tables"]
